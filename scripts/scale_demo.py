"""End-to-end accelerated run at scale (VERDICT r2 task 5; ISSUE 9
sharded mode): the L6 simulator — NOT a synthetic kernel harness — at
>= 64K validators for >= 4 mainnet epochs, with the jax
ExecutionBackend (device epoch sweeps, specs/epoch.py dispatch) and the
resident device fork-choice store (every head query via
head_from_buckets; no per-query host rebuild).

Success criteria, asserted and recorded in SCALE_DEMO_r{N}.json
(N from --record, default 4):
- epochs justify and finalize (justified >= 3, finalized >= 2 after 4
  epochs: the genesis guard skips the first two boundaries, so the first
  justification lands at the end of epoch 2 and the first 2-finalization
  at the end of epoch 3 — pos-evolution.md:793-803, 839-852);
- the resident-store head equals the spec get_head walk at the end;
- per-handler p50/p95 from HandlerTimer (SURVEY.md §5).

Sharded mode (ISSUE 9): ``--sharded PxS`` re-execs under
``xla_force_host_platform_device_count`` (the virtual-host-device form
of a real mesh) and runs the SAME simulation with
``Simulation(sharded=(P, S))`` — epoch sweeps, the resident fork-choice
vote pass and the fused-transition session columns placed/sharded over
the (pods, shard) mesh. ``--compare`` first runs the single-device twin
in the same process and asserts the two runs' per-slot records
(head roots, justified/finalized checkpoints, participation) are
bit-identical. The sharded run's handler timings append to
``bench_history.jsonl`` as ``kind=bench_shard`` (gate with
``scripts/perf_gate.py --kind bench_shard``); ``--no-history`` opts
out.

Usage: [JAX_PLATFORMS=cpu] python scripts/scale_demo.py [n_validators]
       [--record N] [--sharded PxS] [--compare] [--epochs E]
       [--history PATH | --no-history]
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _reexec_with_devices(n_devices: int) -> None:
    """Re-exec with the virtual host-device count pinned BEFORE jax
    initializes (the dryrun_multichip pattern: rebinding an initialized
    backend in-process is unreliable)."""
    if os.environ.get("POS_SCALE_CHILD") == "1":
        return
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        flags = (flags
                 + f" --xla_force_host_platform_device_count={n_devices}"
                 ).strip()
    env = dict(os.environ, POS_SCALE_CHILD="1", JAX_PLATFORMS="cpu",
               XLA_FLAGS=flags)
    os.execve(sys.executable, [sys.executable] + sys.argv, env)


def _parse_args(args):
    opts = {"record": 4, "sharded": None, "compare": False, "epochs": 4,
            "history": os.path.join(_REPO, "bench_history.jsonl")}
    out = []
    i = 0
    while i < len(args):
        a = args[i]
        if a == "--record":
            opts["record"] = int(args[i + 1]); i += 2
        elif a == "--sharded":
            p, s = args[i + 1].lower().split("x")
            opts["sharded"] = (int(p), int(s)); i += 2
        elif a == "--compare":
            opts["compare"] = True; i += 1
        elif a == "--epochs":
            opts["epochs"] = int(args[i + 1]); i += 2
        elif a == "--history":
            opts["history"] = args[i + 1]; i += 2
        elif a == "--no-history":
            opts["history"] = None; i += 1
        else:
            out.append(a); i += 1
    opts["n"] = int(out[0]) if out else 65_536
    return opts


def _run_sim(n, epochs, sharded, timer_reset_after_first=True):
    from pos_evolution_tpu.sim import Simulation
    from pos_evolution_tpu.specs import forkchoice as fc

    t0 = time.time()
    sim = Simulation(n, accelerated_forkchoice=True,
                     sharded=sharded if sharded else False)
    init_s = time.time() - t0
    t0 = time.time()
    per_epoch = []
    for e in range(1, epochs + 1):
        te = time.time()
        sim.run_epochs(e)
        per_epoch.append(round(time.time() - te, 1))
        m = sim.metrics[-1]
        print(f"# [{'sharded' if sharded else 'single'}] epoch {e}: "
              f"{per_epoch[-1]}s  justified={m['justified_epoch']} "
              f"finalized={m['finalized_epoch']} blocks={m['n_blocks']}",
              file=sys.stderr)
        if e == 1 and timer_reset_after_first:
            # epoch 1 is the warm-up: its handler samples are dominated
            # by jit compiles and resident-store rebuild capacity growth
            sim.timer.reset()
    run_s = time.time() - t0
    group = sim.groups[0]
    spec_head = fc.get_head(group.store)
    resident_head = sim._get_head(group)
    records = [(m["head_root"], m["justified_epoch"], m["finalized_epoch"],
                m["participation"], m["n_blocks"]) for m in sim.metrics]
    out = {
        "init_s": round(init_s, 1),
        "run_s": round(run_s, 1),
        "per_epoch_s": per_epoch,
        "justified_epoch": sim.justified_epoch(),
        "finalized_epoch": sim.finalized_epoch(),
        "resident_head_equals_spec_walk": resident_head == spec_head,
        "handler_timers_post_warmup": sim.trace_summary(),
        "last_slots": sim.metrics[-3:],
    }
    if sharded:
        from pos_evolution_tpu.backend import get_backend
        get_backend().disable_sharded()
    return out, records


def main():
    opts = _parse_args(sys.argv[1:])
    if opts["sharded"]:
        _reexec_with_devices(opts["sharded"][0] * opts["sharded"][1])

    import jax

    from pos_evolution_tpu.backend import set_backend
    from pos_evolution_tpu.config import mainnet_config, use_config

    set_backend("jax")
    with use_config(mainnet_config()):
        out = {
            "n_validators": opts["n"],
            "epochs": opts["epochs"],
            "backend": "jax/" + jax.default_backend(),
            "accelerated_forkchoice": True,
            "sharded": (None if not opts["sharded"] else
                        {"pods": opts["sharded"][0],
                         "shard": opts["sharded"][1]}),
        }
        single_records = None
        if opts["compare"] or not opts["sharded"]:
            single, single_records = _run_sim(opts["n"], opts["epochs"],
                                              None)
            if opts["sharded"]:
                out["single_device"] = single
            else:
                out.update(single)
        if opts["sharded"]:
            sharded, sharded_records = _run_sim(opts["n"], opts["epochs"],
                                                opts["sharded"])
            out.update(sharded)
            if single_records is not None:
                out["bit_identical_to_single_device"] = (
                    sharded_records == single_records)
                assert out["bit_identical_to_single_device"], \
                    "sharded run diverged from the single-device twin"

        assert out["justified_epoch"] >= 3, out
        assert out["finalized_epoch"] >= 2, out
        assert out["resident_head_equals_spec_walk"], out
        path = os.path.join(_REPO, f"SCALE_DEMO_r{opts['record']:02d}.json")
        with open(path, "w") as f:
            json.dump(out, f, indent=1)
        print(json.dumps(out, indent=1))

        if opts["sharded"] and opts["history"]:
            from pos_evolution_tpu.profiling import history
            emission = {
                "metric": "scale_demo_sharded",
                "n_validators": opts["n"],
                "mesh": out["sharded"],
                "run_s": out["run_s"],
                "handlers": out["handler_timers_post_warmup"],
            }
            history.append_entry(opts["history"], emission,
                                 kind="bench_shard")
            print(f"# appended bench_shard emission to {opts['history']}",
                  file=sys.stderr)


if __name__ == "__main__":
    main()
