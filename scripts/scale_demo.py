"""End-to-end accelerated run at scale (VERDICT r2 task 5): the L6
simulator — NOT a synthetic kernel harness — at >= 64K validators for
>= 4 mainnet epochs, with the jax ExecutionBackend (device epoch sweeps,
specs/epoch.py dispatch) and the resident device fork-choice store
(every head query via head_from_buckets; no per-query host rebuild).

Success criteria, asserted and recorded in SCALE_DEMO_r{N}.json
(N from --record, default 4):
- epochs justify and finalize (justified >= 3, finalized >= 2 after 4
  epochs: the genesis guard skips the first two boundaries, so the first
  justification lands at the end of epoch 2 and the first 2-finalization
  at the end of epoch 3 — pos-evolution.md:793-803, 839-852);
- the resident-store head equals the spec get_head walk at the end;
- per-handler p50/p95 from HandlerTimer (SURVEY.md §5).

Usage: [JAX_PLATFORMS=cpu] python scripts/scale_demo.py [n_validators]
       [--record N]
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    args = sys.argv[1:]
    record = 4
    if "--record" in args:
        i = args.index("--record")
        try:
            record = int(args[i + 1])
        except (IndexError, ValueError):
            sys.exit("Usage: python scripts/scale_demo.py [n] [--record N]")
        del args[i:i + 2]
    n = int(args[0]) if args else 65_536
    epochs = 4

    import jax

    from pos_evolution_tpu.backend import set_backend
    from pos_evolution_tpu.config import mainnet_config, use_config

    set_backend("jax")
    with use_config(mainnet_config()):
        from pos_evolution_tpu.sim import Simulation
        from pos_evolution_tpu.specs import forkchoice as fc

        t0 = time.time()
        sim = Simulation(n, accelerated_forkchoice=True)
        init_s = time.time() - t0
        print(f"# init {n} validators: {init_s:.1f}s", file=sys.stderr)

        t0 = time.time()
        per_epoch = []
        for e in range(1, epochs + 1):
            te = time.time()
            sim.run_epochs(e)
            per_epoch.append(round(time.time() - te, 1))
            m = sim.metrics[-1]
            print(f"# epoch {e}: {per_epoch[-1]}s  justified="
                  f"{m['justified_epoch']} finalized={m['finalized_epoch']} "
                  f"blocks={m['n_blocks']}", file=sys.stderr)
            if e == 1:
                # epoch 1 is the warm-up: its handler samples are
                # dominated by jit compiles and resident-store rebuild
                # capacity growth — drop them so the recorded p50/p95
                # cover only the steady state
                sim.timer.reset()
        run_s = time.time() - t0

        group = sim.groups[0]
        spec_head = fc.get_head(group.store)
        resident_head = sim._get_head(group)
        out = {
            "n_validators": n,
            "epochs": epochs,
            "backend": "jax/" + jax.default_backend(),
            "accelerated_forkchoice": True,
            "init_s": round(init_s, 1),
            "run_s": round(run_s, 1),
            "per_epoch_s": per_epoch,
            "justified_epoch": sim.justified_epoch(),
            "finalized_epoch": sim.finalized_epoch(),
            "resident_head_equals_spec_walk": resident_head == spec_head,
            "handler_timers_post_warmup": sim.trace_summary(),
            "last_slots": sim.metrics[-3:],
        }
        assert out["justified_epoch"] >= 3, out
        assert out["finalized_epoch"] >= 2, out
        assert out["resident_head_equals_spec_walk"], out
        path = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), f"SCALE_DEMO_r{record:02d}.json")
        with open(path, "w") as f:
            json.dump(out, f, indent=1)
        print(json.dumps(out, indent=1))


if __name__ == "__main__":
    main()
