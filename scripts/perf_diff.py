"""Perf regression attribution: rank what explains a wall-clock delta.

``perf_gate.py`` tells you *that* a metric regressed; this tool says
*why* — it diffs two runs and ranks the phase walls, counters, and
compile-ledger rows whose deltas best explain the wall-clock delta, so
a CI failure prints an attribution table instead of a bare ratio.

Accepted inputs (each side independently):

- a **bench emission** JSON file (``bench_obs.py --json``, or any
  emission with ``phases``/``counts``/``walls`` leaves);
- a **run summary** JSON (``DenseSimulation.summary()`` — the
  ``dense_phases``/``device`` sections are understood);
- an **event log** (``*.jsonl``): ``dense_phase`` events are
  re-aggregated into per-phase totals;
- via ``--history FILE --kind K``: the last two entries of that kind in
  a ``bench_history.jsonl`` (candidate = newest).

Ranking: phases sort by absolute delta-ms; each row carries the share
of the wall delta it explains. Counters rank by relative change,
compile-ledger rows by recompile-count delta (an unexpected epoch-3
recompile names its culprit here). Exit code is always 0 — this is a
diagnostic, the *gate* decides pass/fail.

Usage:
    python scripts/perf_diff.py BASELINE CANDIDATE [--top 10] [--json out]
    python scripts/perf_diff.py --history bench_history.jsonl --kind bench_obs
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

__all__ = ["diff", "load_side", "normalize", "render"]


def _phases_table(obj) -> dict:
    """Pull a ``{phase: total_ms}`` table out of any known shape."""
    if not isinstance(obj, dict):
        return {}
    for key in ("phases", "sampled_phases"):
        tab = obj.get(key)
        if isinstance(tab, dict) and tab:
            out = {}
            for name, row in tab.items():
                if isinstance(row, dict) and "total_ms" in row:
                    out[name] = float(row["total_ms"])
                elif isinstance(row, (int, float)):
                    out[name] = float(row)
            if out:
                return out
    for key in ("dense_phases", "dense_phase_budget"):
        if isinstance(obj.get(key), dict):
            nested = _phases_table(obj[key])
            if nested:
                return nested
    return {}


def _wall_ms(obj) -> float | None:
    if not isinstance(obj, dict):
        return None
    walls = obj.get("walls")
    if isinstance(walls, dict):
        for key in ("steady_ms", "budget_ms", "wall_ms"):
            if isinstance(walls.get(key), (int, float)):
                return float(walls[key])
    for key in ("wall_ms", "sampled_wall_ms"):
        if isinstance(obj.get(key), (int, float)):
            return float(obj[key])
    dense = obj.get("dense_phases")
    if isinstance(dense, dict):
        return _wall_ms(dense)
    return None


def _ledger_rows(obj) -> dict:
    """``{"stage|function|phase": count}`` from any shape carrying a
    compile ledger (emission ``device`` section or a raw summary)."""
    if not isinstance(obj, dict):
        return {}
    led = obj.get("compile_ledger")
    if led is None and isinstance(obj.get("device"), dict):
        led = obj["device"].get("compile_ledger")
    rows = (led or {}).get("rows") if isinstance(led, dict) else None
    out = {}
    for r in rows or []:
        key = f"{r.get('stage')}|{r.get('function')}|{r.get('phase')}"
        out[key] = out.get(key, 0) + int(r.get("count", 0))
    return out


def _counts(obj) -> dict:
    if not isinstance(obj, dict):
        return {}
    counts = obj.get("counts")
    if isinstance(counts, dict):
        return {k: v for k, v in counts.items()
                if isinstance(v, (int, float))}
    tel = obj.get("telemetry")
    if isinstance(tel, dict) and isinstance(tel.get("counts"), dict):
        return {k: v for k, v in tel["counts"].items()
                if isinstance(v, (int, float))}
    return {}


def normalize(obj) -> dict:
    """One side of the diff, reduced to comparable tables."""
    return {"wall_ms": _wall_ms(obj), "phases": _phases_table(obj),
            "counts": _counts(obj), "ledger": _ledger_rows(obj)}


def _from_events(path: str) -> dict:
    """Aggregate ``dense_phase`` events from a JSONL log into one side."""
    from pos_evolution_tpu.telemetry.events import read_jsonl
    phases: dict[str, float] = {}
    wall = 0.0
    n = 0
    for ev in read_jsonl(path):
        if ev.get("type") != "dense_phase":
            continue
        n += 1
        wall += float(ev.get("wall_ms") or 0.0)
        for name, ms in (ev.get("phases") or {}).items():
            phases[name] = phases.get(name, 0.0) + float(ms)
    return {"wall_ms": round(wall, 4) if n else None,
            "phases": {k: round(v, 4) for k, v in phases.items()},
            "counts": {}, "ledger": {}}


def load_side(path: str) -> dict:
    """Load one comparand: ``.jsonl`` -> event aggregation, else a JSON
    document fed through ``normalize``."""
    if path.endswith(".jsonl"):
        return _from_events(path)
    with open(path) as fh:
        return normalize(json.load(fh))


def diff(baseline: dict, candidate: dict, top: int = 10) -> dict:
    """Rank deltas between two normalized (or normalizable) sides."""
    normalized_keys = {"wall_ms", "phases", "counts", "ledger"}
    if set(baseline) != normalized_keys:
        baseline = normalize(baseline)
    if set(candidate) != normalized_keys:
        candidate = normalize(candidate)
    b_ph, c_ph = baseline["phases"], candidate["phases"]
    wall_b, wall_c = baseline["wall_ms"], candidate["wall_ms"]
    wall_delta = (wall_c - wall_b
                  if wall_b is not None and wall_c is not None else None)
    if wall_delta is None:
        wall_delta = sum(c_ph.values()) - sum(b_ph.values())

    phase_rows = []
    for name in sorted(set(b_ph) | set(c_ph)):
        b, c = b_ph.get(name, 0.0), c_ph.get(name, 0.0)
        d = c - b
        row = {"phase": name, "baseline_ms": round(b, 4),
               "candidate_ms": round(c, 4), "delta_ms": round(d, 4),
               "ratio": round(c / b, 4) if b > 0 else None,
               "wall_share_pct": (round(100.0 * d / wall_delta, 2)
                                  if wall_delta else None)}
        phase_rows.append(row)
    phase_rows.sort(key=lambda r: -abs(r["delta_ms"]))

    counter_rows = []
    b_ct, c_ct = baseline["counts"], candidate["counts"]
    for name in sorted(set(b_ct) | set(c_ct)):
        b, c = b_ct.get(name, 0), c_ct.get(name, 0)
        if b == c:
            continue
        counter_rows.append({
            "counter": name, "baseline": b, "candidate": c,
            "delta": c - b, "ratio": round(c / b, 4) if b else None})
    counter_rows.sort(key=lambda r: -(abs(r["ratio"] - 1.0)
                                      if r["ratio"] else float("inf")))

    ledger_rows = []
    b_led, c_led = baseline["ledger"], candidate["ledger"]
    for key in sorted(set(b_led) | set(c_led)):
        b, c = b_led.get(key, 0), c_led.get(key, 0)
        if b == c:
            continue
        stage, fn, phase = (key.split("|") + ["?", "?"])[:3]
        ledger_rows.append({"stage": stage, "function": fn, "phase": phase,
                            "baseline": b, "candidate": c, "delta": c - b})
    ledger_rows.sort(key=lambda r: -abs(r["delta"]))

    return {
        "wall": {"baseline_ms": wall_b, "candidate_ms": wall_c,
                 "delta_ms": (round(wall_delta, 4)
                              if wall_delta is not None else None)},
        "phases": phase_rows[:top],
        "counters": counter_rows[:top],
        "compile_ledger": ledger_rows[:top],
        "top_phase": phase_rows[0]["phase"] if phase_rows else None,
    }


def render(d: dict) -> str:
    lines = []
    w = d["wall"]
    if w["baseline_ms"] is not None and w["candidate_ms"] is not None:
        lines.append(f"wall: {w['baseline_ms']:.2f} ms -> "
                     f"{w['candidate_ms']:.2f} ms "
                     f"({w['delta_ms']:+.2f} ms)")
    if d["phases"]:
        lines.append("phase attribution (|delta| desc):")
        lines.append(f"  {'phase':<22} {'baseline':>10} {'candidate':>10} "
                     f"{'delta':>9} {'share':>7}")
        for r in d["phases"]:
            share = (f"{r['wall_share_pct']:6.1f}%"
                     if r["wall_share_pct"] is not None else "      -")
            lines.append(f"  {r['phase']:<22} {r['baseline_ms']:>10.2f} "
                         f"{r['candidate_ms']:>10.2f} "
                         f"{r['delta_ms']:>+9.2f} {share}")
    if d["counters"]:
        lines.append("counter deltas (relative change desc):")
        for r in d["counters"]:
            ratio = f"x{r['ratio']}" if r["ratio"] is not None else "new"
            lines.append(f"  {r['counter']:<46} {r['baseline']} -> "
                         f"{r['candidate']} ({ratio})")
    if d["compile_ledger"]:
        lines.append("compile-ledger deltas (recompile culprits):")
        for r in d["compile_ledger"]:
            lines.append(f"  {r['stage']:<16} {r['function']:<28} "
                         f"phase={r['phase']:<18} {r['baseline']} -> "
                         f"{r['candidate']} ({r['delta']:+d})")
    if d.get("top_phase"):
        lines.append(f"top attribution: {d['top_phase']}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("baseline", nargs="?",
                    help="emission/summary JSON or event-log .jsonl")
    ap.add_argument("candidate", nargs="?",
                    help="emission/summary JSON or event-log .jsonl")
    ap.add_argument("--history",
                    help="bench_history.jsonl; diffs the last two "
                         "entries of --kind instead of two files")
    ap.add_argument("--kind", help="history kind (with --history)")
    ap.add_argument("--top", type=int, default=10)
    ap.add_argument("--json", help="write the attribution table here")
    args = ap.parse_args(argv)

    if args.history:
        from pos_evolution_tpu.profiling.history import read_history
        entries = [e for e in read_history(args.history)
                   if args.kind in (None, e.get("kind"))]
        if len(entries) < 2:
            print(f"perf_diff: need >= 2 history entries of kind "
                  f"{args.kind!r}, found {len(entries)}", file=sys.stderr)
            return 0
        baseline = normalize(entries[-2].get("emission") or {})
        candidate = normalize(entries[-1].get("emission") or {})
    elif args.baseline and args.candidate:
        baseline = load_side(args.baseline)
        candidate = load_side(args.candidate)
    else:
        ap.error("need BASELINE CANDIDATE files or --history/--kind")
        return 2  # unreachable; ap.error raises

    d = diff(baseline, candidate, top=args.top)
    print(render(d))
    if args.json:
        with open(args.json, "w") as fh:
            json.dump(d, fh, indent=1, sort_keys=True)
            fh.write("\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
