"""obs_top: a live terminal view of a running fleet / dense run.

Everything is read from files the run already writes — heartbeat files
(``utils/watchdog.Heartbeat``: ``*.hb`` / ``*.heartbeat``), fleet
metric snapshots (``worker<id>.pid<pid>.metrics.json``), the tail of an
event log, and the flight recorder's ``*device_ledger.json`` artifact —
so it attaches to any run directory with zero cooperation from the run
itself, including one on the far side of an ssh mount. Sections it can
render (each optional; missing inputs just drop the section):

- **progress**: latest slot / justified / finalized from heartbeat
  payloads or the newest ``slot`` event, plus slots/s across refreshes;
- **worker health**: per-heartbeat age (stale > 3x the refresh interval
  is flagged), per-worker request totals from the fleet snapshots;
- **device**: HBM/RSS watermark from the device ledger artifact (or
  live ``device_memory`` events), compile count + top provenance row;
- **counters**: compile/transfer/dispatch totals from the snapshots.

``--once`` prints a single snapshot and exits (CI artifact mode);
otherwise redraws every ``--interval`` seconds until interrupted.

Usage:
    python scripts/obs_top.py --dir RUNDIR [--events events.jsonl]
        [--interval 2] [--once] [--device-ledger device_ledger.json]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

__all__ = ["collect", "render"]


def _fmt_bytes(n) -> str:
    if n is None:
        return "-"
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024.0
    return f"{n:.1f} TiB"


def _heartbeats(directory: str) -> list[dict]:
    from pos_evolution_tpu.utils.watchdog import read_heartbeat
    rows = []
    for pat in ("*.hb", "*.heartbeat", "*heartbeat*.json"):
        for path in sorted(glob.glob(os.path.join(directory, pat))):
            hb = read_heartbeat(path)
            if hb is not None:
                rows.append({"file": os.path.basename(path),
                             "age_s": round(hb["age_s"], 1),
                             "payload": hb["payload"]})
    return rows


def _tail_events(path: str, want=("slot", "device_memory"),
                 max_bytes: int = 262144) -> dict:
    """Newest event of each wanted type from the tail of a JSONL log —
    bounded read so a multi-GB log never stalls the refresh."""
    out: dict = {}
    try:
        size = os.path.getsize(path)
        with open(path, "rb") as fh:
            fh.seek(max(0, size - max_bytes))
            chunk = fh.read().decode("utf-8", "replace")
        for line in chunk.splitlines():
            try:
                ev = json.loads(line)
            except ValueError:
                continue  # torn first/last line of the window
            if isinstance(ev, dict) and ev.get("type") in want:
                out[ev["type"]] = ev
    except OSError:
        pass
    return out


def _device_ledger(path: str | None, directory: str | None) -> dict | None:
    candidates = [path] if path else []
    if directory:
        candidates += sorted(glob.glob(
            os.path.join(directory, "*device_ledger.json")))
    for cand in candidates:
        try:
            with open(cand) as fh:
                doc = json.load(fh)
            if isinstance(doc, dict) and "flight_recorder" in doc:
                doc["_path"] = cand
                return doc
        except (OSError, ValueError):
            continue
    return None


def collect(directory: str | None, events: str | None = None,
            device_ledger: str | None = None) -> dict:
    """One snapshot of everything obs_top can see right now."""
    snap: dict = {"unix": time.time(), "dir": directory}
    if directory and os.path.isdir(directory):
        snap["heartbeats"] = _heartbeats(directory)
        from pos_evolution_tpu.telemetry.fleet import FleetAggregator
        agg = FleetAggregator.from_dir(directory)
        if agg.snapshots_merged:
            snap["fleet"] = agg.summary()
            snap["counters"] = {
                name: agg.fleet_total(name)
                for name in ("jax_backend_compiles_total",
                             "jax_transfer_bytes_total",
                             "jax_dispatches_total",
                             "serve_requests_total")
                if agg.fleet_total(name)}
    if events:
        snap["events"] = _tail_events(events)
    ledger = _device_ledger(device_ledger, directory)
    if ledger is not None:
        snap["device_ledger"] = ledger
    return snap


def _progress(snap: dict) -> dict:
    """Best available (slot, justified, finalized) view."""
    best: dict = {}
    for hb in snap.get("heartbeats", ()):
        payload = hb.get("payload") or {}
        if payload.get("slot") is not None and \
                payload.get("slot", -1) >= best.get("slot", -1):
            best = {k: payload.get(k) for k in
                    ("slot", "justified_epoch", "finalized_epoch")
                    if payload.get(k) is not None}
    ev = (snap.get("events") or {}).get("slot")
    if ev and ev.get("slot", -1) >= best.get("slot", -1):
        # merge, don't replace: a slot event usually carries no epoch
        # fields, and dropping the heartbeat's justified/finalized on a
        # tie would blank the finality-lag readout
        best.update({k: ev[k] for k in
                     ("slot", "justified_epoch", "finalized_epoch")
                     if ev.get(k) is not None})
    return best


def render(snap: dict, prev: dict | None = None,
           interval: float | None = None) -> str:
    lines = [f"obs_top @ {time.strftime('%H:%M:%S', time.gmtime(snap['unix']))}Z"
             f"  dir={snap.get('dir') or '-'}"]
    prog = _progress(snap)
    if prog:
        line = f"  slot {prog.get('slot', '?')}"
        if prog.get("justified_epoch") is not None:
            line += f"  justified {prog['justified_epoch']}"
        if prog.get("finalized_epoch") is not None:
            line += f"  finalized {prog['finalized_epoch']}"
            if prog.get("justified_epoch") is not None:
                # finality lag: justified-but-unfinalized epochs. 1 is
                # healthy pipelining; growing lag = liveness trouble
                lag = (int(prog["justified_epoch"])
                       - int(prog["finalized_epoch"]))
                line += f"  lag {lag}"
        if prev is not None and interval:
            p = _progress(prev)
            if p.get("slot") is not None and prog.get("slot") is not None:
                rate = (prog["slot"] - p["slot"]) / interval
                line += f"  ({rate:.2f} slots/s)"
        lines.append(line)
    for hb in snap.get("heartbeats", ()):
        stale = interval is not None and hb["age_s"] > 3 * interval
        flag = "  ** STALE **" if stale else ""
        lines.append(f"  hb {hb['file']:<28} age {hb['age_s']:>6.1f}s"
                     f"{flag}")
    fleet = snap.get("fleet")
    if fleet:
        reqs = fleet.get("requests_by_worker") or {}
        for w, meta in sorted((fleet.get("workers") or {}).items()):
            lines.append(f"  worker {w:<4} pid {meta.get('pid')} "
                         f"gen {meta.get('generation')} "
                         f"requests {int(reqs.get(w, 0))}")
    counters = snap.get("counters")
    if counters:
        parts = []
        for name, val in sorted(counters.items()):
            short = name.replace("_total", "")
            if "bytes" in name:
                parts.append(f"{short}={_fmt_bytes(val)}")
            else:
                parts.append(f"{short}={int(val)}")
        lines.append("  " + "  ".join(parts))
    ledger = snap.get("device_ledger")
    if ledger:
        fr = ledger.get("flight_recorder") or {}
        mem = fr.get("memory") or {}
        peaks = mem.get("peak_bytes") or {}
        if peaks:
            peak_line = "  hbm watermark: " + "  ".join(
                f"{dev}={_fmt_bytes(b)}" for dev, b in sorted(peaks.items()))
            peak_line += f"  (source={mem.get('source')})"
            lines.append(peak_line)
        led = fr.get("compile_ledger") or {}
        attr = led.get("attribution") or {}
        if attr.get("backend_compiles") is not None:
            lines.append(f"  compiles: {attr['backend_compiles']} "
                         f"({attr.get('named_pct', '-')}% named)")
        rows = led.get("rows") or []
        if rows:
            r = rows[0]
            lines.append(f"  top compile row: {r.get('function')} "
                         f"phase={r.get('phase')} x{r.get('count')} "
                         f"({r.get('seconds')}s)")
        skew = fr.get("shard_skew") or {}
        table = skew.get("table") or []
        if table:
            worst = max(table, key=lambda r: r.get("max_ms", 0))
            lines.append(f"  worst shard skew: {worst['phase']}/"
                         f"{worst['device']} max {worst['max_ms']} ms "
                         f"over {worst['probes']} probe(s)")
    dm = (snap.get("events") or {}).get("device_memory")
    if dm and "device_ledger" not in snap:
        rows = dm.get("rows") or []
        if rows:
            lines.append("  live memory: " + "  ".join(
                f"{r['device']}={_fmt_bytes(r['bytes_in_use'])}"
                for r in rows))
    if len(lines) == 1:
        lines.append("  (nothing to show yet — no heartbeats, snapshots, "
                     "events, or device ledger found)")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--dir", help="run directory (heartbeats, fleet "
                                  "snapshots, device ledger artifacts)")
    ap.add_argument("--events", help="event log to tail for slot/memory")
    ap.add_argument("--device-ledger",
                    help="explicit flight-recorder artifact path")
    ap.add_argument("--interval", type=float, default=2.0)
    ap.add_argument("--once", action="store_true",
                    help="print one snapshot and exit (CI artifact mode)")
    args = ap.parse_args(argv)

    prev = None
    while True:
        snap = collect(args.dir, events=args.events,
                       device_ledger=args.device_ledger)
        text = render(snap, prev=prev,
                      interval=None if args.once else args.interval)
        if args.once:
            print(text)
            return 0
        # ANSI clear + home, then the frame — a plain terminal "top"
        sys.stdout.write("\x1b[2J\x1b[H" + text + "\n")
        sys.stdout.flush()
        prev = snap
        try:
            time.sleep(args.interval)
        except KeyboardInterrupt:
            return 0


if __name__ == "__main__":
    sys.exit(main())
