#!/usr/bin/env python
"""Deep static analysis from scripts/ — thin wrapper over
``python -m pos_evolution_tpu.analysis`` (see DESIGN.md §21).

Typical invocations::

    python scripts/lint_deep.py --strict            # the CI gate
    python scripts/lint_deep.py --doctor            # self-test (rc 1 = ok)
    python scripts/lint_deep.py tests --rules PEV002,PEV006 --strict
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from pos_evolution_tpu.analysis.__main__ import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
