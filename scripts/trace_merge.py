"""Merge per-process span files into ONE Chrome trace (ISSUE 18 leg b).

Every process in the serving plane — the loadgen/harness process and
each worker — writes its sampled request spans to its own append-only
``spans.<pid>.jsonl`` (``telemetry/tracing.py``); nothing at runtime
coordinates across processes beyond the deterministic trace id riding
the frame protocol. This tool does the joining after the fact:

- discovers every ``spans.*.jsonl`` under the trace directory;
- re-bases all ``t0`` wall-clock stamps to the earliest span (Chrome
  trace timestamps are microsecond offsets, and epoch-seconds-as-µs
  overflows the viewer's usable range);
- renders each process as its own pid lane (``process_name`` metadata
  from the recorded ``proc`` label) with "X" duration slices, so one
  hedged request reads as a ladder: ``balancer_pick``/``client`` in the
  loadgen lane, ``queue_wait``/``service``/``backing`` in each worker
  lane that touched it;
- links the spans of one trace id with Chrome flow arrows (``s``/``t``/
  ``f`` events keyed by the trace id) so the cross-process hops are
  drawn, not inferred — a hedge that lands on two workers shows two
  linked service spans under one arrow chain.

The span files double as the programmatic source: every slice carries
``args.trace``, so Perfetto's query engine (or ``--trace ID`` here) can
pull one request's full timeline.

Usage:
    python scripts/trace_merge.py <trace_dir> [--out merged.json]
        [--trace ID] [--expect-pids N]

Prints a one-line inventory (files / processes / spans / traces);
``--expect-pids`` exits 1 when fewer distinct processes contributed
spans — the CI assertion that tracing actually crossed the process
boundary.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

_META_KEYS = ("trace", "name", "t0", "dur_ms", "pid", "proc", "tid")


def discover_span_files(directory: str) -> list[str]:
    return sorted(glob.glob(os.path.join(directory, "spans.*.jsonl")))


def read_spans(path: str) -> list[dict]:
    """Spans from one file; torn tail lines (a process killed mid-write)
    are skipped, never fatal — same posture as the fleet snapshots."""
    spans = []
    try:
        with open(path) as fh:
            for line in fh:
                try:
                    span = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(span, dict) and "t0" in span \
                        and "trace" in span:
                    spans.append(span)
    except OSError:
        return []
    return spans


def load_directory(directory: str,
                   trace: str | None = None) -> list[dict]:
    spans = [s for path in discover_span_files(directory)
             for s in read_spans(path)]
    if trace is not None:
        spans = [s for s in spans if s.get("trace") == trace]
    return spans


def merge_chrome(spans: list[dict]) -> dict:
    """Span records -> Chrome trace_event JSON object form (the same
    shape ``profiling/export.py`` emits, loadable by Perfetto and
    chrome://tracing)."""
    out: list[dict] = []
    if not spans:
        return {"traceEvents": out, "displayTimeUnit": "ms"}
    t0_min = min(float(s["t0"]) for s in spans)
    procs: dict[int, str] = {}
    for s in spans:
        pid = int(s.get("pid", 0))
        procs.setdefault(pid, str(s.get("proc", f"pid{pid}")))
    for pid, proc in sorted(procs.items()):
        out.append({"ph": "M", "pid": pid, "name": "process_name",
                    "args": {"name": proc}})
    by_trace: dict[str, list[dict]] = {}
    for s in spans:
        ts_us = (float(s["t0"]) - t0_min) * 1e6
        dur_us = max(float(s.get("dur_ms", 0.0)) * 1e3, 0.5)
        args = {k: v for k, v in s.items() if k not in _META_KEYS}
        args["trace"] = s["trace"]
        slice_ = {"name": str(s.get("name", "?")), "cat": "request",
                  "ph": "X", "ts": round(ts_us, 3),
                  "dur": round(dur_us, 3), "pid": int(s.get("pid", 0)),
                  "tid": int(s.get("tid", 0)), "args": args}
        out.append(slice_)
        by_trace.setdefault(str(s["trace"]), []).append(slice_)
    # flow arrows: chain each trace's spans in start order so the
    # cross-process hops are DRAWN. The flow id is the trace id's low
    # bits; the events bind to their slice by (pid, tid, ts-inside).
    for trace, slices in sorted(by_trace.items()):
        if len(slices) < 2:
            continue
        slices = sorted(slices, key=lambda e: e["ts"])
        try:
            flow_id = int(trace, 16) & 0x7FFF_FFFF
        except ValueError:
            flow_id = abs(hash(trace)) & 0x7FFF_FFFF
        last = len(slices) - 1
        for k, e in enumerate(slices):
            ph = "s" if k == 0 else ("f" if k == last else "t")
            ev = {"ph": ph, "cat": "trace", "name": "request",
                  "id": flow_id, "pid": e["pid"], "tid": e["tid"],
                  "ts": round(e["ts"] + min(e["dur"] / 2, 0.25), 3)}
            if ph == "f":
                ev["bp"] = "e"
            out.append(ev)
    return {"traceEvents": out, "displayTimeUnit": "ms"}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("trace_dir", help="directory of spans.*.jsonl files")
    ap.add_argument("--out", help="write the merged Chrome trace here "
                                  "(default: <trace_dir>/merged.json)")
    ap.add_argument("--trace", help="keep only this trace id")
    ap.add_argument("--expect-pids", type=int, default=0,
                    help="exit 1 unless at least this many distinct "
                         "processes contributed spans")
    args = ap.parse_args(argv)

    files = discover_span_files(args.trace_dir)
    spans = load_directory(args.trace_dir, trace=args.trace)
    merged = merge_chrome(spans)
    pids = {s["pid"] for s in spans if "pid" in s}
    traces = {s["trace"] for s in spans}
    out_path = args.out or os.path.join(args.trace_dir, "merged.json")
    with open(out_path, "w") as fh:
        json.dump(merged, fh)
        fh.write("\n")
    print(f"trace_merge: {len(files)} span files, {len(pids)} processes, "
          f"{len(spans)} spans, {len(traces)} traces -> {out_path}")
    if args.expect_pids and len(pids) < args.expect_pids:
        print(f"trace_merge: expected spans from >= {args.expect_pids} "
              f"processes, got {len(pids)} — tracing did not cross the "
              f"process boundary", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
