"""Config #3 with REAL BLS12-381 pairings at reference scale.

In the reference every aggregate carries a real BLS signature
(pos-evolution.md:714-717, :165, :642): 64 committees x 32 slots = 2048
aggregates per epoch covering every active validator. This measures the
full batched device verify pipeline at that scale — the round-4 verdict's
"execute and time fast_aggregate_verify_batch at 2048 aggregates / >=256K
signers, no extrapolation":

    verify path (timed, per stage):
      1. signature decompression  g2prep.g2_decompress_batch   [B]
      2. hash-to-G2               g2prep.hash_to_g2_*          [B]
      3. batched pairing          pairing.fast_aggregate_verify_batch

    setup (untimed, reported): pk-table decompression at N signers via
    g2prep.g1_decompress_batch; signing via the device twist ladder.

All timings are wall-clock on whatever backend is live, labeled — no
cross-backend normalization. A signature swap must flip the affected
lanes to False (asserted) so the pipeline is demonstrably verifying.

Usage: python scripts/bench_config3_real.py [--aggregates 2048]
       [--signers 262144] [--json]
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def run(aggregates: int = 2048, signers: int = 262_144,
        distinct_keys: int = 256, verbose: bool = True,
        preamble: str = "device", chunk: int = 0,
        negctl_slice: int = 0, watchdog_path: str | None = None,
        chunk_timeout: float = 0.0) -> dict:
    """``preamble='oracle'`` creates/hashes/decompresses points with the
    exact host oracle instead of the batched device kernels — on a
    single-core XLA:CPU box the limb ladders run ~3-4x slower than
    CPython bigints, so the oracle path keeps a full-scale CPU run
    feasible while the DEVICE pairing (the config-3 kernel under test)
    is still what gets timed. On TPU leave the default. ``chunk`` splits
    the pairing batch (progress visibility + bounded memory);
    ``negctl_slice`` runs the swapped-signature control on a prefix
    slice instead of the full batch.

    Watchdog supervision (utils/watchdog.py): each pairing chunk runs as
    a supervised step — its cumulative result is committed to
    ``watchdog_path`` the moment the chunk lands, and a chunk that dies
    (or exceeds ``chunk_timeout`` seconds) records an incident and the
    run returns the PARTIAL result instead of losing everything (the
    round-5 failure mode: an LLVM OOM on chunk 4 of 8 erased the run)."""
    import jax
    import jax.numpy as jnp

    from pos_evolution_tpu.crypto import bls12_381 as o
    from pos_evolution_tpu.ops import fp
    from pos_evolution_tpu.ops import g2prep as gp
    from pos_evolution_tpu.ops.pairing import fast_aggregate_verify_batch
    from pos_evolution_tpu.utils.watchdog import Watchdog

    # direct construction, not from_env: run() is an API with explicit
    # params, and a nested call from bench_all must not inherit the
    # outer harness's POS_BENCH_PARTIAL path and clobber its file
    wd = Watchdog(path=watchdog_path, tag="bench_config3_real",
                  timeout_s=chunk_timeout or None)

    def log(msg):
        if verbose:
            print(f"# {msg}", file=sys.stderr, flush=True)

    B, N, K = aggregates, signers, distinct_keys
    C = N // B                                   # lanes per aggregate
    assert B * C == N, "signers must divide into aggregates"
    rng = np.random.default_rng(0xC3)
    out = {"backend": jax.default_backend(), "aggregates": B, "signers": N,
           "lanes_per_aggregate": C, "real_crypto": True}

    # --- setup: keys, committees, bits, messages -----------------------------
    t0 = time.perf_counter()
    sks = [int(rng.integers(2, 2**62)) for _ in range(K)]
    pk_comp = [o.g1_compress(o.ec_mul(o.G1_GEN, sk)) for sk in sks]
    sk_of = np.asarray([sks[i % K] for i in range(N)], dtype=object)
    log(f"{K} distinct keys in {time.perf_counter()-t0:.1f}s")

    # pk table: decompress the K unique keys on device, then tile to N by
    # gather — with tiled inputs the result is element-for-element what a
    # full-N decompression would produce (deposit-time table build; the
    # single-core XLA:CPU ladder at N = 262144 alone ran >1 h, all setup)
    xs = np.zeros((K, fp.L), np.int32)
    signs = np.zeros(K, bool)
    for i, d in enumerate(pk_comp):
        bits_ = int.from_bytes(d, "big")
        signs[i] = bool(bits_ & (1 << 381))
        xs[i] = fp.to_limbs(bits_ & ((1 << 381) - 1))
    tile_idx = jnp.asarray(np.arange(N) % K)
    t0 = time.perf_counter()
    pk_uniq, pk_ok = gp.g1_decompress_batch(
        jnp.asarray(xs), jnp.asarray(signs))
    pk_table = jax.block_until_ready(pk_uniq[tile_idx])
    assert bool(np.asarray(pk_ok).all())
    t_table = time.perf_counter() - t0
    out["pk_table_decompress_s"] = round(t_table, 3)
    out["pk_table_note"] = (f"{K} unique keys device-decompressed, tiled "
                            f"to {N} (tiled inputs give the identical table)")
    log(f"pk table: {K} unique keys decompressed + tiled to {N} in "
        f"{t_table:.1f}s (setup)")

    committees = rng.permutation(N).reshape(B, C).astype(np.int32)
    bits = rng.random((B, C)) < 0.99
    bits[:, 0] = True                            # no empty aggregates
    messages = [rng.bytes(32) for _ in range(B)]

    agg_sk = np.zeros(B, dtype=object)
    for b in range(B):
        agg_sk[b] = int(sum(int(s) for s in
                            sk_of[committees[b][bits[b]]]) % o.R)

    # --- setup: sign (aggregate sk x H(m) on the twist) ----------------------
    out["preamble"] = preamble
    if preamble == "device":
        t0 = time.perf_counter()
        xcand, _ = gp.hash_to_g2_candidates(messages)
        msg_aff, ok = gp.hash_to_g2_finish(jnp.asarray(xcand))
        msg_aff = jax.block_until_ready(msg_aff)
        assert bool(np.asarray(ok).all())
        t_h2g2_setup = time.perf_counter() - t0
        skbits = np.zeros((B, 255), bool)
        for b in range(B):
            skbits[b] = [(agg_sk[b] >> (254 - j)) & 1 for j in range(255)]
        t0 = time.perf_counter()
        sig_aff, sig_inf0 = gp.g2_jac_to_affine(
            gp.g2_mul_scalar_batch(msg_aff, jnp.asarray(skbits)))
        sig_aff = jax.block_until_ready(sig_aff)
        assert not bool(np.asarray(sig_inf0).any())
        t_sign = time.perf_counter() - t0
        sig_np = np.asarray(sig_aff)
        sig_points = []
        for b in range(B):
            sig_points.append((
                o.Fq2(fp.from_limbs(sig_np[b, 0, 0]),
                      fp.from_limbs(sig_np[b, 0, 1])),
                o.Fq2(fp.from_limbs(sig_np[b, 1, 0]),
                      fp.from_limbs(sig_np[b, 1, 1]))))
    else:
        t0 = time.perf_counter()
        h_points = [o.hash_to_g2(m) for m in messages]
        t_h2g2_setup = time.perf_counter() - t0
        t0 = time.perf_counter()
        sig_points = [o.ec_mul(h, int(k)) for h, k in zip(h_points, agg_sk)]
        t_sign = time.perf_counter() - t0
    out["signing_setup_s"] = round(t_sign, 3)
    out["hash_to_g2_setup_s"] = round(t_h2g2_setup, 3)
    log(f"signed {B} aggregates ({preamble}) in {t_sign:.1f}s (setup); "
        f"setup hash-to-G2 {t_h2g2_setup:.1f}s")

    # compress to the 96-byte wire format (what the verify path receives)
    sig_bytes = np.stack([
        np.frombuffer(o.g2_compress(p), np.uint8) for p in sig_points])

    # --- verify path (timed) --------------------------------------------------
    from pos_evolution_tpu.ops.pairing import g2_affine_encode

    # 1) signature decompression
    if preamble == "device":
        t0 = time.perf_counter()
        xl, sg, inf, noncanon = gp.g2_compressed_to_limbs(sig_bytes)
        assert not noncanon.any(), "non-canonical compressed signature encoding"
        sig_g2, sig_ok = gp.g2_decompress_batch(
            jnp.asarray(xl), jnp.asarray(sg))
        sig_g2 = jax.block_until_ready(sig_g2)
        t_decomp = time.perf_counter() - t0
        assert bool(np.asarray(sig_ok).all())
    else:
        t0 = time.perf_counter()
        pts = [o.g2_decompress(row.tobytes()) for row in sig_bytes]
        sig_g2 = jnp.asarray(np.stack([g2_affine_encode(p) for p in pts]))
        t_decomp = time.perf_counter() - t0
        inf = np.zeros(B, bool)

    # 2) hash-to-G2
    if preamble == "device":
        t0 = time.perf_counter()
        xcand2, _ = gp.hash_to_g2_candidates(messages)
        msg_g2, ok2 = gp.hash_to_g2_finish(jnp.asarray(xcand2))
        msg_g2 = jax.block_until_ready(msg_g2)
        t_hash = time.perf_counter() - t0
        assert bool(np.asarray(ok2).all())
    else:
        t0 = time.perf_counter()
        msg_g2 = jnp.asarray(np.stack(
            [g2_affine_encode(o.hash_to_g2(m)) for m in messages]))
        t_hash = time.perf_counter() - t0

    # 3) the batched pairing — the device kernel under test, always.
    # Every chunk is a supervised watchdog step: completed chunks are
    # committed on arrival, a dead/over-budget chunk records an incident
    # and the run reports the partial result instead of dying.
    committees_j = jnp.asarray(committees)
    bits_j = jnp.asarray(bits)
    inf_j = jnp.asarray(inf)
    step = chunk if chunk else B
    verdicts = []
    t_pair = 0.0

    def _pair_chunk(lo, hi):
        """Returns JSON-small facts only (plain bool list, no numpy repr
        in the committed file). The verdict rides the return value so a
        chunk counts toward ``b_done`` if and ONLY if its step completed
        — an append-from-inside would leak a half-done chunk into the
        tally when the supervisor kills the step after the pairing but
        before the return, or double-count under step retries."""
        t0 = time.perf_counter()
        v = fast_aggregate_verify_batch(
            pk_table, committees_j[lo:hi], bits_j[lo:hi],
            msg_g2[lo:hi], sig_g2[lo:hi], inf_j[lo:hi])
        v = np.asarray(jax.block_until_ready(v))
        return {"aggregates": int(hi - lo),
                "seconds": time.perf_counter() - t0,
                "verdicts": v.tolist()}

    for lo in range(0, B, step):
        hi = min(lo + step, B)
        res = wd.step(f"pairing_chunk_{lo}_{hi}", _pair_chunk, lo, hi)
        if res is None:
            log(f"pairing chunk {lo}..{hi} DIED; keeping {lo} completed "
                f"aggregates (incident recorded)")
            break
        verdicts.append(np.asarray(res["verdicts"], dtype=bool))
        t_pair += res["seconds"]
        # overwrite-commit the cumulative tally so a later kill -9 still
        # leaves the progress on disk, not just the per-chunk verdicts
        wd.completed["pairing_progress"] = {
            "aggregates_done": hi, "pairing_s": round(t_pair, 3)}
        wd.commit()
        if chunk:
            log(f"pairing chunk {lo}..{hi}: cumulative {t_pair:.1f}s")
    b_done = sum(v.shape[0] for v in verdicts)
    partial = b_done < B
    if b_done:
        verdict = np.concatenate(verdicts)
        assert verdict.all(), "a valid aggregate failed to verify"

    total = t_decomp + t_hash + t_pair
    n_signed = int(bits[:b_done].sum())
    out.update({
        "sig_decompress_s": round(t_decomp, 3),
        "hash_to_g2_s": round(t_hash, 3),
        "pairing_s": round(t_pair, 3),
        "verify_total_s": round(total, 3),
        "participating_signers": n_signed,
    })
    if partial:
        out.update({
            "partial": True,
            "aggregates_completed": b_done,
            "watchdog_incidents": wd.incidents,
        })
        if b_done:
            # decomp/hash covered the FULL batch; prorate them to the
            # completed fraction so partial rates stay comparable to
            # complete rows instead of biasing low
            frac = b_done / B
            t_part = (t_decomp + t_hash) * frac + t_pair
            out["rate_note"] = ("decomp/hash prorated to completed "
                                "fraction for the rates")
            out["aggregates_per_s"] = round(b_done / t_part, 1)
            out["attestations_per_s"] = round(n_signed / t_part, 1)
        log(f"PARTIAL verify: {b_done}/{B} aggregates in {total:.1f}s "
            f"({len(wd.incidents)} incident(s) recorded)")
        return out
    out.update({
        "aggregates_per_s": round(B / total, 1),
        "attestations_per_s": round(n_signed / total, 1),
    })
    log(f"verify: decomp {t_decomp:.1f}s + hash {t_hash:.1f}s + "
        f"pairing {t_pair:.1f}s = {total:.1f}s "
        f"({n_signed/total:,.0f} attestations/s on {out['backend']})")

    # --- negative control: swapped signatures must fail -----------------------
    ns = negctl_slice if negctl_slice else B
    swapped = np.asarray(sig_g2[:ns]).copy()
    swapped[[0, 1]] = swapped[[1, 0]]
    bad = np.asarray(fast_aggregate_verify_batch(
        pk_table, committees_j[:ns], bits_j[:ns],
        msg_g2[:ns], jnp.asarray(swapped), inf_j[:ns]))
    assert not bad[0] and not bad[1] and bad[2:].all(), \
        "swapped signatures were not rejected"
    out["negative_control"] = (f"swapped sigs rejected, rest verified "
                               f"(on {ns} of {B} aggregates)")
    log(f"negative control passed (swapped sigs rejected; slice {ns})")
    return out


if __name__ == "__main__":
    argv = sys.argv[1:]

    def _arg(name, default):
        if name in argv:
            return int(argv[argv.index(name) + 1])
        return default

    default_partial = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "config3_real_partial.json")
    res = run(aggregates=_arg("--aggregates", 2048),
              signers=_arg("--signers", 262_144),
              preamble=("oracle" if "--preamble-oracle" in argv
                        else "device"),
              chunk=_arg("--chunk", 0),
              negctl_slice=_arg("--negctl-slice", 0),
              watchdog_path=os.environ.get("POS_BENCH_PARTIAL",
                                           default_partial),
              chunk_timeout=float(_arg("--chunk-timeout", 0)))
    # a watchdog-supervised chunk death returns a partial dict from run()
    # (exit 0 through here); unsupervised setup-phase failures still
    # raise, but the commit-on-arrival file has whatever completed
    print(json.dumps(res, indent=1))
