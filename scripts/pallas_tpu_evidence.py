"""On-TPU Pallas evidence (VERDICT r2 task 6): time the two Pallas kernels
against their XLA twins at several shapes, assert parity, and record the
result as an artifact (PALLAS_TPU_r03.json).

Methodology: the shared fused-loop work-difference recipe in
``pos_evolution_tpu/utils/benchtime.py`` (``block_until_ready`` does not
sync on the axon relay; see that module's docstring).
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax
import jax.numpy as jnp

from pos_evolution_tpu.utils.benchtime import fused_measure

from pos_evolution_tpu.crypto.bls import FakeBLS
from pos_evolution_tpu.ops.aggregation import (
    aggregate_verify_batch,
    messages_to_words,
    pack_signature_words,
    precompute_pk_states,
)
from pos_evolution_tpu.ops.pallas_aggregation import (
    aggregate_verify_batch_pallas_jit,
)
from pos_evolution_tpu.ops.pallas_sha256 import merkle_level_pallas
from pos_evolution_tpu.ops.sha256 import sha256_pair_words

def measure(kernel_of_salt, checksum, tag=""):
    """Per-iteration seconds for ``kernel_of_salt(salt) -> array``."""
    return fused_measure(
        lambda salt, acc: acc + checksum(kernel_of_salt(salt)),
        k_hi=9, tag=tag)


def merkle_case(n_pairs: int, rng) -> dict:
    msgs = rng.integers(0, 2**32, (16, n_pairs), dtype=np.uint64).astype(np.uint32)
    pairs_t = jnp.asarray(msgs)
    nodes = jnp.asarray(msgs.T.reshape(2 * n_pairs, 8))

    csum = lambda out: out.sum(dtype=jnp.int32)    # noqa: E731
    t_pl = measure(
        lambda s: merkle_level_pallas(pairs_t.at[0, 0].set(s.astype(jnp.uint32))),
        csum, tag=f"merkle_pallas@{n_pairs}")
    t_xla = measure(
        lambda s: sha256_pair_words(
            nodes.at[0, 0].set(s.astype(jnp.uint32))[0::2], nodes[1::2]),
        csum, tag=f"merkle_xla@{n_pairs}")

    # parity on identical message bytes through both paths
    got_pl = np.asarray(merkle_level_pallas(pairs_t)).T
    got_xla = np.asarray(jax.jit(sha256_pair_words)(nodes[0::2], nodes[1::2]))
    return {"kernel": "merkle_level", "n_pairs": n_pairs,
            "pallas_ms": round(t_pl * 1e3, 3), "xla_ms": round(t_xla * 1e3, 3),
            "parity_ok": bool((got_pl == got_xla).all())}


def aggregation_case(n_aggs: int, lanes: int, n_val: int, rng) -> dict:
    pubkeys = np.stack([np.frombuffer(FakeBLS.SkToPk(i + 1), np.uint8)
                        for i in range(256)])
    # synthetic pk states for the full registry (timing only needs shape);
    # parity below uses a real signed sub-batch
    pk_states = jnp.asarray(
        rng.integers(0, 2**32, (n_val, 8), dtype=np.uint64).astype(np.uint32))
    committees = jnp.asarray(
        rng.integers(0, n_val, (n_aggs, lanes)).astype(np.int32))
    bits = jnp.asarray(rng.random((n_aggs, lanes)) < 0.99)
    messages = jnp.asarray(
        rng.integers(0, 2**32, (n_aggs, 8), dtype=np.uint64).astype(np.uint32))
    signatures = jnp.asarray(
        rng.integers(0, 2**32, (n_aggs, 24), dtype=np.uint64).astype(np.uint32))

    def run(impl, tag):
        return measure(
            lambda s: impl(pk_states, committees, bits,
                           messages.at[0, 0].set(s.astype(jnp.uint32)),
                           signatures),
            lambda ok: ok.sum(dtype=jnp.int32),
            tag=f"agg_{tag}@{n_aggs}x{lanes}")

    t_xla = run(aggregate_verify_batch, "xla")
    t_pl = run(aggregate_verify_batch_pallas_jit, "pallas")

    # parity: a genuinely signed batch must verify on both paths
    A, C = 4, 16
    st = precompute_pk_states(pubkeys)
    comm = rng.permutation(256)[: A * C].reshape(A, C).astype(np.int32)
    msgs = rng.integers(0, 255, (A, 32)).astype(np.uint8)
    sigs = [FakeBLS.Aggregate(
        [FakeBLS._sig_for(pubkeys[v].tobytes(), msgs[a].tobytes())
         for v in comm[a]]) for a in range(A)]
    args = (st, jnp.asarray(comm), jnp.ones((A, C), bool),
            jnp.asarray(messages_to_words(msgs)),
            jnp.asarray(pack_signature_words(sigs)))
    ok_x = np.asarray(aggregate_verify_batch(*args))
    ok_p = np.asarray(aggregate_verify_batch_pallas_jit(*args))
    return {"kernel": "fakebls_aggregation", "n_aggregates": n_aggs,
            "lanes": lanes, "registry": n_val,
            "pallas_ms": round(t_pl * 1e3, 3), "xla_ms": round(t_xla * 1e3, 3),
            "parity_ok": bool(ok_x.all() and ok_p.all()
                              and (ok_x == ok_p).all())}


def main():
    dev = jax.devices()[0]
    rng = np.random.default_rng(0)
    out = {
        "round": 3,
        "backend": jax.default_backend(),
        "device": str(dev),
        "note": ("fake-crypto aggregation pipeline (SHA/XOR FakeBLS), not "
                 "real BLS pairings; merkle kernel is real SHA-256. Times "
                 "are per-iteration work-differences of a fused K-loop "
                 "(see module docstring)."),
        "cases": [],
    }
    for n in (512, 4096, 32768):
        out["cases"].append(merkle_case(n, rng))
        print(out["cases"][-1], file=sys.stderr)
    for n_aggs, lanes, n_val in ((256, 128, 65_536), (2048, 512, 1_000_000)):
        out["cases"].append(aggregation_case(n_aggs, lanes, n_val, rng))
        print(out["cases"][-1], file=sys.stderr)
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "PALLAS_TPU_r03.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
