"""Full multi-epoch END-TO-END simulation at mainnet scale on a device
mesh (ISSUE 9 / ROADMAP item 1) — not per-kernel probes.

Runs ``sim/dense_driver.DenseSimulation`` — the array-level simulation
loop whose registry, latest-message table and participation flags are
sharded-resident from genesis — at 1M validators for several mainnet
epochs on a (pods, shard) mesh: per-slot sharded fork-choice vote pass
+ replicated descent, swap-or-not committee shuffles, committee
aggregate verification sharded over the batch axis, and the fused epoch
sweep with two-axis psum at every boundary. Asserts that finality
advances and that the device head equals the vectorized host spec-walk
on a subsampled pin, then records everything in MULTICHIP_r{N}.json.

A small twin matrix (same seeded config on 2x4 / 1x8 / single-device)
asserts bit-identity before the big run — the mesh is a layout, never a
semantic.

Usage: python scripts/multichip_demo.py [--validators 1048576]
       [--epochs 4] [--record 9] [--mesh 2x4] [--twin-validators 4096]
       [--shuffle-rounds 10] [--no-verify]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _reexec_with_devices(n_devices: int) -> None:
    from pos_evolution_tpu.utils.hostdev import reexec_with_host_devices
    reexec_with_host_devices(n_devices, "POS_MULTICHIP_CHILD")


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--validators", type=int, default=1_048_576)
    ap.add_argument("--epochs", type=int, default=4)
    ap.add_argument("--record", type=int, default=9)
    ap.add_argument("--mesh", default="2x4")
    ap.add_argument("--twin-validators", type=int, default=4096)
    ap.add_argument("--shuffle-rounds", type=int, default=10)
    ap.add_argument("--no-verify", action="store_true",
                    help="skip the per-slot aggregation-verify sweep")
    ap.add_argument("--seed", type=int, default=9)
    args = ap.parse_args()
    pods, shard = (int(x) for x in args.mesh.lower().split("x"))
    _reexec_with_devices(pods * shard)

    import jax

    from pos_evolution_tpu.config import mainnet_config
    from pos_evolution_tpu.parallel.sharded import make_mesh
    from pos_evolution_tpu.sim.dense_driver import DenseSimulation

    mesh = make_mesh(pods * shard, pods)
    cfg = mainnet_config()
    verify = not args.no_verify

    # --- twin matrix: same seeded config, every layout, bit-identical ---
    twin = {"n_validators": args.twin_validators, "bit_identical": None}
    summaries = []
    for m in (mesh, make_mesh(pods * shard, 1), None):
        sim = DenseSimulation(args.twin_validators, cfg=cfg, mesh=m,
                              seed=args.seed,
                              shuffle_rounds=args.shuffle_rounds,
                              verify_aggregates=verify,
                              check_walk_every=8)
        sim.run_epochs(2)
        s = sim.summary()
        s.pop("mesh")
        summaries.append((s, [mm["head_root"] for mm in sim.metrics]))
    twin["bit_identical"] = (summaries[0] == summaries[1] == summaries[2])
    assert twin["bit_identical"], "twin matrix diverged across layouts"
    print(f"# twin matrix ({args.twin_validators} validators, 2 epochs): "
          f"2x4 == 1x{pods * shard} == single-device", file=sys.stderr)

    # --- the 1M end-to-end run ---
    t0 = time.time()
    sim = DenseSimulation(args.validators, cfg=cfg, mesh=mesh,
                          seed=args.seed,
                          shuffle_rounds=args.shuffle_rounds,
                          verify_aggregates=verify,
                          check_walk_every=16)
    init_s = time.time() - t0
    print(f"# init {args.validators} validators sharded-resident on "
          f"{args.mesh}: {init_s:.1f}s", file=sys.stderr)

    per_epoch = []
    t_run = time.time()
    for e in range(1, args.epochs + 1):
        te = time.time()
        sim.run_epochs(e)
        per_epoch.append(round(time.time() - te, 1))
        m = sim.metrics[-1]
        print(f"# epoch {e}: {per_epoch[-1]}s justified="
              f"{m['justified_epoch']} finalized={m['finalized_epoch']} "
              f"blocks={m['n_blocks']}", file=sys.stderr)
    run_s = time.time() - t_run

    out = sim.summary()
    out.update({
        "backend": "jax/" + jax.default_backend(),
        "devices": len(jax.devices()),
        "init_s": round(init_s, 1),
        "run_s": round(run_s, 1),
        "per_epoch_s": per_epoch,
        "slots_per_epoch": cfg.slots_per_epoch,
        "shuffle_rounds": args.shuffle_rounds,
        "verify_aggregates": verify,
        "twin": twin,
        "last_slots": sim.metrics[-3:],
    })
    assert out["finality_reached"], out
    assert out["finalized_epoch"] >= args.epochs - 2, out
    assert out["resident_head_equals_spec_walk"], out
    path = os.path.join(_REPO, f"MULTICHIP_r{args.record:02d}.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out, indent=1))


if __name__ == "__main__":
    main()
