"""Adversarial robustness at MAINNET scale (ISSUE 13 acceptance runs).

Two dense chaos episodes, recorded as ``CHAOS_DENSE_r{N}.json``:

1. **SplitVoter at >= 256K validators** on the virtual mesh: a fully
   partitioned 2-view network with EXACTLY 1/3 of stake controlled —
   both views must finalize conflicting checkpoints (double finality)
   and the ``DenseAccountableSafetyMonitor`` must price the double-vote
   evidence at exactly 1/3 of genesis stake (the Casper FFG accountable
   safety theorem, audited where the paper states it: the full
   validator set).
2. **1M-validator honest-majority episode** under ``DenseFaultPlan``
   drops + a ``DenseEquivocator`` strategy: finality must advance and
   the full dense monitor stack must record ZERO violations — the
   protocol surviving faults and <1/3 Byzantine behavior at the scale
   the spec driver cannot reach.

Both runs ride the sharded ``DenseSimulation`` (ISSUE 9) with the fault
masks applied inside the shard_map vote pass; the whole composition is
seeded, so every number here replays bit-identically on any mesh shape.

Usage: python scripts/dense_chaos_demo.py [--record 13] [--mesh 2x4]
       [--split-validators 393216] [--honest-validators 1048576]
       [--history bench_history.jsonl]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def split_voter_episode(n: int, mesh, seed: int) -> dict:
    """Double finality with accountable evidence at exactly 1/3."""
    from pos_evolution_tpu.config import mainnet_config
    from pos_evolution_tpu.sim.dense_adversary import DenseSplitVoter
    from pos_evolution_tpu.sim.dense_driver import DenseSimulation
    from pos_evolution_tpu.sim.dense_monitors import default_dense_monitors
    from pos_evolution_tpu.sim.faults import DenseFaultPlan

    assert n % 24 == 0, "n must divide by 24 (mesh x the exact 1/3 split)"
    cfg = mainnet_config().replace(slots_per_epoch=16)
    t0 = time.time()
    sim = DenseSimulation(
        n, cfg=cfg, mesh=mesh, seed=seed, shuffle_rounds=10,
        verify_aggregates=False, check_walk_every=0, n_groups=2,
        fault_plan=DenseFaultPlan(partition="full"),
        adversaries=[DenseSplitVoter(controlled=range(n // 3))],
        monitors=default_dense_monitors(parity_every=16))
    sim.run_epochs(4)
    wall = time.time() - t0
    fins = [v for v in sim.monitor_violations
            if v.get("checkpoint") == "finalized"]
    assert fins, f"no double finality: {sim.monitor_violations}"
    v = fins[0]
    assert v["kind"] == "accountable_fault", v
    assert 3 * v["slashable_stake"] == v["total_stake"], v
    assert v["evidence_size"] == n // 3, v
    assert all(view.finalized[0] > 0 for view in sim.views)
    assert sim.views[0].finalized != sim.views[1].finalized
    return {
        "episode": "split_voter",
        "n_validators": n,
        "controlled": n // 3,
        "slots": sim.slot,
        "slots_per_epoch": cfg.slots_per_epoch,
        "wall_s": round(wall, 1),
        "views_finalized": [list(view.finalized) for view in sim.views],
        "double_finality": True,
        "verdict_kind": v["kind"],
        "evidence_size": v["evidence_size"],
        "slashable_stake": v["slashable_stake"],
        "total_stake": v["total_stake"],
        "evidence_exactly_one_third":
            3 * v["slashable_stake"] == v["total_stake"],
        "detected_at_slot": v["slot"],
        "violations": len(sim.monitor_violations),
    }


def honest_majority_episode(n: int, mesh, seed: int) -> dict:
    """1M validators, drops + crash blackout + equivocators: clean."""
    from pos_evolution_tpu.config import mainnet_config
    from pos_evolution_tpu.sim.dense_adversary import DenseEquivocator
    from pos_evolution_tpu.sim.dense_driver import DenseSimulation
    from pos_evolution_tpu.sim.dense_monitors import default_dense_monitors
    from pos_evolution_tpu.sim.faults import (
        DenseCrashWindow,
        DenseFaultPlan,
    )

    cfg = mainnet_config()
    gst = cfg.slots_per_epoch            # faults through epoch 0
    controlled = max(n // 16, 64)
    plan = DenseFaultPlan(
        seed=seed, drop_p=0.10, delay_p=0.05, gst_slot=gst,
        crashes=(DenseCrashWindow(n // 2, n // 2 + n // 32, 4,
                                  4 + cfg.slots_per_epoch),))
    t0 = time.time()
    sim = DenseSimulation(
        n, cfg=cfg, mesh=mesh, seed=seed, shuffle_rounds=10,
        verify_aggregates=True, check_walk_every=0,
        fault_plan=plan,
        adversaries=[DenseEquivocator(controlled=range(controlled),
                                      p_fork=0.5, seed=seed * 7 + 1)],
        monitors=default_dense_monitors(parity_every=16))
    sim.run_epochs(4)
    wall = time.time() - t0
    s = sim.summary()
    assert sim.monitor_violations == [], sim.monitor_violations[:3]
    assert s["finality_reached"], s
    implicated = int(sim.monitors[0].implicated.sum())
    assert implicated > 0, "equivocation evidence never accumulated"
    return {
        "episode": "honest_majority_faulted",
        "n_validators": n,
        "controlled_equivocators": controlled,
        "fault_plan": plan.describe(),
        "slots": sim.slot,
        "slots_per_epoch": cfg.slots_per_epoch,
        "wall_s": round(wall, 1),
        "finalized_epoch": s["finalized_epoch"],
        "justified_epoch": s["justified_epoch"],
        "aggregates_verified": s["aggregates_verified"],
        "monitor_violations": 0,
        "implicated_equivocators": implicated,
        "clean": True,
    }


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--record", type=int, default=13)
    ap.add_argument("--mesh", default="2x4")
    ap.add_argument("--split-validators", type=int, default=393_216)
    ap.add_argument("--honest-validators", type=int, default=1_048_576)
    ap.add_argument("--seed", type=int, default=13)
    ap.add_argument("--history", default=None)
    args = ap.parse_args()
    from pos_evolution_tpu.utils.hostdev import reexec_with_host_devices
    pods, shard = (int(x) for x in args.mesh.lower().split("x"))
    reexec_with_host_devices(pods * shard, "POS_DENSE_CHAOS_CHILD")

    import jax

    from pos_evolution_tpu.parallel.sharded import make_mesh
    mesh = make_mesh(pods * shard, pods)

    t0 = time.time()
    split = split_voter_episode(args.split_validators, mesh, args.seed)
    print(f"# split_voter: double finality at slot "
          f"{split['detected_at_slot']}, evidence "
          f"{split['evidence_size']}/{split['n_validators']} validators "
          f"= exactly 1/3 stake, {split['wall_s']}s", file=sys.stderr)
    honest = honest_majority_episode(args.honest_validators, mesh,
                                     args.seed)
    print(f"# honest_majority: finalized epoch "
          f"{honest['finalized_epoch']}, 0 violations, "
          f"{honest['aggregates_verified']} aggregates verified, "
          f"{honest['wall_s']}s", file=sys.stderr)

    out = {
        "backend": "jax/" + jax.default_backend(),
        "devices": len(jax.devices()),
        "mesh": args.mesh,
        "seed": args.seed,
        "total_wall_s": round(time.time() - t0, 1),
        "split_voter": split,
        "honest_majority": honest,
    }
    path = os.path.join(_REPO, f"CHAOS_DENSE_r{args.record:02d}.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out, indent=1))

    if args.history:
        from pos_evolution_tpu.profiling import history
        emission = {
            "metric": "dense_chaos_demo",
            "run_s": out["total_wall_s"],
            "counts": {
                "split_voter_slots": split["slots"],
                "honest_slots": honest["slots"],
                "violations_split": split["violations"],
                "violations_honest": honest["monitor_violations"],
                "aggregates_verified": honest["aggregates_verified"],
            },
        }
        history.append_entry(args.history, emission,
                             kind="bench_dense_chaos")
        print(f"# appended bench_dense_chaos emission to {args.history}",
              file=sys.stderr)


if __name__ == "__main__":
    main()
