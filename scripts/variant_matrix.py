"""Variant x attack verdict matrix: the per-variant audit demo
(ROADMAP item 5, DESIGN.md §16).

Runs the PR-5 attack strategies through the production driver under every
protocol variant (variants/) and records which attacks the paper says
each successor defeats actually failing — and the ones it does not still
succeeding against Gasper:

- **balancer** (swayer vote balancing, pos-evolution.md:1321-1348):
  must hold the two views split for all of epoch 0 against pre-boost
  Gasper; Goldfish's eta = 1 expiry (:1549) and RLMD's view-merge
  buffers (:1540) must break the tie.
- **exante** (multi-slot withholding ex-ante reorg, :1503-1526): the
  banked private votes reorg the honest slot-3 block under pre-boost
  Gasper; Goldfish expiry, RLMD view-merge and SSF fast confirmation
  (:1562-1569) must keep it canonical.
- **splitvoter** (the accountable-safety worst case, :233-238): under a
  total partition with exactly 1/3 double-voting stake, finality — FFG
  (epochs) or SSF's per-slot gadget (:1626, :1646) — must die
  *accountably*: >= 1/3 of stake implicated by slashing evidence.
  Goldfish/RLMD have no finality gadget; their kappa-deep confirmations
  diverge unaccountably, the motivation the paper gives for SSF.
- **equivocator** (evidence generator, :233-238, 1154-1156): must be
  neutralized by discounting under EVERY variant (no safety violation,
  evidence captured).

Every violating cell writes a replayable repro bundle (config +
episode-start checkpoint + violations + events) and ``--replay`` must
reproduce the verdict — the chaos-fuzz contract, per variant.

Usage:
    python scripts/variant_matrix.py --out variant_out/ \
        --json VARIANT_MATRIX_r08.json --history bench_history.jsonl
    python scripts/variant_matrix.py --replay variant_out/bundle_splitvoter_ssf/
    python scripts/variant_matrix.py --scenarios balancer --variants gasper,goldfish
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pos_evolution_tpu.config import minimal_config, use_config  # noqa: E402

SCHEMA = 1
SCENARIOS = ("balancer", "exante", "splitvoter", "equivocator")
VARIANT_NAMES = ("gasper", "goldfish", "rlmd", "ssf")

# Paper-pinned expectations: True = the attack must succeed, False = the
# variant must defeat it, None = report the measured verdict only.
EXPECTED = {
    ("balancer", "gasper"): True,      # pre-boost Gasper falls (:1330)
    ("balancer", "goldfish"): False,   # eta=1 expiry kills the banks (:1549)
    ("balancer", "rlmd"): False,       # view-merge kills the sway (:1540)
    ("balancer", "ssf"): False,
    ("exante", "gasper"): True,        # no boost, banked votes win (:1503)
    ("exante", "goldfish"): False,
    ("exante", "rlmd"): False,
    ("exante", "ssf"): False,          # fast confirmation anchors B3 (:1568)
    # splitvoter: safety under partition + 1/3 is impossible for every
    # protocol; the CLAIM is accountability (>= 1/3 implicated) where a
    # finality gadget exists.
    ("splitvoter", "gasper"): True,
    ("splitvoter", "ssf"): True,
    ("splitvoter", "goldfish"): None,
    ("splitvoter", "rlmd"): None,
    ("equivocator", "gasper"): False,
    ("equivocator", "goldfish"): False,
    ("equivocator", "rlmd"): False,
    ("equivocator", "ssf"): False,
}

# balancer / exante target pre-boost Gasper (the mainline W/4 boost is
# the Gasper-side fix, exercised in sim/attacks.py); the other cells run
# the stock minimal preset.
_BOOST0 = ("balancer", "exante")


def _active_config(scenario):
    c = minimal_config()
    return c.replace(proposer_score_boost_percent=0) \
        if scenario in _BOOST0 else c


def _chain_contains(store, head: bytes, root: bytes) -> bool:
    cur = head
    while cur in store.blocks:
        if cur == root:
            return True
        nxt = bytes(store.blocks[cur].parent_root)
        if nxt == cur:
            return False
        cur = nxt
    return False


def _variant_head(sim, group_idx: int) -> bytes:
    from pos_evolution_tpu.specs import forkchoice as fc
    v = sim.variant
    if v.needs_view:
        return v.head(sim, sim.groups[group_idx])
    return fc.get_head(sim.store(group_idx))


# -- scenario builders (pure functions of the active config) -------------------


def _inputs_balancer():
    from pos_evolution_tpu.config import cfg
    from pos_evolution_tpu.sim import Balancer
    from pos_evolution_tpu.sim.attacks import (
        committee_balanced_split_schedule,
    )
    from pos_evolution_tpu.specs.genesis import make_genesis
    from pos_evolution_tpu.specs.helpers import get_beacon_proposer_index
    from pos_evolution_tpu.specs.validator import advance_state_to_slot
    n = 64
    state, _ = make_genesis(n)
    corrupted = set(range(int(n * 0.3)))
    corrupted.add(int(get_beacon_proposer_index(
        advance_state_to_slot(state, 1))))
    return {"n": n,
            "schedule": committee_balanced_split_schedule(n, corrupted),
            "adversaries": [Balancer(corrupted)],
            "n_slots": cfg().slots_per_epoch,
            "early_exit": None}


def _inputs_exante():
    from pos_evolution_tpu.sim import Withholder
    from pos_evolution_tpu.sim.adversary import slot_committee
    from pos_evolution_tpu.specs.genesis import make_genesis
    from pos_evolution_tpu.specs.helpers import get_beacon_proposer_index
    from pos_evolution_tpu.specs.validator import advance_state_to_slot
    n = 64
    state, _ = make_genesis(n)
    honest_proposers = {
        int(get_beacon_proposer_index(advance_state_to_slot(state, s)))
        for s in (1, 3, 4)}
    proposer2 = int(get_beacon_proposer_index(
        advance_state_to_slot(state, 2)))
    c2 = [int(v) for v in slot_committee(advance_state_to_slot(state, 2), 2)
          if int(v) not in honest_proposers][:7]
    c3 = [int(v) for v in slot_committee(advance_state_to_slot(state, 3), 3)
          if int(v) not in honest_proposers][:1]
    controlled = set(c2) | set(c3) | {proposer2}
    assert not (controlled & honest_proposers), \
        "scenario needs honest proposers at slots 1/3/4"
    return {"n": n, "schedule": None,
            "adversaries": [Withholder(
                controlled=controlled, fork_slot=2, release_slot=4,
                release_phase="before_attest", vote_slots=(2, 3),
                private_attesters={2: c2, 3: c3})],
            "n_slots": 5, "early_exit": None}


def _inputs_splitvoter():
    from pos_evolution_tpu.config import cfg
    from pos_evolution_tpu.sim import SplitVoter
    from pos_evolution_tpu.sim.attacks import split_brain_schedule
    n = 48
    controlled = set(range(n // 3))
    return {"n": n, "schedule": split_brain_schedule(n, controlled),
            "adversaries": [SplitVoter(controlled)],
            "n_slots": 6 * cfg().slots_per_epoch,
            "early_exit": "accountable_finalized"}


def _inputs_equivocator():
    from pos_evolution_tpu.config import cfg
    from pos_evolution_tpu.sim import Equivocator
    from pos_evolution_tpu.sim.adversary import slot_committee
    from pos_evolution_tpu.specs.genesis import make_genesis
    from pos_evolution_tpu.specs.helpers import get_beacon_proposer_index
    from pos_evolution_tpu.specs.validator import advance_state_to_slot
    n = 64
    state, _ = make_genesis(n)
    proposer2 = int(get_beacon_proposer_index(
        advance_state_to_slot(state, 2)))
    c2 = [int(v) for v in
          slot_committee(advance_state_to_slot(state, 2), 2)[:3]]
    return {"n": n, "schedule": None,
            "adversaries": [Equivocator(set(c2) | {proposer2})],
            "n_slots": 2 * cfg().slots_per_epoch, "early_exit": None}


_INPUTS = {"balancer": _inputs_balancer, "exante": _inputs_exante,
           "splitvoter": _inputs_splitvoter,
           "equivocator": _inputs_equivocator}


def _finalized_conflicts(sim):
    return [v for v in sim.monitor_violations
            if v.get("checkpoint") == "finalized"]


def _evidence_stake(sim) -> tuple[int, int]:
    """(slashable stake, total stake) from the union of the variant's
    cross-view evidence log and the FFG slasher's implicated set."""
    from pos_evolution_tpu.specs.helpers import get_total_active_balance
    ev = set(sim.variant.slashable())
    for m in sim.monitors:
        ev |= getattr(m, "implicated", set())
    reg = sim.genesis_state.validators
    stake = sum(int(reg.effective_balance[i]) for i in ev if i < len(reg))
    return stake, int(get_total_active_balance(sim.genesis_state))


def _verdict(scenario: str, sim, inputs: dict) -> dict:
    v = sim.variant
    out: dict = {}
    if scenario == "balancer":
        h0, h1 = _variant_head(sim, 0), _variant_head(sim, 1)
        out["views_split_at_end"] = h0 != h1
        out["attack_succeeded"] = out["views_split_at_end"]
        if v.name == "ssf" and _finalized_conflicts(sim):
            # known subsampling artifact, reported honestly: the paper's
            # SSF assumes FULL per-slot participation; the carrier's
            # rotating committees let the balancer's targeted-delivery
            # asynchrony build one-sided committee quorums, so per-slot
            # finality can conflict with sub-1/3 evidence even while the
            # fork-choice tie is broken. The violation + repro bundle
            # document exactly this gap (DESIGN.md §16).
            out["note"] = ("committee-subsampled finality conflict under "
                           "targeted-delivery asynchrony (evidence below "
                           "1/3) — the cost of subsampling full-"
                           "participation SSF; see the repro bundle")
    elif scenario == "exante":
        store = sim.store(0)
        strat = inputs["adversaries"][0]
        head = _variant_head(sim, 0)
        (r3,) = [r for r, b in store.blocks.items() if int(b.slot) == 3]
        out["b3_reorged"] = not _chain_contains(store, head, r3)
        out["b2_canonical"] = (bool(strat.chain.blocks)
                               and _chain_contains(store, head,
                                                   strat.chain.tip))
        out["attack_succeeded"] = out["b3_reorged"]
    elif scenario == "splitvoter":
        fin = _finalized_conflicts(sim)
        stake, total = _evidence_stake(sim)
        out["finalized_conflict"] = bool(fin)
        out["max_evidence_stake_ratio"] = round(stake / total, 4)
        # the theorem's promise: the break is attributable to >= 1/3 of
        # TOTAL stake (committee rotation accumulates the SSF evidence)
        out["accountable"] = (bool(fin)
                              and any(x["kind"] == "accountable_fault"
                                      for x in fin)
                              and 3 * stake >= total)
        conf = {g.id: v.confirmed.get(g.id) for g in sim.groups} \
            if v.needs_view else {}
        out["confirmation_diverged"] = (
            len({c[0] for c in conf.values() if c}) > 1)
        out["attack_succeeded"] = (out["finalized_conflict"]
                                   or out["confirmation_diverged"])
    elif scenario == "equivocator":
        safety = [x for x in sim.monitor_violations
                  if x["kind"] in ("accountable_fault",
                                   "protocol_violation")]
        out["safety_violations"] = len(safety)
        mon = next(m for m in sim.monitors
                   if getattr(m, "name", "") == "accountable_safety")
        out["slasher_implicated"] = len(mon.implicated)
        out["attack_succeeded"] = bool(safety)
    out["violations"] = len(sim.monitor_violations)
    out["finalized_epochs"] = [sim.finalized_epoch(g)
                               for g in range(len(sim.groups))]
    return out


def run_cell(scenario: str, variant_name: str, events_path: str | None = None,
             resume_from: bytes | None = None) -> dict:
    """One (scenario, variant) cell through the production driver.
    Deterministic: the same cell always produces the same verdict, and
    ``resume_from`` replays it from a bundle's checkpoint."""
    from pos_evolution_tpu.sim import (
        AccountableSafetyMonitor,
        Simulation,
        VariantSafetyMonitor,
    )
    from pos_evolution_tpu.telemetry import Telemetry
    from pos_evolution_tpu.variants import VARIANTS
    with use_config(_active_config(scenario)):
        inputs = _INPUTS[scenario]()
        variant = VARIANTS[variant_name]()
        monitors = [AccountableSafetyMonitor(), VariantSafetyMonitor()]
        telemetry = (Telemetry.to_file(events_path)
                     if events_path is not None else None)
        t0 = time.perf_counter()
        try:
            if resume_from is not None:
                sim = Simulation.resume(
                    resume_from, schedule=inputs["schedule"],
                    telemetry=telemetry, adversaries=inputs["adversaries"],
                    monitors=monitors, variant=variant)
                checkpoint = resume_from
            else:
                sim = Simulation(inputs["n"], schedule=inputs["schedule"],
                                 adversaries=inputs["adversaries"],
                                 monitors=monitors, variant=variant,
                                 telemetry=telemetry)
                checkpoint = sim.checkpoint()
            while sim.slot <= inputs["n_slots"]:
                sim.run_slot()
                if inputs["early_exit"] == "accountable_finalized" \
                        and _finalized_conflicts(sim):
                    stake, total = _evidence_stake(sim)
                    if 3 * stake >= total:
                        break
            verdict = _verdict(scenario, sim, inputs)
        finally:
            if telemetry is not None:
                telemetry.close()
        wall = time.perf_counter() - t0
        summary = sim.trace_summary().get("get_head", {})
        verdict.update({
            "scenario": scenario, "variant": variant_name,
            "expected_attack_success": EXPECTED.get((scenario,
                                                     variant_name)),
            "wall_s": round(wall, 3),
            "get_head_p50_ms": summary.get("p50_ms"),
            "get_head_p95_ms": summary.get("p95_ms"),
            "slots_run": sim.slot,
        })
        exp = verdict["expected_attack_success"]
        verdict["matches_expectation"] = (
            None if exp is None else verdict["attack_succeeded"] == exp)
        return {"verdict": verdict, "checkpoint": checkpoint,
                "violations": sim.monitor_violations,
                "variant_config": variant.describe()}


# -- bundles -------------------------------------------------------------------


def write_bundle(out_dir: str, scenario: str, variant_name: str,
                 result: dict, events_src: str | None) -> str:
    import shutil
    bundle = os.path.join(out_dir, f"bundle_{scenario}_{variant_name}")
    os.makedirs(bundle, exist_ok=True)
    with open(os.path.join(bundle, "config.json"), "w") as fh:
        json.dump({"schema": SCHEMA, "scenario": scenario,
                   "variant_name": variant_name,
                   "variant": result["variant_config"],
                   "verdict": result["verdict"]},
                  fh, indent=1, sort_keys=True)
        fh.write("\n")
    with open(os.path.join(bundle, "checkpoint.bin"), "wb") as fh:
        fh.write(result["checkpoint"])
    with open(os.path.join(bundle, "violations.json"), "w") as fh:
        json.dump(result["violations"], fh, indent=1, sort_keys=True)
        fh.write("\n")
    if events_src and os.path.exists(events_src):
        shutil.move(events_src, os.path.join(bundle, "events.jsonl"))
    return bundle


def replay_bundle(bundle: str) -> dict:
    """Re-run a cell from its bundle checkpoint via ``Simulation.resume``
    (under the variant that produced it) and compare verdict +
    violations against the recorded ones."""
    with open(os.path.join(bundle, "config.json")) as fh:
        cfg = json.load(fh)
    with open(os.path.join(bundle, "checkpoint.bin"), "rb") as fh:
        checkpoint = fh.read()
    with open(os.path.join(bundle, "violations.json")) as fh:
        recorded = json.load(fh)
    result = run_cell(cfg["scenario"], cfg["variant_name"],
                      resume_from=checkpoint)
    key = lambda v: (v.get("slot"), v["monitor"], v["kind"])  # noqa: E731
    match = (sorted(map(key, result["violations"]))
             == sorted(map(key, recorded))
             and result["verdict"]["attack_succeeded"]
             == cfg["verdict"]["attack_succeeded"])
    return {"match": match, "replayed": result["verdict"],
            "recorded": cfg["verdict"]}


# -- dense tier (ISSUE 20: the matrix at mainnet scale) ------------------------
#
# The same paper claims, judged by the DENSE driver: vectorized
# adversaries (committee-targeted ex-ante reorg, exactly-1/3 SplitVoter,
# equivocation evidence), the variant seam's sharded tallies, DAS
# sidecar + light-client workload riders on every cell, and the dense
# monitor stack judging each variant by its own finality rule.

DENSE_SCENARIOS = ("exante", "splitvoter", "equivocator")
DENSE_CELLS = {
    # gasper runs the ex-ante cell twice: pre-boost (the paper's attack
    # succeeds) and with the W*40% proposer boost (the Gasper-side fix)
    "exante": ("gasper", "gasper_boost", "goldfish", "rlmd", "ssf"),
    "splitvoter": ("gasper", "goldfish", "rlmd", "ssf"),
    "equivocator": ("gasper", "goldfish", "rlmd", "ssf"),
}
EXPECTED_DENSE = {
    ("exante", "gasper"): True,         # banked committees win (:1503)
    ("exante", "gasper_boost"): False,  # boost out-weighs the bank
    ("exante", "goldfish"): False,      # full-participation collapse
    ("exante", "rlmd"): False,
    ("exante", "ssf"): False,
    # splitvoter: safety under partition + 1/3 is impossible everywhere;
    # the claim is HOW it dies — accountably (FFG, and SSF per-slot at
    # exactly 1/3) vs unaccountable confirmation divergence
    ("splitvoter", "gasper"): True,
    ("splitvoter", "ssf"): True,
    ("splitvoter", "goldfish"): True,
    ("splitvoter", "rlmd"): True,
    ("equivocator", "gasper"): False,
    ("equivocator", "goldfish"): False,
    ("equivocator", "rlmd"): False,
    ("equivocator", "ssf"): False,
}


def dense_cell_config(scenario: str, cell: str, n: int) -> dict:
    """One dense cell's full replayable composition (the chaos-bundle
    shape): variant + boost, adversary, network faults, and the DAS +
    light-client workload riders. Pure function of (scenario, cell, n).

    The ex-ante margin is ``span*f - (span-1)*(1-f)`` committees; at
    f=0.40/span=2 that is 0.2 committees — dozens of sigma past
    committee-shuffle variance at mainnet scale (n=393216: ~2457 votes
    vs sigma ~54), still >5 sigma at the smoke default."""
    variant_kind = "gasper" if cell == "gasper_boost" else cell
    boost = 40 if cell == "gasper_boost" else 0
    # both cell-commitment schemes are exercised across the matrix: the
    # device-resident Fr/NTT kzg engine on the ssf/rlmd cells, merkle
    # elsewhere
    scheme = "kzg" if variant_kind in ("ssf", "rlmd") else "merkle"
    base = {
        "schema": SCHEMA, "dense": True, "scenario": scenario,
        "cell": cell, "n_validators": int(n), "slots_per_epoch": 8,
        "seed": 20,
        "variant": {"kind": variant_kind, "boost_percent": boost},
        "workload": {"riders": [
            {"kind": "das", "scheme": scheme, "n_blobs": 1,
             "n_clients": 32, "samples_per_client": 2, "seed": 20,
             "verify_every": 4},
            {"kind": "lightclient", "n_clients": 32, "seed": 20},
        ]},
    }
    if scenario == "exante":
        base.update(n_epochs=2, n_groups=1, faults=None,
                    adversaries=[{"kind": "DenseExAnteReorg",
                                  "controlled": [[0, int(n * 0.40)]],
                                  "fork_slot": 2, "span": 2}])
    elif scenario == "splitvoter":
        base.update(n_epochs=4, n_groups=2,
                    faults={"seed": 20, "partition": "full"},
                    adversaries=[{"kind": "DenseSplitVoter",
                                  "controlled": [[0, n // 3]]}])
    else:   # equivocator
        base.update(n_epochs=2, n_groups=1, faults=None,
                    adversaries=[{"kind": "DenseEquivocator",
                                  "controlled": [[0, n // 4]],
                                  "p_fork": 0.5, "seed": 20}])
    return base


def _dense_mesh(spec: str | None):
    if not spec:
        return None
    import jax

    from pos_evolution_tpu.parallel.sharded import make_mesh
    pods, shard = (int(x) for x in spec.lower().split("x"))
    if len(jax.devices()) < pods * shard:
        print(f"variant_matrix: mesh {spec} needs {pods * shard} devices, "
              f"only {len(jax.devices())} present — running single-device "
              f"(bit-identical results, sharded path NOT exercised)",
              file=sys.stderr)
        return None
    return make_mesh(pods * shard, pods)


def _dense_verdict(cfgd: dict, sim) -> dict:
    scenario = cfgd["scenario"]
    v = sim.monitor_violations
    out: dict = {}
    if scenario == "exante":
        adv = next(a for a in sim.adversaries
                   if a.name == "dense_exante_reorg")
        out["reorged"] = bool(adv.priv) and bool(
            sim._descends(sim._head(0), adv.priv[0]))
        out["withheld_root"] = (sim.roots[adv.priv[0]].hex()[:16]
                                if adv.priv else None)
        out["attack_succeeded"] = out["reorged"]
    elif scenario == "splitvoter":
        fin = [x for x in v if x.get("kind") == "accountable_fault"
               and x.get("checkpoint") == "finalized"]
        out["finalized_conflict"] = bool(fin)
        out["ffg_exact_third"] = any(
            3 * x["slashable_stake"] == x["total_stake"] for x in fin)
        ssf = [x for x in v
               if x.get("kind") == "accountable_double_finality"]
        out["ssf_double_finality"] = bool(ssf)
        out["ssf_exact_third"] = any(
            3 * x["slashable_stake"] == x["total_stake"] for x in ssf)
        out["confirmation_diverged"] = any(
            x.get("kind") == "confirmation_divergence" for x in v)
        out["accountable"] = (out["finalized_conflict"]
                              and out["ffg_exact_third"])
        out["attack_succeeded"] = (out["finalized_conflict"]
                                   or out["ssf_double_finality"]
                                   or out["confirmation_diverged"])
    else:   # equivocator
        safety = [x for x in v
                  if x["kind"] in ("accountable_fault",
                                   "protocol_violation",
                                   "accountable_double_finality")]
        out["safety_violations"] = len(safety)
        implicated = 0
        for m in sim.monitors:
            arr = getattr(m, "implicated", None)
            if arr is not None:
                implicated = max(implicated, int(arr.sum()))
        out["slasher_implicated"] = implicated
        out["attack_succeeded"] = bool(safety)
    out["violations"] = len(v)
    out["violation_kinds"] = sorted({x["kind"] for x in v})
    out["finalized_epochs"] = [view.finalized[0] for view in sim.views]
    return out


def run_dense_cell(cfgd: dict, events_path: str | None = None,
                   resume_from: bytes | None = None, mesh=None,
                   phase_profile: int | None = 8) -> dict:
    """One dense cell through ``DenseSimulation`` under the full dense
    monitor stack, with the FlightRecorder + phase profiler armed when
    the cell records events (attack runs get the same phase/compile
    attribution as benign ones — ``variant_tally``/``workload`` phases
    included). ``resume_from`` replays from a bundle's checkpoint."""
    from pos_evolution_tpu.config import mainnet_config
    from pos_evolution_tpu.sim.dense_adversary import (
        dense_adversary_from_config,
    )
    from pos_evolution_tpu.sim.dense_driver import DenseSimulation
    from pos_evolution_tpu.sim.dense_monitors import default_dense_monitors
    from pos_evolution_tpu.sim.dense_variants import dense_rider_from_config
    from pos_evolution_tpu.sim.faults import DenseFaultPlan
    from pos_evolution_tpu.telemetry import FlightRecorder, Telemetry
    cfg_obj = mainnet_config().replace(
        slots_per_epoch=cfgd["slots_per_epoch"],
        max_committees_per_slot=4)
    telemetry = (Telemetry.to_file(events_path)
                 if events_path is not None else None)
    flight = (FlightRecorder(telemetry=telemetry, sample_every=8).install()
              if telemetry is not None else None)
    profile = phase_profile if telemetry is not None else None
    n_slots = cfgd["n_epochs"] * cfgd["slots_per_epoch"]
    t0 = time.perf_counter()
    try:
        # the DAS riders size their blob grids off the ACTIVE config:
        # pinning it makes fresh runs, resumes and replays rebuild
        # byte-identical sidecars
        with use_config(cfg_obj):
            if resume_from is not None:
                sim = DenseSimulation.resume(
                    resume_from, mesh=mesh, telemetry=telemetry,
                    expect_variant=cfgd["variant"]["kind"],
                    phase_profile=profile, flight_recorder=flight)
                checkpoint = resume_from
            else:
                sim = DenseSimulation(
                    cfgd["n_validators"], cfg=cfg_obj, mesh=mesh,
                    seed=cfgd["seed"], verify_aggregates=False,
                    check_walk_every=0,
                    n_groups=cfgd.get("n_groups", 1),
                    fault_plan=DenseFaultPlan.from_config(
                        cfgd.get("faults")),
                    adversaries=[dense_adversary_from_config(a)
                                 for a in cfgd["adversaries"]],
                    monitors=default_dense_monitors(),
                    variant=cfgd["variant"],
                    riders=[dense_rider_from_config(r)
                            for r in cfgd["workload"]["riders"]],
                    telemetry=telemetry, phase_profile=profile,
                    flight_recorder=flight)
                checkpoint = sim.checkpoint()
            while sim.slot < n_slots:
                sim.run_slot()
    finally:
        if flight is not None:
            flight.detach()
        if telemetry is not None:
            telemetry.close()
    wall = time.perf_counter() - t0
    scenario, cell = cfgd["scenario"], cfgd["cell"]
    verdict = _dense_verdict(cfgd, sim)
    verdict.update({
        "scenario": scenario, "cell": cell,
        "variant": cfgd["variant"]["kind"],
        "boost_percent": cfgd["variant"]["boost_percent"],
        "n_validators": cfgd["n_validators"],
        "wall_s": round(wall, 3), "slots_run": sim.slot,
        "expected_attack_success": EXPECTED_DENSE.get((scenario, cell)),
        "workload": {r.kind: r.stats() for r in sim.riders},
    })
    if sim.variant.name != "gasper":
        verdict["variant_decisions"] = len(sim.variant.decisions)
    phases = sim.phases.summary() if sim.phases.enabled else None
    if phases:
        verdict["phase_ms"] = {
            name: row["total_ms"]
            for name, row in phases.get("phases", {}).items()}
    exp = verdict["expected_attack_success"]
    ok = None if exp is None else verdict["attack_succeeded"] == exp
    # the pins go beyond the binary verdict: SSF must double-finalize
    # at EXACTLY 1/3 implicated stake, gasper's FFG break must be
    # accountable
    if ok and scenario == "splitvoter":
        if cell == "ssf":
            ok = verdict["ssf_double_finality"] and \
                verdict["ssf_exact_third"]
        elif cell == "gasper":
            ok = verdict["accountable"]
        else:
            ok = verdict["confirmation_diverged"]
    verdict["matches_expectation"] = ok
    return {"verdict": verdict, "checkpoint": checkpoint,
            "violations": sim.monitor_violations, "config": cfgd}


def write_dense_bundle(out_dir: str, cfgd: dict, result: dict,
                       events_src: str | None) -> str:
    import shutil
    bundle = os.path.join(
        out_dir, f"bundle_dense_{cfgd['scenario']}_{cfgd['cell']}")
    os.makedirs(bundle, exist_ok=True)
    with open(os.path.join(bundle, "config.json"), "w") as fh:
        json.dump({"schema": SCHEMA, "dense": True, "config": cfgd,
                   "verdict": result["verdict"]},
                  fh, indent=1, sort_keys=True)
        fh.write("\n")
    with open(os.path.join(bundle, "checkpoint.bin"), "wb") as fh:
        fh.write(result["checkpoint"])
    with open(os.path.join(bundle, "violations.json"), "w") as fh:
        json.dump(result["violations"], fh, indent=1, sort_keys=True)
        fh.write("\n")
    if events_src and os.path.exists(events_src):
        shutil.move(events_src, os.path.join(bundle, "events.jsonl"))
    return bundle


def replay_dense_bundle(bundle: str) -> dict:
    """Re-run a dense cell from its bundle checkpoint (under the variant
    + workload that produced it — the checkpoint's variant fingerprint
    refuses anything else) and demand the byte-stable monitor verdict:
    identical (slot, monitor, kind) triples and the same
    attack_succeeded."""
    with open(os.path.join(bundle, "config.json")) as fh:
        doc = json.load(fh)
    with open(os.path.join(bundle, "checkpoint.bin"), "rb") as fh:
        checkpoint = fh.read()
    with open(os.path.join(bundle, "violations.json")) as fh:
        recorded = json.load(fh)
    result = run_dense_cell(doc["config"], resume_from=checkpoint)
    key = lambda v: (v.get("slot"), v["monitor"], v["kind"])  # noqa: E731
    match = (sorted(map(key, result["violations"]))
             == sorted(map(key, recorded))
             and result["verdict"]["attack_succeeded"]
             == doc["verdict"]["attack_succeeded"])
    return {"match": match, "replayed": result["verdict"],
            "recorded": doc["verdict"]}


def dense_parity_leg(variant_name: str, n: int, slots: int = 12,
                     mesh_spec: str = "4x2") -> dict:
    """Spec<->dense parity through the variant seam (ISSUE 20 satellite):
    twin honest runs — single-device (the host-oracle/spec-walk twin)
    vs sharded mesh — must produce bit-identical per-slot heads and
    variant decision streams, with the in-run spec-walk audits
    (``check_walk_every``) green on both."""
    from pos_evolution_tpu.config import mainnet_config
    from pos_evolution_tpu.sim.dense_driver import DenseSimulation
    cfg_obj = mainnet_config().replace(slots_per_epoch=8,
                                       max_committees_per_slot=4)
    mesh = _dense_mesh(mesh_spec)

    def run(m):
        with use_config(cfg_obj):
            sim = DenseSimulation(n, cfg=cfg_obj, mesh=m, seed=20,
                                  verify_aggregates=False,
                                  check_walk_every=4,
                                  variant={"kind": variant_name})
            heads = []
            for _ in range(slots):
                sim.run_slot()
                heads.append(sim.roots[sim._head(0)].hex())
            return heads, list(sim.variant.decisions), sim.summary()

    t0 = time.perf_counter()
    h1, d1, s1 = run(None)
    h2, d2, s2 = run(mesh)
    return {
        "variant": variant_name, "n": int(n), "slots": int(slots),
        "mesh": mesh_spec if mesh is not None else None,
        "sharded_path_exercised": mesh is not None,
        "heads_identical": h1 == h2,
        "decisions_identical": d1 == d2,
        "decisions": len(d1),
        "spec_walk_audits_clean": bool(
            s1["resident_head_equals_spec_walk"]
            and s2["resident_head_equals_spec_walk"]),
        "wall_s": round(time.perf_counter() - t0, 3),
    }


def run_dense_matrix(scenarios, variants, n: int, out_dir: str,
                     events: bool = True, mesh=None) -> dict:
    os.makedirs(out_dir, exist_ok=True)
    rows, bundles = [], []
    for scenario in scenarios:
        for cell in DENSE_CELLS[scenario]:
            base = "gasper" if cell == "gasper_boost" else cell
            if base not in variants:
                continue
            cfgd = dense_cell_config(scenario, cell, n)
            events_path = (os.path.join(
                out_dir, f"dense_{scenario}_{cell}.events.jsonl")
                if events else None)
            result = run_dense_cell(cfgd, events_path=events_path,
                                    mesh=mesh)
            verdict = result["verdict"]
            rows.append(verdict)
            status = {True: "ATTACK SUCCEEDS", False: "defended"}[
                verdict["attack_succeeded"]]
            pin = verdict["matches_expectation"]
            pin_str = {True: "as the paper says", False: "UNEXPECTED",
                       None: "unpinned"}[pin]
            print(f"dense {scenario:>11} x {cell:<13} {status:<15} "
                  f"({pin_str}; {len(result['violations'])} violations, "
                  f"n={n}, {verdict['wall_s']}s)")
            if result["violations"]:
                bundles.append(write_dense_bundle(out_dir, cfgd, result,
                                                  events_path))
            elif events_path and os.path.exists(events_path):
                os.remove(events_path)
    mismatches = [r for r in rows if r["matches_expectation"] is False]
    return {"schema": SCHEMA, "dense": True, "n_validators": int(n),
            "rows": rows, "bundles": bundles,
            "mismatches": len(mismatches)}


def bench_dense_emission(rows: list[dict]) -> dict:
    """bench_dense_variants history emission: per-cell wall time off the
    fixed-shape ex-ante cells (counts deterministic)."""
    emission: dict = {"metric": "bench_dense_variants", "counts": {}}
    for row in rows:
        if row["scenario"] != "exante":
            continue
        cell = row["cell"]
        emission[cell] = {"wall_s": row["wall_s"]}
        emission["counts"][f"{cell}.slots_run"] = row["slots_run"]
        emission["counts"][f"{cell}.attack_succeeded"] = int(
            row["attack_succeeded"])
    return emission


# -- matrix driver -------------------------------------------------------------


def run_matrix(scenarios, variants, out_dir: str,
               events: bool = True) -> dict:
    os.makedirs(out_dir, exist_ok=True)
    rows = []
    bundles = []
    for scenario in scenarios:
        for variant_name in variants:
            events_path = (os.path.join(
                out_dir, f"{scenario}_{variant_name}.events.jsonl")
                if events else None)
            result = run_cell(scenario, variant_name,
                              events_path=events_path)
            verdict = result["verdict"]
            rows.append(verdict)
            status = {True: "ATTACK SUCCEEDS", False: "defended"}[
                verdict["attack_succeeded"]]
            pin = verdict["matches_expectation"]
            pin_str = {True: "as the paper says", False: "UNEXPECTED",
                       None: "unpinned"}[pin]
            print(f"{scenario:>12} x {variant_name:<8} {status:<15} "
                  f"({pin_str}; {len(result['violations'])} violations, "
                  f"{verdict['wall_s']}s)")
            if result["violations"]:
                bundle = write_bundle(out_dir, scenario, variant_name,
                                      result, events_path)
                bundles.append(bundle)
            elif events_path and os.path.exists(events_path):
                os.remove(events_path)
    mismatches = [r for r in rows if r["matches_expectation"] is False]
    return {"schema": SCHEMA, "rows": rows, "bundles": bundles,
            "mismatches": len(mismatches)}


def bench_emission(rows: list[dict]) -> dict:
    """bench_variants history emission: per-variant wall + head-query
    timings off the fixed-shape balancer cells (counts deterministic)."""
    emission: dict = {"metric": "bench_variants", "counts": {}}
    for row in rows:
        if row["scenario"] != "balancer":
            continue
        v = row["variant"]
        emission[v] = {
            "wall_s": row["wall_s"],
            "get_head_p50_ms": row.get("get_head_p50_ms"),
            "get_head_p95_ms": row.get("get_head_p95_ms"),
        }
        emission["counts"][f"{v}.slots_run"] = row["slots_run"]
        emission["counts"][f"{v}.attack_succeeded"] = int(
            row["attack_succeeded"])
    return emission


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="variant x attack verdict matrix under the full "
                    "monitor stack")
    ap.add_argument("--out", default="variant_out")
    ap.add_argument("--json", default=None,
                    help="write the matrix verdict table here")
    ap.add_argument("--history", default=None,
                    help="append a bench_variants emission to this "
                         "bench-history JSONL")
    ap.add_argument("--scenarios", default=",".join(SCENARIOS))
    ap.add_argument("--variants", default=",".join(VARIANT_NAMES))
    ap.add_argument("--no-events", action="store_true")
    ap.add_argument("--replay", metavar="BUNDLE",
                    help="replay a repro bundle (spec or dense tier — "
                         "dispatched on the bundle's config.json) and "
                         "verify the verdict")
    ap.add_argument("--dense", action="store_true",
                    help="run the matrix through the DENSE driver: "
                         "vectorized adversaries, sharded variant "
                         "tallies, DAS + light-client riders on every "
                         "cell (ISSUE 20)")
    ap.add_argument("--dense-validators", type=int, default=2112,
                    help="dense-cell validator count (mainnet pin: "
                         "393216)")
    ap.add_argument("--mesh", default=None, metavar="PxS",
                    help="dense cells on a PxS device mesh (e.g. 4x2; "
                         "re-execs with fake host devices if needed)")
    ap.add_argument("--parity", action="store_true",
                    help="also run the per-variant spec<->dense parity "
                         "legs: twin single-device vs mesh runs must be "
                         "bit-identical")
    ap.add_argument("--parity-n", type=int, default=65536)
    ap.add_argument("--parity-slots", type=int, default=12)
    args = ap.parse_args(argv)

    if args.replay:
        with open(os.path.join(args.replay, "config.json")) as fh:
            dense = bool(json.load(fh).get("dense"))
        out = (replay_dense_bundle if dense else replay_bundle)(args.replay)
        print(json.dumps(out, indent=1, default=str))
        return 0 if out["match"] else 1

    if args.dense and (args.mesh or args.parity):
        need = 8
        if args.mesh:
            p, s = (int(x) for x in args.mesh.lower().split("x"))
            need = max(need, p * s)
        from pos_evolution_tpu.utils.hostdev import reexec_with_host_devices
        reexec_with_host_devices(need, "POS_VM_CHILD")

    scenarios = [s.strip() for s in args.scenarios.split(",") if s.strip()]
    variants = [v.strip() for v in args.variants.split(",") if v.strip()]

    if args.dense:
        dense_scenarios = ([s for s in scenarios if s in DENSE_SCENARIOS]
                           or list(DENSE_SCENARIOS))
        summary = run_dense_matrix(dense_scenarios, variants,
                                   args.dense_validators, args.out,
                                   events=not args.no_events,
                                   mesh=_dense_mesh(args.mesh))
        if args.parity:
            summary["parity"] = [
                dense_parity_leg(v, args.parity_n, args.parity_slots)
                for v in variants]
            for leg in summary["parity"]:
                ok = leg["heads_identical"] and leg["decisions_identical"]
                print(f"parity {leg['variant']:<9} n={leg['n']} "
                      f"{'bit-identical' if ok else 'DIVERGED'} "
                      f"({leg['decisions']} decisions, "
                      f"mesh={leg['mesh']}, {leg['wall_s']}s)")
                if not (ok and leg["spec_walk_audits_clean"]):
                    summary["mismatches"] += 1
    else:
        summary = run_matrix(scenarios, variants, args.out,
                             events=not args.no_events)
    if args.json:
        with open(args.json, "w") as fh:
            json.dump(summary, fh, indent=1, sort_keys=True)
            fh.write("\n")
        print(f"matrix   -> {args.json}")
    if args.history:
        from pos_evolution_tpu.profiling import history
        if args.dense:
            history.append_entry(args.history,
                                 bench_dense_emission(summary["rows"]),
                                 kind="bench_dense_variants")
            print(f"history  -> {args.history} "
                  f"(kind=bench_dense_variants)")
        else:
            history.append_entry(args.history,
                                 bench_emission(summary["rows"]),
                                 kind="bench_variants")
            print(f"history  -> {args.history} (kind=bench_variants)")
    if summary["mismatches"]:
        print(f"{summary['mismatches']} cell(s) CONTRADICT the paper's "
              f"claims", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
