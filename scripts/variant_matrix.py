"""Variant x attack verdict matrix: the per-variant audit demo
(ROADMAP item 5, DESIGN.md §16).

Runs the PR-5 attack strategies through the production driver under every
protocol variant (variants/) and records which attacks the paper says
each successor defeats actually failing — and the ones it does not still
succeeding against Gasper:

- **balancer** (swayer vote balancing, pos-evolution.md:1321-1348):
  must hold the two views split for all of epoch 0 against pre-boost
  Gasper; Goldfish's eta = 1 expiry (:1549) and RLMD's view-merge
  buffers (:1540) must break the tie.
- **exante** (multi-slot withholding ex-ante reorg, :1503-1526): the
  banked private votes reorg the honest slot-3 block under pre-boost
  Gasper; Goldfish expiry, RLMD view-merge and SSF fast confirmation
  (:1562-1569) must keep it canonical.
- **splitvoter** (the accountable-safety worst case, :233-238): under a
  total partition with exactly 1/3 double-voting stake, finality — FFG
  (epochs) or SSF's per-slot gadget (:1626, :1646) — must die
  *accountably*: >= 1/3 of stake implicated by slashing evidence.
  Goldfish/RLMD have no finality gadget; their kappa-deep confirmations
  diverge unaccountably, the motivation the paper gives for SSF.
- **equivocator** (evidence generator, :233-238, 1154-1156): must be
  neutralized by discounting under EVERY variant (no safety violation,
  evidence captured).

Every violating cell writes a replayable repro bundle (config +
episode-start checkpoint + violations + events) and ``--replay`` must
reproduce the verdict — the chaos-fuzz contract, per variant.

Usage:
    python scripts/variant_matrix.py --out variant_out/ \
        --json VARIANT_MATRIX_r08.json --history bench_history.jsonl
    python scripts/variant_matrix.py --replay variant_out/bundle_splitvoter_ssf/
    python scripts/variant_matrix.py --scenarios balancer --variants gasper,goldfish
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pos_evolution_tpu.config import minimal_config, use_config  # noqa: E402

SCHEMA = 1
SCENARIOS = ("balancer", "exante", "splitvoter", "equivocator")
VARIANT_NAMES = ("gasper", "goldfish", "rlmd", "ssf")

# Paper-pinned expectations: True = the attack must succeed, False = the
# variant must defeat it, None = report the measured verdict only.
EXPECTED = {
    ("balancer", "gasper"): True,      # pre-boost Gasper falls (:1330)
    ("balancer", "goldfish"): False,   # eta=1 expiry kills the banks (:1549)
    ("balancer", "rlmd"): False,       # view-merge kills the sway (:1540)
    ("balancer", "ssf"): False,
    ("exante", "gasper"): True,        # no boost, banked votes win (:1503)
    ("exante", "goldfish"): False,
    ("exante", "rlmd"): False,
    ("exante", "ssf"): False,          # fast confirmation anchors B3 (:1568)
    # splitvoter: safety under partition + 1/3 is impossible for every
    # protocol; the CLAIM is accountability (>= 1/3 implicated) where a
    # finality gadget exists.
    ("splitvoter", "gasper"): True,
    ("splitvoter", "ssf"): True,
    ("splitvoter", "goldfish"): None,
    ("splitvoter", "rlmd"): None,
    ("equivocator", "gasper"): False,
    ("equivocator", "goldfish"): False,
    ("equivocator", "rlmd"): False,
    ("equivocator", "ssf"): False,
}

# balancer / exante target pre-boost Gasper (the mainline W/4 boost is
# the Gasper-side fix, exercised in sim/attacks.py); the other cells run
# the stock minimal preset.
_BOOST0 = ("balancer", "exante")


def _active_config(scenario):
    c = minimal_config()
    return c.replace(proposer_score_boost_percent=0) \
        if scenario in _BOOST0 else c


def _chain_contains(store, head: bytes, root: bytes) -> bool:
    cur = head
    while cur in store.blocks:
        if cur == root:
            return True
        nxt = bytes(store.blocks[cur].parent_root)
        if nxt == cur:
            return False
        cur = nxt
    return False


def _variant_head(sim, group_idx: int) -> bytes:
    from pos_evolution_tpu.specs import forkchoice as fc
    v = sim.variant
    if v.needs_view:
        return v.head(sim, sim.groups[group_idx])
    return fc.get_head(sim.store(group_idx))


# -- scenario builders (pure functions of the active config) -------------------


def _inputs_balancer():
    from pos_evolution_tpu.config import cfg
    from pos_evolution_tpu.sim import Balancer
    from pos_evolution_tpu.sim.attacks import (
        committee_balanced_split_schedule,
    )
    from pos_evolution_tpu.specs.genesis import make_genesis
    from pos_evolution_tpu.specs.helpers import get_beacon_proposer_index
    from pos_evolution_tpu.specs.validator import advance_state_to_slot
    n = 64
    state, _ = make_genesis(n)
    corrupted = set(range(int(n * 0.3)))
    corrupted.add(int(get_beacon_proposer_index(
        advance_state_to_slot(state, 1))))
    return {"n": n,
            "schedule": committee_balanced_split_schedule(n, corrupted),
            "adversaries": [Balancer(corrupted)],
            "n_slots": cfg().slots_per_epoch,
            "early_exit": None}


def _inputs_exante():
    from pos_evolution_tpu.sim import Withholder
    from pos_evolution_tpu.sim.adversary import slot_committee
    from pos_evolution_tpu.specs.genesis import make_genesis
    from pos_evolution_tpu.specs.helpers import get_beacon_proposer_index
    from pos_evolution_tpu.specs.validator import advance_state_to_slot
    n = 64
    state, _ = make_genesis(n)
    honest_proposers = {
        int(get_beacon_proposer_index(advance_state_to_slot(state, s)))
        for s in (1, 3, 4)}
    proposer2 = int(get_beacon_proposer_index(
        advance_state_to_slot(state, 2)))
    c2 = [int(v) for v in slot_committee(advance_state_to_slot(state, 2), 2)
          if int(v) not in honest_proposers][:7]
    c3 = [int(v) for v in slot_committee(advance_state_to_slot(state, 3), 3)
          if int(v) not in honest_proposers][:1]
    controlled = set(c2) | set(c3) | {proposer2}
    assert not (controlled & honest_proposers), \
        "scenario needs honest proposers at slots 1/3/4"
    return {"n": n, "schedule": None,
            "adversaries": [Withholder(
                controlled=controlled, fork_slot=2, release_slot=4,
                release_phase="before_attest", vote_slots=(2, 3),
                private_attesters={2: c2, 3: c3})],
            "n_slots": 5, "early_exit": None}


def _inputs_splitvoter():
    from pos_evolution_tpu.config import cfg
    from pos_evolution_tpu.sim import SplitVoter
    from pos_evolution_tpu.sim.attacks import split_brain_schedule
    n = 48
    controlled = set(range(n // 3))
    return {"n": n, "schedule": split_brain_schedule(n, controlled),
            "adversaries": [SplitVoter(controlled)],
            "n_slots": 6 * cfg().slots_per_epoch,
            "early_exit": "accountable_finalized"}


def _inputs_equivocator():
    from pos_evolution_tpu.config import cfg
    from pos_evolution_tpu.sim import Equivocator
    from pos_evolution_tpu.sim.adversary import slot_committee
    from pos_evolution_tpu.specs.genesis import make_genesis
    from pos_evolution_tpu.specs.helpers import get_beacon_proposer_index
    from pos_evolution_tpu.specs.validator import advance_state_to_slot
    n = 64
    state, _ = make_genesis(n)
    proposer2 = int(get_beacon_proposer_index(
        advance_state_to_slot(state, 2)))
    c2 = [int(v) for v in
          slot_committee(advance_state_to_slot(state, 2), 2)[:3]]
    return {"n": n, "schedule": None,
            "adversaries": [Equivocator(set(c2) | {proposer2})],
            "n_slots": 2 * cfg().slots_per_epoch, "early_exit": None}


_INPUTS = {"balancer": _inputs_balancer, "exante": _inputs_exante,
           "splitvoter": _inputs_splitvoter,
           "equivocator": _inputs_equivocator}


def _finalized_conflicts(sim):
    return [v for v in sim.monitor_violations
            if v.get("checkpoint") == "finalized"]


def _evidence_stake(sim) -> tuple[int, int]:
    """(slashable stake, total stake) from the union of the variant's
    cross-view evidence log and the FFG slasher's implicated set."""
    from pos_evolution_tpu.specs.helpers import get_total_active_balance
    ev = set(sim.variant.slashable())
    for m in sim.monitors:
        ev |= getattr(m, "implicated", set())
    reg = sim.genesis_state.validators
    stake = sum(int(reg.effective_balance[i]) for i in ev if i < len(reg))
    return stake, int(get_total_active_balance(sim.genesis_state))


def _verdict(scenario: str, sim, inputs: dict) -> dict:
    v = sim.variant
    out: dict = {}
    if scenario == "balancer":
        h0, h1 = _variant_head(sim, 0), _variant_head(sim, 1)
        out["views_split_at_end"] = h0 != h1
        out["attack_succeeded"] = out["views_split_at_end"]
        if v.name == "ssf" and _finalized_conflicts(sim):
            # known subsampling artifact, reported honestly: the paper's
            # SSF assumes FULL per-slot participation; the carrier's
            # rotating committees let the balancer's targeted-delivery
            # asynchrony build one-sided committee quorums, so per-slot
            # finality can conflict with sub-1/3 evidence even while the
            # fork-choice tie is broken. The violation + repro bundle
            # document exactly this gap (DESIGN.md §16).
            out["note"] = ("committee-subsampled finality conflict under "
                           "targeted-delivery asynchrony (evidence below "
                           "1/3) — the cost of subsampling full-"
                           "participation SSF; see the repro bundle")
    elif scenario == "exante":
        store = sim.store(0)
        strat = inputs["adversaries"][0]
        head = _variant_head(sim, 0)
        (r3,) = [r for r, b in store.blocks.items() if int(b.slot) == 3]
        out["b3_reorged"] = not _chain_contains(store, head, r3)
        out["b2_canonical"] = (bool(strat.chain.blocks)
                               and _chain_contains(store, head,
                                                   strat.chain.tip))
        out["attack_succeeded"] = out["b3_reorged"]
    elif scenario == "splitvoter":
        fin = _finalized_conflicts(sim)
        stake, total = _evidence_stake(sim)
        out["finalized_conflict"] = bool(fin)
        out["max_evidence_stake_ratio"] = round(stake / total, 4)
        # the theorem's promise: the break is attributable to >= 1/3 of
        # TOTAL stake (committee rotation accumulates the SSF evidence)
        out["accountable"] = (bool(fin)
                              and any(x["kind"] == "accountable_fault"
                                      for x in fin)
                              and 3 * stake >= total)
        conf = {g.id: v.confirmed.get(g.id) for g in sim.groups} \
            if v.needs_view else {}
        out["confirmation_diverged"] = (
            len({c[0] for c in conf.values() if c}) > 1)
        out["attack_succeeded"] = (out["finalized_conflict"]
                                   or out["confirmation_diverged"])
    elif scenario == "equivocator":
        safety = [x for x in sim.monitor_violations
                  if x["kind"] in ("accountable_fault",
                                   "protocol_violation")]
        out["safety_violations"] = len(safety)
        mon = next(m for m in sim.monitors
                   if getattr(m, "name", "") == "accountable_safety")
        out["slasher_implicated"] = len(mon.implicated)
        out["attack_succeeded"] = bool(safety)
    out["violations"] = len(sim.monitor_violations)
    out["finalized_epochs"] = [sim.finalized_epoch(g)
                               for g in range(len(sim.groups))]
    return out


def run_cell(scenario: str, variant_name: str, events_path: str | None = None,
             resume_from: bytes | None = None) -> dict:
    """One (scenario, variant) cell through the production driver.
    Deterministic: the same cell always produces the same verdict, and
    ``resume_from`` replays it from a bundle's checkpoint."""
    from pos_evolution_tpu.sim import (
        AccountableSafetyMonitor,
        Simulation,
        VariantSafetyMonitor,
    )
    from pos_evolution_tpu.telemetry import Telemetry
    from pos_evolution_tpu.variants import VARIANTS
    with use_config(_active_config(scenario)):
        inputs = _INPUTS[scenario]()
        variant = VARIANTS[variant_name]()
        monitors = [AccountableSafetyMonitor(), VariantSafetyMonitor()]
        telemetry = (Telemetry.to_file(events_path)
                     if events_path is not None else None)
        t0 = time.perf_counter()
        try:
            if resume_from is not None:
                sim = Simulation.resume(
                    resume_from, schedule=inputs["schedule"],
                    telemetry=telemetry, adversaries=inputs["adversaries"],
                    monitors=monitors, variant=variant)
                checkpoint = resume_from
            else:
                sim = Simulation(inputs["n"], schedule=inputs["schedule"],
                                 adversaries=inputs["adversaries"],
                                 monitors=monitors, variant=variant,
                                 telemetry=telemetry)
                checkpoint = sim.checkpoint()
            while sim.slot <= inputs["n_slots"]:
                sim.run_slot()
                if inputs["early_exit"] == "accountable_finalized" \
                        and _finalized_conflicts(sim):
                    stake, total = _evidence_stake(sim)
                    if 3 * stake >= total:
                        break
            verdict = _verdict(scenario, sim, inputs)
        finally:
            if telemetry is not None:
                telemetry.close()
        wall = time.perf_counter() - t0
        summary = sim.trace_summary().get("get_head", {})
        verdict.update({
            "scenario": scenario, "variant": variant_name,
            "expected_attack_success": EXPECTED.get((scenario,
                                                     variant_name)),
            "wall_s": round(wall, 3),
            "get_head_p50_ms": summary.get("p50_ms"),
            "get_head_p95_ms": summary.get("p95_ms"),
            "slots_run": sim.slot,
        })
        exp = verdict["expected_attack_success"]
        verdict["matches_expectation"] = (
            None if exp is None else verdict["attack_succeeded"] == exp)
        return {"verdict": verdict, "checkpoint": checkpoint,
                "violations": sim.monitor_violations,
                "variant_config": variant.describe()}


# -- bundles -------------------------------------------------------------------


def write_bundle(out_dir: str, scenario: str, variant_name: str,
                 result: dict, events_src: str | None) -> str:
    import shutil
    bundle = os.path.join(out_dir, f"bundle_{scenario}_{variant_name}")
    os.makedirs(bundle, exist_ok=True)
    with open(os.path.join(bundle, "config.json"), "w") as fh:
        json.dump({"schema": SCHEMA, "scenario": scenario,
                   "variant_name": variant_name,
                   "variant": result["variant_config"],
                   "verdict": result["verdict"]},
                  fh, indent=1, sort_keys=True)
        fh.write("\n")
    with open(os.path.join(bundle, "checkpoint.bin"), "wb") as fh:
        fh.write(result["checkpoint"])
    with open(os.path.join(bundle, "violations.json"), "w") as fh:
        json.dump(result["violations"], fh, indent=1, sort_keys=True)
        fh.write("\n")
    if events_src and os.path.exists(events_src):
        shutil.move(events_src, os.path.join(bundle, "events.jsonl"))
    return bundle


def replay_bundle(bundle: str) -> dict:
    """Re-run a cell from its bundle checkpoint via ``Simulation.resume``
    (under the variant that produced it) and compare verdict +
    violations against the recorded ones."""
    with open(os.path.join(bundle, "config.json")) as fh:
        cfg = json.load(fh)
    with open(os.path.join(bundle, "checkpoint.bin"), "rb") as fh:
        checkpoint = fh.read()
    with open(os.path.join(bundle, "violations.json")) as fh:
        recorded = json.load(fh)
    result = run_cell(cfg["scenario"], cfg["variant_name"],
                      resume_from=checkpoint)
    key = lambda v: (v.get("slot"), v["monitor"], v["kind"])  # noqa: E731
    match = (sorted(map(key, result["violations"]))
             == sorted(map(key, recorded))
             and result["verdict"]["attack_succeeded"]
             == cfg["verdict"]["attack_succeeded"])
    return {"match": match, "replayed": result["verdict"],
            "recorded": cfg["verdict"]}


# -- matrix driver -------------------------------------------------------------


def run_matrix(scenarios, variants, out_dir: str,
               events: bool = True) -> dict:
    os.makedirs(out_dir, exist_ok=True)
    rows = []
    bundles = []
    for scenario in scenarios:
        for variant_name in variants:
            events_path = (os.path.join(
                out_dir, f"{scenario}_{variant_name}.events.jsonl")
                if events else None)
            result = run_cell(scenario, variant_name,
                              events_path=events_path)
            verdict = result["verdict"]
            rows.append(verdict)
            status = {True: "ATTACK SUCCEEDS", False: "defended"}[
                verdict["attack_succeeded"]]
            pin = verdict["matches_expectation"]
            pin_str = {True: "as the paper says", False: "UNEXPECTED",
                       None: "unpinned"}[pin]
            print(f"{scenario:>12} x {variant_name:<8} {status:<15} "
                  f"({pin_str}; {len(result['violations'])} violations, "
                  f"{verdict['wall_s']}s)")
            if result["violations"]:
                bundle = write_bundle(out_dir, scenario, variant_name,
                                      result, events_path)
                bundles.append(bundle)
            elif events_path and os.path.exists(events_path):
                os.remove(events_path)
    mismatches = [r for r in rows if r["matches_expectation"] is False]
    return {"schema": SCHEMA, "rows": rows, "bundles": bundles,
            "mismatches": len(mismatches)}


def bench_emission(rows: list[dict]) -> dict:
    """bench_variants history emission: per-variant wall + head-query
    timings off the fixed-shape balancer cells (counts deterministic)."""
    emission: dict = {"metric": "bench_variants", "counts": {}}
    for row in rows:
        if row["scenario"] != "balancer":
            continue
        v = row["variant"]
        emission[v] = {
            "wall_s": row["wall_s"],
            "get_head_p50_ms": row.get("get_head_p50_ms"),
            "get_head_p95_ms": row.get("get_head_p95_ms"),
        }
        emission["counts"][f"{v}.slots_run"] = row["slots_run"]
        emission["counts"][f"{v}.attack_succeeded"] = int(
            row["attack_succeeded"])
    return emission


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="variant x attack verdict matrix under the full "
                    "monitor stack")
    ap.add_argument("--out", default="variant_out")
    ap.add_argument("--json", default=None,
                    help="write the matrix verdict table here")
    ap.add_argument("--history", default=None,
                    help="append a bench_variants emission to this "
                         "bench-history JSONL")
    ap.add_argument("--scenarios", default=",".join(SCENARIOS))
    ap.add_argument("--variants", default=",".join(VARIANT_NAMES))
    ap.add_argument("--no-events", action="store_true")
    ap.add_argument("--replay", metavar="BUNDLE",
                    help="replay a repro bundle and verify the verdict")
    args = ap.parse_args(argv)

    if args.replay:
        out = replay_bundle(args.replay)
        print(json.dumps(out, indent=1, default=str))
        return 0 if out["match"] else 1

    scenarios = [s.strip() for s in args.scenarios.split(",") if s.strip()]
    variants = [v.strip() for v in args.variants.split(",") if v.strip()]
    summary = run_matrix(scenarios, variants, args.out,
                         events=not args.no_events)
    if args.json:
        with open(args.json, "w") as fh:
            json.dump(summary, fh, indent=1, sort_keys=True)
            fh.write("\n")
        print(f"matrix   -> {args.json}")
    if args.history:
        from pos_evolution_tpu.profiling import history
        history.append_entry(args.history, bench_emission(summary["rows"]),
                             kind="bench_variants")
        print(f"history  -> {args.history} (kind=bench_variants)")
    if summary["mismatches"]:
        print(f"{summary['mismatches']} cell(s) CONTRADICT the paper's "
              f"claims", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
