"""Run report: telemetry JSONL -> markdown / JSON, offline.

Reconstructs, from the event log alone (no live ``Simulation``):

- the **finality timeline** — per-slot justified/finalized epochs from
  ``slot`` events, plus the slots where finality actually advanced;
- **fault attribution vs. effects** — per-(action, kind) counts from the
  ``fault`` events ``sim/faults.py`` emits (these match the FaultPlan's
  seeded decisions exactly: same code path records both), next to the
  observable effects (childless gossip edges ≈ drops, handler rejects,
  invariant violations, crash/rejoin, degradations, watchdog incidents);
- **handler percentiles** — p50/p95/count over every event carrying
  ``handler`` + ``duration_ms`` (deliveries and ``get_head`` queries);
- **light-client lag** — worst/final head- and finality-lag per node;
- **merkleization** — totals + hit rate of the incremental-SSZ and
  fused-transition counters (``merkleization`` events: per-slot deltas of
  ``ssz.htr_cache_hit`` / ``ssz.htr_cache_miss`` / dirty-chunk counts and
  the fused sweep's upload/patch/reuse residency decisions);
- **DAS serving** — sampling-client population size, samples served /
  coalesced unique fetches, per-request p50/p95 serving latency,
  proof-path cache hit rate and verification failures, aggregated from
  the per-block ``das_serve`` events (``das/server.py``);
- the **dense phase budget** — ISSUE 18's per-slot breakdown of
  ``DenseSimulation.run_slot`` from the sampled (device-fenced)
  ``dense_phase`` events: per-phase totals + share of the sampled slot
  wall, and the accounted percentage the CI smoke pins at >= 95%;
- **serving** — the live RPC tier's traffic story from ``serve_attach``
  / ``serve_summary`` events (``pos_evolution_tpu/serve/``): per-tier
  p50/p99/p999, goodput vs. shed rate with shed reasons, hedges and
  retries, verified-proof counts, brownout/breaker transitions, chaos
  injections, and the SLO verdict;
- the **property audit** — the online monitor verdicts
  (``sim/monitors.py`` ``monitor`` events: accountable-safety /
  liveness / fork-choice-parity violations with slot, evidence size and
  slashable stake) next to the debug-gated ``invariant_violation``
  events, the attached monitor/adversary roster from ``monitor_attach``,
  and the repro-bundle path when the log lives inside a
  ``scripts/chaos_fuzz.py`` bundle (auto-discovered via a sibling
  ``violations.json``, or passed with ``--bundle``);
- **top device ops** — folded in from a ``top_ops.json`` (the xplane
  summary of ``pos_evolution_tpu/profiling/xplane.py``). When
  ``--top-ops`` is not given, the report auto-discovers
  ``top_ops.json`` / ``bench_trace/top_ops.json`` next to the event log
  (reports used to silently omit device ops whenever the flag was
  forgotten);
- **static cost tables** — a ``profiling/cost.py`` emission passed via
  ``--cost`` lands under ``cost_analysis`` (per-kernel FLOPs / bytes /
  peak memory next to the observed timeline).

Multi-process runs (``serve/harness.py`` with ``events_bus`` fan-out, or
any ISSUE 18 per-process ``EventBus``) write sibling
``events.<pid>.jsonl`` files instead of sharing one log. The report
auto-discovers those next to the given path and merges them with
``telemetry.merge_event_files`` (re-sequenced by wall clock, source pid
preserved as ``src_pid``) — pass the LOGICAL path
(``.../events.jsonl``); it does not need to exist when per-pid siblings
do.

Usage:
    python scripts/run_report.py events.jsonl [--json out.json]
                                 [--markdown out.md] [--top-ops top_ops.json]
                                 [--cost cost.json]

Markdown goes to stdout unless ``--markdown`` is given.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pos_evolution_tpu.telemetry import read_jsonl  # noqa: E402
from pos_evolution_tpu.telemetry.events import (  # noqa: E402
    discover_per_process,
    merge_event_files,
)


def load_events(events_path: str) -> tuple[list[dict], list[str]]:
    """Events for a logical log path: the file itself when it stands
    alone, the merged union when per-process ``events.<pid>.jsonl``
    siblings exist (both when the logical file is also present — a
    harness that wrote its own lines next to its workers' files).
    Returns (events, merged_source_paths)."""
    per_proc = discover_per_process(events_path)
    if not per_proc:
        return read_jsonl(events_path), []
    paths = ([events_path] if os.path.exists(events_path) else []) \
        + per_proc
    return merge_event_files(paths), paths


def _percentile(xs: list[float], q: float) -> float:
    """Linear-interpolation percentile (numpy 'linear' method) — kept
    dependency-free so the report runs anywhere python does."""
    if not xs:
        return float("nan")
    xs = sorted(xs)
    if len(xs) == 1:
        return xs[0]
    pos = (len(xs) - 1) * q / 100.0
    lo = int(pos)
    frac = pos - lo
    hi = min(lo + 1, len(xs) - 1)
    return xs[lo] * (1 - frac) + xs[hi] * frac


def discover_top_ops(events_path: str, events=()) -> str | None:
    """``top_ops.json`` next to the event log, under a sibling
    ``bench_trace/`` (the spots ``bench.py`` writes to), or wherever a
    ``profile_artifacts`` event in the log itself says
    ``Simulation(profile=<dir>)`` dropped its artifacts."""
    here = os.path.dirname(os.path.abspath(events_path))
    # the log's own recorded artifact dir is authoritative — proximity
    # guesses come AFTER it, or a stale bench_trace/ next to the log
    # would shadow this run's actual profile
    cands = [os.path.join(ev["dir"], "top_ops.json")
             for ev in events
             if ev.get("type") == "profile_artifacts" and ev.get("dir")]
    cands += [os.path.join(here, "top_ops.json"),
              os.path.join(here, "bench_trace", "top_ops.json")]
    for cand in cands:
        if os.path.exists(cand):
            return cand
    return None


def discover_device_ledger(events_path: str) -> str | None:
    """The flight recorder's artifact beside the event log
    (``*device_ledger.json``, written by ``FlightRecorder.
    write_artifact``) — same proximity contract as the fleet snapshot
    auto-discovery. Newest mtime wins when several runs share a dir."""
    here = os.path.dirname(os.path.abspath(events_path))
    cands = glob.glob(os.path.join(here, "*device_ledger.json"))
    if not cands:
        return None
    return max(cands, key=os.path.getmtime)


def discover_bundle(events_path: str) -> str | None:
    """The chaos-fuzz repro bundle the event log belongs to, if any:
    ``write_bundle`` moves the violating run's ``events.jsonl`` next to
    its ``violations.json``, so a sibling marks the directory."""
    here = os.path.dirname(os.path.abspath(events_path))
    if os.path.exists(os.path.join(here, "violations.json")):
        return here
    return None


def _device_section(by_type: dict, device_ledger: dict | None) -> dict:
    """The "Device" section (ISSUE 19): flight-recorder artifact first
    (memory watermarks + compile ledger + skew table), live
    ``device_memory`` / ``shard_skew`` events as the fallback when the
    run died before writing one. Empty dict = no section."""
    out: dict = {}
    fr = (device_ledger or {}).get("flight_recorder") or {}
    if fr.get("memory"):
        out["memory"] = fr["memory"]
    if fr.get("compile_ledger"):
        out["compile_ledger"] = fr["compile_ledger"]
    if (fr.get("shard_skew") or {}).get("table"):
        out["shard_skew"] = fr["shard_skew"]
    curve = (device_ledger or {}).get("memory_curve") or []
    dm_events = by_type.get("device_memory", [])
    if "memory" not in out and dm_events:
        # reconstruct watermarks from the event stream alone
        peak: dict[str, int] = {}
        source = None
        for ev in dm_events:
            for row in ev.get("rows") or []:
                dev = row.get("device", "?")
                peak[dev] = max(peak.get(dev, 0),
                                int(row.get("bytes_in_use", 0)))
                source = row.get("platform")
        out["memory"] = {"samples": len(dm_events), "source": source,
                         "peak_bytes": peak}
    if curve or dm_events:
        points = curve or dm_events
        out["memory_events"] = len(points)
        first, last = points[0], points[-1]
        out["memory_span"] = {
            "first": {"site": first.get("site"),
                      "slot": first.get("slot")},
            "last": {"site": last.get("site"), "slot": last.get("slot")},
        }
    skew_events = by_type.get("shard_skew", [])
    if "shard_skew" not in out and skew_events:
        worst = max(skew_events,
                    key=lambda e: e.get("spread_ms") or 0)
        out["shard_skew"] = {
            "probes": len(skew_events),
            "worst": {"phase": worst.get("phase"),
                      "slot": worst.get("slot"),
                      "spread_ms": worst.get("spread_ms")},
        }
    return out


def build_report(events: list[dict], top_ops: dict | None = None,
                 cost: dict | None = None,
                 bundle: str | None = None,
                 device_ledger: dict | None = None) -> dict:
    """Pure JSONL -> report-dict transform (the testable core)."""
    by_type: dict[str, list[dict]] = {}
    for ev in events:
        by_type.setdefault(ev["type"], []).append(ev)

    run_start = (by_type.get("run_start") or [{}])[0]

    # -- finality timeline ----------------------------------------------------
    slots = by_type.get("slot", [])
    timeline = [{"slot": e["slot"], "head_slot": e.get("head_slot"),
                 "justified_epoch": e.get("justified_epoch"),
                 "finalized_epoch": e.get("finalized_epoch"),
                 "participation": e.get("participation")}
                for e in slots]
    advances = []
    prev_fin = None
    for row in timeline:
        fin = row["finalized_epoch"]
        if prev_fin is not None and fin is not None and fin > prev_fin:
            advances.append({"slot": row["slot"], "finalized_epoch": fin})
        if fin is not None:
            prev_fin = fin

    # -- fault attribution vs. effects ----------------------------------------
    fault_counts: dict[str, dict[str, int]] = {}
    for e in by_type.get("fault", []):
        row = fault_counts.setdefault(e["action"], {})
        row[e["kind"]] = row.get(e["kind"], 0) + 1
    # dense-driver fault masks (ISSUE 13): per-(slot, view) aggregates,
    # not per-message events — fold into totals
    dense_faults = by_type.get("dense_fault", [])
    dense_fault_totals = None
    if dense_faults:
        dense_fault_totals = {
            "events": len(dense_faults),
            "dropped_votes": sum(e.get("dropped", 0) for e in dense_faults),
            "delayed_votes": sum(e.get("delayed", 0) for e in dense_faults),
        }
    gossip_spans = {e["span"] for e in by_type.get("gossip", [])
                    if e.get("span")}
    delivered_parents = {e.get("parent") for e in by_type.get("deliver", [])}
    rejects: dict[str, int] = {}
    for e in by_type.get("deliver", []):
        if e.get("status") == "reject":
            rejects[e["handler"]] = rejects.get(e["handler"], 0) + 1
    effects = {
        "gossip_edges": len(gossip_spans),
        "undelivered_gossip_edges": len(gossip_spans - delivered_parents),
        "handler_rejects": rejects,
        "invariant_violations": len(by_type.get("invariant_violation", [])),
        "crashes": [{"group": e["group"], "slot": e["slot"],
                     "lost_in_flight": e.get("lost_in_flight")}
                    for e in by_type.get("crash", [])],
        "rejoins": [{"group": e["group"], "slot": e["slot"],
                     "sync_checkpoint_epoch": e.get("sync_checkpoint_epoch")}
                    for e in by_type.get("rejoin", [])],
        "degradations": [{"component": e.get("component"),
                          "reason": e.get("reason")}
                         for e in by_type.get("degradation", [])],
        "watchdog_incidents": [{"tag": e.get("tag"), "step": e.get("step"),
                                "error": e.get("error")}
                               for e in by_type.get("watchdog_incident", [])],
    }

    # -- handler percentiles --------------------------------------------------
    durations: dict[str, list[float]] = {}
    for ev in events:
        h = ev.get("handler")
        d = ev.get("duration_ms")
        if h is not None and d is not None:
            durations.setdefault(h, []).append(float(d))
    handlers = {
        name: {"count": len(xs),
               "p50_ms": round(_percentile(xs, 50), 4),
               "p95_ms": round(_percentile(xs, 95), 4),
               "total_ms": round(sum(xs), 3)}
        for name, xs in sorted(durations.items())
    }

    # -- light clients --------------------------------------------------------
    lc: dict[int, dict] = {}
    for e in by_type.get("light_client_lag", []):
        row = lc.setdefault(e.get("node", 0), {
            "records": 0, "max_head_lag": 0, "max_finality_lag": 0,
            "final_head_lag": None, "final_finality_lag": None})
        row["records"] += 1
        row["max_head_lag"] = max(row["max_head_lag"], e.get("head_lag", 0))
        row["max_finality_lag"] = max(row["max_finality_lag"],
                                      e.get("finality_lag", 0))
        row["final_head_lag"] = e.get("head_lag")
        row["final_finality_lag"] = e.get("finality_lag")

    # -- merkleization (ssz/incremental.py + ops/transition.py counters) ------
    merk_events = by_type.get("merkleization", [])
    merk_totals: dict[str, int] = {}
    for e in merk_events:
        for k, v in e.items():
            if k.startswith(("ssz_", "fused_", "merkle_")) \
                    and isinstance(v, (int, float)):
                merk_totals[k] = merk_totals.get(k, 0) + v
    merkleization = None
    if merk_totals:
        hits = merk_totals.get("ssz_htr_cache_hit", 0)
        misses = merk_totals.get("ssz_htr_cache_miss", 0)
        dev_pairs = merk_totals.get("merkle_device_pairs", 0)
        host_pairs = merk_totals.get("merkle_host_pairs", 0)
        dev_ms = merk_totals.get("merkle_device_ms", 0)
        merkleization = {
            "slots_with_activity": len(merk_events),
            "totals": {k: (round(v, 3) if isinstance(v, float) else v)
                       for k, v in sorted(merk_totals.items())},
            "htr_hit_rate": (round(hits / (hits + misses), 4)
                             if hits + misses else None),
            # device-vs-host split of the level sweeps (ops/merkle_device)
            "device_pairs": dev_pairs,
            "host_pairs": host_pairs,
            "device_share": (round(dev_pairs / (dev_pairs + host_pairs), 4)
                             if dev_pairs + host_pairs else None),
            "device_pairs_per_s": (round(dev_pairs / (dev_ms / 1e3))
                                   if dev_ms else None),
        }

    # -- DAS serving (das/server.py summaries via das_serve events) -----------
    das_events = by_type.get("das_serve", [])
    das_serving = None
    if das_events:
        p50s = [float(e["p50_ms"]) for e in das_events if "p50_ms" in e]
        p95s = [float(e["p95_ms"]) for e in das_events if "p95_ms" in e]
        attach = (by_type.get("das_attach") or [{}])[0]
        das_serving = {
            "served_blocks": len(das_events),
            "clients": das_events[-1].get("clients"),
            "samples_per_client": attach.get("samples_per_client"),
            "samples_total": sum(e.get("samples", 0) for e in das_events),
            "unique_requests_total": sum(e.get("unique_requests", 0)
                                         for e in das_events),
            "verify_failures": sum(e.get("failed", 0) for e in das_events),
            "clients_all_ok_final": das_events[-1].get("clients_all_ok"),
            "cache_hit_rate": das_events[-1].get("cache_hit_rate"),
            # medians ACROSS served blocks of the per-block per-request
            # percentiles (the true pooled p95 would need the raw samples;
            # a percentile-of-percentiles is ~the max, which worst_p95_ms
            # already reports)
            "p50_ms": round(_percentile(p50s, 50), 4),
            "p95_ms": round(_percentile(p95s, 50), 4),
            "worst_p95_ms": round(max(p95s), 4) if p95s else None,
            "scheme": (das_events[-1].get("scheme")
                       or (attach.get("engine") or {}).get("scheme")),
            "aggregated": das_events[-1].get("aggregated"),
        }
        # served proof bytes per sample: the aggregation win (kzg serves
        # one multiproof per block; merkle serves a branch per cell)
        proof_bytes = sum(e.get("proof_bytes", 0) for e in das_events)
        if proof_bytes and das_serving["samples_total"]:
            das_serving["proof_bytes_total"] = proof_bytes
            das_serving["proof_bytes_per_sample"] = round(
                proof_bytes / das_serving["samples_total"], 4)

    # -- serving (serve/ RPC tier: serve_attach + serve_summary events) -------
    serve_events = by_type.get("serve_summary", [])
    serving = None
    if serve_events:
        last = serve_events[-1]
        attach = (by_type.get("serve_attach") or [{}])[0]
        server = last.get("server") or {}
        load = last.get("load") or {}
        chaos = last.get("chaos") or {}
        serving = {
            "workers": server.get("workers"),
            "pattern": load.get("pattern"),
            "arrivals": load.get("arrivals"),
            "rate": load.get("rate"),
            "wall_s": load.get("wall_s"),
            "tiers": load.get("tiers"),
            "requests_total": server.get("requests_total"),
            "by_status": server.get("by_status"),
            "shed_rate": server.get("shed_rate"),
            "shed_by_reason": server.get("shed_by_reason"),
            "hedges": load.get("hedges"),
            "retries": load.get("retries"),
            "verified_proofs": load.get("verified_proofs"),
            "verify_failures": load.get("verify_failures"),
            "brownout_transitions": server.get("brownout_transitions"),
            "breaker_state": server.get("breaker_state"),
            "breaker_transitions": server.get("breaker_transitions"),
            "singleflight": server.get("singleflight"),
            "scheme_builds": server.get("scheme_builds"),
            "proof_cache": server.get("proof_cache"),
            "slow_loris_closed": server.get("slow_loris_closed"),
            "chaos_stalls": server.get("chaos_stalls"),
            "chaos_injections": chaos.get("injections"),
            "slo_ms": last.get("slo_ms"),
            "slo_ok": last.get("slo_ok"),
            "attach": {k: attach.get(k) for k in
                       ("workers", "pattern", "arrivals", "rate", "chaos")
                       if attach.get(k) is not None} or None,
        }

    # -- multi-process serving (serve_mp_attach + serve_mp_summary) -----------
    mp_events = by_type.get("serve_mp_summary", [])
    serving_mp = None
    if mp_events:
        last = mp_events[-1]
        attach = (by_type.get("serve_mp_attach") or [{}])[0]

        def _mp_phase(result: dict | None) -> dict | None:
            if not result:
                return None
            inter = (result.get("load") or {}).get("tiers", {}).get(
                "interactive", {})
            verdict = result.get("verdict") or {}
            return {
                "arrivals": result.get("arrivals"),
                "rate": result.get("rate"),
                "wall_s": (result.get("load") or {}).get("wall_s"),
                "p50_ms": inter.get("p50_ms"),
                "p99_ms": inter.get("p99_ms"),
                "goodput_pct": inter.get("goodput_pct"),
                "resends": verdict.get("resends"),
                "lost": verdict.get("lost"),
                "verified_proofs": verdict.get("verified_proofs"),
                "verify_failures": verdict.get("verify_failures"),
                "traced": (result.get("load") or {}).get("traced"),
                "fleet_consistent": verdict.get("fleet_consistent"),
                "ok": verdict.get("ok"),
            }

        steady_r = last.get("steady") or {}
        chaos_r = last.get("chaos")
        # the chaos phase's pool carries the interruption story; a
        # no-chaos run falls back to the steady pool
        pool = ((chaos_r or steady_r).get("pool")) or {}
        serving_mp = {
            "fronts": attach.get("fronts") or steady_r.get("fronts"),
            "workers": attach.get("workers") or steady_r.get("workers"),
            "steady": _mp_phase(steady_r),
            "chaos": _mp_phase(chaos_r),
            "worker_rows": pool.get("workers") or [],
            "interruptions": pool.get("interruptions") or [],
            "interruptions_by_reason":
                pool.get("interruptions_by_reason") or {},
            "restarts": pool.get("restarts"),
            "parked": pool.get("parked"),
            "chaos_kills_delivered": pool.get("chaos_kills_delivered"),
            "board_generation": (chaos_r or steady_r).get(
                "board_generation"),
            "respawned_on_current_generation":
                ((chaos_r or steady_r).get("verdict") or {}).get(
                    "respawned_on_current_generation"),
        }
        # ISSUE 18 fleet metrics: the scraped FleetAggregator summary
        # rides the phase result, the consistency verdict rides the
        # phase verdict — the chaos phase (when run) is the story
        fl_verdict = (chaos_r or steady_r).get("verdict") or {}
        fl_raw = (chaos_r or steady_r).get("fleet") or {}
        if fl_raw or fl_verdict.get("fleet_requests_by_worker") \
                is not None:
            serving_mp["fleet"] = {
                "workers_reporting":
                    fl_verdict.get("fleet_workers_reporting"),
                "requests_by_worker":
                    fl_verdict.get("fleet_requests_by_worker")
                    or fl_raw.get("requests_by_worker"),
                "requests_total":
                    fl_verdict.get("fleet_requests_total"),
                "window": fl_verdict.get("fleet_window"),
                "consistent": fl_verdict.get("fleet_consistent"),
                "snapshots_merged": fl_raw.get("snapshots_merged"),
                "snapshots_skipped": fl_raw.get("snapshots_skipped"),
            }

    # -- dense phase budget (profiling/phases.py dense_phase events) ----------
    dense_ph = by_type.get("dense_phase", [])
    dense_budget = None
    if dense_ph:
        ph_totals: dict[str, float] = {}
        sampled_wall = 0.0
        for e in dense_ph:
            sampled_wall += float(e.get("wall_ms") or 0.0)
            for name, ms in (e.get("phases") or {}).items():
                ph_totals[name] = ph_totals.get(name, 0.0) + float(ms)
        accounted = sum(ph_totals.values())
        dense_budget = {
            "sampled_slots": len(dense_ph),
            "sampled_wall_ms": round(sampled_wall, 3),
            "phases": {
                name: {"total_ms": round(ms, 3),
                       "share_pct": (round(100.0 * ms / sampled_wall, 2)
                                     if sampled_wall > 0 else None)}
                for name, ms in sorted(ph_totals.items(),
                                       key=lambda kv: -kv[1])},
            "accounted_pct": (round(100.0 * accounted / sampled_wall, 2)
                              if sampled_wall > 0 else None),
        }

    # -- resilience (resilience/ checkpoint + supervisor events) --------------
    ckpts = by_type.get("checkpoint_saved", [])
    interruptions = by_type.get("supervisor_interruption", [])
    resumes = by_type.get("run_resumed", [])
    quarantines = by_type.get("checkpoint_quarantined", [])
    integrity = by_type.get("integrity_violation", [])
    goodput_ev = (by_type.get("goodput") or [None])[-1]
    resilience = None
    if ckpts or interruptions or resumes or goodput_ev or integrity:
        final = (by_type.get("checkpoint_final") or [{}])[-1]
        segments = by_type.get("run_segment", [])
        run_wall = sum(float(s.get("wall_s", 0)) for s in segments)
        blocked_ms = sum(float(e.get("blocked_ms", 0)) for e in ckpts)
        from pos_evolution_tpu.resilience import replayed_slots_from_events
        replayed = replayed_slots_from_events(events)
        # overhead: the goodput event's figure is canonical (final
        # attempt's in-loop blocked time over that attempt's wall); the
        # event-derived fallback sums blocked_ms over EVERY attempt but
        # run_segment only over completed ones, so it overstates
        # overhead whenever a run was interrupted
        if goodput_ev and goodput_ev.get("ckpt_overhead_pct") is not None:
            overhead_pct = goodput_ev["ckpt_overhead_pct"]
        elif run_wall and not interruptions:
            overhead_pct = round(100.0 * blocked_ms / (run_wall * 1e3), 3)
        else:
            overhead_pct = None
        resilience = {
            "checkpoints_saved": len(ckpts),
            "checkpoint_blocked_ms": round(blocked_ms, 3),
            "checkpoint_overhead_pct": overhead_pct,
            "checkpoint_bytes": final.get("bytes"),
            "async_mode": ckpts[-1].get("async_mode") if ckpts else None,
            "interruptions": [
                {k: e.get(k) for k in ("attempt", "reason", "exit_code",
                                       "wall_s") if e.get(k) is not None}
                for e in interruptions],
            "resumes": [{"step": e.get("step"), "slot": e.get("slot")}
                        for e in resumes],
            "replayed_slots": replayed,
            "quarantined_checkpoints": [
                {"step": e.get("step"), "reason": e.get("reason")}
                for e in quarantines],
            "rejected_checkpoints": [
                {"step": e.get("step"), "reason": e.get("reason")}
                for e in by_type.get("checkpoint_rejected", [])],
            "integrity_violations": [
                {"slot": e.get("slot"), "findings": e.get("findings")}
                for e in integrity],
            "gave_up": bool(by_type.get("supervisor_gaveup")),
        }
        if goodput_ev is not None:
            resilience["goodput"] = {
                k: goodput_ev.get(k) for k in
                ("attempts", "interruptions", "replayed_slots",
                 "final_slot", "goodput_pct", "ckpt_overhead_pct",
                 "total_wall_s", "resumed_on_degraded_mesh")
                if goodput_ev.get(k) is not None}

    # -- variant audit (variants/ per-slot records + variant_safety) ----------
    variant_events = by_type.get("variant", [])
    variant_audit = None
    if variant_events:
        last = variant_events[-1]
        groups = {}
        for gid, row in (last.get("groups") or {}).items():
            groups[gid] = {k: row.get(k) for k in
                           ("head_slot", "confirmed_slot",
                            "fast_confirmed_slot", "justified_slot",
                            "finalized_slot", "n_finalized",
                            "equivocators") if row.get(k) is not None}
        fast_confirms = sum(
            1 for e in variant_events
            for row in (e.get("groups") or {}).values()
            if row.get("fast_confirmed_slot") == e.get("slot") - 1)
        variant_audit = {
            "variant": last.get("variant"),
            "slots_recorded": len(variant_events),
            "final": groups,
            "fast_confirmations": fast_confirms,
            "slashable_evidence": last.get("slashable_evidence", 0),
            "violations": [
                {k: e.get(k) for k in ("slot", "kind", "checkpoint",
                                       "groups", "slots", "roots",
                                       "evidence_size", "slashable_stake",
                                       "accountability_scale", "detail")
                 if e.get(k) is not None}
                for e in by_type.get("monitor", [])
                if e.get("monitor") == "variant_safety"],
        }

    # -- dense variants (ISSUE 20: the variant seam in the dense driver) ------
    dv_attach = by_type.get("variant_attach", [])
    dv_decisions = by_type.get("variant_decision", [])
    dense_variants = None
    if dv_attach or dv_decisions:
        att = dv_attach[-1] if dv_attach else {}
        by_rule: dict = {}
        for e in dv_decisions:
            row = by_rule.setdefault(
                str(e.get("rule")),
                {"count": 0, "last_slot": None, "views": set()})
            row["count"] += 1
            row["last_slot"] = e.get("slot")
            row["views"].add(e.get("view"))
        dense_variants = {
            "variant": att.get("variant"),
            "riders": att.get("riders") or [],
            "decisions": len(dv_decisions),
            "rules": {k: {"count": v["count"],
                          "last_slot": v["last_slot"],
                          "views": sorted(v["views"])}
                      for k, v in sorted(by_rule.items())},
            "violations": [
                {k: e.get(k) for k in
                 ("slot", "kind", "rule", "groups", "decision_slot",
                  "roots", "evidence_size", "slashable_stake",
                  "total_stake", "detail") if e.get(k) is not None}
                for e in by_type.get("monitor", [])
                if e.get("monitor") == "variant_safety"],
        }

    # -- property audit (sim/monitors.py verdicts + invariant checker) --------
    attach = (by_type.get("monitor_attach") or [{}])[0]
    violations = [
        {k: e.get(k) for k in ("slot", "monitor", "kind", "checkpoint",
                               "groups", "epochs", "roots",
                               "evidence_size", "slashable_stake",
                               "total_stake", "epoch",
                               "best_finalized_epoch", "lag_epochs",
                               "bound_epochs", "group", "detail")
         if e.get(k) is not None}
        for e in by_type.get("monitor", [])]
    slashing = by_type.get("slashing_detected", [])
    audit = {
        "monitors": attach.get("monitors") or [],
        "adversaries": attach.get("adversaries") or [],
        "violations": violations,
        "invariant_violations": [
            {k: e.get(k) for k in ("slot", "group", "check", "detail")
             if e.get(k) is not None}
            for e in by_type.get("invariant_violation", [])],
        "slashing_evidence": {
            "detections": sum(e.get("n_new", 0) for e in slashing),
            "implicated_total":
                slashing[-1].get("implicated_total") if slashing else 0,
        },
        "clean": (not violations
                  and not by_type.get("invariant_violation")),
    }
    if bundle:
        audit["repro_bundle"] = bundle

    report = {
        "schema_version": events[0]["v"] if events else None,
        "n_events": len(events),
        "run": {k: run_start.get(k) for k in
                ("n_validators", "n_groups", "accelerated_forkchoice",
                 "debug", "dense", "mesh") if k in run_start},
        "finality": {
            "timeline": timeline,
            "advances": advances,
            "final_justified_epoch":
                timeline[-1]["justified_epoch"] if timeline else None,
            "final_finalized_epoch":
                timeline[-1]["finalized_epoch"] if timeline else None,
        },
        "faults": {"counts": fault_counts, "effects": effects,
                   **({"dense_totals": dense_fault_totals}
                      if dense_fault_totals else {})},
        "property_audit": audit,
        "handlers": handlers,
        "light_clients": {str(k): v for k, v in sorted(lc.items())},
    }
    if resilience:
        report["resilience"] = resilience
    if serving:
        report["serving"] = serving
    if serving_mp:
        report["serving_mp"] = serving_mp
    if dense_budget:
        report["dense_phase_budget"] = dense_budget
    device = _device_section(by_type, device_ledger)
    if device:
        report["device"] = device
    if merkleization:
        report["merkleization"] = merkleization
    if das_serving:
        report["das_serving"] = das_serving
    if variant_audit:
        report["variant_audit"] = variant_audit
    if dense_variants:
        report["dense_variants"] = dense_variants
    if top_ops:
        report["top_device_ops"] = top_ops
    if cost:
        report["cost_analysis"] = cost
    # device-time attribution emitted by profiling.ProfiledRegion runs
    profiles = by_type.get("profile", [])
    if profiles:
        report["profiles"] = [
            {k: p.get(k) for k in ("name", "by_jit", "attribution",
                                   "by_shard_map", "trace_dir", "error")
             if k in p}
            for p in profiles]
    return report


# -- markdown rendering --------------------------------------------------------

def _md_table(headers: list[str], rows: list[list]) -> list[str]:
    out = ["| " + " | ".join(headers) + " |",
           "|" + "|".join("---" for _ in headers) + "|"]
    for r in rows:
        out.append("| " + " | ".join(str(x) for x in r) + " |")
    return out


def to_markdown(report: dict) -> str:
    md = ["# Run report", ""]
    run = report.get("run", {})
    md.append(f"- events: **{report['n_events']}** "
              f"(schema v{report['schema_version']})")
    if run:
        md.append("- run: " + ", ".join(f"{k}={v}" for k, v in run.items()))
    fin = report["finality"]
    md += ["", "## Finality timeline", ""]
    if fin["timeline"]:
        md.append(f"- final justified epoch: "
                  f"**{fin['final_justified_epoch']}**, "
                  f"final finalized epoch: "
                  f"**{fin['final_finalized_epoch']}**")
        if fin["advances"]:
            md += ["", *_md_table(
                ["slot", "finalized epoch"],
                [[a["slot"], a["finalized_epoch"]] for a in fin["advances"]])]
        else:
            md.append("- finality never advanced")
    else:
        md.append("- no slot events in the log")

    faults = report["faults"]
    md += ["", "## Faults: attribution vs. effects", ""]
    if faults["counts"]:
        rows = [[action, kind, n]
                for action, kinds in sorted(faults["counts"].items())
                for kind, n in sorted(kinds.items())]
        md += _md_table(["action", "kind", "count"], rows)
    else:
        md.append("- no fault events (clean network or no FaultPlan sink)")
    eff = faults["effects"]
    md += ["",
           f"- gossip edges: {eff['gossip_edges']} "
           f"(undelivered: {eff['undelivered_gossip_edges']})",
           f"- handler rejects: {eff['handler_rejects'] or 'none'}",
           f"- invariant violations: {eff['invariant_violations']}",
           f"- crashes: {eff['crashes'] or 'none'}",
           f"- rejoins: {eff['rejoins'] or 'none'}"]
    if eff["degradations"]:
        md.append(f"- degradations: {eff['degradations']}")
    if eff["watchdog_incidents"]:
        md.append(f"- watchdog incidents: {eff['watchdog_incidents']}")

    audit = report.get("property_audit") or {}
    md += ["", "## Property audit", ""]
    roster = ", ".join(m.get("kind", "?") for m in audit.get("monitors", []))
    adv = ", ".join(a.get("kind", "?") for a in audit.get("adversaries", []))
    md.append(f"- monitors: {roster or 'none attached'}")
    if adv:
        md.append(f"- adversaries: {adv}")
    se = audit.get("slashing_evidence") or {}
    if se.get("detections"):
        md.append(f"- slashing evidence: {se['detections']} detection(s), "
                  f"{se['implicated_total']} validator(s) implicated")
    if audit.get("clean", True):
        if audit.get("monitors"):
            md.append("- **all properties held** (no monitor or invariant "
                      "violations)")
        else:
            md.append("- no monitors were attached — nothing was audited")
    if audit.get("violations"):
        md += ["", *_md_table(
            ["slot", "monitor", "kind", "evidence", "slashable/total stake"],
            [[v.get("slot"), v.get("monitor"), v.get("kind"),
              v.get("evidence_size", ""),
              (f"{v['slashable_stake']}/{v['total_stake']}"
               if "slashable_stake" in v else "")]
             for v in audit["violations"]])]
    if audit.get("invariant_violations"):
        md += ["", f"- invariant violations: "
               f"{len(audit['invariant_violations'])}"]
        for iv in audit["invariant_violations"][:10]:
            md.append(f"  - {iv}")
    if audit.get("repro_bundle"):
        md.append(f"- repro bundle: `{audit['repro_bundle']}`")

    if report.get("variant_audit"):
        va = report["variant_audit"]
        md += ["", "## Variant audit", ""]
        md.append(f"- protocol variant: **{va.get('variant')}** "
                  f"({va.get('slots_recorded')} slots recorded)")
        md.append(f"- fast confirmations: {va.get('fast_confirmations', 0)}")
        if va.get("slashable_evidence"):
            md.append(f"- variant slashing evidence: "
                      f"{va['slashable_evidence']} validator(s)")
        if va.get("final"):
            md += ["", *_md_table(
                ["group", "head slot", "confirmed", "fast-confirmed",
                 "justified", "finalized"],
                [[gid, row.get("head_slot", ""),
                  row.get("confirmed_slot", ""),
                  row.get("fast_confirmed_slot", ""),
                  row.get("justified_slot", ""),
                  row.get("finalized_slot", "")]
                 for gid, row in sorted(va["final"].items())])]
        if va.get("violations"):
            md += ["", *_md_table(
                ["slot", "kind", "checkpoint", "evidence",
                 "slashable/scale stake"],
                [[v.get("slot"), v.get("kind"), v.get("checkpoint"),
                  v.get("evidence_size", ""),
                  (f"{v['slashable_stake']}/{v['accountability_scale']}"
                   if "slashable_stake" in v else "")]
                 for v in va["violations"]])]
        else:
            md.append("- no variant-safety violations")

    if report.get("dense_variants"):
        dv = report["dense_variants"]
        md += ["", "## Dense variants", ""]
        var = dv.get("variant") or {}
        kind = var.get("kind", "gasper") if isinstance(var, dict) else var
        params = ", ".join(f"{k}={v}" for k, v in sorted(var.items())
                           if k != "kind") if isinstance(var, dict) else ""
        md.append(f"- protocol variant: **{kind}**"
                  + (f" ({params})" if params else ""))
        for r in dv.get("riders", []):
            desc = ", ".join(f"{k}={v}" for k, v in sorted(r.items())
                             if k != "kind")
            md.append(f"- workload rider: **{r.get('kind')}** ({desc})")
        md.append(f"- variant decisions: {dv.get('decisions', 0)}")
        if dv.get("rules"):
            md += ["", *_md_table(
                ["rule", "decisions", "last slot", "views"],
                [[rule, row["count"], row["last_slot"],
                  ",".join(str(v) for v in row["views"])]
                 for rule, row in sorted(dv["rules"].items())])]
        if dv.get("violations"):
            md += ["", *_md_table(
                ["slot", "kind", "rule", "evidence", "slashable/total"],
                [[v.get("slot"), v.get("kind"), v.get("rule", ""),
                  v.get("evidence_size", ""),
                  (f"{v['slashable_stake']}/{v['total_stake']}"
                   if "slashable_stake" in v else "")]
                 for v in dv["violations"]])]
        else:
            md.append("- no dense variant-safety violations")

    if report.get("resilience"):
        res = report["resilience"]
        md += ["", "## Resilience", ""]
        md.append(f"- checkpoints saved: **{res['checkpoints_saved']}** "
                  f"({'async' if res.get('async_mode') else 'sync'} mode, "
                  f"{res['checkpoint_blocked_ms']} ms blocked in-loop"
                  + (f", {res['checkpoint_overhead_pct']}% of run wall"
                     if res.get("checkpoint_overhead_pct") is not None
                     else "") + ")")
        ints = res.get("interruptions") or []
        md.append(f"- interruptions: {len(ints)}"
                  + (" — " + ", ".join(
                      f"attempt {i.get('attempt')}: {i.get('reason')} "
                      f"(exit {i.get('exit_code')})" for i in ints)
                     if ints else " (uninterrupted)"))
        if res.get("resumes"):
            md.append("- resumes: " + ", ".join(
                f"step {r['step']} -> slot {r['slot']}"
                for r in res["resumes"])
                + f" (replayed slots: {res.get('replayed_slots', 0)})")
        gp = res.get("goodput")
        if gp:
            md.append(f"- effective goodput: **{gp.get('goodput_pct')}%** "
                      f"({gp.get('final_slot')} useful slots, "
                      f"{gp.get('replayed_slots')} replayed, "
                      f"{gp.get('attempts')} attempt(s), total wall "
                      f"{gp.get('total_wall_s')}s)")
            if gp.get("resumed_on_degraded_mesh"):
                md.append(f"- resumed on a DEGRADED mesh: "
                          f"{gp['resumed_on_degraded_mesh']}")
        for q in res.get("quarantined_checkpoints") or []:
            md.append(f"- **quarantined checkpoint** step {q['step']}: "
                      f"{q['reason']}")
        for iv in res.get("integrity_violations") or []:
            md.append(f"- **integrity violation** at slot {iv['slot']}: "
                      f"{iv['findings']}")
        if res.get("gave_up"):
            md.append("- **SUPERVISOR GAVE UP** — retry budget exhausted")

    if report.get("merkleization"):
        merk = report["merkleization"]
        md += ["", "## Merkleization", ""]
        if merk.get("htr_hit_rate") is not None:
            md.append(f"- field-root cache hit rate: "
                      f"**{merk['htr_hit_rate']:.1%}** over "
                      f"{merk['slots_with_activity']} active slot(s)")
        if merk.get("device_share") is not None:
            md.append(f"- level-sweep dispatch: "
                      f"**{merk['device_pairs']}** pairs on device / "
                      f"{merk['host_pairs']} on host "
                      f"({merk['device_share']:.1%} device)"
                      + (f", device sweep throughput "
                         f"{merk['device_pairs_per_s']} pairs/s"
                         if merk.get("device_pairs_per_s") else ""))
        md += ["", *_md_table(
            ["counter", "total"],
            [[k, v] for k, v in merk["totals"].items()])]

    if report.get("serving"):
        s = report["serving"]
        md += ["", "## Serving", ""]
        md.append(f"- RPC front: **{s.get('workers')}** workers, "
                  f"pattern **{s.get('pattern')}**, "
                  f"{s.get('arrivals')} arrivals at {s.get('rate')}/s "
                  f"over {s.get('wall_s')}s")
        tiers = s.get("tiers") or {}
        if tiers:
            md += ["", *_md_table(
                ["tier", "arrivals", "goodput %", "shed %",
                 "p50 ms", "p99 ms", "p999 ms"],
                [[name, row.get("arrivals"), row.get("goodput_pct"),
                  row.get("shed_pct"), row.get("p50_ms"),
                  row.get("p99_ms"), row.get("p999_ms")]
                 for name, row in sorted(tiers.items())]), ""]
        md.append(f"- honest rejections: shed rate "
                  f"**{s.get('shed_rate')}** by reason "
                  f"{s.get('shed_by_reason')}")
        md.append(f"- hedged retries: {s.get('hedges')} hedges, "
                  f"{s.get('retries')} retries")
        md.append(f"- verified proofs: **{s.get('verified_proofs')}** "
                  f"(failures: {s.get('verify_failures')})")
        sf = s.get("singleflight") or {}
        md.append(f"- stampede suppression: {s.get('scheme_builds')} "
                  f"backing builds, {sf.get('waits', 0)} coalesced "
                  f"waiters, proof cache {s.get('proof_cache')}")
        md.append(f"- brownout transitions: "
                  f"{s.get('brownout_transitions')}; circuit breaker: "
                  f"{s.get('breaker_state')} "
                  f"({s.get('breaker_transitions')} transitions)")
        if s.get("chaos_injections"):
            md.append(f"- chaos injections: {s['chaos_injections']} "
                      f"(worker stalls served: {s.get('chaos_stalls')}, "
                      f"slow-loris closed: {s.get('slow_loris_closed')})")
        if s.get("slo_ms") is not None:
            verdict = "**met**" if s.get("slo_ok") else "**MISSED**"
            md.append(f"- interactive p99 SLO {s['slo_ms']} ms: {verdict}")

    if report.get("serving_mp"):
        s = report["serving_mp"]
        md += ["", "## Serving (multi-process)", ""]
        md.append(f"- plane: **{s.get('fronts')}** fronts x "
                  f"**{s.get('workers')}** worker processes over "
                  f"shared-memory view generation "
                  f"{s.get('board_generation')}")
        phases = [(name, s.get(name)) for name in ("steady", "chaos")
                  if s.get(name)]
        if phases:
            md += ["", *_md_table(
                ["phase", "arrivals", "rate/s", "goodput %", "p50 ms",
                 "p99 ms", "resends", "lost", "verify fails", "verdict"],
                [[name, p.get("arrivals"), p.get("rate"),
                  p.get("goodput_pct"), p.get("p50_ms"), p.get("p99_ms"),
                  p.get("resends"), p.get("lost"),
                  p.get("verify_failures"),
                  "ok" if p.get("ok") else "FAILED"]
                 for name, p in phases]), ""]
        if s.get("worker_rows"):
            md += [*_md_table(
                ["worker", "pid", "alive", "restarts", "requests",
                 "generation", "rss kb", "hb age s"],
                [[r.get("worker"), r.get("pid"), r.get("alive"),
                  r.get("restarts"), r.get("requests"),
                  r.get("generation"), r.get("rss_kb"),
                  r.get("hb_age_s")]
                 for r in s["worker_rows"]]), ""]
        if s.get("interruptions"):
            md.append(f"- worker interruptions "
                      f"({s.get('interruptions_by_reason')}; "
                      f"{s.get('chaos_kills_delivered')} chaos SIGKILLs "
                      f"delivered, {s.get('restarts')} respawns, "
                      f"{s.get('parked')} parked):")
            md += ["", *_md_table(
                ["worker", "reason", "pid", "exit code", "at wall s"],
                [[r.get("worker"), r.get("reason"), r.get("pid"),
                  r.get("exit_code"), r.get("wall_s")]
                 for r in s["interruptions"]]), ""]
        regen = s.get("respawned_on_current_generation")
        md.append(f"- respawned workers on current shared-memory "
                  f"generation: "
                  f"{'**yes**' if regen else '**NO — silent fork**'}")
        fl = s.get("fleet")
        if fl:
            lohi = fl.get("window") or [None, None]
            md += ["", "### Fleet metrics", ""]
            md.append(f"- workers reporting: "
                      f"**{fl.get('workers_reporting')}** "
                      f"({fl.get('snapshots_merged')} snapshots merged, "
                      f"{fl.get('snapshots_skipped')} skipped)")
            if fl.get("requests_by_worker"):
                md += ["", *_md_table(
                    ["worker", "requests (fleet counter)"],
                    [[w, int(n)] for w, n in sorted(
                        (fl["requests_by_worker"] or {}).items(),
                        key=lambda kv: int(kv[0]))]), ""]
            verdict = ("**consistent**" if fl.get("consistent")
                       else "**INCONSISTENT**")
            md.append(f"- fleet total {fl.get('requests_total')} vs "
                      f"loadgen window [{lohi[0]}, {lohi[1]}]: {verdict}")

    if report.get("dense_phase_budget"):
        d = report["dense_phase_budget"]
        md += ["", "## Dense phase budget", ""]
        md.append(f"- accounted: **{d.get('accounted_pct')}%** of the "
                  f"sampled slot wall ({d.get('sampled_wall_ms')} ms over "
                  f"{d.get('sampled_slots')} fenced slot(s))")
        md += ["", *_md_table(
            ["phase", "total ms", "share %"],
            [[name, row.get("total_ms"), row.get("share_pct")]
             for name, row in (d.get("phases") or {}).items()])]

    if report.get("device"):
        d = report["device"]
        md += ["", "## Device", ""]
        mem = d.get("memory") or {}
        if mem:
            peaks = ", ".join(
                f"{dev}: {b / (1 << 20):.1f} MiB"
                for dev, b in sorted((mem.get("peak_bytes") or {}).items()))
            md.append(f"- memory watermark ({mem.get('samples')} samples, "
                      f"source **{mem.get('source')}**): {peaks or 'n/a'}")
            if mem.get("source") == "host_rss":
                md.append("  - host_rss measures the whole PROCESS "
                          "(python, numpy, caches) — a CPU headroom "
                          "proxy, not accelerator memory")
        led = d.get("compile_ledger") or {}
        attr = led.get("attribution") or {}
        if attr:
            md.append(f"- compile ledger: **{attr.get('named')}/"
                      f"{attr.get('backend_compiles')}** backend "
                      f"compiles on a named (function, phase) row "
                      f"({attr.get('named_pct')}%)")
        rows = led.get("rows") or []
        if rows:
            md += ["", *_md_table(
                ["stage", "function", "phase", "count", "seconds"],
                [[r.get("stage"), r.get("function"), r.get("phase"),
                  r.get("count"), r.get("seconds")]
                 for r in rows[:12]])]
        skew = d.get("shard_skew") or {}
        if skew.get("table"):
            md += ["", "shard skew (per phase x device):", "",
                   *_md_table(
                       ["phase", "device", "mean ms", "max ms", "probes"],
                       [[r.get("phase"), r.get("device"),
                         r.get("mean_ms"), r.get("max_ms"),
                         r.get("probes")]
                        for r in skew["table"][:16]])]
        elif skew.get("worst"):
            w = skew["worst"]
            md.append(f"- worst shard skew: {w.get('spread_ms')} ms "
                      f"spread in **{w.get('phase')}** at slot "
                      f"{w.get('slot')} ({skew.get('probes')} probes)")

    if report.get("das_serving"):
        d = report["das_serving"]
        md += ["", "## DAS serving", ""]
        md.append(f"- clients: **{d['clients']}** "
                  f"({d.get('samples_per_client', '?')} samples each, "
                  f"scheme: {d.get('scheme', '?')})")
        md.append(f"- samples served: **{d['samples_total']}** over "
                  f"{d['served_blocks']} served block(s), coalesced to "
                  f"{d['unique_requests_total']} unique cell fetches")
        md.append(f"- serving latency per coalesced request: "
                  f"p50 **{d['p50_ms']} ms**, p95 **{d['p95_ms']} ms** "
                  f"(typical served block; worst block p95 "
                  f"{d['worst_p95_ms']} ms)")
        if d.get("cache_hit_rate") is not None:
            md.append(f"- proof-path cache hit rate: "
                      f"**{d['cache_hit_rate']:.1%}**")
        if d.get("proof_bytes_per_sample") is not None:
            agg = " (one aggregated multiproof per served block)" \
                if d.get("aggregated") else ""
            md.append(f"- served proof bytes/sample: "
                      f"**{d['proof_bytes_per_sample']}**{agg}")
        md.append(f"- sample verification failures: {d['verify_failures']} "
                  f"(clients fully satisfied at last serve: "
                  f"{d['clients_all_ok_final']})")

    md += ["", "## Handler percentiles", ""]
    if report["handlers"]:
        md += _md_table(
            ["handler", "count", "p50 ms", "p95 ms", "total ms"],
            [[h, v["count"], v["p50_ms"], v["p95_ms"], v["total_ms"]]
             for h, v in report["handlers"].items()])
    else:
        md.append("- no handler timings in the log")

    if report.get("light_clients"):
        md += ["", "## Light clients", ""]
        md += _md_table(
            ["node", "records", "max head lag", "max finality lag",
             "final head lag", "final finality lag"],
            [[k, v["records"], v["max_head_lag"], v["max_finality_lag"],
              v["final_head_lag"], v["final_finality_lag"]]
             for k, v in report["light_clients"].items()])

    if report.get("top_device_ops"):
        md += ["", "## Top device ops", ""]
        for plane, rows in report["top_device_ops"].items():
            md.append(f"### {plane}")
            md += _md_table(["op", "total ms", "count"],
                            [[r["op"], r["total_ms"], r["count"]]
                             for r in rows])
            md.append("")

    if report.get("profiles"):
        md += ["", "## Device-time attribution", ""]
        for p in report["profiles"]:
            md.append(f"### region `{p.get('name', '?')}`")
            if p.get("error"):
                md.append(f"- profiling degraded: {p['error']}")
            attr = p.get("attribution") or {}
            if attr:
                rows = sorted(attr.items(),
                              key=lambda kv: -kv[1].get("total_ms", 0))
                md += _md_table(["span / kernel", "total ms", "ops"],
                                [[k, v.get("total_ms"), v.get("count")]
                                 for k, v in rows])
            sm = p.get("by_shard_map") or {}
            if sm:
                rows = sorted(sm.items(),
                              key=lambda kv: -kv[1].get("total_ms", 0))
                md += ["", "shard_map regions:", "", *_md_table(
                    ["shard_map region", "total ms", "ops"],
                    [[k, v.get("total_ms"), v.get("count")]
                     for k, v in rows])]
            md.append("")

    if report.get("cost_analysis"):
        cost = report["cost_analysis"]
        md += ["", "## Static cost analysis",
               f"(backend {cost.get('backend')}, "
               f"n={cost.get('n_validators')})", ""]
        rows = []
        for k, v in sorted((cost.get("kernels") or {}).items()):
            if "error" in v:
                rows.append([k, "error", v["error"][:40], "", ""])
            else:
                rows.append([k, v.get("flops"), v.get("bytes_accessed"),
                             v.get("temp_bytes"), v.get("peak_bytes")])
        md += _md_table(
            ["kernel", "flops", "bytes accessed", "temp B", "peak B"], rows)
    return "\n".join(md) + "\n"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("events", help="telemetry JSONL file")
    ap.add_argument("--json", help="write the report dict to this path")
    ap.add_argument("--markdown",
                    help="write markdown here instead of stdout")
    ap.add_argument("--top-ops",
                    help="top_ops.json to fold into the report (default: "
                         "auto-discovered next to the event log)")
    ap.add_argument("--cost",
                    help="profiling/cost.py JSON emission to fold in")
    ap.add_argument("--bundle",
                    help="chaos-fuzz repro bundle the log belongs to "
                         "(default: auto-discovered when the log sits "
                         "next to a violations.json)")
    ap.add_argument("--device-ledger",
                    help="flight-recorder artifact to fold into the "
                         "Device section (default: auto-discovered "
                         "*device_ledger.json next to the event log)")
    ap.add_argument("--xplane", metavar="TRACE",
                    help="xplane trace dir/file to summarize into the "
                         "top-device-ops table (absorbs the old "
                         "scripts/trace_summary.py; wins over --top-ops)")
    ap.add_argument("--top-n", type=int, default=10,
                    help="rows per plane for --xplane (default 10)")
    args = ap.parse_args(argv)

    events, merged_from = load_events(args.events)
    if merged_from:
        print(f"# merged {len(merged_from)} per-process event logs: "
              + ", ".join(os.path.basename(p) for p in merged_from),
              file=sys.stderr)
    top_ops_path = args.top_ops or discover_top_ops(args.events, events)
    if args.top_ops is None and top_ops_path is not None:
        print(f"# auto-discovered top-ops table: {top_ops_path}",
              file=sys.stderr)
    top_ops = None
    if args.xplane:
        # the trace_summary.py fold-in: summarize an xplane trace
        # directly into the same table --top-ops would have carried
        from pos_evolution_tpu.profiling.xplane import summarize_path
        blob = summarize_path(args.xplane, args.top_n)
        top_ops = blob.get("planes", blob)
    elif top_ops_path and os.path.exists(top_ops_path):
        with open(top_ops_path) as fh:
            blob = json.load(fh)
        top_ops = blob.get("planes", blob)
    cost = None
    if args.cost and os.path.exists(args.cost):
        with open(args.cost) as fh:
            cost = json.load(fh)
    bundle = args.bundle or discover_bundle(args.events)
    ledger_path = args.device_ledger or discover_device_ledger(args.events)
    device_ledger = None
    if ledger_path and os.path.exists(ledger_path):
        if args.device_ledger is None:
            print(f"# auto-discovered device ledger: {ledger_path}",
                  file=sys.stderr)
        with open(ledger_path) as fh:
            device_ledger = json.load(fh)
    report = build_report(events, top_ops=top_ops, cost=cost, bundle=bundle,
                          device_ledger=device_ledger)
    if args.json:
        with open(args.json, "w") as fh:
            json.dump(report, fh, indent=1, sort_keys=True)
            fh.write("\n")
    md = to_markdown(report)
    if args.markdown:
        with open(args.markdown, "w") as fh:
            fh.write(md)
    else:
        sys.stdout.write(md)
    return 0


if __name__ == "__main__":
    sys.exit(main())
