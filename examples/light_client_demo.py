"""Light-client demo: sync a thin client from a weak-subjectivity checkpoint
through a faulty simulation and watch it converge on the full node's
finalized head.

Run: python examples/light_client_demo.py [--events events.jsonl]

``--events`` records the whole run on the telemetry bus (message
lifecycle spans, fault attribution, per-slot records, light-client lag)
as schema-versioned JSONL; feed it to ``scripts/run_report.py`` for the
finality timeline / fault / handler-percentile report.

What happens:
1. A 64-validator simulation runs with a lossy network (10% of all
   messages — including the light-client update feed — dropped before GST).
2. A light client bootstraps from the full node's finalized checkpoint
   (gated by the weak-subjectivity period check) and receives one update
   per slot, verifying each sync aggregate + merkle proof pair through the
   ExecutionBackend batch kernels.
3. Per-slot head-lag / finality-lag is printed; after a final off-chain
   finality update (the gossip path of real light-client networks), the
   client holds exactly the full node's finalized head.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pos_evolution_tpu.config import minimal_config, use_config


def main():
    events_path = None
    if "--events" in sys.argv:
        try:
            events_path = sys.argv[sys.argv.index("--events") + 1]
        except IndexError:
            sys.exit("Usage: python examples/light_client_demo.py "
                     "[--events events.jsonl]")
    with use_config(minimal_config()) as c:
        from pos_evolution_tpu.sim import Simulation, faulty_schedule, lossy_plan

        telemetry = None
        if events_path is not None:
            from pos_evolution_tpu.telemetry import Telemetry
            telemetry = Telemetry.to_file(events_path)

        gst = 6 * c.slots_per_epoch * c.seconds_per_slot
        plan = lossy_plan(seed=11, drop_p=0.10, gst=gst)
        sim = Simulation(64, schedule=faulty_schedule(64, plan),
                         telemetry=telemetry)

        print("== Light client over a faulty 8-epoch simulation ==")
        node = sim.attach_light_client()
        print(f"bootstrapped from weak-subjectivity checkpoint at slot "
              f"{node.finalized_slot} (trusted root "
              f"{node.finalized_root().hex()[:12]}…)\n")

        print(f"{'slot':>4} {'lc head':>8} {'lc fin':>7} {'head lag':>9} "
              f"{'fin lag':>8}")
        for epoch in range(1, 9):
            sim.run_until_slot(epoch * c.slots_per_epoch)
            r = node.records[-1]
            print(f"{r['slot']:>4} {r['lc_head_slot']:>8} "
                  f"{r['lc_finalized_slot']:>7} {r['head_lag']:>9} "
                  f"{r['finality_lag']:>8}")

        sim.flush_light_clients()
        full = sim.store(0)
        full_root = bytes(full.finalized_checkpoint.root)
        print(f"\nfull node finalized epoch {sim.finalized_epoch()} "
              f"(root {full_root.hex()[:12]}…)")
        print(f"light client finalized slot {node.finalized_slot} "
              f"(root {node.finalized_root().hex()[:12]}…)")
        s = node.summary()
        print(f"updates applied={s['applied']} rejected={s['rejected']} "
              f"forced={s['forced']}")
        assert node.finalized_root() == full_root, \
            "light client must converge on the full node's finalized head"
        print("converged: light client finalized head == full node "
              "finalized head ✓")
        if telemetry is not None:
            telemetry.close()
            print(f"\ntelemetry: {len(telemetry.bus.events)} events -> "
                  f"{events_path}\n  next: python scripts/run_report.py "
                  f"{events_path}")


if __name__ == "__main__":
    main()
