"""Tour of the framework: honest finality, an attack, a variant, the
TPU array level. Run: python examples/demo.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pos_evolution_tpu.config import minimal_config, use_config


def honest_finality():
    print("== 1. Honest Gasper run: justification and finality ==")
    from pos_evolution_tpu.sim import Simulation
    sim = Simulation(64)
    sim.run_epochs(5)
    for m in sim.metrics[:: sim.cfg.slots_per_epoch]:
        print(f"  slot {m['slot']:>2}  head={m['head']}  "
              f"justified={m['justified_epoch']}  finalized={m['finalized_epoch']}")
    assert sim.finalized_epoch() >= 3


def balancing_attack():
    print("\n== 2. Balancing attack vs pre-boost Gasper (liveness failure) ==")
    from pos_evolution_tpu.config import cfg, use_config
    with use_config(cfg().replace(proposer_score_boost_percent=0)):
        from pos_evolution_tpu.sim.attacks import run_balancing_attack
        r = run_balancing_attack(64, n_epochs=3, corrupted_fraction=0.3)
        print(f"  views split: {r.head_L != r.head_R}; "
              f"justified epochs: L={r.justified_epoch_L} R={r.justified_epoch_R} "
              f"(frozen at genesis)")


def ssf():
    print("\n== 3. Single-slot finality (RLMD-GHOST + per-slot FFG + acks) ==")
    from pos_evolution_tpu.models import SSFSimulation
    sim = SSFSimulation(16)
    sim.run_slots(5)
    print(f"  after 5 slots: max finalized slot = {sim.max_finalized_slot()} "
          f"(finalized within the proposing slot)")


def array_level():
    print("\n== 4. Array level: fused epoch sweep + dense fork choice ==")
    import numpy as np
    import jax
    from pos_evolution_tpu.backend import set_backend
    from pos_evolution_tpu.sim import Simulation
    set_backend("jax")
    try:
        t0 = time.time()
        sim = Simulation(64, accelerated_forkchoice=True)
        sim.run_epochs(3)
        print(f"  3 epochs with device epoch sweeps + device get_head: "
              f"{time.time() - t0:.1f}s on {jax.default_backend()}; "
              f"justified={sim.justified_epoch()}")
    finally:
        set_backend("numpy")


if __name__ == "__main__":
    with use_config(minimal_config()):
        honest_finality()
        balancing_attack()
        ssf()
        array_level()
    print("\nAll demos completed.")
