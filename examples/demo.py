"""Tour of the framework: honest finality, an attack, a variant, the
slasher, the TPU array level. Run: python examples/demo.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pos_evolution_tpu.config import minimal_config, use_config


def honest_finality():
    print("== 1. Honest Gasper run: justification and finality ==")
    from pos_evolution_tpu.sim import Simulation
    sim = Simulation(64)
    sim.run_epochs(5)
    for m in sim.metrics[:: sim.cfg.slots_per_epoch]:
        print(f"  slot {m['slot']:>2}  head={m['head']}  "
              f"justified={m['justified_epoch']}  finalized={m['finalized_epoch']}")
    assert sim.finalized_epoch() >= 3


def balancing_attack():
    print("\n== 2. Balancing attack vs pre-boost Gasper (liveness failure) ==")
    from pos_evolution_tpu.config import cfg, use_config
    with use_config(cfg().replace(proposer_score_boost_percent=0)):
        from pos_evolution_tpu.sim.attacks import run_balancing_attack
        r = run_balancing_attack(64, n_epochs=3, corrupted_fraction=0.3)
        print(f"  views split: {r.head_L != r.head_R}; "
              f"justified epochs: L={r.justified_epoch_L} R={r.justified_epoch_R} "
              f"(frozen at genesis)")


def ssf():
    print("\n== 3. Single-slot finality (RLMD-GHOST + per-slot FFG + acks) ==")
    from pos_evolution_tpu.models import SSFSimulation
    sim = SSFSimulation(16)
    sim.run_slots(5)
    print(f"  after 5 slots: max finalized slot = {sim.max_finalized_slot()} "
          f"(finalized within the proposing slot)")


def slasher_demo():
    print("\n== 4. Slasher: equivocation -> evidence -> discounted stake ==")
    from pos_evolution_tpu.specs import forkchoice as fc
    from pos_evolution_tpu.specs.genesis import make_genesis
    from pos_evolution_tpu.specs.helpers import get_indexed_attestation
    from pos_evolution_tpu.specs.slasher import Slasher
    from pos_evolution_tpu.specs.validator import (
        build_block, make_committee_attestation,
    )
    from pos_evolution_tpu.ssz import hash_tree_root
    state, anchor = make_genesis(64)
    store = fc.get_forkchoice_store(state, anchor)
    fc.on_tick(store, store.genesis_time + 24)
    sb_a = build_block(state, 1, graffiti=b"\x0a" * 32)
    sb_b = build_block(state, 1, graffiti=b"\x0b" * 32)
    fc.on_block(store, sb_a)
    fc.on_block(store, sb_b)
    ra, rb = hash_tree_root(sb_a.message), hash_tree_root(sb_b.message)
    a1 = make_committee_attestation(store.block_states[ra], 1, 0, ra)
    a2 = make_committee_attestation(store.block_states[rb], 1, 0, rb)
    watch = Slasher()
    watch.on_attestation(get_indexed_attestation(store.block_states[ra], a1))
    evidence = watch.on_attestation(
        get_indexed_attestation(store.block_states[rb], a2))
    fc.on_attester_slashing(store, evidence[0])
    print(f"  committee equivocated across two blocks -> {len(evidence)} "
          f"AttesterSlashing emitted -> {len(store.equivocating_indices)} "
          f"validators discounted from fork choice")


def array_level():
    print("\n== 5. Array level: fused epoch sweep + dense fork choice ==")
    import jax
    from pos_evolution_tpu.backend import set_backend
    from pos_evolution_tpu.sim import Simulation
    set_backend("jax")
    try:
        t0 = time.time()
        sim = Simulation(64, accelerated_forkchoice=True)
        sim.run_epochs(3)
        print(f"  3 epochs with device epoch sweeps + device get_head: "
              f"{time.time() - t0:.1f}s on {jax.default_backend()}; "
              f"justified={sim.justified_epoch()}")
    finally:
        set_backend("numpy")


if __name__ == "__main__":
    with use_config(minimal_config()):
        honest_finality()
        balancing_attack()
        ssf()
        slasher_demo()
        array_level()
    print("\nAll demos completed.")
