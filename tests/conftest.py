"""Test configuration.

Force JAX onto CPU with 8 virtual devices BEFORE jax is imported anywhere,
so all mesh/collective code paths (SURVEY.md §4.4c) execute in CI without
TPU hardware.
"""

import os
import sys

# POS_TEST_ACCEL=1 opts out of the CPU pin so the accelerator-gated tests
# (compiled Pallas kernels, on-device crypto) run against the real chip.
_ACCEL = os.environ.get("POS_TEST_ACCEL") == "1"

if not _ACCEL:
    os.environ["JAX_PLATFORMS"] = "cpu"
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The axon sitecustomize imports jax at interpreter startup with
# JAX_PLATFORMS=axon (real-TPU tunnel); override post-import so the suite
# runs on the 8-device virtual CPU mesh regardless.
if not _ACCEL:
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass

import pytest  # noqa: E402

from pos_evolution_tpu.config import minimal_config, use_config  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "mesh8: test requires the 8-device virtual CPU mesh (skipped when "
        "POS_TEST_ACCEL=1 runs the suite on a smaller real-chip topology)")


def pytest_collection_modifyitems(config, items):
    if not _ACCEL:
        return
    # On real hardware (usually a single chip) skip tests that require the
    # 8-device virtual CPU mesh instead of letting their fixtures assert.
    # Selection is by explicit @pytest.mark.mesh8 marker, not nodeid
    # substring, so new mesh-requiring tests anywhere opt in reliably.
    try:
        import jax

        n_dev = len(jax.devices())
    except Exception:
        n_dev = 1
    if n_dev >= 8:
        return
    skip = pytest.mark.skip(reason="needs the 8-device CPU mesh (unset POS_TEST_ACCEL)")
    for item in items:
        if "mesh8" in item.keywords:
            item.add_marker(skip)


@pytest.fixture
def minimal_cfg():
    with use_config(minimal_config()) as c:
        yield c
