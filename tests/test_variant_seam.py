"""Protocol-variant seam tests (variants/, DESIGN.md §16).

- kernel host⇄device bit-identity (ops/variant_tally.py twins);
- differential against the ``models/`` PVM oracles on shared
  (block-tree, vote-set) scenarios: the expiry-windowed
  equivocation-discounted kernel head must equal ``pvm.ghost_head``;
- GasperVariant behavior-identity: the default seam is byte-for-byte the
  pre-seam driver on a seeded faulted run;
- Goldfish / RLMD-GHOST / SSF end-to-end through the driver, bit-identical
  across ExecutionBackends;
- checkpoint/resume per variant (uninterrupted-twin equality, fingerprint
  rebuild, mismatch refusal);
- the matrix acceptance pins: Balancer succeeds vs pre-boost Gasper and
  fails vs Goldfish expiry; the ex-ante reorg succeeds vs pre-boost
  Gasper and fails vs SSF fast confirmation; SplitVoter double finality
  under SSF is accountable with >= 1/3 implicated stake; repro bundles
  replay; the per-variant doctored negative trips.
"""

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))), "scripts"))

from pos_evolution_tpu.backend import set_backend  # noqa: E402
from pos_evolution_tpu.ops import variant_tally as vt  # noqa: E402

pytestmark = pytest.mark.usefixtures("minimal_cfg")


@pytest.fixture(autouse=True)
def _numpy_backend():
    set_backend("numpy")
    yield
    set_backend("numpy")


class TestKernels:
    def test_windowed_tally_host_device_bit_identity(self):
        rng = np.random.default_rng(0)
        for trial in range(12):
            k = int(rng.integers(1, 150))
            nb = int(rng.integers(1, 40))
            block_idx = rng.integers(-1, nb, k)
            vote_slot = rng.integers(0, 30, k)
            weight = rng.integers(0, 32_000_000_000, k)
            active = rng.random(k) < 0.8
            lo, hi = sorted(rng.integers(0, 30, 2))
            h = vt.windowed_vote_tally_host(block_idx, vote_slot, weight,
                                            active, lo, hi, nb)
            d = vt.windowed_vote_tally_device(block_idx, vote_slot, weight,
                                              active, lo, hi, nb)
            assert h.dtype == np.int64 and (h == d).all(), trial

    def test_link_tally_host_device_bit_identity(self):
        rng = np.random.default_rng(1)
        for trial in range(12):
            k = int(rng.integers(1, 150))
            nl = int(rng.integers(1, 20))
            link_idx = rng.integers(-1, nl, k)
            weight = rng.integers(0, 32_000_000_000, k)
            active = rng.random(k) < 0.8
            h = vt.link_tally_host(link_idx, weight, active, nl)
            d = vt.link_tally_device(link_idx, weight, active, nl)
            assert (h == d).all(), trial

    def test_window_and_discount_semantics(self):
        # one vote inside the window, one expired, one discounted
        out = vt.windowed_vote_tally_host(
            np.array([0, 0, 0]), np.array([5, 2, 5]),
            np.array([10, 10, 10]), np.array([True, True, False]),
            lo_slot=4, hi_slot=6, n_blocks=1)
        assert out.tolist() == [10]

    def test_empty_votes(self):
        out = vt.windowed_vote_tally_device(
            np.zeros(0, np.int64), np.zeros(0, np.int64),
            np.zeros(0, np.int64), np.zeros(0, bool), 0, 10, 4)
        assert out.tolist() == [0, 0, 0, 0]


class TestPVMDifferential:
    """The retained models/ propose-vote-merge layer is the oracle for
    the variant fork-choice rule: on a shared random (block-tree,
    vote-set) scenario the kernel pipeline (windowed tally -> subtree
    accumulation -> greedy descent) must pick ``pvm.ghost_head``'s
    head, for LMD (eta = inf), RLMD windows and the Goldfish eta = 1
    limit, with equivocation discounting."""

    def _scenario(self, seed: int):
        from pos_evolution_tpu.models.pvm import (
            GENESIS_ROOT,
            HeadVote,
            PVMBlock,
            View,
        )
        rng = np.random.default_rng(seed)
        view = View()
        roots = [GENESIS_ROOT]
        for i in range(int(rng.integers(3, 14))):
            parent = roots[int(rng.integers(0, len(roots)))]
            parent_slot = (0 if parent == GENESIS_ROOT
                           else view.blocks[parent].slot)
            b = PVMBlock(slot=int(parent_slot) + 1 + int(rng.integers(0, 2)),
                         parent=parent, proposer=i)
            view.add_block(b)
            roots.append(b.root)
        slot = max(b.slot for b in view.blocks.values()) + 1
        for v in range(12):
            for _ in range(int(rng.integers(0, 3))):
                view.add_vote(HeadVote(
                    slot=int(rng.integers(1, slot)),
                    block_root=roots[int(rng.integers(0, len(roots)))],
                    validator=v))
        return view, slot

    def _kernel_head(self, view, slot: int, eta):
        from pos_evolution_tpu.backend import get_backend
        from pos_evolution_tpu.models.pvm import GENESIS_ROOT
        roots = list(view.blocks.keys())
        index_of = {r: i for i, r in enumerate(roots)}
        parent = np.array([index_of.get(view.blocks[r].parent, -1)
                           if r != GENESIS_ROOT else -1 for r in roots],
                          np.int32)
        # latest vote per validator (the pvm latest_votes contract);
        # equivocators carry no weight
        latest: dict[int, tuple[int, bytes]] = {}
        for (v, s), root in view.votes.items():
            cur = latest.get(v)
            if cur is None or s > cur[0]:
                latest[v] = (s, root)
        items = sorted(latest.items())
        block_idx = np.array([index_of.get(r, -1) for _, (_, r) in items],
                             np.int64)
        vote_slot = np.array([s for _, (s, _) in items], np.int64)
        weight = np.ones(len(items), np.int64)
        active = np.array([v not in view.equivocators for v, _ in items],
                          bool)
        lo = 0 if eta is None else max(slot - eta, 0)
        backend = get_backend()
        tally = backend.variant_tally(block_idx, vote_slot, weight, active,
                                      lo, slot - 1, len(roots))
        subtree = backend.subtree_weights(parent, tally)
        children: dict[int, list[int]] = {}
        for i, p in enumerate(parent):
            if p >= 0:
                children.setdefault(int(p), []).append(i)
        head = 0
        while True:
            kids = children.get(head, [])
            if not kids:
                return roots[head]
            head = max(kids, key=lambda i: (int(subtree[i]), roots[i]))

    @pytest.mark.parametrize("eta", [None, 1, 2, 4])
    def test_kernel_head_matches_pvm_ghost_head(self, eta):
        from pos_evolution_tpu.models.pvm import ghost_head
        for seed in range(8):
            view, slot = self._scenario(seed)
            assert self._kernel_head(view, slot, eta) \
                == ghost_head(view, slot, eta), (seed, eta)

    def test_equivocator_discounted_like_pvm(self):
        from pos_evolution_tpu.models.pvm import (
            GENESIS_ROOT,
            HeadVote,
            PVMBlock,
            View,
            ghost_head,
        )
        view = View()
        b1 = PVMBlock(slot=1, parent=GENESIS_ROOT, proposer=0)
        b2 = PVMBlock(slot=1, parent=GENESIS_ROOT, proposer=1)
        view.add_block(b1)
        view.add_block(b2)
        view.add_vote(HeadVote(slot=2, block_root=b1.root, validator=5))
        view.add_vote(HeadVote(slot=2, block_root=b2.root, validator=5))
        view.add_vote(HeadVote(slot=2, block_root=b2.root, validator=6))
        for eta in (None, 2):
            assert self._kernel_head(view, 3, eta) \
                == ghost_head(view, 3, eta) == b2.root


def _faulted_schedule(n):
    from pos_evolution_tpu.sim.faults import FaultPlan
    from pos_evolution_tpu.sim.schedule import faulty_schedule
    plan = FaultPlan(seed=11, drop_p=0.08, duplicate_p=0.05,
                     reorder_p=0.1, reorder_max_delay=3.0, gst=48)
    return faulty_schedule(n, plan, n_groups=2)


class TestGasperBehaviorIdentity:
    def test_default_variant_is_gasper_with_no_overlay(self):
        from pos_evolution_tpu.sim import Simulation
        from pos_evolution_tpu.variants import GasperVariant
        sim = Simulation(16)
        assert isinstance(sim.variant, GasperVariant)
        assert sim.groups[0].store.variant_view is None
        assert sim.groups[0].variant_view is None

    def test_seeded_faulted_run_identical_to_explicit_gasper(self):
        """The behavior-identity pin: Simulation() and
        Simulation(variant=GasperVariant()) produce the same heads,
        justification and finality slot by slot on a seeded faulted run,
        and the seam head equals the spec walk throughout."""
        from pos_evolution_tpu.sim import Simulation
        from pos_evolution_tpu.specs import forkchoice as fc
        from pos_evolution_tpu.variants import GasperVariant

        n = 32
        runs = []
        for variant in (None, GasperVariant()):
            sim = Simulation(n, schedule=_faulted_schedule(n),
                             variant=variant)
            heads = []
            for _ in range(12):
                sim.run_slot()
                heads.append(fc.get_head(sim.store(0)))
                assert sim.variant.head(sim, sim.groups[0]) == heads[-1]
            runs.append((sim.metrics, heads,
                         sim.justified_epoch(), sim.finalized_epoch()))
        assert runs[0] == runs[1]


class TestVariantRunsBothBackends:
    @pytest.mark.parametrize("variant_name", ["goldfish", "rlmd", "ssf"])
    def test_driver_run_bit_identical_across_backends(self, variant_name):
        from pos_evolution_tpu.sim import Simulation
        from pos_evolution_tpu.variants import VARIANTS
        runs = {}
        for backend in ("numpy", "jax"):
            set_backend(backend)
            sim = Simulation(32, variant=VARIANTS[variant_name]())
            sim.run_until_slot(10)
            runs[backend] = (sim.metrics,
                             sim.variant.state_blob(sim))
        assert runs["numpy"] == runs["jax"]

    def test_honest_runs_converge_to_spec_head(self):
        """With synchrony and honesty every variant's head equals the
        carrier's LMD head (all latest votes are fresh)."""
        from pos_evolution_tpu.sim import Simulation
        from pos_evolution_tpu.specs import forkchoice as fc
        from pos_evolution_tpu.variants import VARIANTS
        for name in ("goldfish", "rlmd", "ssf"):
            sim = Simulation(32, variant=VARIANTS[name]())
            sim.run_until_slot(10)
            assert sim.variant.head(sim, sim.groups[0]) \
                == fc.get_head(sim.store(0)), name

    def test_ssf_single_slot_finality_honest_run(self):
        """Honest synchronous run: SSF justifies and finalizes each
        round within its own processing boundary (pos-evolution.md:1646),
        tracking head_slot - 1."""
        from pos_evolution_tpu.sim import Simulation
        from pos_evolution_tpu.variants import SsfVariant
        v = SsfVariant()
        sim = Simulation(32, variant=v)
        sim.run_until_slot(10)
        fin = max(s for _, s in v.finalized[0])
        assert fin >= 8
        assert v.lj[0][1] == fin
        assert v.slashable() == set()

    def test_goldfish_fast_confirms_honest_run(self):
        from pos_evolution_tpu.sim import Simulation
        from pos_evolution_tpu.variants import GoldfishVariant
        v = GoldfishVariant()
        sim = Simulation(32, variant=v)
        sim.run_until_slot(10)
        root, slot = v.fast_confirmed[0]
        assert slot >= 8
        assert root in sim.store(0).blocks


class TestCheckpointResume:
    @pytest.mark.parametrize("variant_name", ["goldfish", "rlmd", "ssf"])
    def test_resume_matches_uninterrupted_twin(self, variant_name):
        from pos_evolution_tpu.sim import Simulation
        from pos_evolution_tpu.variants import VARIANTS
        sim = Simulation(32, variant=VARIANTS[variant_name]())
        sim.run_until_slot(8)
        blob = sim.checkpoint()
        twin = Simulation(32, variant=VARIANTS[variant_name]())
        twin.run_until_slot(16)
        resumed = Simulation.resume(blob)  # variant rebuilt from fingerprint
        assert resumed.variant.describe() == twin.variant.describe()
        resumed.run_until_slot(16)
        assert resumed.metrics == twin.metrics
        assert resumed.variant.state_blob(resumed) \
            == twin.variant.state_blob(twin)

    def test_mismatched_variant_refused(self):
        from pos_evolution_tpu.sim import Simulation
        from pos_evolution_tpu.variants import GoldfishVariant, SsfVariant
        sim = Simulation(16, variant=GoldfishVariant())
        sim.run_until_slot(3)
        blob = sim.checkpoint()
        with pytest.raises(ValueError, match="does not match"):
            Simulation.resume(blob, variant=SsfVariant())

    def test_describe_round_trips(self):
        from pos_evolution_tpu.variants import (
            VARIANTS,
            variant_from_config,
        )
        for name, cls in VARIANTS.items():
            v = cls()
            assert variant_from_config(v.describe()).describe() \
                == v.describe(), name
        # None (pre-seam checkpoint) resumes as Gasper
        assert variant_from_config(None).describe() \
            == {"kind": "GasperVariant"}


class TestVariantMatrixPins:
    """The acceptance pins of ISSUE 8, through scripts/variant_matrix.py
    run_cell (the same entry the demo uses)."""

    def test_balancer_succeeds_vs_gasper_fails_vs_goldfish(self):
        import variant_matrix
        gasper = variant_matrix.run_cell("balancer", "gasper")
        goldfish = variant_matrix.run_cell("balancer", "goldfish")
        assert gasper["verdict"]["attack_succeeded"] is True
        assert goldfish["verdict"]["attack_succeeded"] is False

    def test_exante_succeeds_vs_gasper_fails_vs_ssf(self):
        import variant_matrix
        gasper = variant_matrix.run_cell("exante", "gasper")
        ssf = variant_matrix.run_cell("exante", "ssf")
        assert gasper["verdict"]["b3_reorged"] is True
        assert ssf["verdict"]["b3_reorged"] is False

    def test_splitvoter_double_finality_accountable_under_ssf(self,
                                                              tmp_path):
        import variant_matrix
        result = variant_matrix.run_cell("splitvoter", "ssf")
        verdict = result["verdict"]
        assert verdict["finalized_conflict"] is True
        assert verdict["accountable"] is True
        assert verdict["max_evidence_stake_ratio"] >= 0.333  # >= 1/3, rounded
        # repro bundle round-trip: the bundle replays to the same verdict
        bundle = variant_matrix.write_bundle(str(tmp_path), "splitvoter",
                                             "ssf", result, None)
        replay = variant_matrix.replay_bundle(bundle)
        assert replay["match"], replay

    def test_equivocator_defended_under_every_variant(self):
        import variant_matrix
        for name in ("gasper", "ssf"):
            result = variant_matrix.run_cell("equivocator", name)
            assert result["verdict"]["attack_succeeded"] is False
            assert result["verdict"]["slasher_implicated"] > 0


class TestVariantDoctor:
    @pytest.mark.parametrize("variant_name", ["goldfish", "ssf"])
    def test_forged_variant_conflict_trips_monitor(self, variant_name):
        """The per-variant CI negative: a forged conflicting
        confirmation/finality with no evidence behind it must surface as
        an (unexplained) protocol_violation."""
        import chaos_fuzz
        cfg = chaos_fuzz.episode_config(3, 0, 32, 10, doctor=True,
                                        variant=variant_name)
        result = chaos_fuzz.run_episode(cfg)
        hits = [x for x in result["violations"]
                if x["monitor"] == "variant_safety"
                and x["kind"] == "protocol_violation"]
        assert hits, result["violations"]

    def test_store_doctor_still_trips_under_rlmd(self):
        """Variants with no forgeable variant surface fall back to the
        FFG store doctor, caught by the AccountableSafetyMonitor."""
        import chaos_fuzz
        cfg = chaos_fuzz.episode_config(3, 0, 32, 10, doctor=True,
                                        variant="rlmd")
        result = chaos_fuzz.run_episode(cfg)
        hits = [x for x in result["violations"]
                if x["monitor"] == "accountable_safety"
                and x["kind"] == "protocol_violation"]
        assert hits, result["violations"]
