"""Pallas kernel tests (interpret mode on CPU; compiled on TPU)."""

import hashlib

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from pos_evolution_tpu.ops.pallas_sha256 import (  # noqa: E402
    TILE,
    merkle_level_pallas,
    merkleize_words_device,
)
from pos_evolution_tpu.ssz.merkle import ZERO_HASHES, merkleize_chunks  # noqa: E402


def _to_words(data: np.ndarray) -> np.ndarray:
    """(N, 32) uint8 -> (N, 8) u32 big-endian words."""
    q = data.reshape(-1, 8, 4).astype(np.uint32)
    return (q[..., 0] << 24) | (q[..., 1] << 16) | (q[..., 2] << 8) | q[..., 3]


def _zero_words(depth: int) -> np.ndarray:
    return _to_words(ZERO_HASHES[: depth + 1].reshape(-1, 32))


class TestMerkleLevelKernel:
    def test_matches_hashlib(self):
        rng = np.random.default_rng(0)
        n = TILE
        left = rng.integers(0, 256, (n, 32), dtype=np.uint8)
        right = rng.integers(0, 256, (n, 32), dtype=np.uint8)
        msgs = np.concatenate([_to_words(left), _to_words(right)], axis=1)  # (n, 16)
        out = np.asarray(merkle_level_pallas(
            jax.numpy.asarray(msgs.T), interpret=True)).T
        for i in (0, 1, n // 2, n - 1):
            expect = hashlib.sha256(left[i].tobytes() + right[i].tobytes()).digest()
            got = out[i].astype(">u4").tobytes()
            assert got == expect, f"row {i} mismatch"

    def test_unrolled_rounds_graph_on_cpu(self):
        """The unrolled (unroll=True) round graph — the form Mosaic
        compiles on TPU — pinned against hashlib on CPU. Runs `_rounds` /
        `_schedule` eagerly outside pallas_call: the pallas interpreter
        always jits its kernel, and the fully-unrolled SHA graph sends
        XLA:CPU's algebraic simplifier into a multi-minute loop, so the
        ref-plumbing wrapper stays covered by the loop-form interpret
        tests while the unrolled arithmetic is pinned here."""
        import jax.numpy as jnp

        from pos_evolution_tpu.ops.pallas_sha256 import (
            H0, _rounds, _schedule,
        )

        rng = np.random.default_rng(7)
        n = 8
        msgs = rng.integers(0, 2**32, (16, n), dtype=np.uint64).astype(np.uint32)
        w_stack = _schedule([jnp.asarray(msgs[t:t + 1, :]) for t in range(16)])
        init = tuple(jnp.full((1, n), np.uint32(H0[i])) for i in range(8))
        fin = _rounds(init, w_stack, unroll=True)
        state1 = np.stack([np.asarray(fin[i] + init[i])[0] for i in range(8)])
        # second block: fixed padding for a 64-byte message
        zero = jnp.zeros((1, n), dtype=jnp.uint32)
        pad16 = [zero] * 16
        pad16[0] = jnp.full((1, n), np.uint32(0x80000000))
        pad16[15] = jnp.full((1, n), np.uint32(512))
        fin2 = _rounds(tuple(jnp.asarray(state1[i:i + 1]) for i in range(8)),
                       _schedule(pad16), unroll=True)
        out = np.stack([np.asarray(fin2[i])[0] + state1[i] for i in range(8)])
        for col in (0, 3, n - 1):
            assert out[:, col].astype(">u4").tobytes() == \
                hashlib.sha256(msgs[:, col].astype(">u4").tobytes()).digest()

    def test_multi_tile_grid(self):
        rng = np.random.default_rng(1)
        n = 2 * TILE
        msgs = rng.integers(0, 2**32, (16, n), dtype=np.uint64).astype(np.uint32)
        out = np.asarray(merkle_level_pallas(jax.numpy.asarray(msgs), interpret=True))
        assert out.shape == (8, n)
        # spot-check one column against hashlib
        col = 777
        block = msgs[:, col].astype(">u4").tobytes()
        assert out[:, col].astype(">u4").tobytes() == \
            hashlib.sha256(block).digest()


class TestPallasAggregation:
    @pytest.mark.slow
    def test_matches_fakebls_and_xla(self):
        from pos_evolution_tpu.crypto.bls import FakeBLS
        from pos_evolution_tpu.ops.aggregation import (
            aggregate_verify_batch, messages_to_words, pack_signature_words,
            precompute_pk_states,
        )
        from pos_evolution_tpu.ops.pallas_aggregation import (
            aggregate_verify_batch_pallas,
        )
        import jax.numpy as jnp
        rng = np.random.default_rng(0)
        N, A, C = 32, 3, 8
        pubkeys = np.stack([np.frombuffer(FakeBLS.SkToPk(i + 1), np.uint8)
                            for i in range(N)])
        pk_states = precompute_pk_states(pubkeys)
        committees = rng.permutation(N)[:A * C].reshape(A, C).astype(np.int32)
        bits = rng.random((A, C)) < 0.7
        bits[:, 0] = True
        messages = rng.integers(0, 255, (A, 32)).astype(np.uint8)
        sigs = []
        for a in range(A):
            parts = [FakeBLS._sig_for(pubkeys[v].tobytes(), messages[a].tobytes())
                     for v, b in zip(committees[a], bits[a]) if b]
            sigs.append(FakeBLS.Aggregate(parts))
        sw = jnp.asarray(pack_signature_words(sigs))
        mw = jnp.asarray(messages_to_words(messages))
        ok_xla = np.asarray(aggregate_verify_batch(
            pk_states, jnp.asarray(committees), jnp.asarray(bits), mw, sw))
        ok_pl = np.asarray(aggregate_verify_batch_pallas(
            pk_states, jnp.asarray(committees), jnp.asarray(bits), mw, sw,
            interpret=True))
        assert ok_xla.all() and ok_pl.all()
        bad_sw = np.asarray(sw).copy()
        bad_sw[1, 3] ^= 4
        bad = np.asarray(aggregate_verify_batch_pallas(
            pk_states, jnp.asarray(committees), jnp.asarray(bits), mw,
            jnp.asarray(bad_sw), interpret=True))
        assert not bad[1] and bad[0] and bad[2]


class TestCompiledOnAccelerator:
    """Mosaic-compiled (non-interpret) kernel coverage — runs only when an
    accelerator backend is active (the CPU suite covers interpret mode)."""

    @pytest.fixture(autouse=True)
    def _need_accelerator(self):
        if jax.default_backend() == "cpu":
            pytest.skip("no accelerator for compiled Pallas kernels")

    def test_compiled_merkle_level(self):
        import hashlib
        rng = np.random.default_rng(3)
        msgs = rng.integers(0, 2**32, (16, TILE), dtype=np.uint64).astype(np.uint32)
        out = np.asarray(merkle_level_pallas(jax.numpy.asarray(msgs)))
        col = 17
        assert out[:, col].astype(">u4").tobytes() == \
            hashlib.sha256(msgs[:, col].astype(">u4").tobytes()).digest()

    def test_compiled_aggregation(self):
        import jax.numpy as jnp
        from pos_evolution_tpu.crypto.bls import FakeBLS
        from pos_evolution_tpu.ops.aggregation import (
            messages_to_words, pack_signature_words, precompute_pk_states,
        )
        from pos_evolution_tpu.ops.pallas_aggregation import (
            aggregate_verify_batch_pallas_jit,
        )
        rng = np.random.default_rng(4)
        N, A, C = 64, 2, 16
        pubkeys = np.stack([np.frombuffer(FakeBLS.SkToPk(i + 1), np.uint8)
                            for i in range(N)])
        pk_states = precompute_pk_states(pubkeys)
        committees = rng.permutation(N)[:A * C].reshape(A, C).astype(np.int32)
        bits = np.ones((A, C), dtype=bool)
        messages = rng.integers(0, 255, (A, 32)).astype(np.uint8)
        sigs = [FakeBLS.Aggregate(
            [FakeBLS._sig_for(pubkeys[v].tobytes(), messages[a].tobytes())
             for v in committees[a]]) for a in range(A)]
        ok = np.asarray(aggregate_verify_batch_pallas_jit(
            pk_states, jnp.asarray(committees), jnp.asarray(bits),
            jnp.asarray(messages_to_words(messages)),
            jnp.asarray(pack_signature_words(sigs))))
        assert ok.all()


class TestDeviceMerkleize:
    @pytest.mark.parametrize(
        "n,depth",
        [(8, 3), (8, 6),
         pytest.param(1024, 10, marks=pytest.mark.slow)])
    def test_matches_host_merkleize(self, n, depth):
        rng = np.random.default_rng(n)
        chunks = rng.integers(0, 256, (n, 32), dtype=np.uint8)
        want = merkleize_chunks(chunks, limit=2**depth)
        got = np.asarray(merkleize_words_device(
            jax.numpy.asarray(_to_words(chunks)), depth, _zero_words(depth),
            use_pallas=(n // 2 % TILE == 0), interpret=True))
        assert got.astype(">u4").tobytes() == want
