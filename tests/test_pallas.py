"""Pallas kernel tests (interpret mode on CPU; compiled on TPU)."""

import hashlib

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from pos_evolution_tpu.ops.pallas_sha256 import (  # noqa: E402
    TILE,
    merkle_level_pallas,
    merkleize_words_device,
)
from pos_evolution_tpu.ssz.merkle import ZERO_HASHES, merkleize_chunks  # noqa: E402


def _to_words(data: np.ndarray) -> np.ndarray:
    """(N, 32) uint8 -> (N, 8) u32 big-endian words."""
    q = data.reshape(-1, 8, 4).astype(np.uint32)
    return (q[..., 0] << 24) | (q[..., 1] << 16) | (q[..., 2] << 8) | q[..., 3]


def _zero_words(depth: int) -> np.ndarray:
    return _to_words(ZERO_HASHES[: depth + 1].reshape(-1, 32))


class TestMerkleLevelKernel:
    def test_matches_hashlib(self):
        rng = np.random.default_rng(0)
        n = TILE
        left = rng.integers(0, 256, (n, 32), dtype=np.uint8)
        right = rng.integers(0, 256, (n, 32), dtype=np.uint8)
        msgs = np.concatenate([_to_words(left), _to_words(right)], axis=1)  # (n, 16)
        out = np.asarray(merkle_level_pallas(
            jax.numpy.asarray(msgs.T), interpret=True)).T
        for i in (0, 1, n // 2, n - 1):
            expect = hashlib.sha256(left[i].tobytes() + right[i].tobytes()).digest()
            got = out[i].astype(">u4").tobytes()
            assert got == expect, f"row {i} mismatch"

    def test_multi_tile_grid(self):
        rng = np.random.default_rng(1)
        n = 2 * TILE
        msgs = rng.integers(0, 2**32, (16, n), dtype=np.uint64).astype(np.uint32)
        out = np.asarray(merkle_level_pallas(jax.numpy.asarray(msgs), interpret=True))
        assert out.shape == (8, n)
        # spot-check one column against hashlib
        col = 777
        block = msgs[:, col].astype(">u4").tobytes()
        assert out[:, col].astype(">u4").tobytes() == \
            hashlib.sha256(block).digest()


class TestDeviceMerkleize:
    @pytest.mark.parametrize("n,depth", [(8, 3), (8, 6), (1024, 10)])
    def test_matches_host_merkleize(self, n, depth):
        rng = np.random.default_rng(n)
        chunks = rng.integers(0, 256, (n, 32), dtype=np.uint8)
        want = merkleize_chunks(chunks, limit=2**depth)
        got = np.asarray(merkleize_words_device(
            jax.numpy.asarray(_to_words(chunks)), depth, _zero_words(depth),
            use_pallas=(n // 2 % TILE == 0), interpret=True))
        assert got.astype(">u4").tobytes() == want
