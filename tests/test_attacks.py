"""Attack-scenario regression tests (SURVEY.md §2.10, §4.2).

Each documented attack is reproduced with the reference's own numbers,
and each documented mitigation is shown to block the corresponding
attack. The headline scenarios run IN-LOOP through ``Simulation`` via
``AdversaryStrategy`` (sim/adversary.py); ``TestScriptedOracleParity``
pins their asserted outcomes bit-identical to the original one-shot
scripted reproductions (``scripted_run_*``), which stay in the file as
ground truth.
"""

from pos_evolution_tpu.config import minimal_config, use_config
from pos_evolution_tpu.sim.attacks import (
    run_balancing_attack,
    run_bouncing_attack_step,
    run_ex_ante_reorg,
    run_ex_ante_reorg_with_boost,
    run_lmd_balancing_attack,
    scripted_run_ex_ante_reorg,
    scripted_run_ex_ante_reorg_with_boost,
    scripted_run_lmd_balancing_attack,
)


class TestExAnteReorg:
    def test_succeeds_without_boost(self):
        """pos-evolution.md:1516-1522: hidden block + one private attestation
        reorgs the next honest proposal in pre-boost Gasper."""
        with use_config(minimal_config().replace(proposer_score_boost_percent=0)):
            r = run_ex_ante_reorg(64)
        assert r["b3_reorged"]
        assert r["b2_canonical"]

    def test_blocked_by_mainline_boost(self):
        """pos-evolution.md:1350-1355: W/4 proposer boost defeats the simple
        one-attestation ex-ante reorg."""
        with use_config(minimal_config().replace(proposer_score_boost_percent=25)):
            r = run_ex_ante_reorg(64)
        assert not r["b3_reorged"]

    def test_seven_percent_defeats_point8_boost(self):
        """pos-evolution.md:1525-1526: with W_p = 0.8W, a 7% adversary still
        reorgs (7 + 7 + 80 = 94 > 93)."""
        with use_config(minimal_config().replace(proposer_score_boost_percent=80)):
            r = run_ex_ante_reorg_with_boost(800)
        assert r["per_slot_committee"] == 100
        assert r["b3_reorged"]
        assert r["b4_canonical"] and r["b2_canonical"]


class TestBouncingAttack:
    def test_conflicting_justification_deferred_then_promoted(self):
        """pos-evolution.md:1065-1072: a conflicting higher justification
        released past SAFE_SLOTS_TO_UPDATE_JUSTIFIED must NOT flip the
        store's justified checkpoint mid-epoch (the bounce), only
        best_justified; the epoch boundary promotes it (:950-955)."""
        with use_config(minimal_config()):
            r = run_bouncing_attack_step(64)
        assert r["phase1_justified"] == 2 and r["phase1_is_chain_a"]
        assert r["deferral_held"], "mid-epoch bounce was not prevented"
        assert r["best_after_release"] == 3
        assert r["promoted_at_boundary"] == 3 and r["promoted_is_chain_b"]


class TestLMDBalancingDespiteBoost:
    def test_views_split_80_0_and_heads_never_converge(self):
        """pos-evolution.md:1379-1403 with the reference's numbers: W=100
        per slot, 20 Byzantine per slot, W_p = 0.7W. After the slot-5
        release each half's LMD table credits its chain 80:0 (:1394; with
        the boost the leading view shows 150, :1396), and honest votes keep
        splitting every slot despite boost."""
        with use_config(minimal_config().replace(proposer_score_boost_percent=70)):
            r = run_lmd_balancing_attack(800)
        # 80 equivocating votes + 70 boost on the released block (:1396)
        assert r["viewA_L_votes"] == 150 and r["viewA_R_votes"] == 0
        assert r["viewB_R_votes"] == 150 and r["viewB_L_votes"] == 0
        assert all(r["heads_disagree"]), r["heads_disagree"]
        assert r["justified_A"] == 0 and r["justified_B"] == 0


class TestScriptedOracleParity:
    """The Simulation-driven scenarios must reproduce the scripted
    oracles' asserted outcomes bit-identically: same booleans, same vote
    ledgers, same justification — the refactor moved the adversary
    in-loop without changing what the reference says happens."""

    def test_ex_ante_reorg_all_boost_regimes(self):
        for boost in (0, 25):
            with use_config(minimal_config().replace(
                    proposer_score_boost_percent=boost)):
                sim_r = run_ex_ante_reorg(64)
                ora_r = scripted_run_ex_ante_reorg(64)
            for key in ("b3_reorged", "b2_canonical"):
                assert sim_r[key] == ora_r[key], (boost, key)

    def test_ex_ante_reorg_with_boost(self):
        with use_config(minimal_config().replace(
                proposer_score_boost_percent=80)):
            sim_r = run_ex_ante_reorg_with_boost(800)
            ora_r = scripted_run_ex_ante_reorg_with_boost(800)
        for key in ("per_slot_committee", "b3_reorged", "b4_canonical",
                    "b2_canonical"):
            assert sim_r[key] == ora_r[key], key

    def test_lmd_balancing(self):
        with use_config(minimal_config().replace(
                proposer_score_boost_percent=70)):
            sim_r = run_lmd_balancing_attack(800)
            ora_r = scripted_run_lmd_balancing_attack(800)
        assert sim_r == ora_r


class TestBalancingAttack:
    def test_halts_finality_in_preboost_gasper(self):
        """pos-evolution.md:1321-1348: equivocating proposer + swayer votes
        keep two chains tied; no checkpoint beyond genesis justifies."""
        # The reference assumes enough Byzantine validators in *every* slot
        # committee (:1330 "at least six Byzantine validators in every
        # slot"); with random committee draws a 30% pool guarantees the
        # per-slot swayer budget.
        with use_config(minimal_config().replace(proposer_score_boost_percent=0)):
            r = run_balancing_attack(64, n_epochs=4, corrupted_fraction=0.3)
        assert r.tie_maintained, "adversary lost the tie"
        assert r.head_L != r.head_R, "views converged"
        assert r.finalized_epoch_L == 0 and r.finalized_epoch_R == 0
        assert r.justified_epoch_L == 0 and r.justified_epoch_R == 0

    def test_honest_control_run_finalizes(self):
        """Same protocol parameters without the adversary do finalize —
        the attack, not the config, halts finality."""
        with use_config(minimal_config().replace(proposer_score_boost_percent=0)):
            from pos_evolution_tpu.sim import Simulation
            sim = Simulation(64)
            sim.run_epochs(4)
            assert sim.finalized_epoch() >= 2
