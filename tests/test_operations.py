"""Block-operation and epoch-machinery tests: proposer/attester slashings,
voluntary exits, eth1 voting, sync aggregates, registry churn, inactivity
leak (SURVEY.md §2.2, §2.6).
"""

import numpy as np
import pytest

from pos_evolution_tpu.config import (
    DOMAIN_BEACON_PROPOSER,
    DOMAIN_VOLUNTARY_EXIT,
    FAR_FUTURE_EPOCH,
    cfg,
)
from pos_evolution_tpu.crypto.bls import bls
from pos_evolution_tpu.specs.containers import (
    AttesterSlashing,
    BeaconBlockHeader,
    Eth1Data,
    ProposerSlashing,
    SignedBeaconBlockHeader,
    SignedVoluntaryExit,
    VoluntaryExit,
)
from pos_evolution_tpu.specs.epoch import process_registry_updates
from pos_evolution_tpu.specs.genesis import make_genesis, validator_secret_key
from pos_evolution_tpu.specs.helpers import (
    compute_signing_root,
    get_domain,
    get_indexed_attestation,
)
from pos_evolution_tpu.specs.transition import (
    process_attester_slashing,
    process_eth1_data,
    process_proposer_slashing,
    process_sync_aggregate,
    process_voluntary_exit,
    state_transition,
)
from pos_evolution_tpu.specs.validator import (
    build_block,
    make_committee_attestation,
    advance_state_to_slot,
)
from pos_evolution_tpu.ssz import hash_tree_root

pytestmark = pytest.mark.usefixtures("minimal_cfg")


def _signed_header(state, proposer: int, slot: int, body_root: bytes):
    header = BeaconBlockHeader(slot=slot, proposer_index=proposer,
                               parent_root=b"\x01" * 32, state_root=b"\x02" * 32,
                               body_root=body_root)
    domain = get_domain(state, DOMAIN_BEACON_PROPOSER, 0)
    sig = bls.Sign(validator_secret_key(proposer),
                   compute_signing_root(header, domain))
    return SignedBeaconBlockHeader(message=header, signature=sig)


class TestProposerSlashing:
    def test_double_proposal_slashed(self):
        state, _ = make_genesis(16)
        h1 = _signed_header(state, 3, 1, b"\xaa" * 32)
        h2 = _signed_header(state, 3, 1, b"\xbb" * 32)
        slashing = ProposerSlashing(signed_header_1=h1, signed_header_2=h2)
        before = int(state.balances[3])
        process_proposer_slashing(state, slashing)
        assert bool(state.validators.slashed[3])
        assert int(state.balances[3]) < before
        assert int(state.validators.exit_epoch[3]) != FAR_FUTURE_EPOCH

    def test_identical_headers_rejected(self):
        state, _ = make_genesis(16)
        h1 = _signed_header(state, 3, 1, b"\xaa" * 32)
        slashing = ProposerSlashing(signed_header_1=h1, signed_header_2=h1.copy())
        with pytest.raises(AssertionError):
            process_proposer_slashing(state, slashing)

    def test_different_proposers_rejected(self):
        state, _ = make_genesis(16)
        slashing = ProposerSlashing(
            signed_header_1=_signed_header(state, 3, 1, b"\xaa" * 32),
            signed_header_2=_signed_header(state, 4, 1, b"\xbb" * 32))
        with pytest.raises(AssertionError):
            process_proposer_slashing(state, slashing)


class TestAttesterSlashingOperation:
    def test_double_vote_slashes_intersection(self):
        state, _ = make_genesis(32)
        sb = build_block(state, 1)
        state_transition(state, sb, True)
        root = hash_tree_root(sb.message)
        a1 = make_committee_attestation(state, 1, 0, root)
        a2 = make_committee_attestation(state, 1, 0, b"\x42" * 32)
        i1 = get_indexed_attestation(state, a1)
        # second attestation needs a consistent signature over its data
        from pos_evolution_tpu.specs.validator import sign_attestation_data
        sigs = [sign_attestation_data(state, a2.data, int(v))
                for v in np.asarray(get_indexed_attestation(state, a2).attesting_indices)]
        a2.signature = bls.Aggregate(sigs)
        i2 = get_indexed_attestation(state, a2)
        slashing = AttesterSlashing(attestation_1=i1, attestation_2=i2)
        process_attester_slashing(state, slashing)
        for v in np.asarray(i1.attesting_indices):
            assert bool(state.validators.slashed[int(v)])


class TestVoluntaryExit:
    def _signed_exit(self, state, index: int, epoch: int = 0):
        msg = VoluntaryExit(epoch=epoch, validator_index=index)
        domain = get_domain(state, DOMAIN_VOLUNTARY_EXIT, epoch)
        sig = bls.Sign(validator_secret_key(index),
                       compute_signing_root(msg, domain))
        return SignedVoluntaryExit(message=msg, signature=sig)

    def test_exit_after_minimum_service(self):
        state, _ = make_genesis(16)
        c = cfg()
        state.slot = (c.shard_committee_period + 1) * c.slots_per_epoch
        process_voluntary_exit(state, self._signed_exit(state, 7))
        assert int(state.validators.exit_epoch[7]) != FAR_FUTURE_EPOCH

    def test_exit_too_early_rejected(self):
        state, _ = make_genesis(16)
        with pytest.raises(AssertionError):
            process_voluntary_exit(state, self._signed_exit(state, 7))

    def test_exit_queue_respects_churn(self):
        state, _ = make_genesis(16)
        c = cfg()
        state.slot = (c.shard_committee_period + 1) * c.slots_per_epoch
        for i in range(8):
            process_voluntary_exit(state, self._signed_exit(state, i))
        exit_epochs = state.validators.exit_epoch[:8]
        counts = {}
        for e in exit_epochs:
            counts[int(e)] = counts.get(int(e), 0) + 1
        assert max(counts.values()) <= max(
            c.min_per_epoch_churn_limit, 16 // c.churn_limit_quotient)


class TestEth1Voting:
    def test_majority_adopts_new_eth1_data(self):
        state, _ = make_genesis(8)
        c = cfg()
        vote = Eth1Data(deposit_root=b"\x0e" * 32, deposit_count=99,
                        block_hash=b"\x0f" * 32)
        period_len = c.epochs_per_eth1_voting_period * c.slots_per_epoch
        needed = period_len // 2 + 1

        class Body:
            eth1_data = vote
        for _ in range(needed):
            process_eth1_data(state, Body)
        assert state.eth1_data == vote


class TestSyncAggregate:
    def test_participants_rewarded_absentees_penalized(self):
        state, _ = make_genesis(16)
        from pos_evolution_tpu.specs.transition import (
            compute_signing_root_bytes, process_slot,
        )
        from pos_evolution_tpu.specs.containers import SyncAggregate
        from pos_evolution_tpu.config import DOMAIN_SYNC_COMMITTEE
        process_slot(state)
        state.slot = 1
        committee_pks = [bytes(pk) for pk in state.current_sync_committee.pubkeys]
        bits = np.zeros(len(committee_pks), dtype=bool)
        bits[: len(bits) // 2] = True
        from pos_evolution_tpu.specs.helpers import get_block_root_at_slot, get_domain
        domain = get_domain(state, DOMAIN_SYNC_COMMITTEE, 0)
        signing_root = compute_signing_root_bytes(
            get_block_root_at_slot(state, 0), domain)
        # sign with each participating member's key (pk -> index lookup)
        sigs = []
        for pk, b in zip(committee_pks, bits):
            if not b:
                continue
            idx = state.validators.find_pubkey(pk)
            sigs.append(bls.Sign(validator_secret_key(idx), signing_root))
        agg = SyncAggregate(sync_committee_bits=bits,
                            sync_committee_signature=bls.Aggregate(sigs))
        balances_before = state.balances.copy()
        process_sync_aggregate(state, agg)
        deltas = state.balances.astype(np.int64) - balances_before.astype(np.int64)
        # exact accounting: +r per participating seat, -r per absent seat,
        # + proposer reward per participating seat (committee seats may
        # repeat validators at small n, so compare per-validator sums)
        from pos_evolution_tpu.config import (
            PROPOSER_WEIGHT, SYNC_REWARD_WEIGHT, WEIGHT_DENOMINATOR,
        )
        from pos_evolution_tpu.specs.helpers import (
            get_base_reward_per_increment, get_beacon_proposer_index,
            get_total_active_balance,
        )
        c = cfg()
        total_incr = get_total_active_balance(state) // c.effective_balance_increment
        total_base = get_base_reward_per_increment(state) * total_incr
        max_rewards = (total_base * SYNC_REWARD_WEIGHT
                       // WEIGHT_DENOMINATOR // c.slots_per_epoch)
        r = max_rewards // len(committee_pks)
        pr = r * PROPOSER_WEIGHT // (WEIGHT_DENOMINATOR - PROPOSER_WEIGHT)
        expected = np.zeros(16, dtype=np.int64)
        proposer = get_beacon_proposer_index(state)
        for pk, b in zip(committee_pks, bits):
            idx = state.validators.find_pubkey(pk)
            expected[idx] += r if b else -r
            if b:
                expected[proposer] += pr
        assert np.array_equal(deltas, expected)
        assert r > 0  # rewards are actually flowing


class TestRegistryChurn:
    def test_new_deposit_activates_through_queue(self):
        state, _ = make_genesis(16)
        c = cfg()
        from pos_evolution_tpu.specs.containers import Validator
        v = Validator(pubkey=b"\x99" * 48, withdrawal_credentials=b"\x00" * 32,
                      effective_balance=c.max_effective_balance,
                      activation_eligibility_epoch=FAR_FUTURE_EPOCH,
                      activation_epoch=FAR_FUTURE_EPOCH,
                      exit_epoch=FAR_FUTURE_EPOCH,
                      withdrawable_epoch=FAR_FUTURE_EPOCH)
        state.validators.append(v)
        state.balances = np.append(state.balances,
                                   np.uint64(c.max_effective_balance))
        state.previous_epoch_participation = np.append(
            state.previous_epoch_participation, np.uint8(0))
        state.current_epoch_participation = np.append(
            state.current_epoch_participation, np.uint8(0))
        state.inactivity_scores = np.append(state.inactivity_scores, np.uint64(0))

        process_registry_updates(state)  # marks eligibility
        assert int(state.validators.activation_eligibility_epoch[16]) == 1
        # once finality passes the eligibility epoch, the queue activates it
        from pos_evolution_tpu.specs.containers import Checkpoint
        state.slot = 5 * c.slots_per_epoch
        state.finalized_checkpoint = Checkpoint(epoch=4, root=b"\x01" * 32)
        process_registry_updates(state)
        assert int(state.validators.activation_epoch[16]) != FAR_FUTURE_EPOCH

    def test_low_balance_ejected(self):
        state, _ = make_genesis(16)
        c = cfg()
        state.validators.effective_balance[5] = c.ejection_balance
        process_registry_updates(state)
        assert int(state.validators.exit_epoch[5]) != FAR_FUTURE_EPOCH


class TestInactivityLeak:
    def test_leak_drains_offline_and_recovers(self):
        """Quadratic leak (pos-evolution.md:369 machinery): during long
        non-finality, non-participants bleed stake; participants do not."""
        from pos_evolution_tpu.specs import epoch as spec_epoch
        from pos_evolution_tpu.specs.containers import Checkpoint
        state, _ = make_genesis(16)
        c = cfg()
        offline = np.arange(16) >= 10
        start_balance = state.balances.copy()
        for e in range(2, 14):
            state.slot = (e + 1) * c.slots_per_epoch - 1
            # finality stuck at epoch 0 -> leak after 4 epochs
            flags = np.where(offline, 0, 0b111).astype(np.uint8)
            state.previous_epoch_participation = flags.copy()
            state.current_epoch_participation = flags.copy()
            spec_epoch.process_inactivity_updates(state)
            spec_epoch.process_rewards_and_penalties(state)
            state.slot = (e + 1) * c.slots_per_epoch
        online_delta = state.balances[~offline].astype(np.int64) \
            - start_balance[~offline].astype(np.int64)
        offline_delta = state.balances[offline].astype(np.int64) \
            - start_balance[offline].astype(np.int64)
        assert (offline_delta < 0).all(), "offline validators did not leak"
        assert offline_delta.mean() < online_delta.mean() * 5, "leak not dominant"
        assert int(state.inactivity_scores[offline][0]) > 0
        assert int(state.inactivity_scores[~offline][0]) == 0
