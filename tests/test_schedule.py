"""Dedicated coverage for sim/schedule.py (previously only exercised
indirectly through test_sim): Schedule defaults and group algebra, the
canned schedule builders, the adversarial split builders from
sim/attacks.py, and the committee/proposer scheduling invariants the
adversary engine's per-slot arithmetic relies on."""

import numpy as np
import pytest

from pos_evolution_tpu.config import minimal_config, use_config
from pos_evolution_tpu.sim.schedule import (
    Schedule,
    faulty_schedule,
    honest_schedule,
    partition_schedule,
)

pytestmark = pytest.mark.usefixtures("minimal_cfg")


class TestScheduleDefaults:
    def test_honest_schedule_is_single_synchronous_view(self):
        s = honest_schedule(16)
        assert s.n_groups == 1
        assert list(s.members(0)) == list(range(16))
        assert list(s.honest_members(0)) == list(range(16))
        assert s.awake(0, 0) and s.awake(99, 15)
        assert s.block_delay(0, 1, 0) == 0.0
        assert s.attestation_delay(0, 1, 0) == 0.0
        assert s.faults is None

    def test_group_of_coerced_to_int64(self):
        s = Schedule(n_validators=4, group_of=[0, 1, 0, 1])
        assert s.group_of.dtype == np.int64
        assert s.n_groups == 2

    def test_members_partition_the_validator_set(self):
        s = partition_schedule(10, 3)
        all_members = np.concatenate([s.members(g) for g in range(s.n_groups)])
        assert sorted(all_members.tolist()) == list(range(10))

    def test_honest_members_excludes_corrupted(self):
        s = partition_schedule(8, 2, corrupted={0, 3})
        assert 0 not in s.honest_members(0)
        assert 3 not in s.honest_members(1)
        assert set(s.members(0).tolist()) - set(s.honest_members(0).tolist()) \
            == {0}

    def test_faulty_schedule_attaches_plan(self):
        from pos_evolution_tpu.sim.faults import FaultPlan
        plan = FaultPlan(seed=1, drop_p=0.5)
        assert faulty_schedule(8, plan).n_groups == 1
        s = faulty_schedule(8, plan, n_groups=2)
        assert s.faults is plan and s.n_groups == 2


class TestAdversarialSplitBuilders:
    def test_balanced_split_halves_the_honest_set(self):
        from pos_evolution_tpu.sim.attacks import balanced_split_schedule
        corrupted = set(range(10))
        s = balanced_split_schedule(64, corrupted)
        h0, h1 = s.honest_members(0), s.honest_members(1)
        assert len(h0) == len(h1) == (64 - 10) // 2
        assert s.block_delay(0, 1, 1) == 0.0  # not isolated

    def test_split_brain_withholds_all_cross_group_delivery(self):
        from pos_evolution_tpu.sim.attacks import split_brain_schedule
        s = split_brain_schedule(64, set(range(10)))
        v0 = int(s.members(0)[0])
        v1 = int(s.members(1)[0])
        assert s.block_delay(v0, 3, 0) == 0.0
        assert s.block_delay(v0, 3, 1) is None
        assert s.block_delay(v1, 3, 0) is None
        assert s.attestation_delay(0, 3, 1) is None
        assert s.attestation_delay(1, 3, 1) == 0.0

    def test_committee_balanced_split_balances_every_epoch0_slot(self):
        from pos_evolution_tpu.sim.adversary import slot_committee
        from pos_evolution_tpu.sim.attacks import (
            committee_balanced_split_schedule,
        )
        from pos_evolution_tpu.config import cfg
        from pos_evolution_tpu.specs.genesis import make_genesis
        from pos_evolution_tpu.specs.validator import advance_state_to_slot
        n = 64
        corrupted = set(range(19))
        s = committee_balanced_split_schedule(n, corrupted)
        state, _ = make_genesis(n)
        for slot in range(1, cfg().slots_per_epoch):
            committee = [int(v) for v in slot_committee(
                advance_state_to_slot(state, slot), slot)]
            honest = [v for v in committee if v not in corrupted]
            sides = [int(s.group_of[v]) for v in honest]
            assert abs(sides.count(0) - sides.count(1)) <= 1, \
                f"slot {slot} honest committee not balanced"


class TestCommitteeProposerScheduling:
    """The spec-side scheduling the Schedule's group policies are applied
    over: every validator attests exactly once per epoch, and the
    proposer rotation is a deterministic function of the state."""

    def test_slot_committees_partition_the_epoch(self):
        from pos_evolution_tpu.config import cfg
        from pos_evolution_tpu.sim.adversary import slot_committee
        from pos_evolution_tpu.specs.genesis import make_genesis
        from pos_evolution_tpu.specs.validator import advance_state_to_slot
        n = 48
        state, _ = make_genesis(n)
        seen = []
        for slot in range(cfg().slots_per_epoch):
            view = advance_state_to_slot(state, max(slot, 1))
            seen.extend(int(v) for v in slot_committee(view, slot))
        assert sorted(seen) == list(range(n))

    def test_proposer_is_deterministic_and_in_range(self):
        from pos_evolution_tpu.specs.genesis import make_genesis
        from pos_evolution_tpu.specs.helpers import (
            get_beacon_proposer_index,
        )
        from pos_evolution_tpu.specs.validator import advance_state_to_slot
        n = 32
        state, _ = make_genesis(n)
        for slot in (1, 2, 5):
            view = advance_state_to_slot(state, slot)
            p1 = int(get_beacon_proposer_index(view))
            p2 = int(get_beacon_proposer_index(
                advance_state_to_slot(state, slot)))
            assert p1 == p2
            assert 0 <= p1 < n

    def test_committee_assignment_stable_across_config_reentry(self):
        """Same config, same genesis -> same committees (what the chaos
        fuzzer's episode-ordering independence rests on)."""
        from pos_evolution_tpu.sim.adversary import slot_committee
        from pos_evolution_tpu.specs.genesis import make_genesis
        from pos_evolution_tpu.specs.validator import advance_state_to_slot

        def epoch0(n):
            with use_config(minimal_config()):
                state, _ = make_genesis(n)
                return [tuple(int(v) for v in slot_committee(
                    advance_state_to_slot(state, max(s, 1)), s))
                    for s in range(minimal_config().slots_per_epoch)]

        assert epoch0(48) == epoch0(48)
