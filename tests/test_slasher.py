"""Slasher tests: double-vote/surround/equivocating-proposal detection and
the full accountability loop (evidence -> processing -> stake slashed +
fork-choice discounting).
"""

import numpy as np
import pytest

from pos_evolution_tpu.config import DOMAIN_BEACON_PROPOSER, cfg
from pos_evolution_tpu.crypto.bls import bls
from pos_evolution_tpu.specs import forkchoice as fc
from pos_evolution_tpu.specs.containers import (
    AttestationData, BeaconBlockHeader, Checkpoint, IndexedAttestation,
    SignedBeaconBlockHeader,
)
from pos_evolution_tpu.specs.genesis import make_genesis, validator_secret_key
from pos_evolution_tpu.specs.helpers import (
    compute_signing_root, get_domain, get_indexed_attestation,
)
from pos_evolution_tpu.specs.slasher import Slasher
from pos_evolution_tpu.specs.validator import build_block, make_committee_attestation
from pos_evolution_tpu.ssz import hash_tree_root

pytestmark = pytest.mark.usefixtures("minimal_cfg")


def _indexed(validators, source, target, tag=0):
    return IndexedAttestation(
        attesting_indices=np.array(sorted(validators), dtype=np.uint64),
        data=AttestationData(
            slot=target * 8, index=0, beacon_block_root=bytes([tag]) * 32,
            source=Checkpoint(epoch=source, root=bytes([source]) * 32),
            target=Checkpoint(epoch=target, root=bytes([(target * 7 + tag) % 256]) * 32)),
        signature=b"\x00" * 96)


class TestAttesterDetection:
    def test_double_vote_detected_once(self):
        s = Slasher()
        assert s.on_attestation(_indexed([1, 2, 3], 2, 5, tag=0)) == []
        ev = s.on_attestation(_indexed([3, 4], 2, 5, tag=7))
        assert len(ev) == 1
        common = set(int(i) for i in np.asarray(ev[0].attestation_1.attesting_indices)) \
            & set(int(i) for i in np.asarray(ev[0].attestation_2.attesting_indices))
        assert common == {3}
        # replay produces no duplicate evidence
        assert s.on_attestation(_indexed([3, 4], 2, 5, tag=7)) == []

    def test_surround_detected_both_directions(self):
        s = Slasher()
        s.on_attestation(_indexed([5], 2, 5))
        ev = s.on_attestation(_indexed([5], 1, 6))  # surrounds the first
        assert len(ev) == 1
        s2 = Slasher()
        s2.on_attestation(_indexed([6], 1, 6))
        ev2 = s2.on_attestation(_indexed([6], 2, 5))  # surrounded by the first
        assert len(ev2) == 1
        # attestation_1 must be the surrounding vote (valid evidence order)
        from pos_evolution_tpu.specs.helpers import is_slashable_attestation_data
        assert is_slashable_attestation_data(ev2[0].attestation_1.data,
                                             ev2[0].attestation_2.data)

    def test_late_equivocator_same_pair_still_reported(self):
        """A validator whose equivocation is covered by a data pair that
        already produced evidence (for someone else) must still be
        reported when their aggregate arrives later."""
        s = Slasher()
        s.on_attestation(_indexed([1, 2], 2, 5, tag=0))
        ev1 = s.on_attestation(_indexed([1], 2, 5, tag=7))      # implicates 1
        assert len(ev1) == 1
        ev2 = s.on_attestation(_indexed([2], 2, 5, tag=7))      # now 2 too
        assert len(ev2) == 1
        common = set(int(i) for i in np.asarray(ev2[0].attestation_1.attesting_indices)) \
            & set(int(i) for i in np.asarray(ev2[0].attestation_2.attesting_indices))
        assert 2 in common

    def test_distinct_aggregates_same_data_all_covered(self):
        """Priors that are different aggregates of the SAME data must each
        produce evidence covering their validator (regression for the
        aggregate-pair dedup)."""
        s = Slasher()
        s.on_attestation(_indexed([1], 2, 5, tag=0))   # data X, agg {1}
        s.on_attestation(_indexed([2], 2, 5, tag=0))   # data X, agg {2}
        ev = s.on_attestation(_indexed([1, 2], 2, 5, tag=9))  # conflict Y
        covered = set()
        for e in ev:
            covered |= (
                set(int(i) for i in np.asarray(e.attestation_1.attesting_indices))
                & set(int(i) for i in np.asarray(e.attestation_2.attesting_indices)))
        assert covered == {1, 2}

    def test_benign_history_no_evidence(self):
        s = Slasher()
        for e in range(2, 8):
            assert s.on_attestation(_indexed([9], e - 1, e)) == []
        assert s.tracked_validators() == 1


class TestProposerDetection:
    def test_equivocating_headers(self):
        s = Slasher()
        h1 = SignedBeaconBlockHeader(message=BeaconBlockHeader(
            slot=3, proposer_index=4, body_root=b"\xaa" * 32))
        h2 = SignedBeaconBlockHeader(message=BeaconBlockHeader(
            slot=3, proposer_index=4, body_root=b"\xbb" * 32))
        assert s.on_block_header(h1) is None
        ev = s.on_block_header(h2)
        assert ev is not None
        assert s.on_block_header(h2) is None  # no duplicates
        # same header replayed is not evidence
        assert s.on_block_header(h1.copy()) is None


class TestAccountabilityLoop:
    def test_detected_evidence_slashes_and_discounts(self):
        """Watch real equivocating attestations, feed the emitted evidence
        back through the fork-choice handler: stake discounted."""
        state, anchor = make_genesis(64)
        store = fc.get_forkchoice_store(state, anchor)
        fc.on_tick(store, store.genesis_time + cfg().seconds_per_slot * 2)
        sb_a = build_block(state, 1, graffiti=b"\x0a" * 32)
        sb_b = build_block(state, 1, graffiti=b"\x0b" * 32)
        fc.on_block(store, sb_a)
        fc.on_block(store, sb_b)
        ra, rb = hash_tree_root(sb_a.message), hash_tree_root(sb_b.message)
        att1 = make_committee_attestation(store.block_states[ra], 1, 0, ra)
        att2 = make_committee_attestation(store.block_states[rb], 1, 0, rb)
        i1 = get_indexed_attestation(store.block_states[ra], att1)
        i2 = get_indexed_attestation(store.block_states[rb], att2)

        slasher = Slasher()
        assert slasher.on_attestation(i1) == []
        evidence = slasher.on_attestation(i2)
        assert len(evidence) == 1

        fc.on_attester_slashing(store, evidence[0])
        expected = set(int(i) for i in np.asarray(i1.attesting_indices))
        assert store.equivocating_indices == expected
