"""Tests for the static-analysis subsystem (DESIGN.md §21).

Every historical bug class the pass mechanizes is reproduced here as a
paired fixture: the shipped-and-reviewed-out bug must FLAG, the fixed
version must NOT. Plus: suppression and baseline semantics, the lockset
analyzer on a miniature two-thread class, JSON reporter schema
stability, doctor exit codes, and the acceptance pin that the shipped
tree is clean against the checked-in baseline.
"""

import json
import os

import pytest

from pos_evolution_tpu.analysis import (
    AnalysisConfig,
    Baseline,
    analyze_source,
)
from pos_evolution_tpu.analysis.__main__ import gate, main
from pos_evolution_tpu.analysis.core import parse_suppressions
from pos_evolution_tpu.analysis.doctor import (
    DOCTOR_FINDINGS,
    DOCTOR_MISMATCH,
    DOCTOR_OK_NONE,
    EXPECTED,
    run_doctor,
)
from pos_evolution_tpu.analysis.report import (
    FINDING_KEYS,
    SCHEMA_KEYS,
    render_json,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _codes(source, relpath="mod.py", config=None, **cfg_kw):
    if config is None:
        config = AnalysisConfig(**cfg_kw)
    result = analyze_source(source, relpath, config)
    assert result.parse_error is None, result.parse_error
    return [f.code for f in result.findings]


def _hot(relpath="mod.py"):
    return AnalysisConfig(hot_modules=(relpath,))


def _strict_scope(relpath="mod.py"):
    return AnalysisConfig(stateless_strict=(relpath,),
                          stateless_decision=())


def _threaded(relpath="mod.py"):
    return AnalysisConfig(threaded_modules=(relpath,))


# --- PEV001: the PR 7 fresh-closure class -------------------------------------

PR7_BUG = """\
import jax

def reconstruct_check_device(cells, mask):
    @jax.jit
    def _check(c, m):
        return (c * m).sum()
    return _check(cells, mask)
"""

PR7_FIXED = """\
import jax

@jax.jit
def _reconstruct_check(c, m):
    return (c * m).sum()

def reconstruct_check_device(cells, mask):
    return _reconstruct_check(cells, mask)
"""


class TestFreshJitClosure:
    def test_pr7_per_call_closure_flags(self):
        assert _codes(PR7_BUG) == ["PEV001"]

    def test_pr7_module_singleton_fix_is_clean(self):
        assert _codes(PR7_FIXED) == []

    def test_memoized_for_builder_is_exempt(self):
        src = """\
import jax

_CACHE = {}

def _cached(key, build):
    if key not in _CACHE:
        _CACHE[key] = build()
    return _CACHE[key]

def epoch_step_for(mesh):
    return _cached(("epoch", mesh), lambda: jax.jit(lambda r: r + 1))
"""
        assert _codes(src) == []

    def test_helper_core_called_only_from_for_builder_is_exempt(self):
        src = """\
import jax

_CACHE = {}

def _cached(key, build):
    if key not in _CACHE:
        _CACHE[key] = build()
    return _CACHE[key]

def _epoch_core(mesh):
    def step(reg):
        return reg
    return jax.jit(step)

def epoch_step_for(mesh):
    return _cached(("epoch", mesh), lambda: _epoch_core(mesh))
"""
        assert _codes(src) == []

    def test_module_singleton_global_memo_is_exempt(self):
        # the ops/transition._device idiom
        src = """\
import jax

_DEVICE = None

def _device():
    global _DEVICE
    if _DEVICE is None:
        _DEVICE = {"jit": jax.jit(lambda x: x)}
    return _DEVICE
"""
        assert _codes(src) == []

    def test_stacked_decorators_report_once(self):
        src = """\
import jax
from functools import partial
from jax.experimental.shard_map import shard_map

def dry_run_builder(mesh):
    @jax.jit
    @partial(shard_map, mesh=mesh)
    def step(reg):
        return reg
    return step
"""
        assert _codes(src) == ["PEV001"]

    def test_compat_shim_defining_the_constructor_name_is_exempt(self):
        # parallel/sharded.py's pre-0.6 wrapper: def shard_map(...) that
        # forwards to the experimental spelling — callers get audited
        src = """\
from jax.experimental.shard_map import shard_map as _experimental

def shard_map(f, **kwargs):
    if "check_vma" in kwargs:
        kwargs["check_rep"] = kwargs.pop("check_vma")
    return _experimental(f, **kwargs)
"""
        assert _codes(src) == []

    def test_aliased_jit_import_still_flags(self):
        src = ("from jax import jit as J\n\n"
               "def per_call(xs):\n"
               "    return J(lambda v: v * 2)(xs)\n")
        assert _codes(src) == ["PEV001"]

    def test_module_level_decorated_def_is_the_idiom(self):
        src = """\
import jax
from functools import partial

@partial(jax.jit, static_argnames=("capacity",))
def head_and_weights(store, capacity):
    return store
"""
        assert _codes(src) == []


# --- PEV002: the PR 13 determinism contract -----------------------------------

PR13_BUG = """\
import time

def should_drop(seed, slot):
    return time.time() % 1.0 < 0.1
"""

PR13_FIXED = """\
import hashlib
import struct

def stateless_unit(seed, *key):
    h = hashlib.blake2b(
        struct.pack(f"<{len(key) + 1}q", seed, *key), digest_size=8).digest()
    return int.from_bytes(h, "little") / 2.0**64

def should_drop(seed, slot):
    return stateless_unit(seed, slot) < 0.1
"""


class TestNondeterminism:
    def test_pr13_wall_clock_in_stateless_path_flags(self):
        assert _codes(PR13_BUG, config=_strict_scope()) == ["PEV002"]

    def test_pr13_stateless_unit_fix_is_clean(self):
        assert _codes(PR13_FIXED, config=_strict_scope()) == []

    def test_out_of_scope_module_is_not_held_to_the_contract(self):
        assert _codes(PR13_BUG, config=AnalysisConfig(
            stateless_strict=(), stateless_decision=())) == []

    def test_global_rng_cursor_flags_even_in_decision_scope(self):
        src = "import random\n\ndef jitter():\n    return random.random()\n"
        cfg = AnalysisConfig(stateless_strict=(),
                             stateless_decision=("mod.py",))
        assert _codes(src, config=cfg) == ["PEV002"]

    def test_wall_clock_allowed_in_decision_scope(self):
        # the drivers time telemetry spans legitimately
        cfg = AnalysisConfig(stateless_strict=(),
                             stateless_decision=("mod.py",))
        assert _codes(PR13_BUG, config=cfg) == []

    def test_keyed_jax_random_is_deterministic_and_clean(self):
        # jax.random is functional — explicit keys, no global cursor
        src = ("import jax\n\n"
               "def draw(key):\n"
               "    k1, k2 = jax.random.split(key, 2)\n"
               "    return jax.random.uniform(k1)\n")
        assert _codes(src, config=_strict_scope()) == []

    def test_unseeded_default_rng_flags_seeded_does_not(self):
        bad = "import numpy as np\n\ndef draw():\n    return np.random.default_rng()\n"
        good = ("import numpy as np\n\ndef draw(seed):\n"
                "    return np.random.default_rng(seed)\n")
        assert _codes(bad, config=_strict_scope()) == ["PEV002"]
        assert _codes(good, config=_strict_scope()) == []

    def test_aliased_import_cannot_evade_the_contract(self):
        src = ("import time as _t\n\n"
               "def should_drop(seed, slot):\n"
               "    return _t.time() % 1.0 < 0.1\n")
        assert _codes(src, config=_strict_scope()) == ["PEV002"]

    def test_from_import_alias_cannot_evade(self):
        src = ("from time import time as now\n\n"
               "def should_drop(seed, slot):\n"
               "    return now() % 1.0 < 0.1\n")
        assert _codes(src, config=_strict_scope()) == ["PEV002"]

    def test_set_iteration_flags_in_strict_scope(self):
        src = ("def order(xs):\n"
               "    out = []\n"
               "    for x in set(xs):\n"
               "        out.append(x)\n"
               "    return out\n")
        assert _codes(src, config=_strict_scope()) == ["PEV002"]
        assert _codes(src.replace("set(xs)", "sorted(set(xs))"),
                      config=_strict_scope()) == []


# --- PEV003: host sync in hot loops -------------------------------------------

class TestHostSync:
    def test_item_in_hot_loop_flags(self):
        src = ("def drain(batches):\n"
               "    total = 0.0\n"
               "    for b in batches:\n"
               "        total += b.item()\n"
               "    return total\n")
        assert _codes(src, config=_hot()) == ["PEV003"]

    def test_item_outside_loop_is_fine(self):
        src = ("def total_of(x):\n"
               "    return x.item()\n")
        assert _codes(src, config=_hot()) == []

    def test_float_of_traced_expr_in_loop_flags(self):
        src = ("import jax.numpy as jnp\n"
               "def drain(batches):\n"
               "    out = []\n"
               "    for b in batches:\n"
               "        out.append(float(jnp.sum(b)))\n"
               "    return out\n")
        assert _codes(src, config=_hot()) == ["PEV003"]

    def test_comprehension_counts_as_a_loop(self):
        # the most common spelling of the per-element sync
        src = ("def drain(batches):\n"
               "    return [b.item() for b in batches]\n")
        assert _codes(src, config=_hot()) == ["PEV003"]

    def test_cold_module_not_in_scope(self):
        src = ("def drain(batches):\n"
               "    return [b.item() for b in batches]\n")
        assert _codes(src, config=AnalysisConfig(hot_modules=())) == []


# --- PEV004: donation guard ---------------------------------------------------

_PEV004_ONLY = AnalysisConfig(rules=frozenset({"PEV004"}))


class TestDonationGuard:
    def test_unguarded_donation_flags(self):
        src = ("import jax\n"
               "step = jax.jit(lambda c, x: c + x, donate_argnums=(0,))\n")
        assert _codes(src) == ["PEV004"]

    def test_inline_ifexp_guard_passes(self):
        src = ("import jax\n"
               "def build(donate_ok):\n"
               "    return jax.jit(lambda c: c,\n"
               "                   donate_argnums=(0,) if donate_ok else ())\n")
        assert _codes(src, config=_PEV004_ONLY) == []

    def test_donate_param_passes(self):
        # epoch_step_for's contract: the backend-aware caller decides
        src = ("import jax\n"
               "def build(fn, donate=False):\n"
               "    return jax.jit(fn, donate_argnums=(0,) if donate else ())\n")
        assert _codes(src, config=_PEV004_ONLY) == []

    def test_module_backend_guard_passes(self):
        src = ("import jax\n"
               "_donated = jax.jit(lambda c: c, donate_argnums=(0,))\n"
               "_plain = jax.jit(lambda c: c)\n"
               "def pick():\n"
               "    return _plain if jax.default_backend() == 'cpu' "
               "else _donated\n")
        assert _codes(src) == []

    def test_docstring_mention_of_the_guard_does_not_exempt(self):
        src = ('"""This module never calls jax.default_backend()."""\n'
               "import jax\n"
               "step = jax.jit(lambda c: c, donate_argnums=(0,))\n")
        assert _codes(src, config=_PEV004_ONLY) == ["PEV004"]


# --- PEV005: silent worker except ---------------------------------------------

class TestSilentWorkerExcept:
    BUG = """\
import threading

class Pump:
    def __init__(self):
        self.t = threading.Thread(target=self._pump_loop)

    def _pump_loop(self):
        while True:
            try:
                self.step()
            except Exception:
                continue
"""

    def test_silent_swallow_in_worker_loop_flags(self):
        assert _codes(self.BUG) == ["PEV005"]

    def test_emitting_handler_is_clean(self):
        fixed = self.BUG.replace(
            "            except Exception:\n                continue",
            "            except Exception:\n"
            "                self.errors.inc()\n                continue")
        assert _codes(fixed) == []

    def test_captured_exception_for_later_surfacing_is_clean(self):
        # the CheckpointManager._drain_loop idiom
        fixed = self.BUG.replace(
            "            except Exception:\n                continue",
            "            except Exception as e:\n"
            "                self._worker_error = e")
        assert _codes(fixed) == []

    def test_nested_loops_report_the_handler_once(self):
        src = """\
import threading

class Pump:
    def __init__(self):
        self.t = threading.Thread(target=self._pump_loop)

    def _pump_loop(self):
        while True:
            for x in self.batch():
                try:
                    self.step(x)
                except Exception:
                    continue
"""
        assert _codes(src) == ["PEV005"]

    def test_same_shape_outside_a_worker_is_not_flagged(self):
        src = ("def parse_all(lines):\n"
               "    out = []\n"
               "    for line in lines:\n"
               "        try:\n"
               "            out.append(int(line))\n"
               "        except ValueError:\n"
               "            continue\n"
               "    return out\n")
        assert _codes(src) == []


# --- PEV006: mutable shared state ---------------------------------------------

class TestMutableSharedState:
    def test_mutable_default_flags(self):
        assert _codes("def f(acc=[]):\n    return acc\n") == ["PEV006"]
        assert _codes("def f(acc=None):\n    return acc or []\n") == []

    def test_lowercase_module_mutable_mutated_from_function_flags(self):
        src = ("pending = []\n\n"
               "def enqueue(x):\n"
               "    pending.append(x)\n")
        assert _codes(src) == ["PEV006"]

    def test_screaming_snake_singleton_is_the_declared_idiom(self):
        src = ("_KERNEL_CACHE = {}\n\n"
               "def cache_put(k, v):\n"
               "    _KERNEL_CACHE[k] = v\n")
        assert _codes(src) == []


# --- PEV007: fork-unsafety ----------------------------------------------------

_THREADED_PREAMBLE = (
    "import multiprocessing\n"
    "import threading\n\n"
    "def start_pump(fn):\n"
    "    threading.Thread(target=fn, daemon=True).start()\n\n")


class TestForkUnsafety:
    def test_fork_context_in_a_thread_running_module_flags(self):
        src = _THREADED_PREAMBLE + (
            "def launch(fn):\n"
            "    ctx = multiprocessing.get_context(\"fork\")\n"
            "    return ctx.Process(target=fn)\n")
        assert _codes(src) == ["PEV007"]

    def test_spawn_context_is_the_sanctioned_shape(self):
        src = _THREADED_PREAMBLE + (
            "def launch(fn):\n"
            "    ctx = multiprocessing.get_context(\"spawn\")\n"
            "    return ctx.Process(target=fn)\n")
        assert _codes(src) == []

    def test_fork_without_threads_is_not_flagged(self):
        src = ("import multiprocessing\n\n"
               "def launch(fn):\n"
               "    ctx = multiprocessing.get_context(\"fork\")\n"
               "    return ctx.Process(target=fn)\n")
        assert _codes(src) == []

    def test_bare_process_inherits_the_platform_default(self):
        src = _THREADED_PREAMBLE + (
            "def launch(fn):\n"
            "    return multiprocessing.Process(target=fn)\n")
        assert _codes(src) == ["PEV007"]

    def test_child_entry_referencing_a_parent_lock_flags(self):
        src = ("import multiprocessing\n"
               "import threading\n\n"
               "_registry_lock = threading.Lock()\n\n"
               "def child(work):\n"
               "    with _registry_lock:\n"
               "        work()\n\n"
               "def launch(work):\n"
               "    ctx = multiprocessing.get_context(\"spawn\")\n"
               "    return ctx.Process(target=child, args=(work,))\n")
        assert _codes(src) == ["PEV007"]

    def test_child_creating_its_own_lock_is_clean(self):
        src = ("import multiprocessing\n"
               "import threading\n\n"
               "def child(work):\n"
               "    lock = threading.Lock()\n"
               "    with lock:\n"
               "        work()\n\n"
               "def launch(work):\n"
               "    ctx = multiprocessing.get_context(\"spawn\")\n"
               "    return ctx.Process(target=child, args=(work,))\n")
        assert _codes(src) == []

    def test_self_attr_lock_crossing_the_boundary_flags(self):
        src = ("import multiprocessing\n"
               "import threading\n\n"
               "class Pool:\n"
               "    def __init__(self):\n"
               "        self._lock = threading.Lock()\n\n"
               "    def _child_main(self):\n"
               "        with self._lock:\n"
               "            pass\n\n"
               "    def launch(self):\n"
               "        ctx = multiprocessing.get_context(\"spawn\")\n"
               "        return ctx.Process(target=self._child_main)\n")
        assert _codes(src) == ["PEV007"]

    def test_documented_handoff_suppresses(self):
        src = ("import multiprocessing\n"
               "import threading\n\n"
               "_registry_lock = threading.Lock()\n\n"
               "def child(work):\n"
               "    # handoff: re-armed post-spawn by the supervisor\n"
               "    with _registry_lock:  # pev: ignore[PEV007]\n"
               "        work()\n\n"
               "def launch(work):\n"
               "    ctx = multiprocessing.get_context(\"spawn\")\n"
               "    return ctx.Process(target=child, args=(work,))\n")
        assert _codes(src) == []

    def test_unmutated_module_list_is_fine(self):
        src = ("default_tiers = [0, 1]\n\n"
               "def tiers():\n"
               "    return list(default_tiers)\n")
        assert _codes(src) == []

    def test_local_shadowing_the_module_name_is_not_a_mutation(self):
        src = ("pending = []\n\n"
               "def f(x):\n"
               "    pending = []\n"
               "    pending.append(x)\n"
               "    return pending\n")
        assert _codes(src) == []

    def test_param_shadowing_the_module_name_is_not_a_mutation(self):
        src = ("pending = []\n\n"
               "def f(pending, x):\n"
               "    pending.append(x)\n"
               "    return pending\n")
        assert _codes(src) == []


# --- PEV101/PEV102: the PR 12 lockset class -----------------------------------

PR12_BUG = """\
import threading

class MetricsSeries:
    def __init__(self):
        self._lock = threading.Lock()
        self.series = {}

    def inc(self, key, amount=1):
        self.series[key] = self.series.get(key, 0) + amount
"""

PR12_FIXED = """\
import threading

class MetricsSeries:
    def __init__(self):
        self._lock = threading.Lock()
        self.series = {}

    def inc(self, key, amount=1):
        with self._lock:
            self.series[key] = self.series.get(key, 0) + amount
"""


class TestLockset:
    def test_pr12_unlocked_counter_flags(self):
        codes = _codes(PR12_BUG, config=_threaded())
        assert codes == ["PEV101"]

    def test_pr12_locked_fix_is_clean(self):
        assert _codes(PR12_FIXED, config=_threaded()) == []

    def test_get_or_create_race_flags_and_locked_version_passes(self):
        bug = """\
import threading

class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._metrics = {}

    def get_or_create(self, name):
        m = self._metrics.get(name)
        if m is None:
            m = object()
            self._metrics[name] = m
        return m
"""
        assert _codes(bug, config=_threaded()) == ["PEV101"]
        fixed = bug.replace(
            "        m = self._metrics.get(name)\n"
            "        if m is None:\n"
            "            m = object()\n"
            "            self._metrics[name] = m\n"
            "        return m",
            "        with self._lock:\n"
            "            m = self._metrics.get(name)\n"
            "            if m is None:\n"
            "                m = object()\n"
            "                self._metrics[name] = m\n"
            "        return m")
        assert _codes(fixed, config=_threaded()) == []

    def test_two_thread_mini_class_without_lock(self):
        src = """\
import threading

class TickPump:
    def __init__(self):
        self.ticks = 0
        self.t = threading.Thread(target=self._tick_loop)

    def _tick_loop(self):
        while True:
            self.ticks += 1
"""
        assert _codes(src, config=_threaded()) == ["PEV101"]

    def test_method_not_thread_reachable_is_not_flagged_without_lock(self):
        src = """\
import threading

class TickPump:
    def __init__(self):
        self.ticks = 0
        self.polls = 0
        self.t = threading.Thread(target=self._tick_loop)

    def _tick_loop(self):
        while True:
            self.tick()

    def tick(self):
        self.ticks += 1

    def unrelated_main_thread_only(self):
        self.polls += 1
"""
        result = analyze_source(src, "mod.py", _threaded())
        flagged = {(f.code, f.context) for f in result.findings}
        # tick() is reachable from the thread target through the call
        # graph; the main-thread-only method is not
        assert flagged == {("PEV101", "TickPump.tick")}

    def test_inconsistent_blind_store_flags_pev102(self):
        src = """\
import threading

class View:
    def __init__(self):
        self._lock = threading.Lock()
        self.current = None

    def get(self):
        with self._lock:
            return self.current

    def publish(self, view):
        self.current = view
"""
        assert _codes(src, config=_threaded()) == ["PEV102"]

    def test_helper_always_called_under_lock_is_credited(self):
        src = """\
import threading

class Breaker:
    def __init__(self):
        self._lock = threading.Lock()
        self.state = "closed"
        self.transitions = []

    def _set(self, state):
        self.state = state
        self.transitions.append(state)

    def trip(self):
        with self._lock:
            self._set("open")

    def heal(self):
        with self._lock:
            self._set("closed")
"""
        assert _codes(src, config=_threaded()) == []

    def test_inherited_lock_discipline_applies_to_subclass(self):
        src = """\
import threading

class _Metric:
    def __init__(self):
        self._lock = threading.Lock()
        self.series = {}

    def inc(self, key):
        with self._lock:
            self.series[key] = self.series.get(key, 0) + 1

class Gauge(_Metric):
    def set(self, key, value):
        self.series[key] = value
"""
        result = analyze_source(src, "mod.py", _threaded())
        # a dict-subscript store counts as a read-modify-write (insertion
        # races a concurrent resize), so the subclass's unlocked write
        # against the BASE class's discipline is the stronger PEV101 —
        # exactly the real telemetry/registry.py Gauge.set finding
        assert [(f.code, f.context) for f in result.findings] \
            == [("PEV101", "Gauge.set")]

    def test_wrong_lock_is_not_credited(self):
        # the classic wrong-lock race: a lockish-NAMED but unrelated lock
        src = """\
import threading

other_lock = threading.Lock()

class Tally:
    def __init__(self):
        self._lock = threading.Lock()
        self.n = 0

    def bump(self):
        with other_lock:
            self.n += 1

    def read(self):
        with self._lock:
            return self.n
"""
        assert _codes(src, config=_threaded()) == ["PEV101"]

    def test_verified_local_alias_of_the_class_lock_is_credited(self):
        src = """\
import threading

class Tally:
    def __init__(self):
        self._lock = threading.Lock()
        self.n = 0

    def bump(self):
        lock = self._lock
        with lock:
            self.n += 1
"""
        assert _codes(src, config=_threaded()) == []

    def test_chained_assignment_records_every_target(self):
        src = """\
import threading

class Pair:
    def __init__(self):
        self._lock = threading.Lock()
        self.a = 0
        self.b = 0

    def bump(self):
        self.a = self.b = self.a + 1

    def read(self):
        with self._lock:
            return self.a
"""
        result = analyze_source(src, "mod.py", _threaded())
        codes = sorted((f.code, f.context) for f in result.findings)
        # the self.a RMW must not be shadowed by the self.b store
        assert ("PEV101", "Pair.bump") in codes

    def test_untheaded_module_is_out_of_scope(self):
        assert _codes(PR12_BUG, config=AnalysisConfig(
            threaded_modules=())) == []


# --- suppressions -------------------------------------------------------------

class TestSuppressions:
    def test_same_line_code_suppression(self):
        src = PR7_BUG.replace("@jax.jit", "@jax.jit  # pev: ignore[PEV001]")
        assert _codes(src) == []

    def test_comment_line_above_covers_the_next_line(self):
        src = PR7_BUG.replace(
            "    @jax.jit",
            "    # one-shot demo path\n"
            "    # pev: ignore[PEV001]\n"
            "    @jax.jit")
        assert _codes(src) == []

    def test_comment_above_survives_an_intervening_blank_line(self):
        src = PR7_BUG.replace(
            "    @jax.jit",
            "    # pev: ignore[PEV001]\n"
            "\n"
            "    @jax.jit")
        assert _codes(src) == []

    def test_wrong_code_does_not_suppress(self):
        src = PR7_BUG.replace("@jax.jit", "@jax.jit  # pev: ignore[PEV006]")
        assert _codes(src) == ["PEV001"]

    def test_bare_ignore_suppresses_everything_on_the_line(self):
        src = PR7_BUG.replace("@jax.jit", "@jax.jit  # pev: ignore")
        assert _codes(src) == []

    def test_suppressed_findings_are_counted(self):
        src = PR7_BUG.replace("@jax.jit", "@jax.jit  # pev: ignore[PEV001]")
        result = analyze_source(src, "mod.py", AnalysisConfig())
        assert result.suppressed == 1 and result.findings == []

    def test_parse_suppressions_shapes(self):
        sup = parse_suppressions(
            "x = 1  # pev: ignore[PEV001, PEV102]\n"
            "# pev: ignore\n"
            "y = 2\n")
        assert sup[1] == frozenset({"PEV001", "PEV102"})
        assert sup[2] is None and sup[3] is None

    def test_malformed_code_list_fails_closed(self):
        # a typo must suppress NOTHING, never widen to everything
        assert parse_suppressions("x = 1  # pev: ignore[pev001]\n") == {}
        assert parse_suppressions("x = 1  # pev: ignore[PEV001\n") == {}
        assert parse_suppressions("x = 1  # pev: ignore[]\n") == {}
        src = PR7_BUG.replace("@jax.jit", "@jax.jit  # pev: ignore[pev001]")
        assert _codes(src) == ["PEV001"]


# --- baseline semantics -------------------------------------------------------

class TestBaseline:
    def _one_finding(self):
        result = analyze_source(PR7_BUG, "pkg/mod.py", AnalysisConfig())
        assert len(result.findings) == 1
        return result.findings[0]

    def test_baselined_finding_is_absorbed_line_independently(self):
        f = self._one_finding()
        bl = Baseline(entries=[dict(Baseline.entry_for(f, "demo path"))])
        shifted = f.__class__(**{**f.__dict__, "line": f.line + 40})
        new, absorbed, stale = bl.match([shifted])
        assert new == [] and len(absorbed) == 1 and stale == []

    def test_unmatched_finding_is_new_and_entry_goes_stale(self):
        f = self._one_finding()
        entry = Baseline.entry_for(f, "demo path")
        entry["key"] = "something_else = jax.jit(fn)"
        bl = Baseline(entries=[entry])
        new, absorbed, stale = bl.match([f])
        assert new == [f] and absorbed == [] and stale == [entry]

    def test_count_budget_absorbs_exactly_n(self):
        f = self._one_finding()
        entry = Baseline.entry_for(f, "two known copies")
        entry["count"] = 2
        bl = Baseline(entries=[entry])
        new, absorbed, _ = bl.match([f, f, f])
        assert len(absorbed) == 2 and len(new) == 1

    def test_justification_is_mandatory(self, tmp_path):
        f = self._one_finding()
        entry = Baseline.entry_for(f, "")
        p = tmp_path / "bl.json"
        p.write_text(json.dumps({"version": 1, "entries": [entry]}))
        with pytest.raises(AssertionError):
            Baseline.load(p)

    def test_load_dump_roundtrip(self, tmp_path):
        f = self._one_finding()
        bl = Baseline(entries=[Baseline.entry_for(f, "demo path")])
        p = tmp_path / "bl.json"
        p.write_text(bl.dump())
        assert Baseline.load(p).entries == bl.entries


# --- reporters ----------------------------------------------------------------

class TestReporters:
    def test_json_schema_stability(self):
        summary = gate(["pos_evolution_tpu/analysis"], root=REPO_ROOT)
        blob = render_json(summary)
        assert tuple(sorted(blob)) == tuple(sorted(SCHEMA_KEYS))
        assert blob["version"] == 1
        for f in blob["findings"]:
            assert tuple(sorted(f)) == tuple(sorted(FINDING_KEYS))
        # every registered code is documented in the report
        assert set(blob["rules"]) >= {"PEV001", "PEV002", "PEV003",
                                      "PEV004", "PEV005", "PEV006",
                                      "PEV007", "PEV101", "PEV102"}
        json.dumps(blob)  # must be serializable as-is

    def test_text_report_carries_locations_and_tally(self):
        from pos_evolution_tpu.analysis.__main__ import Summary
        from pos_evolution_tpu.analysis.report import render_text
        result = analyze_source(PR7_BUG, "pkg/mod.py", AnalysisConfig())
        text = render_text(Summary(files_scanned=1, new=result.findings))
        assert "pkg/mod.py:4" in text and "PEV001=1" in text


# --- doctor & CLI gate semantics ----------------------------------------------

class TestDoctorAndCLI:
    def test_doctor_finds_exactly_the_expected_codes(self):
        lines = []
        assert run_doctor(out=lines.append) == DOCTOR_FINDINGS
        joined = "\n".join(lines)
        for code, n in EXPECTED.items():
            assert joined.count(f" {code} ") == n, code

    def test_doctor_detects_a_broken_analyzer(self, monkeypatch):
        import pos_evolution_tpu.analysis.doctor as doctor_mod
        # analyzer "finds nothing": clean pass on the doctored file
        monkeypatch.setattr(
            doctor_mod, "analyze_source",
            lambda *a, **k: type("R", (), {"findings": []})())
        assert doctor_mod.run_doctor(out=lambda s: None) == DOCTOR_OK_NONE

    def test_doctor_detects_a_mismatch(self, monkeypatch):
        import pos_evolution_tpu.analysis.doctor as doctor_mod
        monkeypatch.setitem(doctor_mod.EXPECTED, "PEV001", 7)
        assert doctor_mod.run_doctor(out=lambda s: None) == DOCTOR_MISMATCH

    def test_cli_doctor_exit_code(self, capsys):
        assert main(["--doctor"]) == DOCTOR_FINDINGS
        assert "doctor: all" in capsys.readouterr().out

    def test_cli_strict_gate_is_clean_on_the_shipped_tree(self, capsys):
        # THE acceptance pin: tree + checked-in baseline = rc 0
        rc = main(["--root", REPO_ROOT, "--strict",
                   "--baseline", os.path.join(REPO_ROOT,
                                              "analysis_baseline.json")])
        out = capsys.readouterr().out
        assert rc == 0, f"shipped tree must gate clean:\n{out}"
        assert "0 new finding(s)" in out

    def test_cli_tests_scope_gate_is_clean(self, capsys):
        rc = main(["--root", REPO_ROOT, "tests",
                   "--rules", "PEV002,PEV006",
                   "--assume-scope", "decision", "--baseline", "none"])
        assert rc == 0, capsys.readouterr().out

    def test_cli_rules_filter(self):
        summary = gate(["pos_evolution_tpu/analysis"], root=REPO_ROOT,
                       config=AnalysisConfig(rules=frozenset({"PEV006"})))
        assert all(f.code == "PEV006" for f in summary.new)

    def test_syntax_error_is_reported_not_crashed(self):
        result = analyze_source("def broken(:\n", "bad.py", AnalysisConfig())
        assert result.parse_error is not None
        assert [f.code for f in result.findings] == ["PEV000"]

    def test_nonexistent_path_is_a_loud_error_not_a_clean_pass(self, capsys):
        rc = main(["--root", REPO_ROOT, "no_such_dir", "--baseline", "none"])
        assert rc == 2
        assert "does not exist" in capsys.readouterr().err
