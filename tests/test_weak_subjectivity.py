"""Weak subjectivity + long-range attack tests (pos-evolution.md:1198-1317)."""

import numpy as np
import pytest

from pos_evolution_tpu.config import cfg, mainnet_config, minimal_config, use_config
from pos_evolution_tpu.specs import forkchoice as fc
from pos_evolution_tpu.specs.genesis import make_genesis
from pos_evolution_tpu.specs.weak_subjectivity import (
    compute_weak_subjectivity_period,
    get_latest_weak_subjectivity_checkpoint_epoch,
    is_within_weak_subjectivity_period,
)
from pos_evolution_tpu.specs.containers import Checkpoint
from pos_evolution_tpu.specs.validator import build_block
from pos_evolution_tpu.sim import Simulation
from pos_evolution_tpu.ssz import hash_tree_root


class TestWeakSubjectivityPeriod:
    def test_mainnet_scale_magnitude(self):
        """pos-evolution.md:1307-1313: ~3,277 epochs of churn margin at
        262,144 validators with safety decay 10% (the reference's table; on
        top of MIN_VALIDATOR_WITHDRAWABILITY_DELAY)."""
        with use_config(mainnet_config()):
            state, _ = make_genesis(0)
            n = 262144
            from pos_evolution_tpu.specs.containers import ValidatorRegistry
            reg = ValidatorRegistry(n)
            reg.effective_balance[:] = cfg().max_effective_balance
            reg.activation_epoch[:] = 0
            state.validators = reg
            state.balances = np.full(n, cfg().max_effective_balance, dtype=np.uint64)
            ws = compute_weak_subjectivity_period(state)
            churn_margin = ws - cfg().min_validator_withdrawability_delay
            assert 3200 <= churn_margin <= 3350, churn_margin

    def test_monotonic_in_validator_count(self):
        with use_config(mainnet_config()):
            periods = []
            from pos_evolution_tpu.specs.containers import ValidatorRegistry
            for n in (8192, 65536, 262144):
                state, _ = make_genesis(0)
                reg = ValidatorRegistry(n)
                reg.effective_balance[:] = cfg().max_effective_balance
                reg.activation_epoch[:] = 0
                state.validators = reg
                state.balances = np.full(n, cfg().max_effective_balance,
                                         dtype=np.uint64)
                periods.append(compute_weak_subjectivity_period(state))
            assert periods == sorted(periods)


@pytest.mark.usefixtures("minimal_cfg")
class TestLongRangeAttack:
    def test_conflicting_history_rejected_after_finality(self):
        """pos-evolution.md:1216-1217: blocks conflicting with the finalized
        (weak-subjectivity) checkpoint are rejected outright."""
        sim = Simulation(64)
        sim.run_epochs(5)
        store = sim.store()
        assert sim.finalized_epoch() >= 3

        # Long-range attacker: re-proposes an alternative block at slot 1
        # from genesis using (still-valid) old keys.
        attacker_block = build_block(sim.genesis_state, 1, graffiti=b"\x66" * 32)
        with pytest.raises(AssertionError):
            fc.on_block(store, attacker_block)

    def test_checkpoint_sync_gate(self):
        """is_within_weak_subjectivity_period accepts a fresh checkpoint and
        rejects a stale one (pos-evolution.md:1293-1302)."""
        sim = Simulation(64)
        sim.run_epochs(2)
        store = sim.store()
        ws_state = sim.genesis_state.copy()
        # the gate checks header.state_root == checkpoint.root (:1295)
        ws_state.latest_block_header.state_root = b"\xcc" * 32
        ws_checkpoint = Checkpoint(epoch=0, root=b"\xcc" * 32)
        assert is_within_weak_subjectivity_period(store, ws_state, ws_checkpoint)
        # push the clock far beyond the WS period
        store.time += (compute_weak_subjectivity_period(ws_state) + 10) \
            * cfg().slots_per_epoch * cfg().seconds_per_slot
        assert not is_within_weak_subjectivity_period(store, ws_state, ws_checkpoint)

    def test_ws_checkpoint_epoch_alignment(self):
        sim = Simulation(64)
        sim.run_epochs(4)
        state = sim.store().block_states[fc.get_head(sim.store())]
        epoch = get_latest_weak_subjectivity_checkpoint_epoch(state)
        assert 0 <= epoch <= int(state.finalized_checkpoint.epoch)

    def test_checkpoint_for_state_satisfies_gate(self):
        """checkpoint_for_state builds a (state, checkpoint) pair that
        passes the sync gate for a raw head-anchor state — the driver's
        crash-restart rejoin path (sim/driver._rejoin_group)."""
        from pos_evolution_tpu.specs.weak_subjectivity import (
            checkpoint_for_state,
        )
        from pos_evolution_tpu.utils.snapshot import (
            load_anchor, resume_store, snapshot_head,
        )
        sim = Simulation(32)
        sim.run_epochs(2)
        snap = snapshot_head(sim.store())
        store = resume_store(snap)
        ws_state, ws_checkpoint = checkpoint_for_state(load_anchor(snap)[0])
        # the pair satisfies both gate asserts and the period check
        assert is_within_weak_subjectivity_period(store, ws_state,
                                                  ws_checkpoint)
