"""ISSUE 13: adversarial robustness at mainnet scale in the dense
driver — vectorized fault masks (drop/delay/crash/partition) inside the
sharded vote pass, the four masked-transform adversary strategies, the
dense monitor stack classifying accountable faults vs protocol
violations, bit-identity of faulted+adversarial runs across mesh shapes
and vs the single-device twin, checkpoint -> resume onto a different
mesh MID-ATTACK, and the dense chaos-fuzz episode matrix with
replayable bundles + the doctored forged-double-finality negative."""

import json
import os
import sys

import numpy as np
import pytest

pytest.importorskip("jax")

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))), "scripts"))

GWEI = 10**9


@pytest.fixture(scope="module", autouse=True)
def _release_compiled_kernels():
    """Dense chaos episodes compile kernels for shapes (384-576
    validators, per-variant tallies, 2x4 meshes) no later test file
    reuses; leaving them cached measurably slows the rest of the
    suite."""
    yield
    import gc

    import jax
    jax.clear_caches()
    gc.collect()


def _mesh(pods, shard):
    from pos_evolution_tpu.parallel.sharded import make_mesh
    return make_mesh(pods * shard, pods)


def _cfg(slots_per_epoch=8):
    from pos_evolution_tpu.config import mainnet_config
    return mainnet_config().replace(slots_per_epoch=slots_per_epoch,
                                    max_committees_per_slot=4)


def _monitors(**kw):
    from pos_evolution_tpu.sim.dense_monitors import default_dense_monitors
    return default_dense_monitors(**kw)


# --- stateless vectorized draws ------------------------------------------------


class TestStatelessUnitArray:
    def test_deterministic_and_uniform(self):
        from pos_evolution_tpu.sim.faults import (
            stateless_unit_array,
            stateless_word,
        )
        a = stateless_unit_array(7, 20, 3, 0, n=4096)
        b = stateless_unit_array(7, 20, 3, 0, n=4096)
        assert np.array_equal(a, b)
        assert a.dtype == np.float64
        assert (a >= 0).all() and (a < 1).all()
        assert 0.45 < a.mean() < 0.55          # roughly uniform
        # different identity -> different draws; same word -> same seed
        c = stateless_unit_array(7, 20, 4, 0, n=4096)
        assert not np.array_equal(a, c)
        assert stateless_word(7, 20, 3, 0) == stateless_word(7, 20, 3, 0)

    def test_prefix_stable_in_n(self):
        """Growing the axis never perturbs earlier indices (the mask for
        validator v is a pure function of the identity and v)."""
        from pos_evolution_tpu.sim.faults import stateless_unit_array
        a = stateless_unit_array(3, 20, 9, 1, n=128)
        b = stateless_unit_array(3, 20, 9, 1, n=1024)
        assert np.array_equal(a, b[:128])


class TestDenseFaultPlan:
    def test_masks_disjoint_gst_and_crash(self):
        from pos_evolution_tpu.sim.faults import (
            DenseCrashWindow,
            DenseFaultPlan,
        )
        plan = DenseFaultPlan(seed=5, drop_p=0.2, delay_p=0.2, gst_slot=10,
                              crashes=(DenseCrashWindow(8, 24, 3, 7),))
        dropped, delayed = plan.delivery_masks(4, 0, 256)
        assert dropped.any() and delayed.any()
        assert not (dropped & delayed).any()     # disjoint fates
        d2, l2 = plan.delivery_masks(10, 0, 256)  # at/after GST: off
        assert not d2.any() and not l2.any()
        crashed = plan.crashed_mask(5, 256)
        assert crashed[8:24].all() and not crashed[:8].any() \
            and not crashed[24:].any()
        assert not plan.crashed_mask(7, 256).any()   # rejoined

    def test_describe_round_trip(self):
        from pos_evolution_tpu.sim.faults import (
            DenseCrashWindow,
            DenseFaultPlan,
        )
        plan = DenseFaultPlan(seed=5, drop_p=0.1, delay_p=0.05,
                              gst_slot=12, partition="full",
                              crashes=(DenseCrashWindow(0, 8, 2, 5),))
        clone = DenseFaultPlan.from_config(
            json.loads(json.dumps(plan.describe())))
        assert clone == plan


class TestMaskedStakeTally:
    def test_host_equals_sharded_kernel(self):
        from pos_evolution_tpu.ops.epoch import masked_stake_host
        from pos_evolution_tpu.parallel.partition import (
            shard_leaf,
            spec_for,
        )
        from pos_evolution_tpu.parallel.sharded import masked_stake_for
        rng = np.random.default_rng(3)
        mask = rng.random(512) < 0.3
        eff = rng.integers(1, 64, 512).astype(np.int64) * GWEI
        host = masked_stake_host(mask, eff)
        for shape in [(1, 8), (2, 4), (4, 2)]:
            mesh = _mesh(*shape)
            got = int(masked_stake_for(mesh)(
                shard_leaf(mesh, spec_for("messages/evidence"), mask),
                shard_leaf(mesh, spec_for("messages/stake"), eff)))
            assert got == host, shape


# --- faulted == unfaulted-with-masks, across every layout ----------------------


class TestFaultedDeterminism:
    def _chaos_sim(self, mesh, n=384, seed=21):
        from pos_evolution_tpu.sim.dense_adversary import DenseEquivocator
        from pos_evolution_tpu.sim.dense_driver import DenseSimulation
        from pos_evolution_tpu.sim.faults import (
            DenseCrashWindow,
            DenseFaultPlan,
        )
        plan = DenseFaultPlan(seed=seed, drop_p=0.1, delay_p=0.08,
                              gst_slot=10,
                              crashes=(DenseCrashWindow(300, 340, 3, 9),))
        return DenseSimulation(
            n, cfg=_cfg(), mesh=mesh, seed=seed, shuffle_rounds=6,
            check_walk_every=0, fault_plan=plan,
            adversaries=[DenseEquivocator(controlled=range(24), seed=2)],
            monitors=_monitors(parity_every=4))

    def test_all_pass_plan_is_bit_identical_to_no_plan(self):
        from pos_evolution_tpu.sim.dense_driver import DenseSimulation
        from pos_evolution_tpu.sim.faults import DenseFaultPlan
        base = DenseSimulation(256, cfg=_cfg(), mesh=None, seed=11,
                               shuffle_rounds=6, check_walk_every=8)
        base.run_epochs(3)
        masked = DenseSimulation(256, cfg=_cfg(), mesh=None, seed=11,
                                 shuffle_rounds=6, check_walk_every=8,
                                 fault_plan=DenseFaultPlan(seed=9))
        masked.run_epochs(3)
        assert base.metrics == masked.metrics

    def test_bit_identical_across_mesh_shapes_and_single_device(self):
        """The ISSUE 13 determinism satellite: a seeded
        faulted+adversarial dense run is bit-identical on 1x8 / 2x4 /
        4x2 and vs the single-device twin."""
        runs = []
        for mesh in (None, _mesh(1, 8), _mesh(2, 4), _mesh(4, 2)):
            sim = self._chaos_sim(mesh)
            sim.run_epochs(3)
            runs.append((sim.metrics,
                         [(v["monitor"], v["kind"], v["slot"])
                          for v in sim.monitor_violations],
                         [int(x) for x in
                          np.flatnonzero(sim.monitors[0].implicated)]))
        for other in runs[1:]:
            assert other == runs[0]

    def test_checkpoint_resume_mid_attack_on_different_mesh(self):
        """The other determinism satellite: checkpoint -> resume onto a
        DIFFERENT mesh mid-attack matches the uninterrupted run,
        including monitor state and the fault-mask stream."""
        from pos_evolution_tpu.sim.dense_driver import DenseSimulation
        ref = self._chaos_sim(_mesh(2, 4))
        ref.run_epochs(3)
        half = self._chaos_sim(_mesh(2, 4))
        half.run_epochs(1)
        data = half.checkpoint()
        for target in (_mesh(4, 2), None):
            resumed = DenseSimulation.resume(data, mesh=target)
            resumed.run_epochs(3)
            assert resumed.metrics == ref.metrics
            assert [(v["monitor"], v["kind"], v["slot"])
                    for v in resumed.monitor_violations] == \
                   [(v["monitor"], v["kind"], v["slot"])
                    for v in ref.monitor_violations]
            assert np.array_equal(resumed.monitors[0].implicated,
                                  ref.monitors[0].implicated)


# --- the strategies -------------------------------------------------------------


class TestDenseStrategies:
    def test_equivocator_faulted_episode_is_clean_with_evidence(self):
        sim = TestFaultedDeterminism()._chaos_sim(None)
        sim.run_epochs(4)
        assert sim.monitor_violations == []
        s = sim.summary()
        assert s["finality_reached"]
        # the double votes were observed and implicated at origination
        assert sim.monitors[0].implicated.sum() > 0
        assert sim.monitors[0].implicated[:24].sum() == \
            sim.monitors[0].implicated.sum()   # only controlled implicated

    def test_withholder_honest_majority_reorg_fails_clean(self):
        from pos_evolution_tpu.sim.dense_adversary import DenseWithholder
        from pos_evolution_tpu.sim.dense_driver import DenseSimulation
        adv = DenseWithholder(controlled=range(20), fork_slot=3,
                              release_slot=6)
        sim = DenseSimulation(256, cfg=_cfg(), mesh=None, seed=13,
                              shuffle_rounds=6, check_walk_every=0,
                              adversaries=[adv],
                              monitors=_monitors(parity_every=2))
        sim.run_epochs(4)
        assert sim.monitor_violations == []
        assert sim.summary()["finality_reached"]
        assert adv.priv and adv.released
        # the private chain was grown invisibly, revealed, and LOST
        priv_roots = {sim.roots[i] for i in adv.priv}
        assert sim.roots[sim._head(0)] not in priv_roots
        for i in adv.priv:
            assert sim.views[0].vis_host[i]     # revealed at release

    def test_withholder_private_blocks_invisible_before_release(self):
        from pos_evolution_tpu.sim.dense_adversary import DenseWithholder
        from pos_evolution_tpu.sim.dense_driver import DenseSimulation
        adv = DenseWithholder(controlled=range(20), fork_slot=3,
                              release_slot=10)
        sim = DenseSimulation(256, cfg=_cfg(), mesh=None, seed=13,
                              shuffle_rounds=6, check_walk_every=0,
                              adversaries=[adv])
        while sim.slot < 8:
            sim.run_slot()
        assert adv.priv and not adv.released
        for i in adv.priv:
            assert not sim.views[0].vis_host[i]
        assert adv.bank      # committee votes banked, not broadcast

    def test_splitvoter_double_finality_accountable_exactly_one_third(self):
        from pos_evolution_tpu.sim.dense_adversary import DenseSplitVoter
        from pos_evolution_tpu.sim.dense_driver import DenseSimulation
        from pos_evolution_tpu.sim.faults import DenseFaultPlan
        n = 384
        sim = DenseSimulation(
            n, cfg=_cfg(), mesh=None, seed=7, shuffle_rounds=6,
            verify_aggregates=False, check_walk_every=0, n_groups=2,
            fault_plan=DenseFaultPlan(partition="full"),
            adversaries=[DenseSplitVoter(controlled=range(n // 3))],
            monitors=_monitors(parity_every=4))
        sim.run_epochs(5)
        fins = [v for v in sim.monitor_violations
                if v["checkpoint"] == "finalized"]
        assert fins, sim.monitor_violations
        v = fins[0]
        assert v["kind"] == "accountable_fault"
        # the theorem's bound, pinned EXACTLY: evidence = the controlled
        # third, at genesis stake
        assert v["slashable_stake"] * 3 == v["total_stake"]
        assert v["evidence_size"] == n // 3
        # both views really finalized conflicting checkpoints
        assert all(view.finalized[0] > 0 for view in sim.views)
        assert sim.views[0].finalized != sim.views[1].finalized
        # liveness is loudly disarmed on a partitioned network
        liveness = sim.monitors[1]
        assert liveness.disarmed_reason is not None

    def test_balancer_stalls_justification_liveness_flagged(self):
        from pos_evolution_tpu.sim.dense_adversary import DenseBalancer
        from pos_evolution_tpu.sim.dense_driver import DenseSimulation
        from pos_evolution_tpu.sim.faults import DenseFaultPlan
        n = 384
        bal = DenseBalancer(controlled=range((n * 5) // 16))
        sim = DenseSimulation(
            n, cfg=_cfg(), mesh=None, seed=17, shuffle_rounds=6,
            verify_aggregates=False, check_walk_every=0, n_groups=2,
            fault_plan=DenseFaultPlan(partition="delay"),
            adversaries=[bal],
            monitors=_monitors(bound_epochs=2, parity_every=4))
        sim.run_epochs(6)
        assert all(v.cur_just[0] == 0 for v in sim.views)
        kinds = {v["kind"] for v in sim.monitor_violations}
        assert kinds == {"liveness_violation"}
        assert bal.infeasible_slots == []    # the :1330 precondition held


# --- the monitors' negative -----------------------------------------------------


class TestDoctoredDenseNegative:
    def test_forged_double_finality_trips_protocol_violation(self):
        """Conflicting finalized checkpoints with an EMPTY evidence
        column must be classified protocol_violation — the dense CI
        negative (a safety break the evidence cannot explain fails
        loudly)."""
        from pos_evolution_tpu.sim.dense_driver import DenseSimulation
        from pos_evolution_tpu.sim.faults import DenseFaultPlan
        sim = DenseSimulation(
            384, cfg=_cfg(), mesh=None, seed=5, shuffle_rounds=6,
            verify_aggregates=False, check_walk_every=0, n_groups=2,
            fault_plan=DenseFaultPlan(partition="full"),
            monitors=_monitors(parity_every=4))
        sim.run_epochs(2)
        tips = [i for i in range(len(sim.roots))
                if sim.block_slots[i] == sim.slot]
        sim.views[0].finalized = (1, tips[0])
        sim.views[1].finalized = (1, tips[1])
        sim.run_slot()
        kinds = [v["kind"] for v in sim.monitor_violations
                 if v.get("checkpoint") == "finalized"]
        assert "protocol_violation" in kinds, sim.monitor_violations


# --- chaos_fuzz --dense ---------------------------------------------------------


class TestDenseChaosFuzz:
    def test_episode_config_pure_function(self):
        from chaos_fuzz import episode_config_dense
        a = episode_config_dense(9, 2, 384, 4)
        b = episode_config_dense(9, 2, 384, 4)
        assert a == b
        assert a["dense"] is True
        json.dumps(a)   # bundle-serializable

    def test_fuzz_matrix_bundles_and_replay(self, tmp_path):
        """Two fixed-seed dense episodes run clean-or-explained; a
        violating/explained bundle replays to the identical verdicts
        through DenseSimulation.resume."""
        from chaos_fuzz import fuzz_dense, replay_bundle
        out = str(tmp_path / "dense")
        summary = fuzz_dense(2, 3, 384, 4, out)
        assert summary["episodes"] == 2
        assert summary["violating"] == 0
        assert summary["incidents"] == 0
        for bundle in summary["bundles"]:
            assert os.path.exists(os.path.join(bundle, "config.json"))
            assert os.path.exists(os.path.join(bundle, "checkpoint.bin"))
            assert os.path.exists(os.path.join(bundle, "events.jsonl"))
            rep = replay_bundle(bundle)
            assert rep["match"] is True, rep

    def test_doctor_trips_and_records(self, tmp_path):
        from chaos_fuzz import episode_config_dense, run_dense_episode
        cfg = episode_config_dense(5, 0, 384, 2, doctor=True)
        result = run_dense_episode(cfg)
        assert any(v["kind"] == "protocol_violation"
                   for v in result["violations"])
        assert result["unexpected"] == [] and result["missed"] == []

    def test_doctor_missed_fails_loudly(self):
        """If the forgery does NOT trip (here: simulated by judging a
        clean run against the doctor expectation), the episode is
        flagged missed — the negative cannot silently pass."""
        from chaos_fuzz import _dense_expectations
        out = _dense_expectations(
            {"expect": {"clean": False, "protocol_violation": True}},
            {"violations": [],
             "summary": {"finality_reached": True, "views": []}})
        assert "protocol_violation_not_tripped" in out["missed"]

    def test_bench_dense_chaos_gate_doctored_slow_fails(self, tmp_path):
        """The history emission passes the perf gate against itself and
        a doctored-slow (x10) emission FAILS it."""
        import subprocess
        from pos_evolution_tpu.profiling import history
        emission = {"metric": "dense_chaos", "run_s": 4.2,
                    "counts": {"episodes": 2, "slots": 64, "blocks": 120,
                               "violations": 3, "violating_episodes": 0}}
        hist = str(tmp_path / "hist.jsonl")
        for _ in range(3):
            history.append_entry(hist, emission, kind="bench_dense_chaos")
        cand = str(tmp_path / "cand.json")
        json.dump(emission, open(cand, "w"))
        slow = dict(emission, run_s=emission["run_s"] * 10)
        slow_p = str(tmp_path / "slow.json")
        json.dump(slow, open(slow_p, "w"))
        gate = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "scripts", "perf_gate.py")
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        ok = subprocess.run(
            [sys.executable, gate, "--candidate", cand, "--history", hist,
             "--kind", "bench_dense_chaos", "--strict-timing"], env=env)
        assert ok.returncode == 0
        bad = subprocess.run(
            [sys.executable, gate, "--candidate", slow_p, "--history",
             hist, "--kind", "bench_dense_chaos", "--strict-timing"],
            env=env)
        assert bad.returncode == 1


# --- property audit report over dense events ------------------------------------


class TestDenseRunReport:
    def test_property_audit_renders_dense_monitor_events(self, tmp_path):
        from chaos_fuzz import episode_config_dense, run_dense_episode
        from run_report import build_report, to_markdown
        events = str(tmp_path / "events.jsonl")
        cfg = episode_config_dense(7, 0, 384, 5, scenario="splitvoter")
        run_dense_episode(cfg, events_path=events)
        rows = [json.loads(line) for line in open(events)]
        report = build_report(rows)
        audit = report["property_audit"]
        assert audit["violations"], audit
        assert any(v["kind"] == "accountable_fault"
                   for v in audit["violations"])
        assert audit["monitors"] and audit["adversaries"]
        md = to_markdown(report)
        assert "Property audit" in md
        assert "accountable_fault" in md
