"""BLS12-381 tests (component N1): pairing algebra, signature scheme,
serialization, and the spec layer running on the real crypto backend
(the bls-setting toggle of SURVEY.md §4.4a).
"""

import pytest

from pos_evolution_tpu.crypto import bls12_381 as B
from pos_evolution_tpu.crypto.bls import FakeBLS, set_bls_backend


class TestPairing:
    def test_generators_in_subgroups(self):
        assert B.g1_on_curve(B.G1_GEN)
        assert B.g2_on_curve(B.G2_GEN)
        assert B.subgroup_check_g1(B.G1_GEN)
        assert B.subgroup_check_g2(B.G2_GEN)

    def test_bilinearity(self):
        e1 = B.pairing(B.G1_GEN, B.G2_GEN)
        assert not e1.is_one()
        e2 = B.pairing(B.ec_mul(B.G1_GEN, 2), B.G2_GEN)
        assert e2 == e1 * e1
        # e(2P, 3Q) == e(P, Q)^6
        e6 = B.pairing(B.ec_mul(B.G1_GEN, 2), B.ec_mul(B.G2_GEN, 3))
        assert e6 == e1.pow(6)

    def test_pairings_equal_product_check(self):
        # e(g1, 5*g2) == e(5*g1, g2)
        assert B.pairings_equal(
            [(B.G1_GEN, B.ec_mul(B.G2_GEN, 5))],
            [(B.ec_mul(B.G1_GEN, 5), B.G2_GEN)])


class TestSerialization:
    def test_g1_roundtrip(self):
        for k in (1, 2, 7, 123456789):
            p = B.ec_mul(B.G1_GEN, k)
            assert B.g1_decompress(B.g1_compress(p)) == p

    def test_g2_roundtrip(self):
        for k in (1, 3, 99):
            p = B.ec_mul(B.G2_GEN, k)
            assert B.g2_decompress(B.g2_compress(p)) == p

    def test_infinity(self):
        assert B.g1_decompress(B.g1_compress(None)) is None
        assert B.g2_decompress(B.g2_compress(None)) is None

    def test_invalid_x_rejected(self):
        bad = (B._FLAG_COMPRESSED | 5).to_bytes(48, "big")
        # x = 5 has no y on G1 (or decompresses fine; accept either but
        # require determinism)
        try:
            p = B.g1_decompress(bad)
            assert B.g1_on_curve(p)
        except ValueError:
            pass


class TestSignatures:
    def test_sign_verify(self):
        pk = B.PyBLS.SkToPk(42)
        msg = b"\x01" * 32
        sig = B.PyBLS.Sign(42, msg)
        assert len(pk) == 48 and len(sig) == 96
        assert B.PyBLS.Verify(pk, msg, sig)
        assert not B.PyBLS.Verify(pk, b"\x02" * 32, sig)
        assert not B.PyBLS.Verify(B.PyBLS.SkToPk(43), msg, sig)

    def test_fast_aggregate_verify(self):
        msg = b"\x07" * 32
        pks = [B.PyBLS.SkToPk(k) for k in (1, 2, 3)]
        agg = B.PyBLS.Aggregate([B.PyBLS.Sign(k, msg) for k in (1, 2, 3)])
        assert B.PyBLS.FastAggregateVerify(pks, msg, agg)
        assert not B.PyBLS.FastAggregateVerify(pks[:2], msg, agg)
        assert not B.PyBLS.FastAggregateVerify([], msg, agg)


class TestNativeBLS:
    """C++ core (native/bls12_381.cpp) must be byte-identical to the
    Python oracle."""

    @pytest.fixture(autouse=True)
    def _need_native(self):
        from pos_evolution_tpu.crypto import native_bls
        if not native_bls.available():
            pytest.skip("native BLS library not built")

    def test_keys_and_signatures_match_oracle(self):
        from pos_evolution_tpu.crypto.native_bls import NativeBLS
        msg = b"\x11" * 32
        for sk in (1, 99, 2**200):
            assert NativeBLS.SkToPk(sk) == B.PyBLS.SkToPk(sk)
        assert NativeBLS.Sign(99, msg) == B.PyBLS.Sign(99, msg)

    def test_cross_verification(self):
        from pos_evolution_tpu.crypto.native_bls import NativeBLS
        msg = b"\x22" * 32
        pk = NativeBLS.SkToPk(7)
        sig_py = B.PyBLS.Sign(7, msg)
        assert NativeBLS.Verify(pk, msg, sig_py)
        assert not NativeBLS.Verify(pk, b"\x23" * 32, sig_py)
        sig_c = NativeBLS.Sign(7, msg)
        assert B.PyBLS.Verify(pk, msg, sig_c)

    def test_fast_aggregate_verify(self):
        from pos_evolution_tpu.crypto.native_bls import NativeBLS
        msg = b"\x33" * 32
        pks = [NativeBLS.SkToPk(k) for k in (1, 2, 3)]
        agg = NativeBLS.Aggregate([NativeBLS.Sign(k, msg) for k in (1, 2, 3)])
        assert agg == B.PyBLS.Aggregate([B.PyBLS.Sign(k, msg) for k in (1, 2, 3)])
        assert NativeBLS.FastAggregateVerify(pks, msg, agg)
        assert not NativeBLS.FastAggregateVerify(pks[:2], msg, agg)

    def test_spec_transition_on_native_bls(self, minimal_cfg):
        from pos_evolution_tpu.crypto.native_bls import NativeBLS
        set_bls_backend(NativeBLS)
        try:
            from pos_evolution_tpu.specs.genesis import make_genesis
            from pos_evolution_tpu.specs.transition import state_transition
            from pos_evolution_tpu.specs.validator import build_block
            state, _ = make_genesis(4)
            sb = build_block(state, 1)
            state_transition(state, sb, True)
            assert int(state.slot) == 1
        finally:
            set_bls_backend(FakeBLS)


class TestSpecOnRealBLS:
    def test_block_transition_with_real_crypto(self, minimal_cfg):
        """The spec layer is crypto-agnostic: a block with a real-BLS
        proposer signature, RANDAO reveal, and aggregate attestation
        passes state_transition (pos-evolution.md:412-424)."""
        set_bls_backend(B.PyBLS)
        try:
            from pos_evolution_tpu.specs.genesis import make_genesis
            from pos_evolution_tpu.specs.transition import state_transition
            from pos_evolution_tpu.specs.validator import (
                attest_all_committees, build_block,
            )
            from pos_evolution_tpu.ssz import hash_tree_root
            state, _ = make_genesis(4)
            sb1 = build_block(state, 1)
            state_transition(state, sb1, True)
            atts = attest_all_committees(state, 1, hash_tree_root(sb1.message))
            sb2 = build_block(state, 2, attestations=atts)
            state_transition(state, sb2, True)
            assert int(state.slot) == 2
            assert (state.current_epoch_participation > 0).any()
        finally:
            set_bls_backend(FakeBLS)

    def test_bad_signature_rejected_with_real_crypto(self, minimal_cfg):
        set_bls_backend(B.PyBLS)
        try:
            from pos_evolution_tpu.specs.genesis import make_genesis
            from pos_evolution_tpu.specs.transition import state_transition
            from pos_evolution_tpu.specs.validator import build_block
            state, _ = make_genesis(4)
            sb = build_block(state, 1)
            sb.signature = B.PyBLS.Sign(999, b"\x00" * 32)
            with pytest.raises(AssertionError):
                state_transition(state.copy(), sb, True)
        finally:
            set_bls_backend(FakeBLS)
