"""DAS subsystem tests (das/, ops/das_verify.py, driver wiring, DESIGN.md §15).

Pins, in order: the GF(2^8) erasure layer (field laws, any-50%
reconstruction, corruption rejection), generalized-index multiproofs,
the pluggable commitment scheme, host<->device bit-identity of the
batched sample-verification and reconstruction kernels on randomized
(blob, sample, corruption) inputs, the blob engine + availability store
+ fork-choice gate, the coalescing server with its LRU caches and
latency metrics, the end-to-end faulted simulation with sidecar
backfill, checkpoint/resume with a reattached engine, the run-report
"DAS serving" section, and the compile-prewarm knob (ROADMAP item 2
remainder) via ``jax_backend_compiles_total``.
"""

import numpy as np
import pytest

from pos_evolution_tpu.das import erasure
from pos_evolution_tpu.das.commitment import (
    CellCommitmentScheme,
    MerkleCellScheme,
    get_scheme,
    register_scheme,
)
from pos_evolution_tpu.ssz.merkle import (
    build_multiproof,
    is_valid_merkle_branch,
    merkleize_chunks,
    multiproof_helper_gindices,
    verify_multiproof,
)

pytestmark = pytest.mark.usefixtures("minimal_cfg")


# --- erasure layer ------------------------------------------------------------

class TestErasure:
    def test_field_laws(self):
        rng = np.random.default_rng(0)
        for _ in range(200):
            a, b, c = (int(x) for x in rng.integers(0, 256, 3))
            assert erasure.gf_mul(a, erasure.gf_mul(b, c)) == \
                erasure.gf_mul(erasure.gf_mul(a, b), c)
            assert erasure.gf_mul(a, b ^ c) == \
                erasure.gf_mul(a, b) ^ erasure.gf_mul(a, c)
        for a in range(1, 256):
            assert erasure.gf_mul(a, erasure.gf_inv(a)) == 1

    def test_gf_matmul_matches_scalar(self):
        rng = np.random.default_rng(1)
        a = rng.integers(0, 256, (3, 4), dtype=np.uint8)
        b = rng.integers(0, 256, (4, 5), dtype=np.uint8)
        out = erasure.gf_matmul(a, b)
        for i in range(3):
            for j in range(5):
                acc = 0
                for t in range(4):
                    acc ^= erasure.gf_mul(int(a[i, t]), int(b[t, j]))
                assert acc == int(out[i, j])

    def test_extension_is_systematic_and_polynomial(self):
        rng = np.random.default_rng(2)
        k = 8
        data = rng.integers(0, 256, (k, 16), dtype=np.uint8)
        grid = erasure.extend_blob(data)
        assert grid.shape == (2 * k, 16)
        assert (grid[:k] == data).all()

    def test_any_half_reconstructs(self):
        rng = np.random.default_rng(3)
        k = 8
        data = rng.integers(0, 256, (k, 32), dtype=np.uint8)
        grid = erasure.extend_blob(data)
        for _ in range(10):
            present = np.zeros(2 * k, dtype=bool)
            extra = int(rng.integers(0, k))  # any >= 50% works, not just 50%
            present[rng.choice(2 * k, k + extra, replace=False)] = True
            rec, full, ok = erasure.reconstruct_blob(grid, present)
            assert ok and (rec == data).all() and (full == grid).all()

    def test_below_half_raises(self):
        k = 8
        grid = erasure.extend_blob(np.zeros((k, 8), dtype=np.uint8))
        present = np.zeros(2 * k, dtype=bool)
        present[: k - 1] = True
        with pytest.raises(ValueError):
            erasure.reconstruct_blob(grid, present)

    def test_any_corrupted_present_cell_flips_verdict(self):
        rng = np.random.default_rng(4)
        k = 8
        grid = erasure.extend_blob(
            rng.integers(0, 256, (k, 8), dtype=np.uint8))
        for _ in range(8):
            bad = grid.copy()
            row = int(rng.integers(0, 2 * k))
            col = int(rng.integers(0, 8))
            bad[row, col] ^= int(rng.integers(1, 256))
            present = np.ones(2 * k, dtype=bool)
            _, _, ok = erasure.reconstruct_blob(bad, present)
            assert not ok, f"corruption at ({row},{col}) slipped through"


# --- multiproofs --------------------------------------------------------------

class TestMultiproof:
    def _leaves(self, n, seed=0):
        return np.random.default_rng(seed).integers(
            0, 256, (n, 32), dtype=np.uint8)

    def test_random_subsets_verify(self):
        rng = np.random.default_rng(5)
        leaves = self._leaves(16)
        root = merkleize_chunks(leaves)
        for _ in range(10):
            count = int(rng.integers(1, 9))
            idx = sorted(int(i) for i in
                         rng.choice(16, count, replace=False))
            proof = build_multiproof(leaves, idx, 4)
            assert verify_multiproof([leaves[i].tobytes() for i in idx],
                                     idx, proof, 4, root)

    def test_multiproof_cheaper_than_branches(self):
        leaves = self._leaves(32)
        idx = list(range(8))  # adjacent leaves share almost every sibling
        proof = build_multiproof(leaves, idx, 5)
        assert len(proof) < 8 * 5

    def test_wrong_leaf_or_proof_rejected(self):
        leaves = self._leaves(16, seed=6)
        root = merkleize_chunks(leaves)
        idx = [2, 7, 11]
        proof = build_multiproof(leaves, idx, 4)
        good = [leaves[i].tobytes() for i in idx]
        assert verify_multiproof(good, idx, proof, 4, root)
        bad = list(good)
        bad[1] = b"\x00" * 32
        assert not verify_multiproof(bad, idx, proof, 4, root)
        assert not verify_multiproof(good, idx, proof[:-1], 4, root)
        assert not verify_multiproof(good, idx, proof, 4, b"\x13" * 32)

    def test_duplicate_leaf_indices_must_agree(self):
        """Samplers draw cells with replacement, so the same index can
        arrive twice — a conflicting value at a repeated gindex must NOT
        verify (a last-write-wins dict would silently keep the honest
        copy and wave the corrupted one through)."""
        leaves = self._leaves(16, seed=8)
        root = merkleize_chunks(leaves)
        proof = build_multiproof(leaves, [3], 4)
        good = leaves[3].tobytes()
        assert verify_multiproof([good, good], [3, 3], proof, 4, root)
        assert not verify_multiproof([b"\x66" * 32, good], [3, 3],
                                     proof, 4, root)
        assert not verify_multiproof([good, b"\x66" * 32], [3, 3],
                                     proof, 4, root)

    def test_single_leaf_equals_plain_branch(self):
        """Helpers for one leaf, deepest-first, ARE the plain branch."""
        leaves = self._leaves(16, seed=7)
        root = merkleize_chunks(leaves)
        proof = build_multiproof(leaves, [5], 4)
        assert len(multiproof_helper_gindices([5], 4)) == 4
        assert is_valid_merkle_branch(leaves[5].tobytes(), proof, 4, 5, root)
        assert verify_multiproof([leaves[5].tobytes()], [5], proof, 4, root)


# --- commitment schemes -------------------------------------------------------

class TestCommitment:
    def _grid(self, seed=0):
        from pos_evolution_tpu.config import cfg
        rng = np.random.default_rng(seed)
        c = cfg()
        return erasure.extend_blob(rng.integers(
            0, 256, (c.das_cells_per_blob, c.das_cell_bytes), dtype=np.uint8))

    def test_branches_match_single_branch(self):
        sch = get_scheme("merkle")
        grid = self._grid()
        leaves, branches = sch.branches(grid, [1, 6, 9])
        for j, i in enumerate([1, 6, 9]):
            single = sch.branch(grid, i)
            assert (branches[j] == single).all()
            assert is_valid_merkle_branch(
                leaves[j].tobytes(),
                [single[d].tobytes() for d in range(single.shape[0])],
                single.shape[0], i, sch.commit(grid))

    def test_multiproof_roundtrip_and_rejection(self):
        sch = get_scheme("merkle")
        grid = self._grid(1)
        com = sch.commit(grid)
        idx = [0, 3, 9, 14]
        proof = sch.prove_cells(grid, idx)
        assert sch.verify_cells(com, grid[idx], idx, proof)
        bad = grid[idx].copy()
        bad[2, 0] ^= 1
        assert not sch.verify_cells(com, bad, idx, proof)

    def test_scheme_registry_pluggable(self):
        class XorScheme(CellCommitmentScheme):
            name = "xor-test"
        register_scheme(XorScheme)
        assert isinstance(get_scheme("xor-test"), XorScheme)
        assert isinstance(get_scheme("merkle"), MerkleCellScheme)
        with pytest.raises(ValueError):
            get_scheme("kzg-not-yet")


# --- batched kernels: host == device on randomized inputs ---------------------

class TestBackendParity:
    def _batch(self, seed, corrupt_fraction=0.25):
        """Random (blob, sample, corruption) batch + the expected verdicts."""
        from pos_evolution_tpu.config import cfg
        from pos_evolution_tpu.ops.das_verify import DasSampleBatch
        rng = np.random.default_rng(seed)
        c = cfg()
        sch = get_scheme("merkle")
        n_blobs = 3
        grids = [erasure.extend_blob(rng.integers(
            0, 256, (c.das_cells_per_blob, c.das_cell_bytes),
            dtype=np.uint8)) for _ in range(n_blobs)]
        coms = [sch.commit(g) for g in grids]
        s = 24
        blob_ids = rng.integers(0, n_blobs, s)
        n_cells = 2 * c.das_cells_per_blob
        cell_ids = rng.integers(0, n_cells, s)
        depth = sch.depth_for(n_cells)
        cells = np.zeros((s, c.das_cell_bytes), dtype=np.uint8)
        branches = np.zeros((s, depth, 32), dtype=np.uint8)
        commitments = np.zeros((s, 32), dtype=np.uint8)
        for j in range(s):
            g = grids[blob_ids[j]]
            cells[j] = g[cell_ids[j]]
            branches[j] = sch.branch(g, int(cell_ids[j]))
            commitments[j] = np.frombuffer(coms[blob_ids[j]], dtype=np.uint8)
        expect = np.ones(s, dtype=bool)
        corrupt = rng.random(s) < corrupt_fraction
        for j in np.nonzero(corrupt)[0]:
            cells[j, int(rng.integers(0, c.das_cell_bytes))] ^= \
                int(rng.integers(1, 256))
            expect[j] = False
        return DasSampleBatch(cells=cells, branches=branches,
                              indices=cell_ids.astype(np.int64),
                              commitments=commitments), expect

    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_verify_samples_bit_identical(self, seed):
        from pos_evolution_tpu.ops.das_verify import (
            verify_samples_device,
            verify_samples_host,
        )
        batch, expect = self._batch(seed)
        h = verify_samples_host(batch)
        d = verify_samples_device(batch)
        assert (h["ok"] == expect).all(), "host verdicts wrong"
        for key in ("ok", "roots", "leaves"):
            assert (h[key] == d[key]).all(), f"host/device diverge on {key}"

    @pytest.mark.parametrize("seed", [0, 1])
    def test_reconstruct_bit_identical(self, seed):
        from pos_evolution_tpu.config import cfg
        from pos_evolution_tpu.ops.das_verify import (
            reconstruct_check_device,
            reconstruct_check_host,
        )
        rng = np.random.default_rng(seed)
        c = cfg()
        k = c.das_cells_per_blob
        data = rng.integers(0, 256, (k, c.das_cell_bytes), dtype=np.uint8)
        grid = erasure.extend_blob(data)
        present = np.zeros(2 * k, dtype=bool)
        present[rng.choice(2 * k, k + 2, replace=False)] = True
        okh, dh = reconstruct_check_host(grid, present)
        okd, dd = reconstruct_check_device(grid, present)
        assert okh and okd and (dh == dd).all() and (dh == data).all()
        # one corrupted PRESENT cell must flip both verdicts identically
        bad = grid.copy()
        row = int(np.nonzero(present)[0][-1])
        bad[row, 0] ^= 0x5A
        okh2, dh2 = reconstruct_check_host(bad, present)
        okd2, dd2 = reconstruct_check_device(bad, present)
        assert not okh2 and not okd2 and (dh2 == dd2).all()

    def test_backend_dispatch(self):
        from pos_evolution_tpu.backend import set_backend
        from pos_evolution_tpu.ops.das_verify import verify_das_samples
        batch, expect = self._batch(9)
        try:
            set_backend("numpy")
            h = verify_das_samples(batch)
            set_backend("jax")
            d = verify_das_samples(batch)
        finally:
            set_backend("numpy")
        assert (h["ok"] == d["ok"]).all()
        assert (h["ok"] == expect).all()


# --- blob engine + availability store -----------------------------------------

class TestBlobEngineStore:
    def test_sidecar_verification_and_gate(self):
        from pos_evolution_tpu.das import BlobEngine, BlobStore
        from pos_evolution_tpu.das.containers import parse_das_graffiti
        from pos_evolution_tpu.specs.genesis import make_genesis
        from pos_evolution_tpu.specs.validator import build_block
        from pos_evolution_tpu.ssz import hash_tree_root

        state, anchor = make_genesis(16)
        eng = BlobEngine(seed=11)
        parent_root = hash_tree_root(anchor)
        grids, coms, graffiti = eng.build_for(1, parent_root)
        assert parse_das_graffiti(graffiti)[0] == len(grids)
        sb = build_block(state, 1, graffiti=graffiti)
        block_root = hash_tree_root(sb.message)
        sidecars = eng.sidecars_for(sb, block_root, grids, coms)

        store = BlobStore(eng)
        assert not store.is_available(block_root, sb.message)
        for sc in sidecars:
            assert store.on_sidecar(sc)
        assert store.is_available(block_root, sb.message)

        # a corrupted sidecar is rejected and never feeds the gate
        bad = sidecars[0].copy()
        cells = np.asarray(bad.cells).copy()
        cells[1, 2] ^= 1
        bad.cells = cells
        store2 = BlobStore(eng)
        assert not store2.on_sidecar(bad)
        # corrupt + recommitted: commitment matches but erasure check fails
        bad2 = sidecars[0].copy()
        bad2.cells = cells
        bad2.commitment = eng.scheme.commit(cells)
        assert not store2.on_sidecar(bad2)
        assert not store2.is_available(block_root, sb.message)

    def test_bad_das_geometry_is_loud(self):
        """The documented config constraints are enforced at engine
        construction: violating any of them would otherwise produce
        structurally wrong roots or colliding payloads, silently."""
        import dataclasses

        from pos_evolution_tpu.config import cfg, use_config
        from pos_evolution_tpu.das import BlobEngine
        from pos_evolution_tpu.das.containers import (
            CellRows,
            validate_das_config,
        )

        validate_das_config()  # the active minimal config is fine
        good = cfg()
        for bad in (dataclasses.replace(good, das_cells_per_blob=12),
                    dataclasses.replace(good, das_cells_per_blob=256),
                    dataclasses.replace(good, das_cell_bytes=96),
                    dataclasses.replace(good, das_max_blobs_per_block=300),
                    dataclasses.replace(good, das_samples_per_client=0)):
            with use_config(bad), pytest.raises(ValueError):
                BlobEngine()
        # the htr sweep guards its own geometry too (96B = 3 chunks)
        with pytest.raises(ValueError):
            CellRows().htr(np.zeros((2, 96), dtype=np.uint8))

    def test_poisoned_sidecar_cannot_block_the_honest_one(self):
        """A Byzantine sidecar that is self-consistent under its own
        (wrong) commitment verifies in isolation — it must be held as a
        CANDIDATE, not a first-writer-wins occupant, so the honest
        sidecar still satisfies the graffiti-bound availability gate."""
        from pos_evolution_tpu.das import BlobEngine, BlobStore
        from pos_evolution_tpu.specs.genesis import make_genesis
        from pos_evolution_tpu.specs.validator import build_block
        from pos_evolution_tpu.ssz import hash_tree_root

        state, anchor = make_genesis(16)
        eng = BlobEngine(seed=13)
        grids, coms, graffiti = eng.build_for(1, hash_tree_root(anchor))
        sb = build_block(state, 1, graffiti=graffiti)
        block_root = hash_tree_root(sb.message)
        sidecars = eng.sidecars_for(sb, block_root, grids, coms)

        rng = np.random.default_rng(14)
        evil_grid = erasure.extend_blob(rng.integers(
            0, 256, (grids[0].shape[0] // 2, grids[0].shape[1]),
            dtype=np.uint8))
        evil = sidecars[0].copy()
        evil.cells = evil_grid
        evil.commitment = eng.scheme.commit(evil_grid)

        store = BlobStore(eng)
        assert store.on_sidecar(evil)  # self-consistent: verifies alone
        for sc in sidecars:            # honest set arrives second
            assert store.on_sidecar(sc)
        assert store.is_available(block_root, sb.message)
        served = store.sidecars_for_block(block_root)
        assert [bytes(s.commitment) for s in served] == \
            [bytes(c) for c in coms]

    def test_regenerate_is_bit_identical(self):
        from pos_evolution_tpu.das import BlobEngine
        from pos_evolution_tpu.specs.genesis import make_genesis
        from pos_evolution_tpu.specs.validator import build_block
        from pos_evolution_tpu.ssz import hash_tree_root
        state, anchor = make_genesis(16)
        eng = BlobEngine(seed=5)
        parent_root = hash_tree_root(anchor)
        grids, coms, graffiti = eng.build_for(1, parent_root)
        sb = build_block(state, 1, graffiti=graffiti)
        root = hash_tree_root(sb.message)
        first = eng.sidecars_for(sb, root, grids, coms)
        again = eng.regenerate(sb, root)
        assert len(first) == len(again)
        for a, b in zip(first, again):
            assert hash_tree_root(a) == hash_tree_root(b)

    def test_fork_choice_gate_blocks_unavailable(self):
        from pos_evolution_tpu.das import BlobEngine, BlobStore
        from pos_evolution_tpu.specs import forkchoice as fc
        from pos_evolution_tpu.specs.genesis import make_genesis
        from pos_evolution_tpu.specs.validator import build_block
        from pos_evolution_tpu.ssz import hash_tree_root

        state, anchor = make_genesis(16)
        store = fc.get_forkchoice_store(state, anchor)
        eng = BlobEngine(seed=2)
        store.blob_store = BlobStore(eng)
        parent_root = hash_tree_root(anchor)
        grids, coms, graffiti = eng.build_for(1, parent_root)
        sb = build_block(state, 1, graffiti=graffiti)
        fc.on_tick(store, store.genesis_time + 12)
        with pytest.raises(AssertionError, match="blob data not available"):
            fc.on_block(store, sb)
        root = hash_tree_root(sb.message)
        for sc in eng.sidecars_for(sb, root, grids, coms):
            store.blob_store.on_sidecar(sc)
        fc.on_block(store, sb)  # now imports
        assert root in store.blocks


# --- sampler + server ---------------------------------------------------------

class TestSamplerServer:
    def test_selection_deterministic_and_diverse(self):
        from pos_evolution_tpu.das import SamplingClientPopulation
        pop = SamplingClientPopulation(500, samples_per_client=4, seed=9)
        b1, c1 = pop.select_cells(b"\x01" * 32, 2, 16)
        pop2 = SamplingClientPopulation(500, samples_per_client=4, seed=9)
        b2, c2 = pop2.select_cells(b"\x01" * 32, 2, 16)
        assert (b1 == b2).all() and (c1 == c2).all()
        b3, c3 = pop2.select_cells(b"\x02" * 32, 2, 16)
        assert not (c1 == c3).all()  # selection depends on the block
        assert c1.min() >= 0 and c1.max() < 16 and b1.max() < 2
        # the population covers the grid (availability needs spread)
        assert len(np.unique(b1 * 16 + c1)) == 32

    def test_lru_cache_semantics(self):
        from pos_evolution_tpu.das import LRUCache
        from pos_evolution_tpu.das.server import _MISS
        lru = LRUCache(2)
        lru.put("a", 1)
        lru.put("b", 2)
        assert lru.get("a") == 1       # refreshes "a"
        lru.put("c", 3)                # evicts "b" (LRU)
        assert lru.get("b") is _MISS
        assert lru.get("a") == 1 and lru.get("c") == 3
        assert lru.hits == 3 and lru.misses == 1

    def test_serve_coalesces_and_detects_corruption(self):
        from pos_evolution_tpu.config import cfg
        from pos_evolution_tpu.das import (
            BlobEngine,
            DasServer,
            SamplingClientPopulation,
        )
        from pos_evolution_tpu.telemetry.registry import MetricsRegistry
        c = cfg()
        eng = BlobEngine(seed=4)
        grids, coms, _ = eng.build_for(2, b"\x07" * 32)

        class _FakeSidecar:
            def __init__(self, cells, commitment):
                self.cells = cells
                self.commitment = commitment

        sidecars = [_FakeSidecar(g, co) for g, co in zip(grids, coms)]
        registry = MetricsRegistry()
        server = DasServer(eng.scheme, registry=registry)
        pop = SamplingClientPopulation(1000, samples_per_client=4, seed=1)
        s1 = server.serve_samples(b"\x07" * 32, sidecars, pop)
        assert s1["samples"] == 4000
        assert s1["unique_requests"] <= 2 * 2 * c.das_cells_per_blob
        assert s1["failed"] == 0 and s1["clients_all_ok"] == 1000
        assert s1["p95_ms"] >= s1["p50_ms"] >= 0
        # second serve of the same block: all unique fetches hit the LRU
        s2 = server.serve_samples(b"\x07" * 32, sidecars, pop)
        assert s2["cache_misses"] == 0
        assert s2["cache_hits"] == s2["unique_requests"]
        # a corrupted served cell -> failed samples, attributed to clients
        bad_cells = np.asarray(grids[0]).copy()
        bad_cells[:, 0] ^= 0xFF
        sidecars[0].cells = bad_cells
        server2 = DasServer(eng.scheme, registry=registry)
        s3 = server2.serve_samples(b"\x08" * 32, sidecars, pop)
        assert s3["failed"] > 0
        assert s3["clients_all_ok"] < 1000
        counts = registry.counts()
        assert counts["das_samples_total"] == 12000
        assert counts["das_sample_verify_failures_total"] == s3["failed"]
        assert counts["das_request_seconds;stat=count"] == \
            s1["unique_requests"] + s2["unique_requests"] \
            + s3["unique_requests"]


# --- end-to-end simulation ----------------------------------------------------

class TestDasSimulation:
    def test_faulted_das_sim_serves_and_reports(self, tmp_path):
        """A lossy DAS run: dropped sidecars backfill at import time, the
        population is served every slot, and the offline report carries
        the DAS serving section."""
        import json

        from pos_evolution_tpu.config import cfg
        from pos_evolution_tpu.sim import Simulation, faulty_schedule, lossy_plan
        from pos_evolution_tpu.telemetry import Telemetry
        c = cfg()
        tel = Telemetry.to_file(str(tmp_path / "events.jsonl"))
        plan = lossy_plan(seed=13, drop_p=0.15,
                          gst=c.slots_per_epoch * c.seconds_per_slot)
        sim = Simulation(32, schedule=faulty_schedule(32, plan),
                         das=True, telemetry=tel)
        sim.attach_das_clients(2000, seed=7)
        sim.run_epochs(2)
        tel.close()

        serves = tel.bus.of_type("das_serve")
        assert serves, "population was never served"
        assert serves[-1]["failed"] == 0
        assert serves[-1]["clients_all_ok"] == 2000
        counts = tel.registry.counts()
        accepted = sum(v for k, v in counts.items()
                       if k.startswith("das_sidecars_accepted_total"))
        assert accepted > 0
        # faults dropped sidecars pre-GST; imports pulled them by req/resp
        assert any(k.startswith("das_blob_backfills_total")
                   for k in counts), "lossy run should exercise backfill"
        # finality parity with a blob-free twin: DAS must not slow the chain
        sim_plain = Simulation(
            32, schedule=faulty_schedule(32, lossy_plan(
                seed=13, drop_p=0.15,
                gst=c.slots_per_epoch * c.seconds_per_slot)))
        sim_plain.run_epochs(2)
        assert sim.finalized_epoch() == sim_plain.finalized_epoch()
        assert sim.justified_epoch() == sim_plain.justified_epoch()

        # offline report: DAS serving section present in md and json
        import os
        import subprocess
        import sys
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        out_json = tmp_path / "report.json"
        out_md = tmp_path / "report.md"
        r = subprocess.run(
            [sys.executable, os.path.join(repo, "scripts", "run_report.py"),
             str(tmp_path / "events.jsonl"), "--json", str(out_json),
             "--markdown", str(out_md)],
            capture_output=True, text=True, timeout=120, cwd=repo)
        assert r.returncode == 0, r.stderr
        md = out_md.read_text()
        assert "## DAS serving" in md
        assert "p50" in md and "cache hit rate" in md
        report = json.loads(out_json.read_text())
        das = report["das_serving"]
        assert das["clients"] == 2000
        assert das["verify_failures"] == 0
        assert das["p95_ms"] >= das["p50_ms"] >= 0

    def test_checkpoint_resume_with_das(self):
        from pos_evolution_tpu.config import cfg
        from pos_evolution_tpu.sim import Simulation
        from pos_evolution_tpu.specs import forkchoice as fc
        c = cfg()
        sim = Simulation(32, das=True)
        sim.run_until_slot(c.slots_per_epoch + 2)
        blob = sim.checkpoint()
        # a mismatched engine must refuse loudly: its regenerated
        # sidecars could never satisfy the checkpointed graffiti
        # commitments, so the resumed chain would stall silently forever
        from pos_evolution_tpu.das import BlobEngine
        with pytest.raises(ValueError, match="does not match"):
            Simulation.resume(blob, das=BlobEngine(seed=sim.das.seed + 1))
        twin = Simulation.resume(blob, das=sim.das)
        target = 2 * c.slots_per_epoch
        sim.run_until_slot(target)
        twin.run_until_slot(target)
        assert fc.get_head(twin.store()) == fc.get_head(sim.store())
        assert twin.finalized_epoch() == sim.finalized_epoch()
        # availability state carried over: resumed gate still satisfied
        head = fc.get_head(twin.store())
        block = twin.store().blocks[head]
        assert twin.groups[0].blob_store.is_available(head, block)


# --- compile prewarm (ROADMAP item 2 remainder) -------------------------------

class TestCompilePrewarm:
    def test_prewarm_pins_block_sweep_recompiles(self):
        """``Simulation(prewarm=True)`` compiles every padded
        attestation-batch shape at init: the fused sweep's jit cache must
        not grow during the run, and ``jax_backend_compiles_total`` must
        stay flat after the first epoch (the epoch 2-3 compile-storm
        symptom of ROADMAP item 2)."""
        from pos_evolution_tpu.backend import set_backend
        from pos_evolution_tpu.ops import transition
        from pos_evolution_tpu.sim import Simulation
        from pos_evolution_tpu.telemetry import jaxrt
        from pos_evolution_tpu.telemetry.registry import MetricsRegistry

        registry = MetricsRegistry()
        set_backend("jax")
        jaxrt.install(registry)
        try:
            transition.reset_session()
            sim = Simulation(64, prewarm=True)
            fn = transition._sweep_fn()
            warmed = fn._cache_size()
            assert warmed > 0, "prewarm compiled nothing"
            sim.run_epochs(1)
            mark = registry.counter("jax_backend_compiles_total").value()
            sim.run_epochs(3)
            assert fn._cache_size() == warmed, \
                "a block-sweep shape escaped the prewarm lattice"
            delta = registry.counter(
                "jax_backend_compiles_total").value() - mark
            assert delta == 0, \
                f"{delta} mid-run recompiles after the warm-up epoch"
        finally:
            jaxrt.install(None)
            set_backend("numpy")
            transition.reset_session()

    def test_compile_cache_knob_sets_jax_config(self, tmp_path):
        import jax

        from pos_evolution_tpu.sim import Simulation
        prev = jax.config.jax_compilation_cache_dir
        try:
            Simulation(16, compile_cache=tmp_path / "xla-cache")
            assert jax.config.jax_compilation_cache_dir == \
                str(tmp_path / "xla-cache")
        finally:
            jax.config.update("jax_compilation_cache_dir", prev)
