
š/device:TPU:0 (fixture)DXLA OpsÀ„="€Ð¬ó"À–±€´ÄÃ!"€ÚÄ	€¨Ö¹"€‡§€Êµî	XLA Ops#1€‰z"€”ëÜ"=95jit(run)/while/body/jit(head_and_weights)/scatter-add"C?;jit(run)/while/body/jit(aggregate_verify_batch)/dot-general"jit(run)/transpose
8	/host:CPUpython Â"€À²Í;"bench_epoch