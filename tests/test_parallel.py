"""Multi-device tests on the virtual 8-device CPU mesh (SURVEY.md §4.4c):
the sharded epoch pass must equal the single-chip kernel exactly; SSF
tallies and gossip must execute their collective paths.
"""

import numpy as np
import pytest

from pos_evolution_tpu.config import minimal_config

jax = pytest.importorskip("jax")

pytestmark = pytest.mark.mesh8


@pytest.fixture(scope="module")
def mesh():
    from pos_evolution_tpu.parallel.sharded import make_mesh
    assert len(jax.devices()) == 8, "conftest must force 8 virtual CPU devices"
    return make_mesh(8, n_pods=2)


def _dense_registry(n, seed=0):
    import jax.numpy as jnp
    from pos_evolution_tpu.ops.epoch import DenseRegistry
    rng = np.random.default_rng(seed)
    gwei = 10**9
    bal = rng.integers(20 * gwei, 40 * gwei, n).astype(np.int64)
    return DenseRegistry(
        effective_balance=jnp.asarray(np.minimum(bal // gwei, 32) * gwei),
        balance=jnp.asarray(bal),
        activation_epoch=jnp.asarray(
            np.where(rng.random(n) < 0.9, 0, 99).astype(np.int64)),
        exit_epoch=jnp.asarray(
            np.where(rng.random(n) < 0.95, 2**62, 5).astype(np.int64)),
        withdrawable_epoch=jnp.asarray(np.full(n, 2**62, np.int64)),
        slashed=jnp.asarray(rng.random(n) < 0.05),
        prev_flags=jnp.asarray(rng.integers(0, 8, n).astype(np.uint8)),
        cur_flags=jnp.asarray(rng.integers(0, 8, n).astype(np.uint8)),
        inactivity_scores=jnp.asarray(rng.integers(0, 30, n).astype(np.int64)),
    )


class TestShardedEpoch:
    def test_matches_single_chip(self, mesh):
        import jax.numpy as jnp
        from pos_evolution_tpu.ops.epoch import process_epoch_dense
        from pos_evolution_tpu.parallel.sharded import (
            shard_registry, sharded_epoch_step,
        )
        cfg = minimal_config()
        reg = _dense_registry(256)
        bits = jnp.asarray(np.array([0, 1, 1, 0], dtype=bool))
        single = process_epoch_dense(reg, 9, 6, bits, 7, 8, 12345, cfg)

        step = sharded_epoch_step(mesh, cfg)
        sharded_reg = shard_registry(mesh, reg)
        multi = step(sharded_reg, jnp.int64(9), jnp.int64(6), bits,
                     jnp.int64(7), jnp.int64(8), jnp.int64(12345))

        for f in reg._fields:
            a = np.asarray(getattr(single.registry, f))
            b = np.asarray(getattr(multi.registry, f))
            assert np.array_equal(a, b), f"sharded {f} diverges"
        assert int(single.total_active_balance) == int(multi.total_active_balance)
        assert np.array_equal(np.asarray(single.new_justification_bits),
                              np.asarray(multi.new_justification_bits))
        assert int(single.finalize_epoch) == int(multi.finalize_epoch)


class TestSSFTally:
    def test_supermajority_cross_pod(self, mesh):
        import jax.numpy as jnp
        from pos_evolution_tpu.parallel.sharded import ssf_supermajority_tally
        n = 128
        gwei = 10**9
        eff = jnp.asarray(np.full(n, 32 * gwei, np.int64))
        total = jnp.int64(n * 32 * gwei)
        tally = ssf_supermajority_tally(mesh)
        votes = jnp.asarray(np.arange(n) < 86)  # 86/128 > 2/3
        s, ok = tally(votes, eff, total)
        assert bool(ok) and int(s) == 86 * 32 * gwei
        votes = jnp.asarray(np.arange(n) < 85)  # 85/128 < 2/3 (85*3=255<256)
        s, ok = tally(votes, eff, total)
        assert not bool(ok)


class TestRingAllreduce:
    def test_ring_matches_psum(self, mesh):
        """The explicit ppermute ring must equal the fused psum tally."""
        import jax.numpy as jnp
        from pos_evolution_tpu.parallel.sharded import (
            ring_allreduce_tally, ssf_supermajority_tally,
        )
        n = 128
        gwei = 10**9
        rng = np.random.default_rng(5)
        eff = jnp.asarray(rng.integers(16, 33, n).astype(np.int64) * gwei)
        votes = jnp.asarray(rng.random(n) < 0.6)
        ring = ring_allreduce_tally(mesh)
        psum_tally = ssf_supermajority_tally(mesh)
        total = jnp.int64(int(np.asarray(eff).sum()))
        s_ring = int(ring(votes, eff))
        s_psum, _ = psum_tally(votes, eff, total)
        assert s_ring == int(s_psum)
        assert s_ring == int(np.asarray(eff)[np.asarray(votes)].sum())


class TestGossip:
    def test_masked_all_gather(self, mesh):
        import jax.numpy as jnp
        from pos_evolution_tpu.parallel.sharded import gossip_all_gather
        n = 64
        msgs = jnp.asarray(np.arange(n, dtype=np.int64))
        # recipient i hears only senders with the same parity (a partition)
        mask = np.zeros((n, n), dtype=bool)
        for i in range(n):
            mask[i, i % 2::2] = True
        gossip = gossip_all_gather(mesh)
        out = np.asarray(gossip(msgs, jnp.asarray(mask)))
        evens = sum(range(0, n, 2))
        odds = sum(range(1, n, 2))
        assert out[0] == evens and out[1] == odds and out[2] == evens

    def test_factored_matches_dense(self, mesh):
        """The O(n + D^2) factored fabric equals the dense n x n mask
        built from the same factors (VERDICT r4 item 8)."""
        import jax.numpy as jnp
        from pos_evolution_tpu.parallel.sharded import (
            gossip_all_gather, gossip_factored)
        n, d = 64, mesh.size
        per = n // d
        rng = np.random.default_rng(3)
        msgs = np.arange(10, 10 + n, dtype=np.int64)
        send_up = rng.random(n) < 0.8
        recv_up = rng.random(n) < 0.9
        link = rng.random((d, d)) < 0.7
        np.fill_diagonal(link, True)

        dense_mask = (recv_up[:, None] & send_up[None, :]
                      & link[np.arange(n) // per][:, np.arange(n) // per])
        want = np.asarray(gossip_all_gather(mesh)(
            jnp.asarray(msgs), jnp.asarray(dense_mask)))
        got = np.asarray(gossip_factored(mesh)(
            jnp.asarray(msgs), jnp.asarray(send_up), jnp.asarray(recv_up),
            jnp.asarray(link)))
        assert np.array_equal(got, want)

    def test_factored_full_partition(self, mesh):
        """Two isolated halves: each recipient hears only its side."""
        import jax.numpy as jnp
        from pos_evolution_tpu.parallel.sharded import gossip_factored
        n, d = 64, mesh.size
        per = n // d
        msgs = np.ones(n, dtype=np.int64)
        up = np.ones(n, dtype=bool)
        link = np.zeros((d, d), dtype=bool)
        link[:d // 2, :d // 2] = True
        link[d // 2:, d // 2:] = True
        out = np.asarray(gossip_factored(mesh)(
            jnp.asarray(msgs), jnp.asarray(up), jnp.asarray(up),
            jnp.asarray(link)))
        assert np.array_equal(out[:n // 2], np.full(n // 2, n // 2))
        assert np.array_equal(out[n // 2:], np.full(n // 2, n // 2))
        assert per * (d // 2) == n // 2  # the halves align with devices


class TestNumpyCollectivesParity:
    def test_same_interface(self):
        from pos_evolution_tpu.parallel.collectives import NumpyCollectives
        c = NumpyCollectives
        x = np.arange(4)
        assert np.array_equal(c.psum(x, "shard"), x)
        assert c.all_gather(x, "shard").shape == (1, 4)
        assert c.axis_index("shard") == 0


class TestShardedVoteWeights:
    def test_matches_single_chip_segment_sum(self, mesh):
        """Config #1 sharded: validator-sharded latest-message accumulation
        psum-merged == single-device segment_sum (and the host oracle)."""
        import jax.numpy as jnp
        from pos_evolution_tpu.parallel.sharded import sharded_vote_weights

        n, capacity = 256, 32
        rng = np.random.default_rng(3)
        msg_block = rng.integers(-1, capacity, n).astype(np.int32)
        weight = rng.integers(1, 33, n).astype(np.int64) * 10**9

        votes = sharded_vote_weights(mesh, capacity)
        got = np.asarray(votes(jnp.asarray(msg_block), jnp.asarray(weight)))

        want = np.zeros(capacity + 1, np.int64)
        np.add.at(want, np.where(msg_block >= 0, msg_block, capacity),
                  np.where(msg_block >= 0, weight, 0))
        assert np.array_equal(got, want[:capacity])

    def test_feeds_subtree_pass(self, mesh):
        """The replicated psum output composes with the binary-lifting
        subtree pass to reproduce the single-chip head weights."""
        import jax.numpy as jnp
        from pos_evolution_tpu.ops.forkchoice import _subtree_accumulate
        from pos_evolution_tpu.parallel.sharded import sharded_vote_weights

        n, capacity = 128, 16
        rng = np.random.default_rng(4)
        msg_block = rng.integers(0, capacity, n).astype(np.int32)
        weight = np.full(n, 10**9, np.int64)
        parent = jnp.asarray(np.arange(-1, capacity - 1, dtype=np.int32))
        real = jnp.ones(capacity, bool)

        votes = sharded_vote_weights(mesh, capacity)
        vw = votes(jnp.asarray(msg_block), jnp.asarray(weight))
        got = np.asarray(_subtree_accumulate(parent, real, vw, capacity))

        vw_single = np.bincount(msg_block, weights=weight.astype(float),
                                minlength=capacity).astype(np.int64)
        want = np.asarray(_subtree_accumulate(
            parent, real, jnp.asarray(vw_single), capacity))
        assert np.array_equal(got, want)


class TestShardedAggregation:
    def test_matches_single_chip_kernel(self, mesh):
        """Config #3 sharded: committee-sharded aggregate verification
        all-gather-merged == the single-chip kernel, valid + corrupt."""
        import jax.numpy as jnp
        from pos_evolution_tpu.ops.aggregation import (
            aggregate_verify_batch, precompute_pk_states)
        from pos_evolution_tpu.parallel.sharded import (
            sharded_aggregation_verify)

        n, n_agg, lanes = 64, 16, 8
        rng = np.random.default_rng(5)
        pk_states = precompute_pk_states(
            rng.integers(0, 256, (n, 48)).astype(np.uint8))
        committees = rng.integers(0, n, (n_agg, lanes)).astype(np.int32)
        bits = rng.integers(0, 2, (n_agg, lanes)).astype(bool)
        msg_words = rng.integers(0, 2**32, (n_agg, 8),
                                 dtype=np.uint64).astype(np.uint32)
        sigs = rng.integers(0, 2**32, (n_agg, 24),
                            dtype=np.uint64).astype(np.uint32)
        verify = sharded_aggregation_verify(mesh)
        got = np.asarray(verify(pk_states, jnp.asarray(committees),
                                jnp.asarray(bits), jnp.asarray(msg_words),
                                jnp.asarray(sigs)))
        want = np.asarray(aggregate_verify_batch(
            pk_states, jnp.asarray(committees), jnp.asarray(bits),
            jnp.asarray(msg_words), jnp.asarray(sigs)))
        assert np.array_equal(got, want)


class TestShardedShuffle:
    def test_matches_single_chip_permutation(self, mesh):
        """Config #2 sharded: index-sharded swap-or-not == the single-chip
        permutation (which is itself pinned to the scalar spec oracle)."""
        import jax.numpy as jnp
        from pos_evolution_tpu.ops.shuffle import (
            _seed_words, host_pivots, shuffle_permutation_jax)
        from pos_evolution_tpu.parallel.sharded import sharded_shuffle

        n, rounds = 512, 10
        seed = bytes(reversed(range(32)))
        shuf = sharded_shuffle(mesh, n, rounds)
        got = np.asarray(shuf(jnp.asarray(_seed_words(seed)),
                              jnp.asarray(host_pivots(seed, n, rounds)),
                              jnp.arange(n, dtype=jnp.int32)))
        want = np.asarray(shuffle_permutation_jax(seed, n, rounds))
        assert np.array_equal(got, want)
        assert sorted(got) == list(range(n))  # a real permutation
