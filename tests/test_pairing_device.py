"""Differential tests: device batched pairing (ops/pairing.py) vs the
exact Python oracle (crypto/bls12_381.py).

The device computes e(P, Q)^3 (the x-chain hard part uses the identity
3*(q^4-q^2+1)/r = (x-1)^2 (x+q) (x^2+q^2-1) + 3; gcd(3, r) = 1 keeps
every is-one decision intact), so oracle comparisons cube the oracle
value. The device Miller value differs from the oracle's by Fq2-constant
line scalings, which the final exponentiation provably kills — all
comparisons happen after final exponentiation.

XLA:CPU note: jitting the whole pipeline is compile-prohibitive on CPU
(it is the TPU path); CPU tests call the pipeline EAGERLY — the dense
algebra keeps eager dispatch counts low, and the in-pipeline lax.scans
compile their small bodies once. The wide-batch differentials (including
the bench-critical FastAggregateVerify vs PyBLS) run in the DEFAULT
suite — they add several scan-body compiles at other batch shapes
(minutes on XLA:CPU, cheap on TPU); set POS_TEST_PAIRING=0 to opt out
when iterating locally."""

import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from pos_evolution_tpu.crypto import bls12_381 as oracle  # noqa: E402
from pos_evolution_tpu.ops import fp, pairing, tower  # noqa: E402

_WIDE = pytest.mark.skipif(
    os.environ.get("POS_TEST_PAIRING") == "0",
    reason="wide-batch pairing differentials disabled (POS_TEST_PAIRING=0)")


def enc_pair(p, q):
    return (jax.numpy.asarray(pairing.g1_affine_encode(p)[None]),
            jax.numpy.asarray(pairing.g2_affine_encode(q)[None]))


class TestHardPartIdentity:
    def test_exact_identity(self):
        q, r, x = oracle.Q, oracle.R, -oracle.BLS_X
        h = (q**4 - q**2 + 1) // r
        assert (q**4 - q**2 + 1) % r == 0
        assert (x - 1)**2 * (x + q) * (x**2 + q**2 - 1) + 3 == 3 * h
        import math
        assert math.gcd(3, r) == 1

    def test_w_factor_annihilated(self):
        """The Miller value carries a loop-dependent w^(3M) factor (each
        line is scaled by w^3 and amplified by later squarings, so M is
        an odd accumulation — NOT a pure xi power). It cancels for every
        M because ord(w) | 6(q^2-1) (w^6 = xi in Fq2*) and the full
        final-exp exponent e = 3(q^12-1)/r is a multiple of 6(q^2-1)."""
        q, r = oracle.Q, oracle.R
        assert (q**12 - 1) % r == 0
        e = 3 * (q**12 - 1) // r
        assert e % (6 * (q**2 - 1)) == 0


@pytest.mark.slow
class TestPairingEndToEnd:
    def test_miller_finalexp_infinity_and_oracle_parity(self):
        """One batch=1 shape end-to-end (eager): full device pairing ==
        oracle pairing cubed; the infinity mask yields one; and the
        final exponentiation alone matches the oracle on an arbitrary
        Fq12 input (all sharing the same compiled scan bodies)."""
        p = oracle.ec_mul(oracle.G1_GEN, 0xDEADBEEFCAFE)
        q = oracle.ec_mul(oracle.G2_GEN, 0x1337C0DE)
        ep, eq = enc_pair(p, q)
        f = pairing.miller_loop(ep, eq)
        got = tower.fq12_decode(pairing.final_exponentiation(f), (0,))
        assert got == oracle.pairing(p, q).pow(3)

        inf = jax.numpy.asarray(np.array([True]))
        f_inf = pairing.miller_loop(ep, eq, inf)
        assert tower.fq12_decode(f_inf, (0,)) == oracle.FQ12_ONE

        rng = np.random.default_rng(0)

        def rand_fq2():
            return oracle.Fq2(int.from_bytes(rng.bytes(48), "big"),
                              int.from_bytes(rng.bytes(48), "big"))

        g = oracle.Fq12(
            oracle.Fq6(rand_fq2(), rand_fq2(), rand_fq2()),
            oracle.Fq6(rand_fq2(), rand_fq2(), rand_fq2()))
        enc = jax.numpy.asarray(tower.fq12_encode(g)[None])
        got_fe = tower.fq12_decode(pairing.final_exponentiation(enc), (0,))
        assert got_fe == g.pow(3 * oracle._FINAL_EXP)


@pytest.mark.slow
@_WIDE
class TestPairingWide:
    def test_bilinearity_on_device(self):
        """e(2P, Q) == e(P, Q)^2 — all-device check over a batch of 2."""
        p = oracle.ec_mul(oracle.G1_GEN, 777)
        p2 = oracle.ec_double(p)
        q = oracle.ec_mul(oracle.G2_GEN, 31337)
        ps = jax.numpy.asarray(np.stack(
            [pairing.g1_affine_encode(p2), pairing.g1_affine_encode(p)]))
        qs = jax.numpy.asarray(np.stack(
            [pairing.g2_affine_encode(q), pairing.g2_affine_encode(q)]))
        out = pairing.pairing(ps, qs)
        left = tower.fq12_decode(out, (0,))
        right = tower.fq12_decode(out, (1,)).sq()
        assert left == right


@pytest.mark.slow
class TestG1Aggregation:
    def test_masked_sum_matches_oracle(self):
        rng = np.random.default_rng(1)
        pts = [oracle.ec_mul(oracle.G1_GEN, int(rng.integers(2, 2**40)))
               for _ in range(6)]
        mask = np.array([True, False, True, True, False, True])
        enc = jax.numpy.asarray(
            np.stack([pairing.g1_affine_encode(p) for p in pts])[None])
        got_j = pairing.g1_sum_masked(enc, jax.numpy.asarray(mask[None]))
        aff, inf = pairing.g1_to_affine(got_j)
        acc = None
        for p, m in zip(pts, mask):
            if m:
                acc = oracle.ec_add(acc, p)
        assert not bool(np.asarray(inf)[0])
        x = fp.from_limbs(np.asarray(fp.canon(aff[0, 0]))) % oracle.Q
        y = fp.from_limbs(np.asarray(fp.canon(aff[0, 1]))) % oracle.Q
        assert (x, y) == acc

    def test_empty_mask_is_infinity(self):
        pts = [oracle.G1_GEN, oracle.G1_GEN]
        enc = jax.numpy.asarray(
            np.stack([pairing.g1_affine_encode(p) for p in pts])[None])
        mask = jax.numpy.asarray(np.zeros((1, 2), dtype=bool))
        _, inf = pairing.g1_to_affine(
            pairing.g1_sum_masked(enc, mask))
        assert bool(np.asarray(inf)[0])

    def test_cancellation_to_infinity(self):
        """P + (-P) through the unified add."""
        p = oracle.ec_mul(oracle.G1_GEN, 99)
        np_ = oracle.ec_neg(p)
        enc = jax.numpy.asarray(np.stack(
            [pairing.g1_affine_encode(p), pairing.g1_affine_encode(np_)])[None])
        mask = jax.numpy.asarray(np.ones((1, 2), dtype=bool))
        _, inf = pairing.g1_to_affine(
            pairing.g1_sum_masked(enc, mask))
        assert bool(np.asarray(inf)[0])


@pytest.mark.slow
@_WIDE
class TestFastAggregateVerify:
    def test_matches_pybls(self):
        """Device batched verify vs PyBLS verdicts: a valid aggregate, a
        wrong-message signature, and an empty bitlist."""
        sks = [11, 22, 33, 44]
        pk_bytes = [oracle.PyBLS.SkToPk(sk) for sk in sks]
        pk_table = jax.numpy.asarray(np.stack(
            [pairing.g1_affine_encode(oracle.g1_decompress(b))
             for b in pk_bytes]))
        msgs = [b"attestation-0", b"attestation-1", b"attestation-2"]
        committees = np.array([[0, 1, 2, 3]] * 3, dtype=np.int32)
        bits = np.array([
            [True, True, True, True],
            [True, False, True, False],
            [False, False, False, False],
        ])
        sig0 = oracle.PyBLS.Aggregate(
            [oracle.PyBLS.Sign(sk, msgs[0]) for sk in sks])
        sig1_wrong = oracle.PyBLS.Aggregate(
            [oracle.PyBLS.Sign(sks[0], msgs[0]),       # signed msg 0, not 1
             oracle.PyBLS.Sign(sks[2], msgs[1])])
        sig2 = oracle.PyBLS.Sign(sks[0], msgs[2])
        sigs = [sig0, sig1_wrong, sig2]

        msg_g2 = jax.numpy.asarray(np.stack(
            [pairing.g2_affine_encode(oracle.hash_to_g2(m)) for m in msgs]))
        sig_pts = [oracle.g2_decompress(s) for s in sigs]
        sig_g2 = jax.numpy.asarray(np.stack(
            [pairing.g2_affine_encode(s) for s in sig_pts]))
        sig_inf = jax.numpy.asarray(
            np.array([s is None for s in sig_pts]))

        got = np.asarray(pairing.fast_aggregate_verify_batch(
            pk_table, jax.numpy.asarray(committees),
            jax.numpy.asarray(bits), msg_g2, sig_g2, sig_inf))

        want = []
        for i in range(3):
            members = [pk_bytes[v] for v, b in zip(committees[i], bits[i]) if b]
            want.append(oracle.PyBLS.FastAggregateVerify(
                members, msgs[i], sigs[i]))
        assert want == [True, False, False]
        assert got.tolist() == want
