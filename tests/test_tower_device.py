"""Differential tests: device tower arithmetic (ops/tower.py, dense
[..., d, 32] algebra representation) vs the exact Python oracle
(crypto/bls12_381.py) — Fq2/Fq6/Fq12 ops, Frobenius, pow ladder."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

# Compile-dominated oracle differentials (~6 min on XLA:CPU): slow tier,
# run with `pytest -m ""` (full) or `-m slow`.
pytestmark = pytest.mark.slow

from pos_evolution_tpu.crypto import bls12_381 as oracle  # noqa: E402
from pos_evolution_tpu.ops import tower  # noqa: E402


def rand_fq2(rng) -> oracle.Fq2:
    return oracle.Fq2(int.from_bytes(rng.bytes(48), "big"),
                      int.from_bytes(rng.bytes(48), "big"))


def rand_fq6(rng) -> oracle.Fq6:
    return oracle.Fq6(rand_fq2(rng), rand_fq2(rng), rand_fq2(rng))


def rand_fq12(rng) -> oracle.Fq12:
    return oracle.Fq12(rand_fq6(rng), rand_fq6(rng))


def batch(encoded):
    return jax.numpy.asarray(np.stack(encoded))


class TestStructureTensors:
    def test_tensor_entries_small(self):
        for T in (tower._T2, tower._T6, tower._T12):
            assert np.abs(T).max() <= 2

    def test_subalgebra_nesting(self):
        assert (tower._T12[:2, :2, :2] == tower._T2).all()
        assert (tower._T12[:6, :6, :6] == tower._T6).all()


class TestFq2:
    def test_mul_add_sub(self):
        rng = np.random.default_rng(0)
        xs = [rand_fq2(rng) for _ in range(8)]
        ys = [rand_fq2(rng) for _ in range(8)]
        ex = batch([tower.fq2_encode(v) for v in xs])
        ey = batch([tower.fq2_encode(v) for v in ys])
        mul = jax.jit(tower.fq2_mul)(ex, ey)
        add = jax.jit(tower.alg_add)(ex, ey)
        sub = jax.jit(tower.alg_sub)(ex, ey)
        for i in range(8):
            assert tower.fq2_decode(mul, (i,)) == xs[i] * ys[i]
            assert tower.fq2_decode(add, (i,)) == xs[i] + ys[i]
            assert tower.fq2_decode(sub, (i,)) == xs[i] - ys[i]

    def test_sq_conj_xi_inv_muli(self):
        rng = np.random.default_rng(1)
        xs = [rand_fq2(rng) for _ in range(4)]
        e = batch([tower.fq2_encode(v) for v in xs])
        sq = jax.jit(tower.fq2_sq)(e)
        cj = jax.jit(tower.fq2_conj)(e)
        xi = jax.jit(tower.fq2_mul_xi)(e)
        iv = jax.jit(tower.fq2_inv)(e)
        m3 = jax.jit(lambda v: tower.fq2_muli(v, 3))(e)
        for i in range(4):
            assert tower.fq2_decode(sq, (i,)) == xs[i].sq()
            assert tower.fq2_decode(cj, (i,)) == xs[i].conj()
            assert tower.fq2_decode(xi, (i,)) == xs[i] * oracle.XI
            assert tower.fq2_decode(iv, (i,)) == xs[i].inv()
            assert tower.fq2_decode(m3, (i,)) == xs[i] * 3


class TestFq6:
    def test_mul_v_inv(self):
        rng = np.random.default_rng(2)
        x, y = rand_fq6(rng), rand_fq6(rng)
        ex = batch([tower.fq6_encode(x)])
        ey = batch([tower.fq6_encode(y)])
        assert tower.fq6_decode(jax.jit(tower.alg_mul)(ex, ey), (0,)) == x * y
        assert tower.fq6_decode(jax.jit(tower.fq6_mul_v)(ex), (0,)) \
            == x.mul_by_v()
        got = tower.fq6_decode(jax.jit(tower.fq6_inv)(ex), (0,))
        assert got * x == oracle.FQ6_ONE


class TestFq12:
    def test_mul_sq_conj_inv(self):
        rng = np.random.default_rng(3)
        x, y = rand_fq12(rng), rand_fq12(rng)
        ex = batch([tower.fq12_encode(x)])
        ey = batch([tower.fq12_encode(y)])
        assert tower.fq12_decode(jax.jit(tower.fq12_mul)(ex, ey), (0,)) == x * y
        assert tower.fq12_decode(jax.jit(tower.fq12_sq)(ex), (0,)) == x.sq()
        assert tower.fq12_decode(jax.jit(tower.fq12_conj)(ex), (0,)) == x.conj()
        got = tower.fq12_decode(jax.jit(tower.fq12_inv)(ex), (0,))
        assert got * x == oracle.FQ12_ONE

    def test_sparse_mul(self):
        """Sparse right operand at chosen Fq-component slots == dense mul
        of its embedding (the Miller-loop line multiplication shape)."""
        rng = np.random.default_rng(4)
        x = rand_fq12(rng)
        slots = (0, 1, 4, 5, 8, 9)   # Fq2 slots w^0, w^2, w^3 flattened
        svals = [int.from_bytes(rng.bytes(48), "big") % oracle.Q
                 for _ in slots]
        dense = [0] * 12
        for s, v in zip(slots, svals):
            dense[s] = v
        y_or = tower._fq12_from_coeffs(dense)
        ex = batch([tower.fq12_encode(x)])
        ysp = batch([np.stack([tower.fp.to_limbs(v) for v in svals])])
        got = jax.jit(lambda a, b: tower.alg_mul(a, b, y_slots=slots))(ex, ysp)
        assert tower.fq12_decode(got, (0,)) == x * y_or

    def test_frobenius(self):
        rng = np.random.default_rng(5)
        x = rand_fq12(rng)
        ex = batch([tower.fq12_encode(x)])
        got1 = tower.fq12_decode(jax.jit(tower.fq12_frob1)(ex), (0,))
        got2 = tower.fq12_decode(jax.jit(tower.fq12_frob2)(ex), (0,))
        assert got1 == x.pow(oracle.Q)
        assert got2 == x.pow(oracle.Q * oracle.Q)

    def test_pow_bits(self):
        rng = np.random.default_rng(6)
        xs = [rand_fq12(rng) for _ in range(2)]
        e = int.from_bytes(rng.bytes(8), "big")
        bits = np.array([b == "1" for b in bin(e)[2:]], dtype=bool)
        enc = batch([tower.fq12_encode(v) for v in xs])
        got = jax.jit(lambda v: tower.fq12_pow_bits(v, bits))(enc)
        for i in range(2):
            assert tower.fq12_decode(got, (i,)) == xs[i].pow(e)


class TestCyclotomic:
    def test_cyclotomic_sq_matches_dense_in_subgroup(self):
        """Granger-Scott squaring == dense squaring for easy-part outputs
        (the only inputs the final-exponentiation ladders feed it), and
        the cyclotomic pow ladder == the generic ladder there too."""
        rng = np.random.default_rng(7)
        x = rand_fq12(rng)
        # easy part maps any unit into the cyclotomic subgroup
        cyc = x.conj() * x.inv()
        cyc = cyc.pow(oracle.Q * oracle.Q) * cyc
        enc = batch([tower.fq12_encode(cyc)])
        got = tower.fq12_decode(jax.jit(tower.fq12_cyclotomic_sq)(enc), (0,))
        assert got == cyc.sq()

        e = int.from_bytes(rng.bytes(8), "big")
        bits = np.array([b == "1" for b in bin(e)[2:]], dtype=bool)
        gotp = tower.fq12_decode(
            jax.jit(lambda v: tower.fq12_pow_bits_cyclotomic(v, bits))(enc),
            (0,))
        assert gotp == cyc.pow(e)

    def test_cyclotomic_sq_of_one_is_one(self):
        one = batch([tower.fq12_encode(oracle.FQ12_ONE)])
        got = tower.fq12_decode(tower.fq12_cyclotomic_sq(one), (0,))
        assert got == oracle.FQ12_ONE
