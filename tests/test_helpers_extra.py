"""Coverage for remaining spec helpers: domains/fork versioning, sync
committee assignment, proposer weighting, churn limits, seeds.
"""

import numpy as np
import pytest

from pos_evolution_tpu.config import (
    DOMAIN_BEACON_ATTESTER,
    DOMAIN_BEACON_PROPOSER,
    cfg,
)
from pos_evolution_tpu.specs.containers import Fork
from pos_evolution_tpu.specs.genesis import make_genesis
from pos_evolution_tpu.specs.helpers import (
    compute_domain,
    compute_epoch_at_slot,
    compute_proposer_index,
    compute_start_slot_at_epoch,
    get_active_validator_indices,
    get_domain,
    get_seed,
    get_validator_churn_limit,
    integer_squareroot,
    is_assigned_to_sync_committee,
)

pytestmark = pytest.mark.usefixtures("minimal_cfg")


class TestTimeMath:
    def test_epoch_slot_roundtrip(self):
        spe = cfg().slots_per_epoch
        for e in (0, 1, 7, 1000):
            assert compute_epoch_at_slot(compute_start_slot_at_epoch(e)) == e
            assert compute_epoch_at_slot(compute_start_slot_at_epoch(e) + spe - 1) == e

    def test_integer_squareroot(self):
        for n in (0, 1, 2, 3, 4, 15, 16, 17, 10**12, 32 * 10**9 * 10**6):
            s = integer_squareroot(n)
            assert s * s <= n < (s + 1) * (s + 1)


class TestDomains:
    def test_domain_depends_on_fork_version(self):
        d1 = compute_domain(DOMAIN_BEACON_PROPOSER, b"\x00" * 4, b"\x01" * 32)
        d2 = compute_domain(DOMAIN_BEACON_PROPOSER, b"\x01\x00\x00\x00", b"\x01" * 32)
        d3 = compute_domain(DOMAIN_BEACON_ATTESTER, b"\x00" * 4, b"\x01" * 32)
        assert d1 != d2 and d1 != d3
        assert d1[:4] == DOMAIN_BEACON_PROPOSER

    def test_get_domain_selects_fork_by_epoch(self):
        state, _ = make_genesis(8)
        state.fork = Fork(previous_version=b"\x00" * 4,
                          current_version=b"\x01\x00\x00\x00", epoch=5)
        state.slot = 6 * cfg().slots_per_epoch
        old = get_domain(state, DOMAIN_BEACON_PROPOSER, epoch=3)
        new = get_domain(state, DOMAIN_BEACON_PROPOSER, epoch=6)
        assert old != new
        assert new == compute_domain(DOMAIN_BEACON_PROPOSER, b"\x01\x00\x00\x00",
                                     bytes(state.genesis_validators_root))


class TestSeeds:
    def test_seed_varies_by_epoch_and_domain(self):
        state, _ = make_genesis(8)
        state.randao_mixes = np.random.default_rng(0).integers(
            0, 255, state.randao_mixes.shape).astype(np.uint8)
        s1 = get_seed(state, 1, DOMAIN_BEACON_ATTESTER)
        s2 = get_seed(state, 2, DOMAIN_BEACON_ATTESTER)
        s3 = get_seed(state, 1, DOMAIN_BEACON_PROPOSER)
        assert len({s1, s2, s3}) == 3


class TestSyncAssignment:
    def test_assignment_matches_membership(self):
        state, _ = make_genesis(16)
        members = {bytes(pk) for pk in state.current_sync_committee.pubkeys}
        for v in range(16):
            assigned = is_assigned_to_sync_committee(state, 0, v)
            assert assigned == (state.validators.pubkeys[v].tobytes() in members)

    def test_far_future_period_rejected(self):
        state, _ = make_genesis(16)
        far = 10 * cfg().epochs_per_sync_committee_period
        with pytest.raises(AssertionError):
            is_assigned_to_sync_committee(state, far, 0)


class TestProposerSampling:
    def test_weighting_by_effective_balance(self):
        """pos-evolution.md:622: acceptance probability ~ balance/32."""
        state, _ = make_genesis(64)
        half = cfg().max_effective_balance // 2
        state.validators.effective_balance[:32] = half  # first half at 16 ETH
        indices = get_active_validator_indices(state, 0)
        rng = np.random.default_rng(0)
        counts = np.zeros(64)
        for trial in range(400):
            seed = rng.integers(0, 255, 32, dtype=np.uint8).tobytes()
            counts[compute_proposer_index(state, indices, seed)] += 1
        light = counts[:32].sum()
        heavy = counts[32:].sum()
        # heavy validators should win roughly twice as often
        assert 1.5 < heavy / light < 2.7, (light, heavy)


class TestChurn:
    def test_churn_floor(self):
        state, _ = make_genesis(8)
        assert get_validator_churn_limit(state) == cfg().min_per_epoch_churn_limit


class TestMainnetCommitteeScale:
    def test_reference_example_numbers(self):
        """pos-evolution.md:472-475: at 262,144 active validators there are
        64 committees per slot of 128 validators each."""
        from pos_evolution_tpu.config import mainnet_config, use_config
        with use_config(mainnet_config()):
            from pos_evolution_tpu.specs.containers import ValidatorRegistry
            from pos_evolution_tpu.specs.genesis import make_genesis as mg
            from pos_evolution_tpu.specs.helpers import (
                get_beacon_committee, get_committee_count_per_slot,
            )
            state, _ = mg(0)
            n = 262_144
            reg = ValidatorRegistry(n)
            reg.effective_balance[:] = cfg().max_effective_balance
            reg.activation_epoch[:] = 0
            state.validators = reg
            state.balances = np.full(n, cfg().max_effective_balance,
                                     dtype=np.uint64)
            assert get_committee_count_per_slot(state, 0) == 64
            committee = get_beacon_committee(state, 0, 0)
            assert committee.shape[0] == 128

    def test_committees_partition_the_slot(self):
        """All committees of one slot are disjoint (pos-evolution.md:455)."""
        state, _ = make_genesis(64)
        from pos_evolution_tpu.specs.helpers import (
            get_beacon_committee, get_committee_count_per_slot,
        )
        count = get_committee_count_per_slot(state, 0)
        seen = set()
        for i in range(count):
            members = set(int(v) for v in get_beacon_committee(state, 2, i))
            assert not (members & seen)
            seen |= members
