"""Device-trace tests (SURVEY.md §5 tracing/profiling): jax.profiler
traces must capture device work dispatched inside the traced region."""

import glob

import pytest

jax = pytest.importorskip("jax")

from pos_evolution_tpu.utils.metrics import device_trace, trace_region  # noqa: E402


class TestDeviceTrace:
    def test_trace_writes_xplane(self, tmp_path):
        import jax.numpy as jnp
        import numpy as np

        with device_trace(tmp_path, "test-region"):
            with trace_region("inner-op"):
                np.asarray(jnp.arange(2048.0) ** 2)
        files = glob.glob(str(tmp_path / "**" / "*.xplane.pb"), recursive=True)
        assert files, "device trace produced no xplane protobuf"

    def test_trace_region_free_when_untraced(self):
        # TraceAnnotation outside any active trace must be a no-op
        with trace_region("orphan"):
            pass
