"""Fault-injection layer tests (sim/faults.py + driver integration):
message loss / duplication / reorder under partial synchrony, crash-restart
view groups rejoining via weak-subjectivity checkpoint sync, and the
bit-identical whole-simulation checkpoint/resume contract.

The protocol claims under test are the reference's own: finalization under
≤Δ-bounded faults with an honest supermajority resumes once the network
stabilizes (ebb-and-flow, pos-evolution.md:1184-1190), and crashed
validators rejoin through "checkpoints that act as new genesis" (:1216).
"""

import numpy as np
import pytest

from pos_evolution_tpu.config import minimal_config
from pos_evolution_tpu.sim import (
    CrashWindow,
    FaultPlan,
    Simulation,
    chaos_plan,
    faulty_schedule,
    lossy_plan,
)

pytestmark = pytest.mark.usefixtures("minimal_cfg")


def _gst_seconds(epochs: int) -> int:
    c = minimal_config()
    return epochs * c.slots_per_epoch * c.seconds_per_slot


class TrackingSim(Simulation):
    """Records attestations the fault layer dropped (single-group runs:
    a drop means the attestation was delivered to NO ONE)."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.dropped_atts = []

    def _send(self, dst, base_time, delay, kind, payload, slot, src, msg_id):
        n0 = len(dst.queue)
        super()._send(dst, base_time, delay, kind, payload, slot, src, msg_id)
        if (kind == "attestation" and delay is not None and not dst.crashed
                and len(dst.queue) == n0):
            self.dropped_atts.append(payload)


class TestFaultPlanDecisions:
    def test_stateless_and_seeded(self):
        """The same message identity always draws the same fate — across
        plan instances (what makes checkpoint/resume replay exact)."""
        a = FaultPlan(seed=3, drop_p=0.5, duplicate_p=0.3, reorder_p=0.3)
        b = FaultPlan(seed=3, drop_p=0.5, duplicate_p=0.3, reorder_p=0.3)
        for slot in range(40):
            key = ("attestation", slot, 0, slot % 4, 0, 0.0)
            assert a.delivery_offsets(*key) == b.delivery_offsets(*key)
        c = FaultPlan(seed=4, drop_p=0.5)
        fates_a = [bool(a.delivery_offsets("block", s, 1, 0, 0, 0.0))
                   for s in range(64)]
        fates_c = [bool(c.delivery_offsets("block", s, 1, 0, 0, 0.0))
                   for s in range(64)]
        assert fates_a != fates_c, "seed must matter"

    def test_probabilities_roughly_respected(self):
        plan = FaultPlan(seed=11, drop_p=0.2)
        n = 2000
        drops = sum(not plan.delivery_offsets("block", s, 0, 0, 0, 0.0)
                    for s in range(n))
        assert 0.15 * n < drops < 0.25 * n

    def test_gst_switches_faults_off(self):
        plan = FaultPlan(seed=0, drop_p=1.0, gst=100.0)
        assert plan.delivery_offsets("block", 1, 0, 0, 0, 99.0) == []
        assert plan.delivery_offsets("block", 1, 0, 0, 0, 100.0) == [0.0]

    def test_crash_windows_pure_function_of_slot(self):
        plan = FaultPlan(crashes=(CrashWindow(2, 8, 16),))
        assert not plan.crashed(2, 7)
        assert plan.crashed(2, 8) and plan.crashed(2, 15)
        assert not plan.crashed(2, 16) and plan.rejoins(2, 16)
        assert not plan.crashed(1, 10)


class TestMessageDropInvariants:
    def test_finalization_resumes_after_gst(self):
        """≤Δ-bounded faults + honest supermajority: heavy loss before
        GST, then the chain must re-finalize (pos-evolution.md:1184-1190).
        """
        c = minimal_config()
        plan = lossy_plan(seed=5, drop_p=0.35, gst=_gst_seconds(3))
        sim = Simulation(64, schedule=faulty_schedule(64, plan))
        sim.run_epochs(6)
        # post-GST epochs finalize: by the end of epoch 6 the finalized
        # checkpoint sits at least two epochs past GST
        assert sim.finalized_epoch() >= 4
        # and the head keeps advancing every slot after GST
        post = [m for m in sim.metrics
                if m["slot"] >= 3 * c.slots_per_epoch]
        head_slots = [m["head_slot"] for m in post]
        assert head_slots == sorted(head_slots)

    def test_dropped_attestations_never_enter_latest_messages(self):
        """A dropped attestation was delivered to no view: none of its
        participants may carry a latest message for that epoch (each
        validator attests exactly once per epoch in the duty loop), and
        it must not have been packed into any block either."""
        from pos_evolution_tpu.specs.helpers import get_indexed_attestation
        from pos_evolution_tpu.ssz import hash_tree_root
        plan = FaultPlan(seed=9, drop_p=0.15, record_log=True)
        sim = TrackingSim(64, schedule=faulty_schedule(64, plan))
        sim.run_epochs(3)
        assert sim.dropped_atts, "fault plan should have dropped something"
        assert plan.dropped("attestation"), "plan log should record drops"
        store = sim.store()
        onchain = set()
        for atts in sim.groups[0].block_atts.values():
            onchain.update(atts)
        for att in sim.dropped_atts:
            target_key = (int(att.data.target.epoch),
                          bytes(att.data.target.root))
            state = store.checkpoint_states.get(target_key)
            if state is None:
                continue
            indexed = get_indexed_attestation(state, att)
            epoch = int(att.data.target.epoch)
            for v in np.asarray(indexed.attesting_indices):
                m = store.latest_messages.get(int(v))
                assert m is None or int(m.epoch) != epoch, \
                    f"validator {v}'s dropped epoch-{epoch} vote landed"
            assert hash_tree_root(att) not in onchain, \
                "a dropped attestation was packed into a block"

    def test_duplicates_and_reorders_are_harmless(self):
        """Duplication and bounded reorder are semantically absorbed by
        the handlers (latest-message semantics dedup): the run finalizes
        on schedule like the honest run."""
        plan = FaultPlan(seed=2, duplicate_p=0.3, reorder_p=0.3,
                         reorder_max_delay=3.0)
        sim = Simulation(64, schedule=faulty_schedule(64, plan))
        sim.run_epochs(5)
        ref = Simulation(64)
        ref.run_epochs(5)
        assert sim.finalized_epoch() >= ref.finalized_epoch() - 1
        assert sim.finalized_epoch() >= 3


class TestCrashRestart:
    def test_crashed_group_rejoins_and_refinalizes(self):
        """25% of validators (one of four view groups) crash, miss two
        epochs, rejoin via weak-subjectivity checkpoint sync, and the
        whole network — including the rejoined group — finalizes past the
        outage; with 10% message loss on top until GST (the acceptance
        scenario scaled to the fast tier; the @slow variant runs the full
        64 epochs)."""
        c = minimal_config()
        spe = c.slots_per_epoch
        # drops heal at epoch 2, the crash at epoch 5: by rejoin time the
        # live 3/4 of the stake has justified real epochs, so the sync
        # anchor is a post-genesis justified checkpoint
        plan = FaultPlan(
            seed=1, drop_p=0.10, gst=_gst_seconds(2),
            crashes=(CrashWindow(group=3, crash_slot=3 * spe,
                                 rejoin_slot=5 * spe),))
        sim = Simulation(64, schedule=faulty_schedule(64, plan, n_groups=4))
        sim.run_epochs(8)
        # every group, including the rejoined one, finalized past the heal
        for g in range(4):
            assert sim.finalized_epoch(g) >= 5, f"group {g} stuck"
        # the rejoined group's store was anchored at the sync checkpoint:
        # history before it is gone (new-genesis sync), and the group
        # kept following the chain afterwards (it did not freeze at the
        # anchor — the head-snapshot-anchor failure mode)
        g3 = sim.groups[3]
        anchor_slot = min(int(b.slot) for b in g3.store.blocks.values())
        assert anchor_slot >= spe, "rejoin kept pre-crash history"
        head = sim._get_head(g3)
        assert int(g3.store.blocks[head].slot) >= 7 * spe, \
            "rejoined group froze at its sync anchor"

    def test_rejoin_is_weak_subjectivity_gated(self):
        """A rejoin whose checkpoint fails the WS gate must refuse to
        sync (long-range defense, pos-evolution.md:1200)."""
        import pos_evolution_tpu.sim.driver as drv
        c = minimal_config()
        plan = FaultPlan(crashes=(CrashWindow(1, c.slots_per_epoch,
                                              2 * c.slots_per_epoch),))
        sim = Simulation(64, schedule=faulty_schedule(64, plan, n_groups=2))
        orig = drv.fc.on_tick

        def stale_gate(store, time):  # age the rejoiner's clock instead
            return orig(store, time)

        from pos_evolution_tpu.specs import weak_subjectivity as ws
        real = ws.is_within_weak_subjectivity_period
        try:
            ws.is_within_weak_subjectivity_period = \
                lambda *a, **kw: False
            with pytest.raises(RuntimeError, match="weak-subjectivity"):
                sim.run_epochs(3)
        finally:
            ws.is_within_weak_subjectivity_period = real

    @pytest.mark.slow
    def test_acceptance_64_epochs_loss_plus_crash(self):
        """The ISSUE acceptance scenario at full scale: a 64-epoch
        minimal-config run with 10% message loss plus a crash-restart of
        25% of validators (rejoining via checkpoint sync) re-finalizes
        after the faults heal."""
        c = minimal_config()
        spe = c.slots_per_epoch
        plan = FaultPlan(
            seed=42, drop_p=0.10, gst=_gst_seconds(6),
            crashes=(CrashWindow(group=3, crash_slot=2 * spe,
                                 rejoin_slot=5 * spe),))
        sim = Simulation(64, schedule=faulty_schedule(64, plan, n_groups=4))
        sim.run_epochs(64)
        for g in range(4):
            assert sim.finalized_epoch(g) >= 62, f"group {g} stuck"


class TestCheckpointResume:
    def _plan(self):
        c = minimal_config()
        return FaultPlan(
            seed=13, drop_p=0.12, duplicate_p=0.05, reorder_p=0.1,
            gst=_gst_seconds(3),
            crashes=(CrashWindow(group=1, crash_slot=c.slots_per_epoch,
                                 rejoin_slot=2 * c.slots_per_epoch),))

    def test_resume_reproduces_uninterrupted_metrics_exactly(self):
        """Property: for every checkpoint slot k — including one inside
        the crash window — resume(checkpoint at k) continues to produce
        the uninterrupted run's per-slot metrics EXACTLY."""
        c = minimal_config()
        end_slot = 4 * c.slots_per_epoch
        ref = Simulation(32, schedule=faulty_schedule(32, self._plan(),
                                                      n_groups=2))
        ref.run_until_slot(end_slot)
        # k=11 is mid-crash for group 1; k=17 is just after rejoin
        for k in (5, 11, 17, 25):
            sim = Simulation(32, schedule=faulty_schedule(32, self._plan(),
                                                          n_groups=2))
            sim.run_until_slot(k)
            data = sim.checkpoint()
            resumed = Simulation.resume(
                data, schedule=faulty_schedule(32, self._plan(), n_groups=2))
            assert resumed.slot == k + 1
            resumed.run_until_slot(end_slot)
            assert resumed.metrics == ref.metrics, f"divergence from k={k}"

    def test_resume_restores_queues_pools_and_stores(self):
        sim = Simulation(32, schedule=faulty_schedule(32, self._plan(),
                                                      n_groups=2))
        sim.run_until_slot(9)
        data = sim.checkpoint()
        back = Simulation.resume(
            data, schedule=faulty_schedule(32, self._plan(), n_groups=2))
        for g0, g1 in zip(sim.groups, back.groups):
            assert sorted((m.time, m.seq, m.kind) for m in g0.queue) == \
                sorted((m.time, m.seq, m.kind) for m in g1.queue)
            assert list(g0.pool.keys()) == list(g1.pool.keys())
            assert g0.block_atts == g1.block_atts
            assert g0.store.blocks.keys() == g1.store.blocks.keys()
            assert g0.store.latest_messages == g1.store.latest_messages
            assert g0.crashed == g1.crashed
        assert back.metrics == sim.metrics

    def test_resume_preserves_resident_degradation(self):
        """A degraded device mirror must STAY degraded across resume —
        resurrecting it would re-trust the device exactly in the case it
        was caught diverging (and would drop the incident record)."""
        pytest.importorskip("jax")
        sim = Simulation(32, accelerated_forkchoice=True)
        sim.run_until_slot(4)
        sim.groups[0].resident._degrade("test-injected divergence")
        back = Simulation.resume(sim.checkpoint())
        assert back.groups[0].resident.degraded
        assert back.groups[0].resident.incidents == \
            ["test-injected divergence"]
        back.run_until_slot(8)                 # keeps running on host path

    def test_honest_run_resume_without_schedule(self):
        ref = Simulation(32)
        ref.run_until_slot(20)
        sim = Simulation(32)
        sim.run_until_slot(8)
        back = Simulation.resume(sim.checkpoint())
        back.run_until_slot(20)
        assert back.metrics == ref.metrics
