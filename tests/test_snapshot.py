"""Checkpoint/resume + observability tests (SURVEY.md §5)."""

import numpy as np
import pytest

from pos_evolution_tpu.specs import forkchoice as fc
from pos_evolution_tpu.specs.genesis import make_genesis
from pos_evolution_tpu.specs.validator import attest_all_committees, build_block
from pos_evolution_tpu.sim import Simulation
from pos_evolution_tpu.ssz import hash_tree_root
from pos_evolution_tpu.utils import (
    HandlerTimer,
    StoreInvariantChecker,
    load_anchor,
    load_store,
    resume_store,
    save_anchor,
    save_store,
    slot_record,
    snapshot_head,
)

pytestmark = pytest.mark.usefixtures("minimal_cfg")


class TestStateRoundtrip:
    def test_beacon_state_ssz_roundtrip(self):
        from pos_evolution_tpu.specs.containers import BeaconState
        from pos_evolution_tpu.ssz import deserialize, serialize
        state, _ = make_genesis(16)
        data = serialize(state)
        back = deserialize(data, BeaconState)
        assert hash_tree_root(back) == hash_tree_root(state)

    def test_post_transition_state_roundtrip(self):
        from pos_evolution_tpu.specs.containers import BeaconState
        from pos_evolution_tpu.specs.transition import state_transition
        from pos_evolution_tpu.ssz import deserialize, serialize
        state, _ = make_genesis(16)
        sb = build_block(state, 1)
        state_transition(state, sb, True)
        back = deserialize(serialize(state), BeaconState)
        assert hash_tree_root(back) == hash_tree_root(state)


class TestAnchorResume:
    def test_resume_from_head_snapshot_continues_chain(self):
        """Resume == the reference's own anchor mechanism (:1077, :1216)."""
        sim = Simulation(32)
        sim.run_epochs(3)
        snap = snapshot_head(sim.store())

        store2 = resume_store(snap)
        head = fc.get_head(store2)
        anchor_state = store2.block_states[head]
        # the resumed store accepts and follows new blocks
        slot = int(anchor_state.slot) + 1
        fc.on_tick(store2, store2.genesis_time + slot * 12)
        sb = build_block(anchor_state, slot)
        fc.on_block(store2, sb)
        assert fc.get_head(store2) == hash_tree_root(sb.message)

    def test_anchor_consistency_enforced(self):
        state, block = make_genesis(8)
        block.state_root = b"\x09" * 32
        with pytest.raises(AssertionError):
            save_anchor(state, block)


class TestFullStoreSnapshot:
    def test_store_roundtrip_preserves_head_and_messages(self):
        sim = Simulation(32)
        sim.run_epochs(2)
        store = sim.store()
        data = save_store(store)
        back = load_store(data)
        assert fc.get_head(back) == fc.get_head(store)
        assert back.latest_messages == store.latest_messages
        assert back.justified_checkpoint == store.justified_checkpoint
        # the restored store keeps processing
        slot = fc.get_current_slot(back) + 1
        fc.on_tick(back, back.genesis_time + slot * 12)
        head_state = back.block_states[fc.get_head(back)]
        sb = build_block(head_state, slot)
        fc.on_block(back, sb)


class TestResumeCacheCoherence:
    """Checkpoint/resume x the PR-6 caches (ssz/incremental.py
    ``ContainerTreeCache`` lineage caches + ``cached_root`` memos): a
    resumed simulation must rebuild (or safely drop) both, and its
    every subsequent root must stay bit-identical to a twin that never
    went through serialization."""

    def test_resumed_roots_bit_identical_to_unsnapshotted_twin(self):
        from pos_evolution_tpu.sim import Simulation
        from pos_evolution_tpu.specs import forkchoice as fc
        from pos_evolution_tpu.ssz import incremental

        sim = Simulation(32)
        sim.run_epochs(2)  # plenty of incremental-cache traffic
        head0 = fc.get_head(sim.store())
        # the live run's states carry lineage caches by now
        assert any("_htr_cache" in s.__dict__
                   for s in sim.store().block_states.values()), \
            "expected live states to carry incremental caches"
        blob = sim.checkpoint()

        twin = Simulation.resume(blob)
        # caches are optimization handles, never serialized state: the
        # resumed stores start clean and rebuild on first use
        for s in twin.store().block_states.values():
            assert "_htr_cache" not in s.__dict__
            assert "_htr_memo" not in s.__dict__
        # resumed head state's incremental root == full re-merkleization
        # == the live twin's root, bit for bit
        head = fc.get_head(twin.store())
        assert head == head0
        resumed_state = twin.store().block_states[head]
        live_state = sim.store().block_states[head0]
        incremental_root = hash_tree_root(resumed_state)
        prev = incremental.set_enabled(False)
        try:
            full_root = hash_tree_root(resumed_state)
        finally:
            incremental.set_enabled(prev)
        assert incremental_root == full_root
        assert incremental_root == hash_tree_root(live_state)

        # continue BOTH runs: every later block/state root must agree
        sim.run_epochs(3)
        twin.run_epochs(3)
        assert fc.get_head(twin.store()) == fc.get_head(sim.store())
        assert twin.metrics == sim.metrics
        h = fc.get_head(sim.store())
        assert hash_tree_root(twin.store().block_states[h]) == \
            hash_tree_root(sim.store().block_states[h])

    def test_resumed_queue_payload_memos_rebuild(self):
        """``cached_root`` memos on gossip payloads are per-object; the
        deserialized copies must recompute identical roots (a stale or
        missing memo either way would split dedup/span identity)."""
        from pos_evolution_tpu.sim import Simulation
        from pos_evolution_tpu.ssz import cached_root

        sim = Simulation(32)
        sim.run_epochs(1)
        blob = sim.checkpoint()
        twin = Simulation.resume(blob)
        for root, sb in sim.block_archive.items():
            copy = twin.block_archive[root]
            assert "_htr_memo" not in copy.message.__dict__
            # archive keys are MESSAGE roots (the gossip identity)
            assert cached_root(copy.message) == \
                cached_root(sb.message) == root

    def test_anchor_snapshot_of_cached_state_roundtrips(self):
        """``save_anchor`` hashes through the incremental cache when one
        is attached; the serialized bytes must deserialize to the same
        root with NO cache (the cache must never leak into — or be
        needed by — the snapshot)."""
        from pos_evolution_tpu.sim import Simulation

        sim = Simulation(32)
        sim.run_epochs(2)
        snap = snapshot_head(sim.store())
        state, block = load_anchor(snap)
        assert "_htr_cache" not in state.__dict__
        assert hash_tree_root(state) == bytes(block.state_root)


class TestDenseCheckpoints:
    def test_npz_roundtrip(self, tmp_path):
        jax = pytest.importorskip("jax")
        from pos_evolution_tpu.ops.epoch import densify
        from pos_evolution_tpu.utils.snapshot import load_dense, save_dense
        state, _ = make_genesis(16)
        reg = densify(state)
        p = str(tmp_path / "reg.npz")
        save_dense(p, reg)
        back = load_dense(p)
        for f in reg._fields:
            assert np.array_equal(np.asarray(getattr(reg, f)),
                                  np.asarray(getattr(back, f))), f

    def test_orbax_roundtrip(self, tmp_path):
        jax = pytest.importorskip("jax")
        ocp = pytest.importorskip("orbax.checkpoint")
        from pos_evolution_tpu.ops.epoch import densify
        from pos_evolution_tpu.utils.snapshot import (
            load_dense_orbax, save_dense_orbax,
        )
        state, _ = make_genesis(16)
        reg = densify(state)
        p = str(tmp_path / "orbax_ckpt")
        save_dense_orbax(p, reg)
        back = load_dense_orbax(p)
        for f in reg._fields:
            assert np.array_equal(np.asarray(getattr(reg, f)),
                                  np.asarray(getattr(back, f))), f

    @pytest.mark.mesh8
    def test_orbax_restore_onto_mesh(self, tmp_path):
        """Restore re-places arrays sharded over the *current* mesh."""
        jax = pytest.importorskip("jax")
        pytest.importorskip("orbax.checkpoint")
        from pos_evolution_tpu.ops.epoch import densify
        from pos_evolution_tpu.parallel.sharded import make_mesh
        from pos_evolution_tpu.utils.snapshot import (
            load_dense_orbax, save_dense_orbax,
        )
        state, _ = make_genesis(16)
        reg = densify(state)
        p = str(tmp_path / "orbax_mesh_ckpt")
        save_dense_orbax(p, reg)
        mesh = make_mesh(8, n_pods=2)
        back = load_dense_orbax(p, mesh=mesh)
        assert len(back.balance.sharding.device_set) == 8
        assert np.array_equal(np.asarray(back.balance), np.asarray(reg.balance))


class TestObservability:
    def test_handler_timer_percentiles(self):
        sim = Simulation(32)
        timer = HandlerTimer()
        timed_head = timer.wrap("get_head", fc.get_head)
        sim.run_epochs(1)
        for _ in range(5):
            timed_head(sim.store())
        s = timer.summary()["get_head"]
        assert s["count"] == 5 and s["p50_ms"] >= 0

    def test_slot_record_fields(self):
        sim = Simulation(32)
        sim.run_epochs(2)
        rec = slot_record(sim.store(), sim.slot)
        assert rec["head_slot"] == 2 * 8
        assert 0 <= rec["participation"] <= 1
        assert rec["n_latest_messages"] > 0

    def test_invariant_checker_catches_violations(self):
        """Negative path: a handler that mutates before failing must be
        reported (the pos-evolution.md:1041 contract enforcement works)."""
        state, anchor = make_genesis(16)
        store = fc.get_forkchoice_store(state, anchor)
        checker = StoreInvariantChecker(store)

        def bad_handler(store_arg):
            store_arg.equivocating_indices.add(99)  # mutate...
            raise AssertionError("then fail")

        with pytest.raises(AssertionError):
            checker.call(bad_handler)
        assert len(checker.violations) == 1
        assert "mutated the store" in checker.violations[0]

    def test_invariant_checker_passes_on_honest_handlers(self):
        state, anchor = make_genesis(16)
        store = fc.get_forkchoice_store(state, anchor)
        checker = StoreInvariantChecker(store)
        fc.on_tick(store, store.genesis_time + 12)
        sb = build_block(state, 1)
        sb.signature = b"\x00" * 96  # invalid: handler must not mutate
        with pytest.raises(AssertionError):
            checker.call(fc.on_block, sb)
        assert checker.violations == []
