"""ISSUE 9: sharded end-to-end simulation — partition rules, the
backend's sharded mode, bit-identity of the validator-axis sweeps across
mesh shapes (1x8 / 2x4 / 4x2 / 8x1) against the single-device jax path
and the NumPy oracles, the DenseSimulation mainnet-scale loop,
checkpoint -> resume on a *different* mesh shape, the resident head
memo, the vectorized host walk, and the bench_shard perf gate."""

import json
import os
import sys

import numpy as np
import pytest

from pos_evolution_tpu.config import minimal_config, use_config

jax = pytest.importorskip("jax")

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))), "scripts"))

MESH_SHAPES = [(1, 8), (2, 4), (4, 2), (8, 1)]


def _mesh(pods, shard):
    from pos_evolution_tpu.parallel.sharded import make_mesh
    return make_mesh(pods * shard, pods)


@pytest.fixture
def jax_backend_sharded_off():
    """Always leave the process-global sharded mode off after a test."""
    from pos_evolution_tpu.backend import set_backend
    backend = set_backend("jax")
    yield backend
    backend.disable_sharded()


# --- partition rules ----------------------------------------------------------


class TestPartitionRules:
    def test_named_tree_map_names_namedtuple_fields(self):
        from pos_evolution_tpu.parallel.partition import named_tree_map
        from pos_evolution_tpu.ops.epoch import DenseRegistry
        reg = DenseRegistry(*(np.zeros(4) for _ in DenseRegistry._fields))
        names = []
        named_tree_map(lambda n, x: names.append(n), {"registry": reg})
        assert "registry/effective_balance" in names
        assert "registry/inactivity_scores" in names

    def test_match_rules_validator_columns_vs_scalars(self):
        from pos_evolution_tpu.parallel.partition import (
            PARTITION_RULES,
            REPLICATED,
            VALIDATOR_SPEC,
            match_partition_rules,
        )
        tree = {"registry": {"balance": np.zeros(8)},
                "messages": {"msg_block": np.zeros(8),
                             "total": np.int64(3)},      # scalar
                "tree": {"parent": np.zeros(4)}}
        specs = match_partition_rules(PARTITION_RULES, tree)
        assert specs["registry"]["balance"] == VALIDATOR_SPEC
        assert specs["messages"]["msg_block"] == VALIDATOR_SPEC
        assert specs["messages"]["total"] == REPLICATED  # scalars replicate
        assert specs["tree"]["parent"] == REPLICATED
        # spec_for is the live placement entry point (resident / session /
        # registry / dense-driver sites all consult the table through it)
        from pos_evolution_tpu.parallel.partition import spec_for
        assert spec_for("session/balances") == VALIDATOR_SPEC
        assert spec_for("messages/assigned") == VALIDATOR_SPEC
        assert spec_for("tree/rank") == REPLICATED

    def test_unmatched_leaf_raises(self):
        from pos_evolution_tpu.parallel.partition import (
            match_partition_rules,
        )
        with pytest.raises(ValueError, match="no partition rule"):
            match_partition_rules([(r"^only/this$", None)],
                                  {"other": np.zeros(4)})

    @pytest.mark.mesh8
    def test_shard_leaf_and_build_sharded_round_trip(self):
        from pos_evolution_tpu.parallel.partition import (
            VALIDATOR_SPEC,
            build_sharded,
            shard_leaf,
        )
        mesh = _mesh(2, 4)
        x = np.arange(64, dtype=np.int64)
        placed = shard_leaf(mesh, VALIDATOR_SPEC, x)
        assert np.array_equal(np.asarray(placed), x)
        # every device holds only its slice
        assert all(s.data.shape == (8,) for s in placed.addressable_shards)

        built = build_sharded(mesh, VALIDATOR_SPEC, (64,), np.int64,
                              lambda lo, hi: np.arange(lo, hi))
        assert np.array_equal(np.asarray(built), x)

    @pytest.mark.mesh8
    def test_shard_leaf_rejects_indivisible(self):
        from pos_evolution_tpu.parallel.partition import (
            VALIDATOR_SPEC,
            shard_leaf,
        )
        with pytest.raises(ValueError, match="divide"):
            shard_leaf(_mesh(2, 4), VALIDATOR_SPEC, np.zeros(13))


# --- kernel bit-identity across every mesh shape ------------------------------


@pytest.mark.mesh8
class TestKernelsAcrossMeshShapes:
    @pytest.mark.parametrize("shape", MESH_SHAPES)
    def test_vote_pass_matches_numpy_oracle(self, shape):
        from pos_evolution_tpu.parallel.sharded import vote_weights_for
        mesh = _mesh(*shape)
        n, capacity = 256, 32
        rng = np.random.default_rng(1)
        msg_block = rng.integers(-1, capacity, n).astype(np.int32)
        weight = rng.integers(1, 33, n).astype(np.int64) * 10**9
        got = np.asarray(vote_weights_for(mesh, capacity)(
            jax.numpy.asarray(msg_block), jax.numpy.asarray(weight)))
        want = np.zeros(capacity + 1, np.int64)
        np.add.at(want, np.where(msg_block >= 0, msg_block, capacity),
                  np.where(msg_block >= 0, weight, 0))
        assert np.array_equal(got, want[:capacity])

    @pytest.mark.parametrize("shape", MESH_SHAPES)
    def test_link_and_windowed_tally_match_host(self, shape,
                                                jax_backend_sharded_off):
        from pos_evolution_tpu.ops.variant_tally import (
            link_tally_host,
            windowed_vote_tally_host,
        )
        backend = jax_backend_sharded_off
        rng = np.random.default_rng(2)
        k, nl = 41, 6  # deliberately not a power of two, not mesh-divisible
        li = rng.integers(-1, nl, k)
        w = rng.integers(1, 100, k).astype(np.int64)
        ac = rng.random(k) < 0.8
        vs = rng.integers(0, 12, k)
        backend.enable_sharded(8, shape[0], mesh=_mesh(*shape))
        got_link = backend.link_tally(li, w, ac, nl)
        got_win = backend.variant_tally(li, vs, w, ac, 3, 9, nl)
        assert np.array_equal(got_link, link_tally_host(li, w, ac, nl))
        assert np.array_equal(
            got_win, windowed_vote_tally_host(li, vs, w, ac, 3, 9, nl))

    @pytest.mark.parametrize("shape", [(2, 4), (8, 1)])
    def test_epoch_sweep_matches_numpy_spec_pipeline(self, shape,
                                                     jax_backend_sharded_off):
        """jax sharded process_epoch == the pure-NumPy spec pipeline,
        state-root-identical (registry size NOT mesh-divisible, so the
        inert-row padding contract is exercised too)."""
        from pos_evolution_tpu.backend import set_backend
        from pos_evolution_tpu.ssz import hash_tree_root
        with use_config(minimal_config()) as c:
            from pos_evolution_tpu.specs.epoch import process_epoch
            from pos_evolution_tpu.specs.genesis import make_genesis
            state, _ = make_genesis(50)
            state.slot = np.uint64(c.slots_per_epoch * 3 - 1)
            s_np = state.copy()
            set_backend("numpy")
            process_epoch(s_np)
            backend = set_backend("jax")
            backend.enable_sharded(mesh=_mesh(*shape))
            s_sh = state.copy()
            process_epoch(s_sh)
            backend.disable_sharded()
            assert hash_tree_root(s_np) == hash_tree_root(s_sh)


# --- the dense end-to-end driver ----------------------------------------------


@pytest.mark.mesh8
class TestDenseSimulation:
    def _cfg(self):
        from pos_evolution_tpu.config import mainnet_config
        return mainnet_config().replace(slots_per_epoch=8,
                                        max_committees_per_slot=4)

    def _run(self, mesh, n=256, epochs=4, seed=11):
        from pos_evolution_tpu.sim.dense_driver import DenseSimulation
        sim = DenseSimulation(n, cfg=self._cfg(), mesh=mesh, seed=seed,
                              shuffle_rounds=6, check_walk_every=8)
        sim.run_epochs(epochs)
        return sim

    def test_finality_and_layout_bit_identity(self):
        """The same seeded config on a 2x4 mesh and on a single device:
        finality advances and EVERYTHING observable — per-slot head
        roots, checkpoints, aggregate verdict counts, the host-walk
        pins — is bit-identical (mesh = layout, never semantics; the
        per-kernel tests above cover all four mesh shapes)."""
        runs = [self._run(_mesh(2, 4)), self._run(None)]
        summaries = []
        for sim in runs:
            s = sim.summary()
            s.pop("mesh")
            summaries.append((s, sim.metrics))
        assert summaries[0] == summaries[1]
        s = summaries[0][0]
        assert s["finality_reached"] and s["finalized_epoch"] >= 2
        assert s["resident_head_equals_spec_walk"]
        assert s["aggregates_verified"] > 0

    def test_checkpoint_resume_on_different_mesh(self):
        """Mid-run checkpoint on 2x4 resumes bit-identically on 4x2 — a
        DIFFERENT mesh shape: the gather/re-shard contract of the
        snapshot layer (mesh shape is not part of the format)."""
        from pos_evolution_tpu.sim.dense_driver import DenseSimulation
        sim = self._run(_mesh(2, 4), epochs=2)
        data = sim.checkpoint()
        resumed_42 = DenseSimulation.resume(data, mesh=_mesh(4, 2))
        for s in (sim, resumed_42):
            s.run_epochs(4)
        ss = []
        for s in (sim, resumed_42):
            d = s.summary()
            d.pop("mesh")
            ss.append((d, s.metrics))
        assert ss[0] == ss[1]
        assert ss[0][0]["finality_reached"]

    def test_registry_is_shard_resident_from_genesis(self):
        from pos_evolution_tpu.sim.dense_driver import DenseSimulation
        mesh = _mesh(2, 4)
        sim = DenseSimulation(256, cfg=self._cfg(), mesh=mesh, seed=1)
        for col in (sim.registry.balance, sim.msg_block):
            shards = col.addressable_shards
            assert len(shards) == 8
            assert all(s.data.shape == (32,) for s in shards)


# --- the spec-level Simulation under sharded mode -----------------------------


@pytest.mark.mesh8
class TestShardedSimulation:
    def _records(self, sim):
        return [(m["head_root"], m["justified_epoch"], m["finalized_epoch"],
                 m["participation"], m["n_blocks"]) for m in sim.metrics]

    def test_bit_identical_to_single_device(self, jax_backend_sharded_off):
        """Acceptance pin: sharded and single-device driver runs agree on
        head roots, justified/finalized checkpoints and every per-slot
        record, on both 1x8 and 2x4 mesh shapes."""
        with use_config(minimal_config()):
            from pos_evolution_tpu.sim import Simulation
            outs = []
            for sharded in (False, (1, 8), (2, 4)):
                sim = Simulation(64, accelerated_forkchoice=True,
                                 sharded=sharded)
                sim.run_epochs(3)
                if sharded:
                    jax_backend_sharded_off.disable_sharded()
                assert not sim.groups[0].resident.degraded, \
                    sim.groups[0].resident.incidents
                outs.append(self._records(sim))
            assert outs[0] == outs[1] == outs[2]

    def test_ssf_variant_link_tally_through_sharded_mode(
            self, jax_backend_sharded_off):
        """ROADMAP item 5 remainder: the live SsfVariant dispatches its
        supermajority-link tallies through the sharded backend kernel
        when a mesh is active — whole-sim results identical to the
        single-device run (finalized chain, justified sets, evidence)."""
        with use_config(minimal_config()):
            from pos_evolution_tpu.sim import Simulation
            from pos_evolution_tpu.variants import SsfVariant

            def run(sharded):
                sim = Simulation(32, variant=SsfVariant(), sharded=sharded)
                sim.run_epochs(2)
                if sharded:
                    jax_backend_sharded_off.disable_sharded()
                v = sim.variant
                return (sorted((g, tuple(ch)) for g, ch in
                               v.finalized.items()),
                        sorted((g, tuple(sorted(cps))) for g, cps in
                               v.justified.items()),
                        sorted(v._slashable))

            single = run(False)
            sharded = run((2, 4))
            assert single == sharded
            assert single[0], "SSF finalized nothing — vacuous comparison"

    def test_checkpoint_resume_across_mesh_shapes(self,
                                                  jax_backend_sharded_off):
        """A sharded driver checkpoint resumes bit-identically under a
        DIFFERENT mesh shape (residents rebuild sharded on the current
        mesh) and the checkpoint records the mesh shape."""
        with use_config(minimal_config()):
            from pos_evolution_tpu.sim import Simulation
            sim = Simulation(64, accelerated_forkchoice=True,
                             sharded=(2, 4))
            sim.run_epochs(1)
            data = sim.checkpoint()
            sim.run_epochs(3)
            want = self._records(sim)
            jax_backend_sharded_off.disable_sharded()

            resumed = Simulation.resume(data, sharded=(4, 2))
            assert resumed.sharded == {"pods": 4, "shard": 2}
            resumed.run_epochs(3)
            jax_backend_sharded_off.disable_sharded()
            assert self._records(resumed) == want


# --- host walk + resident memo ------------------------------------------------


class TestGetHeadHost:
    def _forked_store(self, n=64):
        from pos_evolution_tpu.specs import forkchoice as fc
        from pos_evolution_tpu.specs.containers import LatestMessage
        from pos_evolution_tpu.specs.genesis import make_genesis
        from pos_evolution_tpu.specs.validator import build_block
        from pos_evolution_tpu.ssz import hash_tree_root
        state, anchor = make_genesis(n)
        store = fc.get_forkchoice_store(state, anchor)
        roots = [hash_tree_root(anchor)]
        parent_state = state
        for slot in (1, 2, 3):
            fc.on_tick(store, store.genesis_time + slot * 12)
            sb = build_block(parent_state, slot, graffiti=bytes([slot]) * 32)
            fc.on_block(store, sb)
            roots.append(hash_tree_root(sb.message))
            parent_state = store.block_states[roots[-1]]
        # a competing fork off block 1
        fork_state = store.block_states[roots[1]]
        sb = build_block(fork_state, 3, graffiti=b"\xff" * 32)
        fc.on_block(store, sb)
        roots.append(hash_tree_root(sb.message))
        rng = np.random.default_rng(5)
        for v in range(n):
            store.latest_messages[v] = LatestMessage(
                epoch=0, root=roots[rng.integers(1, len(roots))])
        return store

    def test_host_walk_matches_spec_walk(self, jax_backend_sharded_off):
        """The vectorized host walk behind the resident self-check must
        equal the pure-Python spec walk on a forked store with a full
        latest-message table."""
        from pos_evolution_tpu.ops.forkchoice import get_head_host
        from pos_evolution_tpu.specs import forkchoice as fc
        with use_config(minimal_config()):
            store = self._forked_store()
            assert get_head_host(store) == fc.get_head(store)
            # and after the boost moves (proposer boost is part of the walk)
            store.proposer_boost_root = list(store.blocks.keys())[-1]
            assert get_head_host(store) == fc.get_head(store)

    def test_resident_memo_invalidates_on_mutation(self,
                                                   jax_backend_sharded_off):
        """Repeated head queries are memoized; a landed vote batch, a new
        block or a boost change invalidates — the memoized answer always
        equals a fresh spec walk."""
        from pos_evolution_tpu.ops.resident import ResidentForkChoice
        from pos_evolution_tpu.specs import forkchoice as fc
        with use_config(minimal_config()):
            store = self._forked_store()
            store.proposer_boost_root = b"\x00" * 32
            resident = ResidentForkChoice(store, selfcheck_every=0)
            h1 = resident.head(store)
            queries_after_first = resident._head_queries
            assert resident.head(store) == h1
            assert resident._head_queries == queries_after_first, \
                "second identical query must answer from the memo"
            assert h1 == fc.get_head(store)
            # land votes that flip the head to the fork tip
            fork_tip = list(store.blocks.keys())[-1]
            movers = list(range(40))
            for v in movers:
                from pos_evolution_tpu.specs.containers import LatestMessage
                store.latest_messages[v] = LatestMessage(epoch=1,
                                                         root=fork_tip)
            resident.note_attestation(np.array(movers, np.int64), 1,
                                      fork_tip)
            h2 = resident.head(store)
            assert h2 == fc.get_head(store)
            assert resident._head_queries == queries_after_first + 1


# --- bench_shard gate ---------------------------------------------------------


class TestBenchShardGate:
    def _emission(self, run_s=30.0, p50=5.0):
        return {"metric": "scale_demo_sharded", "n_validators": 512,
                "mesh": {"pods": 2, "shard": 4}, "run_s": run_s,
                "handlers": {"get_head": {"count": 289, "p50_ms": p50,
                                          "p95_ms": p50 * 3,
                                          "total_s": run_s / 10}}}

    def test_gate_passes_real_fails_doctored_slow(self, tmp_path):
        import perf_gate

        from pos_evolution_tpu.profiling import history
        hist = tmp_path / "hist.jsonl"
        for _ in range(3):
            history.append_entry(hist, self._emission(), kind="bench_shard")
        cand = tmp_path / "cand.json"
        cand.write_text(json.dumps(self._emission(31.0, 5.2)))
        assert perf_gate.main(["--candidate", str(cand),
                               "--history", str(hist),
                               "--kind", "bench_shard",
                               "--strict-timing"]) == 0
        slow = tmp_path / "slow.json"
        slow.write_text(json.dumps(self._emission(300.0, 50.0)))
        assert perf_gate.main(["--candidate", str(slow),
                               "--history", str(hist),
                               "--kind", "bench_shard",
                               "--strict-timing"]) == 1
