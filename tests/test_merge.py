"""Merge-transition validation tests (pos-evolution.md:1011-1013).

Covers the two helpers the reference's ``on_block`` consults when a block
crosses the PoW→PoS boundary, and their wiring into ``on_block``.
"""

import pytest

from pos_evolution_tpu.config import cfg, use_config
from pos_evolution_tpu.specs import forkchoice as fc
from pos_evolution_tpu.specs import merge
from pos_evolution_tpu.specs.containers import ExecutionPayload
from pos_evolution_tpu.specs.genesis import make_genesis
from pos_evolution_tpu.specs.validator import build_block
from pos_evolution_tpu.ssz import hash_tree_root

pytestmark = pytest.mark.usefixtures("minimal_cfg")

TTD = None  # read from cfg() inside tests


@pytest.fixture(autouse=True)
def _clean_pow_chain():
    merge.clear_pow_chain()
    merge.set_pow_block_provider(None)
    yield
    merge.clear_pow_chain()
    merge.set_pow_block_provider(None)


def _payload(parent_hash: bytes) -> ExecutionPayload:
    return ExecutionPayload(parent_hash=parent_hash, block_number=1,
                            block_hash=b"\xee" * 32)


def _terminal_pair(ttd: int):
    """Register grandparent (below TTD) and parent (at TTD); return parent hash."""
    gp = merge.PowBlock(block_hash=b"\xaa" * 32, parent_hash=b"\x00" * 32,
                        total_difficulty=ttd - 1)
    p = merge.PowBlock(block_hash=b"\xbb" * 32, parent_hash=gp.block_hash,
                       total_difficulty=ttd)
    merge.register_pow_block(gp)
    merge.register_pow_block(p)
    return p.block_hash


class TestPredicates:
    def test_default_payload_is_not_transition(self):
        state, _ = make_genesis(16)
        sb = build_block(state, 1)
        assert not merge.is_merge_transition_block(state, sb.message.body)

    def test_real_payload_on_premerge_state_is_transition(self):
        state, _ = make_genesis(16)
        sb = build_block(state, 1, execution_payload=_payload(b"\xbb" * 32))
        assert merge.is_merge_transition_block(state, sb.message.body)

    def test_postmerge_state_is_not_transition(self):
        state, _ = make_genesis(16)
        state.latest_execution_payload_header.block_number = 7
        sb = build_block(state, 1, execution_payload=_payload(b"\xbb" * 32))
        assert merge.is_merge_transition_complete(state)
        assert not merge.is_merge_transition_block(state, sb.message.body)

    def test_terminal_pow_block_straddles_ttd(self):
        ttd = cfg().terminal_total_difficulty
        below = merge.PowBlock(b"\x01" * 32, b"\x00" * 32, ttd - 1)
        at = merge.PowBlock(b"\x02" * 32, b"\x01" * 32, ttd)
        above = merge.PowBlock(b"\x03" * 32, b"\x02" * 32, ttd + 5)
        assert merge.is_valid_terminal_pow_block(at, below)
        assert not merge.is_valid_terminal_pow_block(below, below)
        # Parent already at TTD → this block is past, not at, the boundary.
        assert not merge.is_valid_terminal_pow_block(above, at)


class TestValidateMergeBlock:
    def test_valid_terminal_parent_accepted(self):
        state, _ = make_genesis(16)
        parent_hash = _terminal_pair(cfg().terminal_total_difficulty)
        sb = build_block(state, 1, execution_payload=_payload(parent_hash))
        merge.validate_merge_block(sb.message)  # no raise

    def test_unavailable_pow_block_rejected(self):
        state, _ = make_genesis(16)
        sb = build_block(state, 1, execution_payload=_payload(b"\xcc" * 32))
        with pytest.raises(AssertionError, match="unavailable"):
            merge.validate_merge_block(sb.message)

    def test_insufficient_difficulty_rejected(self):
        ttd = cfg().terminal_total_difficulty
        gp = merge.PowBlock(b"\xaa" * 32, b"\x00" * 32, ttd - 10)
        p = merge.PowBlock(b"\xbb" * 32, gp.block_hash, ttd - 1)
        merge.register_pow_block(gp)
        merge.register_pow_block(p)
        state, _ = make_genesis(16)
        sb = build_block(state, 1, execution_payload=_payload(p.block_hash))
        with pytest.raises(AssertionError, match="terminal total difficulty"):
            merge.validate_merge_block(sb.message)

    def test_terminal_block_hash_override(self):
        th = b"\x7f" * 32
        with use_config(cfg().replace(terminal_block_hash=th,
                                      terminal_block_hash_activation_epoch=0)):
            state, _ = make_genesis(16)
            ok = build_block(state, 1, execution_payload=_payload(th))
            merge.validate_merge_block(ok.message)  # no raise
            bad = build_block(state, 1, execution_payload=_payload(b"\x11" * 32))
            with pytest.raises(AssertionError, match="terminal block"):
                merge.validate_merge_block(bad.message)

    def test_override_activation_epoch_gate(self):
        th = b"\x7f" * 32
        far = 2**32
        with use_config(cfg().replace(terminal_block_hash=th,
                                      terminal_block_hash_activation_epoch=far)):
            state, _ = make_genesis(16)
            sb = build_block(state, 1, execution_payload=_payload(th))
            with pytest.raises(AssertionError, match="activation epoch"):
                merge.validate_merge_block(sb.message)


class TestOnBlockWiring:
    def _store(self, n=32):
        state, anchor = make_genesis(n)
        store = fc.get_forkchoice_store(state, anchor)
        return store, state

    def _tick(self, store, slot):
        fc.on_tick(store, store.genesis_time + slot * cfg().seconds_per_slot)

    def test_transition_block_without_pow_view_rejected(self):
        store, state = self._store()
        self._tick(store, 1)
        sb = build_block(state, 1, execution_payload=_payload(b"\xdd" * 32))
        with pytest.raises(AssertionError, match="unavailable"):
            fc.on_block(store, sb)
        assert hash_tree_root(sb.message) not in store.blocks

    def test_transition_block_with_terminal_parent_accepted(self):
        store, state = self._store()
        parent_hash = _terminal_pair(cfg().terminal_total_difficulty)
        self._tick(store, 1)
        sb = build_block(state, 1, execution_payload=_payload(parent_hash))
        fc.on_block(store, sb)
        root = hash_tree_root(sb.message)
        assert root in store.blocks
        # Post-state has recorded the payload header: merge is complete, so
        # a descendant with another payload is NOT re-validated.
        post = store.block_states[root]
        assert merge.is_merge_transition_complete(post)
        self._tick(store, 2)
        child = build_block(post, 2,
                            execution_payload=_payload(b"\x55" * 32))
        fc.on_block(store, child)  # no PoW view needed post-merge
        assert hash_tree_root(child.message) in store.blocks

    def test_store_pow_view_isolated_from_global_registry(self):
        """A store with its own PowChainView never sees globally-registered
        PoW blocks (the cross-Simulation leak the r4 advisor flagged)."""
        state, anchor = make_genesis(32)
        own_view = merge.PowChainView()
        store = fc.get_forkchoice_store(state, anchor, pow_chain=own_view)
        parent_hash = _terminal_pair(cfg().terminal_total_difficulty)  # global
        self._tick(store, 1)
        sb = build_block(state, 1, execution_payload=_payload(parent_hash))
        with pytest.raises(AssertionError, match="unavailable"):
            fc.on_block(store, sb)
        # Register in the store's own view: now it validates.
        own_view.register(merge.PowBlock(b"\xaa" * 32, b"\x00" * 32,
                                         cfg().terminal_total_difficulty - 1))
        own_view.register(merge.PowBlock(parent_hash, b"\xaa" * 32,
                                         cfg().terminal_total_difficulty))
        fc.on_block(store, sb)
        assert hash_tree_root(sb.message) in store.blocks

    def test_simulations_do_not_share_pow_state(self):
        """Two Simulation instances in one process have independent PoW
        views, each isolated from the module default registry."""
        from pos_evolution_tpu.sim.driver import Simulation
        a, b = Simulation(16), Simulation(16)
        assert a.pow_chain is not b.pow_chain
        a.pow_chain.register(merge.PowBlock(b"\x42" * 32, b"\x00" * 32, 1))
        assert b.pow_chain.get(b"\x42" * 32) is None
        assert merge.get_pow_block(b"\x42" * 32) is None
        for grp in a.groups:
            assert grp.store.pow_chain is a.pow_chain
