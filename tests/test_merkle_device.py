"""Device-resident merkleization (ISSUE 15, ops/merkle_device.py).

Four contracts, each pinned host⇄device bit-identical:

1. **Dispatch**: ``pair_hash`` picks host/device per mode, backend,
   batch size, and silicon — and produces the same bytes on every path,
   including the full Pallas -> XLA -> NumPy fallback ladder.
2. **Edge geometry**: non-power-of-two leaf counts, zero-hash padded
   levels (limit >> count), single-leaf and zero-chunk trees, growing
   lists, and mixed dirty/clean lockstep batches.
3. **Consumers**: incremental SSZ trees, the DAS commitment scheme's
   shared-tree proof paths, checkpoint payload digests, and the dense
   state witness all reproduce their host-path outputs exactly when the
   device path is forced.
4. **Hygiene**: importing an op module no longer flips process-global
   jax config (the ISSUE 15 satellite).
"""

import subprocess
import sys

import numpy as np
import pytest

from pos_evolution_tpu.backend import set_backend
from pos_evolution_tpu.ops import merkle_device as md
from pos_evolution_tpu.ssz.hash import sha256_pairs, sha256_pairs_lanes
from pos_evolution_tpu.ssz.incremental import ChunkTree
from pos_evolution_tpu.ssz.merkle import (
    merkle_tree_branch,
    merkleize_chunks,
    mix_in_length,
)

pytestmark = pytest.mark.usefixtures("minimal_cfg")


@pytest.fixture
def device_mode():
    """jax backend + forced device dispatch, restored afterwards."""
    set_backend("jax")
    prev = md.set_mode("device")
    try:
        yield
    finally:
        md.set_mode(prev)
        set_backend("numpy")


def _rand_rows(rng, n):
    return rng.integers(0, 256, size=(n, 32)).astype(np.uint8)


# --- dispatch -----------------------------------------------------------------

class TestPairHashDispatch:
    def test_host_path_parity_and_counters(self):
        rng = np.random.default_rng(0)
        left, right = _rand_rows(rng, 37), _rand_rows(rng, 37)
        before = md.stats()
        out = md.pair_hash(left, right)
        assert (out == sha256_pairs(left, right)).all()
        after = md.stats()
        assert after["host_sweeps"] == before["host_sweeps"] + 1
        assert after["host_pairs"] == before["host_pairs"] + 37
        assert after["device_sweeps"] == before["device_sweeps"]

    @pytest.mark.parametrize("n", [1, 5, 100])
    def test_device_path_parity(self, device_mode, n):
        rng = np.random.default_rng(n)
        left, right = _rand_rows(rng, n), _rand_rows(rng, n)
        before = md.stats()
        out = md.pair_hash(left, right)
        assert (out == sha256_pairs_lanes(left, right)).all()
        after = md.stats()
        assert after["device_sweeps"] == before["device_sweeps"] + 1
        assert after["device_pairs"] == before["device_pairs"] + n

    def test_empty_batch(self, device_mode):
        out = md.pair_hash(np.empty((0, 32), np.uint8),
                           np.empty((0, 32), np.uint8))
        assert out.shape == (0, 32)

    def test_auto_on_cpu_jax_stays_host(self):
        """jax-on-CPU never beats the host kernel, so auto mode keeps
        even huge batches on host silicon."""
        set_backend("jax")
        try:
            assert md.get_mode() == "auto"
            assert not md.device_eligible(1 << 20)
        finally:
            set_backend("numpy")

    def test_auto_threshold_and_accelerator_rule(self, monkeypatch):
        """Past the crossover AND on a real accelerator, auto goes to
        the device; below the crossover it never does."""
        import jax
        set_backend("jax")
        try:
            monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
            from pos_evolution_tpu.config import cfg
            floor = cfg().merkle_device_min_pairs
            assert md.device_eligible(floor)
            assert not md.device_eligible(floor - 1)
        finally:
            set_backend("numpy")

    def test_numpy_backend_never_device(self):
        prev = md.set_mode("device")
        try:
            assert not md.device_eligible(1 << 20)
        finally:
            md.set_mode(prev)

    def test_mode_validation(self):
        with pytest.raises(ValueError):
            md.set_mode("gpu")


class TestFallbackLadder:
    def test_pallas_failure_falls_to_xla(self, device_mode, monkeypatch):
        """Top rung forced on and broken: the sweep lands on XLA, the
        fallback is counted, and the bytes don't change."""
        monkeypatch.setattr(md, "_pallas_usable", lambda m: True)

        def boom(words):
            raise RuntimeError("no mosaic on this box")

        monkeypatch.setattr(md, "_pallas_level", boom)
        rng = np.random.default_rng(1)
        left, right = _rand_rows(rng, 80), _rand_rows(rng, 80)
        before = md.stats()
        out = md.pair_hash(left, right)
        assert (out == sha256_pairs_lanes(left, right)).all()
        after = md.stats()
        assert after["fallback_xla"] == before["fallback_xla"] + 1
        assert after["device_sweeps"] == before["device_sweeps"] + 1

    def test_xla_failure_falls_to_numpy(self, device_mode, monkeypatch):
        """Both device rungs broken: the bottom rung still answers,
        counted as a host sweep plus a loud fallback."""
        monkeypatch.setattr(md, "_pallas_usable", lambda m: False)

        def boom():
            raise RuntimeError("jax exploded")

        monkeypatch.setattr(md, "_xla_level_for", boom)
        rng = np.random.default_rng(2)
        left, right = _rand_rows(rng, 80), _rand_rows(rng, 80)
        before = md.stats()
        out = md.pair_hash(left, right)
        assert (out == sha256_pairs(left, right)).all()
        after = md.stats()
        assert after["fallback_numpy"] == before["fallback_numpy"] + 1
        assert after["host_sweeps"] == before["host_sweeps"] + 1
        assert after["device_sweeps"] == before["device_sweeps"]


# --- edge geometry ------------------------------------------------------------

class TestMerkleizeGeometry:
    @pytest.mark.parametrize("n,limit", [
        (0, None), (0, 64), (1, None), (1, 64), (2, 2),
        (5, None), (5, 64), (9, 16), (33, 64), (100, 2048),
    ])
    def test_device_matches_host(self, device_mode, n, limit):
        """Non-pow2 counts, single leaves, empty trees, and zero-hash
        padded levels (limit >> count) — identical roots."""
        rng = np.random.default_rng(n + (limit or 0))
        chunks = _rand_rows(rng, n)
        assert md.merkleize(chunks, limit) == merkleize_chunks(chunks, limit)

    def test_limit_overflow_raises(self, device_mode):
        with pytest.raises(ValueError):
            md.merkleize(_rand_rows(np.random.default_rng(0), 5), 4)

    def test_host_mode_delegates(self):
        rng = np.random.default_rng(3)
        chunks = _rand_rows(rng, 50)
        assert md.merkleize(chunks, 64) == merkleize_chunks(chunks, 64)

    def test_tree_levels_match_reference(self, device_mode):
        from pos_evolution_tpu.ssz.merkle import _tree_levels
        rng = np.random.default_rng(4)
        leaves = _rand_rows(rng, 11)
        got = md.tree_levels(leaves, 4)
        want = _tree_levels(leaves, 4)
        assert len(got) == len(want)
        for g, w in zip(got, want):
            assert (g == w).all()


class TestChunkTreeDevice:
    def test_randomized_mutations_bit_identical(self, device_mode):
        """The incremental tree under forced-device sweeps reproduces
        full host re-merkleization across point writes, growth, and
        no-op rounds."""
        rng = np.random.default_rng(5)
        limit = 256
        tree = ChunkTree(limit)
        chunks = _rand_rows(rng, 60)
        assert tree.root(chunks) == merkleize_chunks(chunks, limit)
        for round_ in range(6):
            if round_ == 2:  # no-op round: cache hit, no sweeps
                assert tree.root(chunks) == merkleize_chunks(chunks, limit)
                continue
            if round_ == 4:  # grow
                chunks = np.concatenate([chunks, _rand_rows(rng, 30)])
            else:
                chunks[rng.integers(0, chunks.shape[0], 7)] ^= 0x3C
            assert tree.root(chunks) == merkleize_chunks(chunks, limit)

    def test_single_leaf_and_shrink_rebuild(self, device_mode):
        tree = ChunkTree(None)
        one = _rand_rows(np.random.default_rng(6), 1)
        assert tree.root(one) == merkleize_chunks(one, None)
        big = _rand_rows(np.random.default_rng(7), 9)
        assert tree.root(big) == merkleize_chunks(big, None)
        assert tree.root(big[:3]) == merkleize_chunks(big[:3], None)


class TestLockstepSweeper:
    def test_mixed_dirty_clean_batch(self):
        """Four trees — clean, dirty, growing, first-build — driven by
        one LevelSweeper: every root identical to a standalone twin, the
        clean tree contributes nothing, and each level hashes in ONE
        launch across the dirty trees."""
        rng = np.random.default_rng(8)
        data = [_rand_rows(rng, n) for n in (40, 40, 24, 16)]
        solo = [ChunkTree(64) for _ in data]
        batched = [ChunkTree(64) for _ in data]
        for t_list in (solo, batched):
            for tree, chunks in zip(t_list[:3], data[:3]):
                tree.root(chunks)  # pre-warm 3 of 4 (the 4th first-builds)
        data[1] = data[1].copy()
        data[1][5] ^= 0xFF  # dirty
        data[2] = np.concatenate([data[2], _rand_rows(rng, 8)])  # grow

        want = [tree.root(chunks) for tree, chunks in zip(solo, data)]

        before = md.stats()
        sweeper = md.LevelSweeper()
        fins = [tree.root(chunks, sweeper)
                for tree, chunks in zip(batched, data)]
        sweeper.run()
        got = [fin() for fin in fins]
        after = md.stats()
        assert got == want
        # tree 0 is clean (finisher without a job); 3 dirty trees joined
        assert after["batched_jobs"] == before["batched_jobs"] + 3
        # lockstep: rounds = deepest dirty tree's level count, NOT the
        # sum over trees
        launches = after["batched_launches"] - before["batched_launches"]
        assert launches == 6  # depth of a 64-limit tree

    def test_abandoned_sweep_never_serves_stale_root(self):
        """A tree registered on a sweeper that never runs (an exception
        between registration and run) has its leaves written but not its
        internal nodes — the next query must rebuild, not diff against
        the half-updated state and serve the OLD root as a 'cache hit'."""
        rng = np.random.default_rng(22)
        tree = ChunkTree(64)
        chunks = _rand_rows(rng, 20)
        tree.root(chunks)
        mutated = chunks.copy()
        mutated[7] ^= 0xAA
        sweeper = md.LevelSweeper()
        tree.root(mutated, sweeper)
        # sweeper.run() never happens — e.g. a sibling field raised
        assert tree.root(mutated) == merkleize_chunks(mutated, 64)
        # and an abandoned REBUILD generator must also recover
        tree2 = ChunkTree(64)
        s2 = md.LevelSweeper()
        tree2.root(chunks, s2)  # first build, registered, never run
        assert tree2.root(chunks) == merkleize_chunks(chunks, 64)

    def test_state_root_device_parity(self, device_mode):
        """The full incremental BeaconState root (lockstep + forced
        device sweeps) == the host full-merkleization oracle."""
        from pos_evolution_tpu.specs.containers import BeaconState
        from pos_evolution_tpu.specs.genesis import make_genesis_state
        from pos_evolution_tpu.ssz.incremental import state_root
        state = make_genesis_state(24)
        assert state_root(state) == BeaconState.htr(state)
        state.balances[3] += 17
        state.slot += 1
        assert state_root(state) == BeaconState.htr(state)


# --- consumers ----------------------------------------------------------------

class TestBackendMethods:
    def test_merkle_level_pair(self):
        from pos_evolution_tpu.backend import jax_backend, numpy_backend
        rng = np.random.default_rng(9)
        left, right = _rand_rows(rng, 33), _rand_rows(rng, 33)
        h = numpy_backend.merkle_level(left, right)
        set_backend("jax")
        prev = md.set_mode("device")
        try:
            d = jax_backend.merkle_level(left, right)
        finally:
            md.set_mode(prev)
            set_backend("numpy")
        assert (h == d).all()

    def test_merkleize_and_paths_pair(self):
        from pos_evolution_tpu.backend import jax_backend, numpy_backend
        rng = np.random.default_rng(10)
        leaves = _rand_rows(rng, 20)
        idx = [0, 7, 19, 7]
        h_root = numpy_backend.merkleize(leaves, 32)
        h_sel, h_br = numpy_backend.build_multiproof_paths(leaves, idx, 5)
        set_backend("jax")
        prev = md.set_mode("device")
        try:
            d_root = jax_backend.merkleize(leaves, 32)
            d_sel, d_br = jax_backend.build_multiproof_paths(leaves, idx, 5)
        finally:
            md.set_mode(prev)
            set_backend("numpy")
        assert h_root == d_root
        assert (h_sel == d_sel).all() and (h_br == d_br).all()
        # oracle: the per-index scalar branch walk
        for j, i in enumerate(idx):
            want = merkle_tree_branch(leaves, i, 5)
            assert [h_br[j, d].tobytes() for d in range(5)] == want
            assert h_sel[j].tobytes() == leaves[i].tobytes()


class TestDasConsumers:
    def test_commitment_scheme_device_parity(self, device_mode):
        """commit / branches / prove_cells through the forced-device
        dispatch layer == the host reference (and the multiproof still
        verifies)."""
        from pos_evolution_tpu.config import cfg
        from pos_evolution_tpu.das.commitment import MerkleCellScheme
        from pos_evolution_tpu.ssz.merkle import verify_multiproof
        rng = np.random.default_rng(11)
        n_cells = 2 * cfg().das_cells_per_blob
        cells = rng.integers(0, 256, (n_cells, cfg().das_cell_bytes),
                             dtype=np.uint8)
        scheme = MerkleCellScheme()
        leaves = scheme.cell_leaves(cells)
        commitment = scheme.commit(cells)
        assert commitment == merkleize_chunks(leaves)
        idx = [0, 3, 3, n_cells - 1]
        sel, br = scheme.branches(cells, idx)
        depth = scheme.depth_for(n_cells)
        for j, i in enumerate(idx):
            assert [br[j, d].tobytes() for d in range(depth)] \
                == merkle_tree_branch(leaves, i, depth)
            assert sel[j].tobytes() == leaves[i].tobytes()
        proof = scheme.prove_cells(cells, idx)
        assert scheme.verify_cells(commitment, cells[idx], idx, proof)

    def test_das_verify_small_batch_routes_host(self, monkeypatch):
        """Below the crossover the jax backend's das_verify answers from
        the host path — proven by breaking the device path and watching
        the verdicts still arrive (bit-identical, so routing is the only
        observable)."""
        from pos_evolution_tpu.backend import jax_backend
        from pos_evolution_tpu.config import cfg
        from pos_evolution_tpu.das.commitment import MerkleCellScheme
        from pos_evolution_tpu.ops import das_verify as dv
        rng = np.random.default_rng(12)
        n_cells = 2 * cfg().das_cells_per_blob
        cells = rng.integers(0, 256, (n_cells, cfg().das_cell_bytes),
                             dtype=np.uint8)
        scheme = MerkleCellScheme()
        commitment = scheme.commit(cells)
        idx = [1, 5, 9]
        sel_leaves, branches = scheme.branches(cells, idx)
        batch = dv.DasSampleBatch(
            cells=cells[idx], branches=branches,
            indices=np.asarray(idx, dtype=np.int64),
            commitments=np.repeat(
                np.frombuffer(commitment, np.uint8)[None, :], 3, axis=0))

        def boom(b):
            raise AssertionError("small batch must not reach the device")

        monkeypatch.setattr(dv, "verify_samples_device", boom)
        assert batch.size < md.small_batch_floor(per_item_pairs=16)
        out = jax_backend.das_verify(batch)
        assert out["ok"].all()


class TestDigestBytes:
    def test_host_device_parity_and_length_binding(self, device_mode):
        rng = np.random.default_rng(13)
        blob = rng.integers(0, 256, 4097, dtype=np.uint8).tobytes()
        d_dev = md.digest_bytes(blob)
        prev = md.set_mode("host")
        try:
            assert md.digest_bytes(blob) == d_dev
        finally:
            md.set_mode(prev)
        # zero-padding must not collide across lengths
        assert md.digest_bytes(b"\x01" * 31) != md.digest_bytes(
            b"\x01" * 31 + b"\x00")
        assert md.digest_bytes(b"") != md.digest_bytes(b"\x00")

    def test_array_and_bytes_agree(self):
        blob = bytes(range(64))
        assert md.digest_bytes(blob) == md.digest_bytes(
            np.frombuffer(blob, np.uint8))

    def test_oracle(self):
        """digest = mix_in_length(merkleize(chunks), n) exactly."""
        blob = bytes(range(70))
        padded = np.zeros(96, np.uint8)
        padded[:70] = np.frombuffer(blob, np.uint8)
        want = mix_in_length(
            merkleize_chunks(padded.reshape(-1, 32), None), 70)
        assert md.digest_bytes(blob) == want


class TestCheckpointDigests:
    def test_merkle_digest_roundtrip_and_bitflip(self, tmp_path):
        import os

        from pos_evolution_tpu.resilience.manager import (
            CheckpointCorruption,
            CheckpointManager,
        )
        mgr = CheckpointManager(tmp_path, digest="merkle")
        payload = np.random.default_rng(14).integers(
            0, 256, 5000, dtype=np.uint8).tobytes()
        mgr.save(3, {"cols.npz": payload})
        assert mgr.load(3)["cols.npz"] == payload
        manifest = mgr.validate(3)
        assert "merkle" in manifest["files"]["cols.npz"]
        # flip one byte on disk: the merkle digest must catch it
        p = os.path.join(mgr._step_dir(3), "cols.npz")
        raw = bytearray(open(p, "rb").read())
        raw[1234] ^= 0x01
        open(p, "wb").write(bytes(raw))
        with pytest.raises(CheckpointCorruption, match="merkle"):
            mgr.validate(3)

    def test_legacy_sha256_steps_still_validate(self, tmp_path):
        """A store can hold steps written under either digest — the
        per-file manifest entry names its own algorithm."""
        from pos_evolution_tpu.resilience.manager import CheckpointManager
        old = CheckpointManager(tmp_path, digest="sha256")
        old.save(1, b"legacy payload")
        new = CheckpointManager(tmp_path, digest="merkle")
        new.save(2, b"merkle payload")
        assert new.load(1) == {"payload.bin": b"legacy payload"}
        assert new.load(2) == {"payload.bin": b"merkle payload"}
        step, payloads = new.latest_valid()
        assert step == 2 and payloads["payload.bin"] == b"merkle payload"

    def test_unknown_digest_refused(self, tmp_path):
        from pos_evolution_tpu.resilience.manager import CheckpointManager
        with pytest.raises(ValueError):
            CheckpointManager(tmp_path, digest="crc32")

    def test_async_writer_inherits_caller_backend(self, tmp_path):
        """The digest policy is pinned at gather time: a save issued
        under the jax backend + forced device mode hashes its payload on
        the device path even though the bytes land on the writer
        thread."""
        from pos_evolution_tpu.resilience.manager import CheckpointManager
        mgr = CheckpointManager(tmp_path, digest="merkle", async_mode=True)
        payload = np.random.default_rng(15).integers(
            0, 256, 64 * 33, dtype=np.uint8).tobytes()
        set_backend("jax")
        prev = md.set_mode("device")
        before = md.stats()["device_sweeps"]
        try:
            mgr.save(1, {"payload.bin": lambda: payload}, wait=True)
        finally:
            md.set_mode(prev)
            set_backend("numpy")
        mgr.close()
        assert md.stats()["device_sweeps"] > before
        assert mgr.load(1)["payload.bin"] == payload


class TestStateWitness:
    def test_dense_witness_host_device_identical(self):
        """state_digest over a real dense run: forced-device column
        digests == host column digests (the witness is path-blind)."""
        from pos_evolution_tpu.config import mainnet_config
        from pos_evolution_tpu.resilience import state_digest
        from pos_evolution_tpu.sim.dense_driver import DenseSimulation
        cfg_ = mainnet_config().replace(slots_per_epoch=8,
                                        max_committees_per_slot=4)
        sim = DenseSimulation(64, cfg=cfg_, mesh=None, seed=21,
                              shuffle_rounds=4, check_walk_every=0,
                              verify_aggregates=False)
        sim.run_epochs(1)
        host_digest = state_digest(sim)
        set_backend("jax")
        prev = md.set_mode("device")
        try:
            dev_digest = state_digest(sim)
        finally:
            md.set_mode(prev)
            set_backend("numpy")
        assert host_digest == dev_digest


# --- import hygiene (ISSUE 15 satellite) --------------------------------------

class TestImportSideEffects:
    def test_op_imports_leave_x64_alone(self):
        """Importing the SHA-256 op modules must not flip the
        process-global x64 flag; first kernel USE must."""
        code = (
            "import os; os.environ['JAX_PLATFORMS']='cpu'\n"
            "import jax\n"
            "import pos_evolution_tpu.ops.sha256 as s\n"
            "import pos_evolution_tpu.ops.pallas_sha256  # noqa: F401\n"
            "import pos_evolution_tpu.ops.merkle_device  # noqa: F401\n"
            "assert not jax.config.jax_enable_x64, 'import flipped x64'\n"
            "import numpy as np, jax.numpy as jnp\n"
            "w = jnp.asarray(np.zeros((2, 16), np.uint32))\n"
            "s.sha256_words(w)\n"
            "assert jax.config.jax_enable_x64, 'first use must enable x64'\n"
            "print('ok')\n"
        )
        out = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, text=True, timeout=300)
        assert out.returncode == 0, out.stderr
        assert "ok" in out.stdout
