"""utils/metrics.py coverage: HandlerTimer percentiles/summary, the
structured slot_record, the light-client lag record, and the
StoreInvariantChecker contract."""

import numpy as np
import pytest

from pos_evolution_tpu.utils.metrics import (
    HandlerTimer,
    StoreInvariantChecker,
    light_client_lag_record,
    slot_record,
)

pytestmark = pytest.mark.usefixtures("minimal_cfg")


class TestHandlerTimer:
    def test_track_collects_samples(self):
        t = HandlerTimer()
        for _ in range(5):
            with t.track("h"):
                pass
        assert len(t.samples["h"]) == 5
        assert all(x >= 0 for x in t.samples["h"])

    def test_track_records_on_exception(self):
        t = HandlerTimer()
        with pytest.raises(ValueError):
            with t.track("boom"):
                raise ValueError()
        assert len(t.samples["boom"]) == 1

    def test_percentile_matches_numpy(self):
        t = HandlerTimer()
        t.samples["h"] = [0.1, 0.2, 0.3, 0.4]
        assert t.percentile("h", 50) == pytest.approx(float(np.percentile(t.samples["h"], 50)))
        assert t.percentile("h", 95) == pytest.approx(float(np.percentile(t.samples["h"], 95)))

    def test_percentile_of_unknown_handler_is_nan(self):
        assert np.isnan(HandlerTimer().percentile("nope", 50))

    def test_summary_shape_and_totals(self):
        t = HandlerTimer()
        t.samples["a"] = [0.001, 0.002, 0.003]
        t.samples["b"] = [0.5]
        s = t.summary()
        assert set(s) == {"a", "b"}
        for name, row in s.items():
            assert set(row) == {"count", "p50_ms", "p95_ms", "total_s"}
        assert s["a"]["count"] == 3
        assert s["a"]["total_s"] == pytest.approx(0.006, abs=1e-6)
        assert s["b"]["p50_ms"] == pytest.approx(500.0)

    def test_wrap_preserves_return_value(self):
        t = HandlerTimer()
        fn = t.wrap("f", lambda x: x * 2)
        assert fn(21) == 42
        assert len(t.samples["f"]) == 1


class TestSlotRecord:
    def test_fields_and_values_from_live_store(self):
        from pos_evolution_tpu.sim import Simulation
        sim = Simulation(32)
        sim.run_epochs(3)
        store = sim.store(0)
        rec = slot_record(store, sim.slot)
        expected_keys = {
            "slot", "head_root", "head_slot", "justified_epoch",
            "finalized_epoch", "justification_bits", "participation",
            "n_blocks", "n_latest_messages", "equivocators",
        }
        assert set(rec) == expected_keys
        assert rec["slot"] == sim.slot
        assert rec["finalized_epoch"] == sim.finalized_epoch()
        assert 0.0 <= rec["participation"] <= 1.0
        assert rec["n_blocks"] == len(store.blocks)
        assert len(rec["justification_bits"]) == 4


class TestLightClientLagRecord:
    def test_lags_computed_against_full_node(self):
        from pos_evolution_tpu.config import cfg
        from pos_evolution_tpu.lightclient import LightClientStore
        from pos_evolution_tpu.specs.containers import (
            BeaconBlockHeader,
            SyncCommittee,
        )
        spe = cfg().slots_per_epoch
        store = LightClientStore(
            finalized_header=BeaconBlockHeader(slot=2 * spe),
            current_sync_committee=SyncCommittee(),
            optimistic_header=BeaconBlockHeader(slot=3 * spe + 1),
        )
        rec = light_client_lag_record(store, slot=3 * spe + 2,
                                      full_head_slot=3 * spe + 2,
                                      full_finalized_epoch=3)
        assert rec["head_lag"] == 1
        assert rec["finality_lag"] == 1
        assert rec["lc_finalized_slot"] == 2 * spe


class TestStoreInvariantChecker:
    def test_clean_failed_handler_records_no_violation(self):
        from pos_evolution_tpu.sim import Simulation
        sim = Simulation(32)
        sim.run_epochs(1)
        checker = StoreInvariantChecker(sim.store(0))

        def failing_handler(store):
            raise AssertionError("rejects without mutating")

        with pytest.raises(AssertionError):
            checker.call(failing_handler)
        assert checker.violations == []

    def test_mutating_failed_handler_is_flagged(self):
        from pos_evolution_tpu.sim import Simulation
        sim = Simulation(32)
        sim.run_epochs(1)
        checker = StoreInvariantChecker(sim.store(0))

        def dirty_handler(store):
            store.time += 1
            raise AssertionError("mutated before failing")

        with pytest.raises(AssertionError):
            checker.call(dirty_handler)
        assert len(checker.violations) == 1
