"""Incremental SSZ merkleization + fused block transition (ISSUE 6).

Two bit-identity contracts, both pinned by randomized property tests:

1. **Incremental == full merkleization.** ``ssz/incremental.py``'s
   persistent trees must reproduce ``merkleize_chunks`` (+
   ``mix_in_length``) exactly under arbitrary mutation sequences —
   point writes, wholesale rewrites, list grow/shrink, zero-content
   appends whose only root effect is the length mix-in.
2. **Fused transition == spec reference.** The batched attestation sweep
   (``ops/transition.py``, dispatched via ``ExecutionBackend``) must give
   the same post-state as the reference per-attestation loop — per
   attestation on the host path, per block chain across both backends.
"""

import numpy as np
import pytest

from pos_evolution_tpu.backend import set_backend
from pos_evolution_tpu.config import (
    PARTICIPATION_FLAG_WEIGHTS,
    PROPOSER_WEIGHT,
    WEIGHT_DENOMINATOR,
    cfg,
)
from pos_evolution_tpu.specs.containers import (
    BeaconState,
    SignedBeaconBlock,
    Validator,
)
from pos_evolution_tpu.specs.genesis import make_genesis
from pos_evolution_tpu.specs.helpers import (
    get_base_reward,
    get_beacon_proposer_index,
    increase_balance,
)
from pos_evolution_tpu.specs.transition import (
    _validate_attestation,
    process_slots,
    state_transition,
)
from pos_evolution_tpu.specs.validator import attest_all_committees, build_block
from pos_evolution_tpu.ssz import cached_root, hash_tree_root
from pos_evolution_tpu.ssz.incremental import (
    ChunkTree,
    RegistryTree,
    reset_stats,
    set_enabled,
    state_root,
    stats,
)
from pos_evolution_tpu.ssz.merkle import merkleize_chunks, mix_in_length

pytestmark = pytest.mark.usefixtures("minimal_cfg")


def _rand_chunks(rng, n):
    return rng.integers(0, 256, size=(n, 32)).astype(np.uint8)


# --- ChunkTree vs merkleize_chunks --------------------------------------------

class TestChunkTree:
    @pytest.mark.parametrize("limit", [None, 1, 16, 1024, 2**35])
    def test_randomized_mutations_bit_identical(self, limit):
        rng = np.random.default_rng(0xC0 + (limit or 0) % 97)
        tree = ChunkTree(limit)
        cap = min(limit if limit is not None else 64, 64)
        n = int(rng.integers(0, min(cap, 12) + 1))
        chunks = _rand_chunks(rng, n)
        for round_ in range(40):
            move = rng.integers(0, 5)
            if move == 0 and chunks.shape[0] < cap:            # append
                chunks = np.concatenate(
                    [chunks, _rand_chunks(rng, int(rng.integers(1, 4)))])
                chunks = chunks[:cap]
            elif move == 1 and chunks.shape[0] > 0:            # shrink
                chunks = chunks[:int(rng.integers(0, chunks.shape[0] + 1))]
            elif move == 2 and chunks.shape[0] > 0:            # point writes
                k = int(rng.integers(1, chunks.shape[0] + 1))
                rows = rng.choice(chunks.shape[0], size=k, replace=False)
                chunks = chunks.copy()
                chunks[rows] = _rand_chunks(rng, k)
            elif move == 3:                                    # rewrite
                chunks = _rand_chunks(rng, int(rng.integers(0, cap + 1)))
            # move == 4: no-op round (cache-hit path)
            assert tree.root(chunks) == merkleize_chunks(chunks, limit), \
                f"divergence at round {round_} (n={chunks.shape[0]})"

    def test_zero_content_append_changes_nothing_at_chunk_level(self):
        # The tree caches on chunk CONTENT; the length mix-in lives with the
        # caller. Appending zero bytes that do not alter any packed chunk
        # must serve the cached root (the mix_in_length edge is the
        # caller's job — pinned at state level below).
        tree = ChunkTree(64)
        chunks = np.zeros((4, 32), dtype=np.uint8)
        r1 = tree.root(chunks)
        assert r1 == merkleize_chunks(chunks, 64)
        before = stats()["dirty_chunks"]
        assert tree.root(chunks.copy()) == r1
        assert stats()["dirty_chunks"] == before  # pure cache hit

    def test_odd_count_zero_padding(self):
        rng = np.random.default_rng(7)
        tree = ChunkTree(None)
        for n in (1, 3, 5, 7, 9, 6, 2):
            chunks = _rand_chunks(rng, n)
            assert tree.root(chunks) == merkleize_chunks(chunks, None)

    def test_empty(self):
        for limit in (None, 1, 8, 2**30):
            assert ChunkTree(limit).root(
                np.empty((0, 32), dtype=np.uint8)) == \
                merkleize_chunks(np.empty((0, 32), dtype=np.uint8), limit)

    def test_limit_overflow_raises(self):
        with pytest.raises(ValueError):
            ChunkTree(2).root(_rand_chunks(np.random.default_rng(1), 3))


# --- RegistryTree vs full registry merkleization ------------------------------

class TestRegistryTree:
    def _full_root(self, reg):
        limit = cfg().validator_registry_limit
        return mix_in_length(
            merkleize_chunks(reg.validator_roots(), limit), len(reg))

    def test_randomized_registry_mutations(self):
        state, _ = make_genesis(16)
        reg = state.validators
        tree = RegistryTree()
        limit = cfg().validator_registry_limit
        rng = np.random.default_rng(21)
        assert tree.root(reg, limit) == self._full_root(reg)
        for _ in range(25):
            move = rng.integers(0, 4)
            if move == 0:      # scalar column point write
                i = int(rng.integers(0, len(reg)))
                reg.effective_balance[i] = np.uint64(rng.integers(1, 2**35))
            elif move == 1:    # slash + exit epochs
                i = int(rng.integers(0, len(reg)))
                reg.slashed[i] = True
                reg.exit_epoch[i] = np.uint64(rng.integers(0, 2**20))
            elif move == 2:    # row column write (credentials)
                i = int(rng.integers(0, len(reg)))
                reg.withdrawal_credentials[i] = rng.integers(
                    0, 256, 32).astype(np.uint8)
            else:              # append a validator (registry grow)
                v = Validator()
                v.effective_balance = np.uint64(32 * 10**9)
                reg.append(v)
            assert tree.root(reg, limit) == self._full_root(reg)

    def test_no_mutation_is_a_cache_hit(self):
        state, _ = make_genesis(8)
        tree = RegistryTree()
        limit = cfg().validator_registry_limit
        r1 = tree.root(state.validators, limit)
        before = stats()["dirty_chunks"]
        assert tree.root(state.validators, limit) == r1
        assert stats()["dirty_chunks"] == before


# --- BeaconState: incremental state_root == full htr --------------------------

def _mutate_state(state, rng, round_):
    """One randomized mutation drawn from the shapes the transition layer
    actually performs — returns a tag for failure messages."""
    n = len(state.validators)
    move = int(rng.integers(0, 10))
    if move == 0:
        rows = rng.choice(n, size=int(rng.integers(1, n)), replace=False)
        state.balances[rows] += np.uint64(1000)
        return "balances"
    if move == 1:
        rows = rng.choice(n, size=int(rng.integers(1, n)), replace=False)
        state.current_epoch_participation[rows] |= np.uint8(
            rng.integers(1, 8))
        return "participation"
    if move == 2:
        state.slot = int(state.slot) + 1
        return "slot"
    if move == 3:
        i = int(rng.integers(0, state.randao_mixes.shape[0]))
        state.randao_mixes[i] = rng.integers(0, 256, 32).astype(np.uint8)
        return "randao"
    if move == 4:
        i = int(rng.integers(0, state.block_roots.shape[0]))
        state.block_roots[i] = rng.integers(0, 256, 32).astype(np.uint8)
        return "block_roots"
    if move == 5:  # list GROW: historical roots accumulate
        state.historical_roots = np.concatenate(
            [state.historical_roots,
             rng.integers(0, 256, (1, 32)).astype(np.uint8)])
        return "historical_roots grow"
    if move == 6:  # eth1 vote list grow, periodically cleared (SHRINK)
        if round_ % 7 == 6 and len(state.eth1_data_votes):
            state.eth1_data_votes = []
            return "eth1_data_votes clear"
        state.eth1_data_votes = list(state.eth1_data_votes) + [
            state.eth1_data.copy()]
        return "eth1_data_votes grow"
    if move == 7:
        state.validators.effective_balance[
            int(rng.integers(0, n))] = np.uint64(31 * 10**9)
        return "registry effective_balance"
    if move == 8:  # registry + parallel columns grow (deposit shape)
        state.validators.append(Validator())
        for f in ("balances", "previous_epoch_participation",
                  "current_epoch_participation", "inactivity_scores"):
            col = getattr(state, f)
            setattr(state, f, np.concatenate(
                [col, np.zeros(1, dtype=col.dtype)]))
        return "deposit grow"
    state.justification_bits = np.roll(state.justification_bits, 1)
    state.finalized_checkpoint.epoch = int(
        state.finalized_checkpoint.epoch) + 1
    return "finality"


class TestIncrementalStateRoot:
    def test_randomized_state_mutations_bit_identical(self):
        state, _ = make_genesis(16)
        rng = np.random.default_rng(1234)
        full = BeaconState.htr  # the from-scratch oracle
        assert state_root(state) == full(state)
        for round_ in range(60):
            tag = _mutate_state(state, rng, round_)
            assert state_root(state) == full(state), \
                f"divergence after round {round_}: {tag}"

    def test_zero_append_mix_in_length_edge(self):
        # Appending a ZERO balance/participation row can leave every packed
        # chunk byte-identical (8 uint64 per chunk); the root must still
        # change, via the length mix-in alone.
        state, _ = make_genesis(8)  # 8 balances = exactly one chunk
        assert state_root(state) == BeaconState.htr(state)
        state.balances = np.concatenate(
            [state.balances, np.zeros(0, dtype=np.uint64)])
        r8 = state_root(state)
        state.previous_epoch_participation = np.concatenate(
            [state.previous_epoch_participation,
             np.zeros(1, dtype=np.uint8)])  # 9th zero byte: chunk unchanged
        r9 = state_root(state)
        assert r8 != r9
        assert r9 == BeaconState.htr(state)

    def test_hash_tree_root_routes_through_incremental(self):
        state, _ = make_genesis(8)
        reset_stats()
        assert hash_tree_root(state) == BeaconState.htr(state)
        assert stats()["htr_calls"] == 1  # __ssz_root__ hook engaged

    def test_disabled_falls_back_to_full(self):
        state, _ = make_genesis(8)
        prev = set_enabled(False)
        try:
            reset_stats()
            assert state_root(state) == BeaconState.htr(state)
            assert stats()["htr_calls"] == 0
        finally:
            set_enabled(prev)

    def test_copy_shares_cache_and_both_roots_stay_correct(self):
        state, _ = make_genesis(16)
        state_root(state)  # warm the cache
        fork = state.copy()
        assert fork.__dict__.get("_htr_cache") is \
            state.__dict__.get("_htr_cache")
        # diverge both sides; whichever asks next diffs against the other's
        # last-hashed leaves — roots must stay exact either way
        state.balances[0] += np.uint64(7)
        fork.balances[1] += np.uint64(9)
        for s in (state, fork, state, fork):
            assert state_root(s) == BeaconState.htr(s)

    def test_base_container_copy_strips_cache(self):
        # Container.copy() (deepcopy path for non-BeaconState containers)
        # must not carry a memoized root into a mutable copy.
        state, _ = make_genesis(8)
        sb = build_block(state.copy(), 1)
        root = cached_root(sb.message)
        assert sb.message.__dict__.get("_htr_memo") == root
        twin = sb.message.copy()
        assert "_htr_memo" not in twin.__dict__
        twin.slot = int(twin.slot) + 1
        assert cached_root(twin) != root
        assert cached_root(sb.message) == root == hash_tree_root(sb.message)


# --- fused transition parity --------------------------------------------------

def _reference_process_attestation(state, attestation):
    """The pre-fusion spec loop (reference :744-749): per-attester
    ``get_base_reward``, sequential flag updates, per-flag unset-gated
    proposer-reward numerator. Kept verbatim as the parity oracle."""
    attesting, flag_indices, is_current = _validate_attestation(
        state, attestation)
    participation = (state.current_epoch_participation if is_current
                     else state.previous_epoch_participation)
    base_rewards = np.array(
        [get_base_reward(state, int(i)) for i in attesting], dtype=np.int64)
    numerator = 0
    new_flags = participation[attesting]
    for flag_index, weight in enumerate(PARTICIPATION_FLAG_WEIGHTS):
        if flag_index not in flag_indices:
            continue
        unset = ((new_flags >> np.uint8(flag_index)) & np.uint8(1)) == 0
        numerator += int(base_rewards[unset].sum()) * weight
        new_flags = new_flags | np.uint8(1 << flag_index)
    participation[attesting] = new_flags
    denom = ((WEIGHT_DENOMINATOR - PROPOSER_WEIGHT)
             * WEIGHT_DENOMINATOR // PROPOSER_WEIGHT)
    increase_balance(state, get_beacon_proposer_index(state),
                     numerator // denom)


def _build_chain(n_validators, slots):
    """One honest chain: returns (genesis_state, [signed blocks])."""
    state, _ = make_genesis(n_validators)
    genesis = state.copy()
    blocks, atts = [], []
    for slot in range(1, slots + 1):
        sb = build_block(state, slot, attestations=atts)
        state_transition(state, sb, True)
        atts = attest_all_committees(state, slot, cached_root(sb.message))
        blocks.append(sb)
    return genesis, blocks


def _assert_swept_columns_equal(a, b, tag):
    for f in ("balances", "current_epoch_participation",
              "previous_epoch_participation"):
        assert np.array_equal(getattr(a, f), getattr(b, f)), f"{tag}: {f}"


class TestFusedTransitionParity:
    def test_sweeps_equal_reference_loop_on_real_blocks(self):
        """For every block of an honest chain: the reference sequential
        per-attestation loop, the batched host sweep, and the batched
        device sweep must mutate identical pre-states identically."""
        from pos_evolution_tpu.ops import transition as optr
        genesis, blocks = _build_chain(32, 2 * cfg().slots_per_epoch + 2)
        optr.reset_session()
        state = genesis.copy()
        try:
            for sb in blocks:
                atts = list(sb.message.body.attestations)
                if atts:
                    pre = state.copy()
                    process_slots(pre, int(sb.message.slot))
                    ref_s, host_s, dev_s = (pre.copy(), pre.copy(),
                                            pre.copy())
                    for att in atts:
                        _reference_process_attestation(ref_s, att)
                    rows = [_validate_attestation(host_s, a) for a in atts]
                    optr.apply_attestation_rows_host(host_s, rows)
                    optr.apply_attestation_rows_device(dev_s, rows)
                    tag = f"slot {int(sb.message.slot)}"
                    _assert_swept_columns_equal(ref_s, host_s, tag)
                    _assert_swept_columns_equal(ref_s, dev_s, tag)
                state_transition(state, sb, True)
        finally:
            optr.reset_session()

    @pytest.mark.parametrize("backend", ["numpy", "jax"])
    def test_chain_replay_matches_block_state_roots(self, backend):
        """``state_transition(validate_result=True)`` re-checks every
        block's embedded state_root — replaying the chain under each
        backend is therefore a per-block bit-identity test against the
        roots the build-time states committed to."""
        from pos_evolution_tpu.ops.transition import reset_session
        genesis, blocks = _build_chain(32, 2 * cfg().slots_per_epoch + 3)
        set_backend(backend)
        reset_session()
        try:
            replay = genesis.copy()
            for sb in blocks:
                state_transition(replay, sb, True)
            assert hash_tree_root(replay) == bytes(
                blocks[-1].message.state_root)
        finally:
            set_backend("numpy")

    def test_device_session_reuse_patch_upload_decisions(self):
        """The residency session's three regimes, driven directly: an
        untouched state reuses the carry, a few perturbed rows (what
        sync-aggregate rewards do between blocks at scale) scatter-patch
        it, a wholesale rewrite re-uploads — and every regime stays
        bit-identical to the host sweep. (At toy validator counts real
        blocks always re-upload — sync rewards touch every row — which is
        why this drives the session synthetically.)"""
        from pos_evolution_tpu.ops import transition as optr
        state, _ = make_genesis(64)
        state.slot = 5
        rng = np.random.default_rng(3)

        def rows_for(seed):
            r = np.random.default_rng(seed)
            return [(np.sort(r.choice(64, size=8, replace=False))
                     .astype(np.int64), [0, 1], True)]

        optr.reset_session()
        mark = optr.session_stats()  # process-cumulative: assert deltas

        def since():
            return {k: v - mark[k] for k, v in optr.session_stats().items()}

        try:
            def sweep(seed):
                host, dev = state.copy(), state.copy()
                optr.apply_attestation_rows_host(host, rows_for(seed))
                optr.apply_attestation_rows_device(dev, rows_for(seed))
                _assert_swept_columns_equal(host, dev, f"seed {seed}")
                # adopt the device write-back as the next pre-state
                for f in ("balances", "previous_epoch_participation",
                          "current_epoch_participation"):
                    setattr(state, f, getattr(dev, f))

            sweep(0)
            assert since()["uploads"] == 1
            sweep(1)   # untouched since write-back: pure reuse
            assert since()["reuses"] == 1
            state.balances[rng.choice(64, 3, replace=False)] += np.uint64(5)
            sweep(2)   # 3 of 64 rows moved: scatter-patch
            assert since()["patches"] == 1
            state.balances = state.balances + np.uint64(1)  # wholesale
            sweep(3)
            assert since()["uploads"] == 2
        finally:
            optr.reset_session()

    def test_multi_block_apply_equals_sequential(self):
        from pos_evolution_tpu.ops.resident import apply_block_batch
        genesis, blocks = _build_chain(32, cfg().slots_per_epoch + 2)
        seq = genesis.copy()
        for sb in blocks:
            state_transition(seq, sb, True)
        for backend in ("numpy", "jax"):
            from pos_evolution_tpu.ops.transition import reset_session
            set_backend(backend)
            reset_session()
            try:
                batch = genesis.copy()
                seen = []
                apply_block_batch(
                    batch, blocks,
                    on_applied=lambda sb, st: seen.append(
                        int(sb.message.slot)))
                assert seen == [int(sb.message.slot) for sb in blocks]
                assert hash_tree_root(batch) == hash_tree_root(seq), backend
            finally:
                set_backend("numpy")

    def test_on_block_batch_equals_sequential_on_block(self):
        from pos_evolution_tpu.specs import forkchoice as fc
        genesis, blocks = _build_chain(32, cfg().slots_per_epoch + 2)
        spe, sps = cfg().slots_per_epoch, cfg().seconds_per_slot

        def fresh_store():
            state, anchor = make_genesis(32)
            store = fc.get_forkchoice_store(state, anchor)
            fc.on_tick(store, store.genesis_time
                       + (len(blocks) + 1) * sps)
            return store

        seq, bat = fresh_store(), fresh_store()
        for sb in blocks:
            fc.on_block(seq, sb)
        fc.on_block_batch(bat, list(blocks))
        assert set(seq.blocks) == set(bat.blocks)
        assert seq.justified_checkpoint.as_key() == \
            bat.justified_checkpoint.as_key()
        assert seq.finalized_checkpoint.as_key() == \
            bat.finalized_checkpoint.as_key()
        for root in seq.blocks:
            assert hash_tree_root(seq.block_states[root]) == \
                hash_tree_root(bat.block_states[root]), \
                f"state divergence at {root.hex()[:12]}"

    def test_on_block_batch_commits_prefix_on_mid_run_failure(self):
        from pos_evolution_tpu.specs import forkchoice as fc
        genesis, blocks = _build_chain(32, 6)
        state, anchor = make_genesis(32)
        store = fc.get_forkchoice_store(state, anchor)
        fc.on_tick(store, store.genesis_time + 10 * cfg().seconds_per_slot)
        bad = blocks[3].message.copy()
        bad.state_root = b"\x00" * 32  # corrupt the batch TAIL: mutating a
        # mid-run block changes its root and the suffix no longer
        # parent-links, which the batch pre-pass rejects before ANY
        # commit — also worth pinning:
        bad_signed = SignedBeaconBlock(message=bad,
                                       signature=blocks[3].signature)
        with pytest.raises(AssertionError):
            fc.on_block_batch(store, blocks[:3] + [bad_signed] + blocks[4:])
        assert all(cached_root(sb.message) not in store.blocks
                   for sb in blocks[:3]), "linkage reject must commit nothing"
        # intact prefix + corrupt tail: the transition fails MID-RUN and
        # the committed prefix stays, exactly like the sequential loop
        with pytest.raises(AssertionError):
            fc.on_block_batch(store, blocks[:3] + [bad_signed])
        committed = {cached_root(sb.message) for sb in blocks[:3]}
        assert committed <= set(store.blocks)
        for root in committed:
            assert root in store.block_states
        assert cached_root(bad) not in store.blocks

    def test_prefix_commit_is_not_an_invariant_violation(self):
        """The debug StoreInvariantChecker must not report the batch's
        documented prefix-commit as a torn write — while still flagging a
        handler WITHOUT the contract marker that mutates on failure."""
        from pos_evolution_tpu.specs import forkchoice as fc
        from pos_evolution_tpu.utils.metrics import StoreInvariantChecker
        genesis, blocks = _build_chain(32, 6)
        state, anchor = make_genesis(32)
        store = fc.get_forkchoice_store(state, anchor)
        fc.on_tick(store, store.genesis_time + 10 * cfg().seconds_per_slot)
        bad = blocks[3].message.copy()
        bad.state_root = b"\x00" * 32
        bad_signed = SignedBeaconBlock(message=bad,
                                       signature=blocks[3].signature)
        checker = StoreInvariantChecker(store)
        with pytest.raises(AssertionError):
            checker.call(fc.on_block_batch, blocks[:3] + [bad_signed])
        assert checker.violations == []  # prefix commit is the contract
        assert cached_root(blocks[0].message) in store.blocks

        def torn(store_, _arg):
            del store_.blocks[cached_root(blocks[0].message)]
            raise AssertionError("fail after mutating")

        with pytest.raises(AssertionError):
            checker.call(torn, None)
        assert len(checker.violations) == 1  # unmarked handlers still flag
