"""SSZ unit tests: batched sha256, merkleization, containers, proofs.

Cross-checked against independent hashlib-based computations (the golden
-vector strategy of SURVEY.md §4.5).
"""

import hashlib

import numpy as np
import pytest

from pos_evolution_tpu import ssz
from pos_evolution_tpu.ssz import (
    Bitlist, Bitvector, Bytes32, Container, List, Vector,
    boolean, deserialize, hash_tree_root, serialize, uint8, uint64,
)
from pos_evolution_tpu.ssz.hash import sha256_batch
from pos_evolution_tpu.ssz.merkle import (
    ZERO_HASHES, is_valid_merkle_branch, merkle_tree_branch, merkleize_chunks,
)


def h(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


class TestSha256Batch:
    def test_matches_hashlib_various_lengths(self):
        rng = np.random.default_rng(0)
        for length in [0, 1, 31, 32, 37, 55, 56, 63, 64, 65, 100, 128, 200]:
            msgs = rng.integers(0, 256, size=(5, length), dtype=np.uint8)
            got = sha256_batch(msgs)
            for i in range(5):
                assert got[i].tobytes() == h(msgs[i].tobytes()), f"len={length}"

    def test_large_batch(self):
        msgs = np.arange(64 * 1000, dtype=np.uint64).astype(np.uint8).reshape(1000, 64)
        got = sha256_batch(msgs)
        assert got[123].tobytes() == h(msgs[123].tobytes())

    def test_empty_batch(self):
        assert sha256_batch(np.empty((0, 32), dtype=np.uint8)).shape == (0, 32)


class TestMerkleize:
    def test_zero_hashes(self):
        assert ZERO_HASHES[0].tobytes() == b"\x00" * 32
        assert ZERO_HASHES[1].tobytes() == h(b"\x00" * 64)

    def test_single_chunk(self):
        c = np.frombuffer(b"\x01" * 32, dtype=np.uint8).reshape(1, 32)
        assert merkleize_chunks(c) == b"\x01" * 32

    def test_two_chunks(self):
        a, b = b"\xaa" * 32, b"\xbb" * 32
        chunks = np.frombuffer(a + b, dtype=np.uint8).reshape(2, 32)
        assert merkleize_chunks(chunks) == h(a + b)

    def test_three_chunks_pads_to_four(self):
        a, b, c = b"\x01" * 32, b"\x02" * 32, b"\x03" * 32
        chunks = np.frombuffer(a + b + c, dtype=np.uint8).reshape(3, 32)
        expect = h(h(a + b) + h(c + b"\x00" * 32))
        assert merkleize_chunks(chunks) == expect

    def test_limit_padding(self):
        a = b"\x05" * 32
        chunks = np.frombuffer(a, dtype=np.uint8).reshape(1, 32)
        # depth-2 tree: root = H(H(a || 0), zero_hashes[1])
        expect = h(h(a + b"\x00" * 32) + ZERO_HASHES[1].tobytes())
        assert merkleize_chunks(chunks, limit=4) == expect

    def test_empty_with_limit(self):
        empty = np.empty((0, 32), dtype=np.uint8)
        assert merkleize_chunks(empty, limit=8) == ZERO_HASHES[3].tobytes()


class TestBasicTypes:
    def test_uint64_htr(self):
        assert hash_tree_root(5, uint64) == (5).to_bytes(8, "little") + b"\x00" * 24

    def test_uint64_roundtrip(self):
        assert deserialize(serialize(12345, uint64), uint64) == 12345

    def test_boolean(self):
        assert hash_tree_root(True, boolean) == b"\x01" + b"\x00" * 31
        assert serialize(False, boolean) == b"\x00"

    def test_bytes32(self):
        v = bytes(range(32))
        assert hash_tree_root(v, Bytes32) == v
        assert deserialize(serialize(v, Bytes32), Bytes32) == v


class TestCollections:
    def test_vector_uint64_htr(self):
        vec = Vector(uint64, 4)
        vals = [1, 2, 3, 4]
        packed = b"".join(int(x).to_bytes(8, "little") for x in vals)
        assert hash_tree_root(vals, vec) == packed.ljust(32, b"\x00")

    def test_vector_uint64_two_chunks(self):
        vec = Vector(uint64, 8)
        vals = list(range(8))
        packed = b"".join(int(x).to_bytes(8, "little") for x in vals)
        assert hash_tree_root(vals, vec) == h(packed[:32] + packed[32:])

    def test_list_uint64_htr_mixes_length(self):
        lst = List(uint64, 8)
        vals = np.array([7, 9], dtype=np.uint64)
        packed = (int(7).to_bytes(8, "little") + int(9).to_bytes(8, "little")).ljust(32, b"\x00")
        # limit 8 uint64s = 2 chunks -> depth 1
        inner = h(packed + b"\x00" * 32)
        expect = h(inner + (2).to_bytes(32, "little"))
        assert hash_tree_root(vals, lst) == expect

    def test_list_roundtrip_numpy(self):
        lst = List(uint64, 100)
        vals = np.arange(10, dtype=np.uint64)
        out = deserialize(serialize(vals, lst), lst)
        assert np.array_equal(out, vals)

    def test_bitvector(self):
        bv = Bitvector(10)
        bits = np.array([1, 0, 1, 1, 0, 0, 0, 0, 1, 1], dtype=bool)
        assert serialize(bits, bv) == bytes([0b00001101, 0b00000011])
        assert np.array_equal(deserialize(serialize(bits, bv), bv), bits)

    def test_bitlist_roundtrip_and_htr(self):
        bl = Bitlist(16)
        bits = np.array([1, 1, 0, 1], dtype=bool)
        assert np.array_equal(deserialize(serialize(bits, bl), bl), bits)
        packed = bytes([0b00001011]).ljust(32, b"\x00")
        expect = h(packed + (4).to_bytes(32, "little"))
        assert hash_tree_root(bits, bl) == expect

    def test_bitlist_empty(self):
        bl = Bitlist(16)
        assert serialize(np.zeros(0, dtype=bool), bl) == b"\x01"
        assert deserialize(b"\x01", bl).size == 0


class Point(Container):
    x: uint64
    y: uint64


class Nested(Container):
    p: Point
    tag: Bytes32
    items: List(uint64, 4)


class TestContainers:
    def test_point_htr(self):
        p = Point(x=3, y=4)
        cx = (3).to_bytes(8, "little").ljust(32, b"\x00")
        cy = (4).to_bytes(8, "little").ljust(32, b"\x00")
        assert p.hash_tree_root() == h(cx + cy)

    def test_defaults(self):
        p = Point()
        assert p.x == 0 and p.y == 0

    def test_equality_and_copy(self):
        p = Nested(p=Point(x=1, y=2), tag=b"\x07" * 32, items=np.array([5], dtype=np.uint64))
        q = p.copy()
        assert p == q
        q.p.x = 9
        assert p.p.x == 1  # deep copy

    def test_serialize_roundtrip_variable(self):
        n = Nested(p=Point(x=1, y=2), tag=b"\x07" * 32,
                   items=np.array([5, 6, 7], dtype=np.uint64))
        out = deserialize(serialize(n), Nested)
        assert out == n

    def test_fixed_container_roundtrip(self):
        p = Point(x=123, y=2**60)
        assert deserialize(serialize(p), Point) == p


class TestMerkleBranch:
    @pytest.mark.parametrize("index", [0, 1, 5, 7])
    def test_branch_verifies(self, index):
        rng = np.random.default_rng(1)
        leaves = rng.integers(0, 256, size=(8, 32), dtype=np.uint8)
        depth = 3
        root = merkleize_chunks(leaves, limit=8)
        branch = merkle_tree_branch(leaves, index, depth)
        assert is_valid_merkle_branch(leaves[index].tobytes(), branch, depth, index, root)
        # wrong leaf fails
        assert not is_valid_merkle_branch(b"\x42" * 32, branch, depth, index, root)

    def test_branch_beyond_leaf_count(self):
        leaves = np.ones((3, 32), dtype=np.uint8)
        root = merkleize_chunks(leaves, limit=8)
        branch = merkle_tree_branch(leaves, 2, 3)
        assert is_valid_merkle_branch(leaves[2].tobytes(), branch, 3, 2, root)

    def test_branch_roundtrip_property(self):
        """merkle_tree_branch ↔ is_valid_merkle_branch round-trip for random
        leaf counts and indices, including padding-to-power-of-two (odd leaf
        counts and depths deeper than the natural tree)."""
        from pos_evolution_tpu.ssz.merkle import next_pow_of_two
        rng = np.random.default_rng(7)
        for _ in range(40):
            n = int(rng.integers(1, 50))
            natural_depth = (next_pow_of_two(n) - 1).bit_length()
            depth = natural_depth + int(rng.integers(0, 3))  # virtual padding
            index = int(rng.integers(0, n))
            leaves = rng.integers(0, 256, size=(n, 32), dtype=np.uint8)
            root = merkleize_chunks(leaves, limit=1 << depth)
            branch = merkle_tree_branch(leaves, index, depth)
            assert len(branch) == depth
            assert is_valid_merkle_branch(
                leaves[index].tobytes(), branch, depth, index, root), \
                f"n={n} depth={depth} index={index}"
            # wrong leaf and wrong index must fail (a sibling index only
            # collides when its leaf happens to be identical — random
            # leaves make that negligible)
            assert not is_valid_merkle_branch(
                b"\x99" * 32, branch, depth, index, root)
            wrong = (index + 1) % n
            if wrong != index:
                assert not is_valid_merkle_branch(
                    leaves[wrong].tobytes(), branch, depth, wrong, root)

    def test_branch_at_padding_boundary(self):
        """The pad-to-power-of-two edge exactly: the last real leaf of an
        odd count proves against zero-hash siblings."""
        for n in (3, 5, 7, 9, 33):
            leaves = np.arange(n * 32, dtype=np.uint64).astype(np.uint8).reshape(n, 32)
            from pos_evolution_tpu.ssz.merkle import next_pow_of_two
            depth = (next_pow_of_two(n) - 1).bit_length()
            root = merkleize_chunks(leaves, limit=1 << depth)
            branch = merkle_tree_branch(leaves, n - 1, depth)
            assert is_valid_merkle_branch(
                leaves[n - 1].tobytes(), branch, depth, n - 1, root)
