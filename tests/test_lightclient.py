"""Sync-committee & light-client subsystem tests (lightclient/ +
ops/sync_verify.py + driver integration).

Covers the acceptance contract of the subsystem: a light client
bootstrapped from a weak-subjectivity checkpoint follows a 64+-slot faulted
simulation to the same finalized head as a full node, and the
``ops/sync_verify`` device path is bit-identical to the NumPy host path on
every output array.
"""

import numpy as np
import pytest

from pos_evolution_tpu.config import minimal_config
from pos_evolution_tpu.ssz import hash_tree_root, is_valid_merkle_branch, merkleize_chunks

pytestmark = pytest.mark.usefixtures("minimal_cfg")


def _branch_list(branch) -> list:
    return [branch[i].tobytes() for i in range(branch.shape[0])]


# ---------------------------------------------------------------------------
# Proof layer: branches into the BeaconState field tree
# ---------------------------------------------------------------------------

class TestProofs:
    def test_state_field_roots_merkleize_to_state_root(self):
        from pos_evolution_tpu.lightclient import state_field_roots
        from pos_evolution_tpu.specs.genesis import make_genesis_state
        state = make_genesis_state(16)
        chunks = state_field_roots(state)
        assert merkleize_chunks(chunks) == hash_tree_root(state)

    def test_sync_committee_branches_verify(self):
        from pos_evolution_tpu.lightclient import (
            CURRENT_SYNC_COMMITTEE_INDEX,
            NEXT_SYNC_COMMITTEE_INDEX,
            STATE_TREE_DEPTH,
            current_sync_committee_branch,
            next_sync_committee_branch,
        )
        from pos_evolution_tpu.specs.genesis import make_genesis_state
        state = make_genesis_state(16)
        # genesis seeds both committees identically; distinguish them so the
        # wrong-index negative check below is meaningful
        state.next_sync_committee.aggregate_pubkey = b"\x11" * 48
        root = hash_tree_root(state)
        cur = current_sync_committee_branch(state)
        assert is_valid_merkle_branch(
            hash_tree_root(state.current_sync_committee), _branch_list(cur),
            STATE_TREE_DEPTH, CURRENT_SYNC_COMMITTEE_INDEX, root)
        nxt = next_sync_committee_branch(state)
        assert is_valid_merkle_branch(
            hash_tree_root(state.next_sync_committee), _branch_list(nxt),
            STATE_TREE_DEPTH, NEXT_SYNC_COMMITTEE_INDEX, root)
        # a branch for the wrong field index must not verify
        assert not is_valid_merkle_branch(
            hash_tree_root(state.current_sync_committee), _branch_list(cur),
            STATE_TREE_DEPTH, NEXT_SYNC_COMMITTEE_INDEX, root)

    def test_finality_branch_verifies(self):
        from pos_evolution_tpu.lightclient import (
            FINALIZED_ROOT_DEPTH,
            FINALIZED_ROOT_INDEX,
            finality_branch,
        )
        from pos_evolution_tpu.specs.genesis import make_genesis_state
        state = make_genesis_state(16)
        state.finalized_checkpoint.epoch = 3
        state.finalized_checkpoint.root = b"\x42" * 32
        branch = finality_branch(state)
        assert is_valid_merkle_branch(
            b"\x42" * 32, _branch_list(branch),
            FINALIZED_ROOT_DEPTH, FINALIZED_ROOT_INDEX, hash_tree_root(state))
        # leaf is the checkpoint ROOT, not its epoch
        assert not is_valid_merkle_branch(
            (3).to_bytes(32, "little"), _branch_list(branch),
            FINALIZED_ROOT_DEPTH, FINALIZED_ROOT_INDEX, hash_tree_root(state))

    def test_header_for_block_matches_block_root(self):
        from pos_evolution_tpu.lightclient import header_for_block
        from pos_evolution_tpu.specs.genesis import make_genesis
        from pos_evolution_tpu.specs.transition import state_transition
        from pos_evolution_tpu.specs.validator import build_block
        state, anchor = make_genesis(16)
        assert hash_tree_root(header_for_block(anchor)) == hash_tree_root(anchor)
        sb = build_block(state, 1)
        state_transition(state, sb, True)
        assert hash_tree_root(header_for_block(sb.message)) == \
            hash_tree_root(sb.message)


# ---------------------------------------------------------------------------
# Sync-aggregate duty (specs/validator.make_sync_aggregate)
# ---------------------------------------------------------------------------

class TestSyncAggregateDuty:
    def test_full_participation_block_passes_transition(self):
        from pos_evolution_tpu.specs.genesis import make_genesis
        from pos_evolution_tpu.specs.transition import state_transition
        from pos_evolution_tpu.specs.validator import (
            advance_state_to_slot,
            build_block,
            make_sync_aggregate,
        )
        state, anchor = make_genesis(16)
        head = hash_tree_root(anchor)
        agg = make_sync_aggregate(advance_state_to_slot(state, 1), head)
        assert np.asarray(agg.sync_committee_bits, dtype=bool).any()
        sb = build_block(state, 1, sync_aggregate=agg)
        state_transition(state, sb, True)  # signature verified in-transition
        assert np.array_equal(
            np.asarray(sb.message.body.sync_aggregate.sync_committee_bits),
            np.asarray(agg.sync_committee_bits))

    def test_participant_subset_limits_bits(self):
        from pos_evolution_tpu.specs.genesis import make_genesis
        from pos_evolution_tpu.specs.validator import (
            advance_state_to_slot,
            make_sync_aggregate,
        )
        state, anchor = make_genesis(16)
        head = hash_tree_root(anchor)
        advanced = advance_state_to_slot(state, 1)
        full = make_sync_aggregate(advanced, head)
        half = make_sync_aggregate(advanced, head, participants=range(8))
        n_full = int(np.asarray(full.sync_committee_bits, dtype=bool).sum())
        n_half = int(np.asarray(half.sync_committee_bits, dtype=bool).sum())
        assert 0 < n_half < n_full

    def test_empty_participants_gives_empty_aggregate(self):
        from pos_evolution_tpu.specs.genesis import make_genesis
        from pos_evolution_tpu.specs.validator import (
            advance_state_to_slot,
            make_sync_aggregate,
        )
        state, anchor = make_genesis(16)
        agg = make_sync_aggregate(advance_state_to_slot(state, 1),
                                  hash_tree_root(anchor), participants=())
        assert not np.asarray(agg.sync_committee_bits, dtype=bool).any()


# ---------------------------------------------------------------------------
# Bootstrap + store state machine
# ---------------------------------------------------------------------------

class TestBootstrapAndStore:
    def _sim(self, epochs=0):
        from pos_evolution_tpu.sim import Simulation
        sim = Simulation(64)
        if epochs:
            sim.run_epochs(epochs)
        return sim

    def test_bootstrap_initializes_store(self):
        from pos_evolution_tpu.lightclient import (
            bootstrap_from_store,
            initialize_light_client_store,
        )
        sim = self._sim()
        trusted_root, bootstrap = bootstrap_from_store(sim.store(0))
        state = sim.genesis_state
        store = initialize_light_client_store(
            trusted_root, bootstrap, bytes(state.fork.current_version),
            bytes(state.genesis_validators_root))
        assert hash_tree_root(store.finalized_header) == trusted_root
        assert store.next_sync_committee is None

    def test_bootstrap_rejects_tampered_committee_proof(self):
        from pos_evolution_tpu.lightclient import (
            bootstrap_from_store,
            initialize_light_client_store,
        )
        sim = self._sim()
        trusted_root, bootstrap = bootstrap_from_store(sim.store(0))
        bootstrap.current_sync_committee_branch[0, 0] ^= 1
        state = sim.genesis_state
        with pytest.raises(AssertionError):
            initialize_light_client_store(
                trusted_root, bootstrap, bytes(state.fork.current_version),
                bytes(state.genesis_validators_root))

    def test_update_validation_rejects_tampering(self):
        from pos_evolution_tpu.lightclient import build_update, validate_light_client_update
        sim = self._sim()
        node = sim.attach_light_client()
        sim.run_epochs(4)
        g = sim.groups[0]
        update = build_update(g.store, sim._get_head(g), archive=sim.block_archive)
        assert update is not None
        current_slot = sim.slot
        validate_light_client_update(node.store, update, current_slot)
        # future update
        with pytest.raises(AssertionError):
            validate_light_client_update(node.store, update,
                                         int(update.signature_slot) - 1)
        # corrupted aggregate signature
        bad = update.copy()
        sig = bytearray(bytes(bad.sync_aggregate.sync_committee_signature))
        sig[0] ^= 0xFF
        bad.sync_aggregate.sync_committee_signature = bytes(sig)
        with pytest.raises(AssertionError):
            validate_light_client_update(node.store, bad, current_slot)
        # corrupted finality branch
        bad2 = update.copy()
        bad2.finality_branch[1, 0] ^= 1
        with pytest.raises(AssertionError):
            validate_light_client_update(node.store, bad2, current_slot)

    def test_force_update_after_timeout(self):
        """Liveness escape hatch: with every finality proof stripped, the
        best-seen valid update force-applies after one sync-committee
        period without finality."""
        from pos_evolution_tpu.lightclient import (
            LightClientUpdate,
            bootstrap_from_store,
            build_update,
            initialize_light_client_store,
            is_finality_update,
            process_light_client_store_force_update,
            process_light_client_update,
            update_timeout_slots,
        )
        sim = self._sim(epochs=4)
        genesis = sim.genesis_state
        trusted_root, bootstrap = bootstrap_from_store(sim.store(0))
        store = initialize_light_client_store(
            trusted_root, bootstrap, bytes(genesis.fork.current_version),
            bytes(genesis.genesis_validators_root))
        base = int(store.finalized_header.slot)
        g = sim.groups[0]
        update = build_update(g.store, sim._get_head(g), archive=sim.block_archive)
        stripped = LightClientUpdate(
            attested_header=update.attested_header,
            next_sync_committee=update.next_sync_committee,
            next_sync_committee_branch=update.next_sync_committee_branch,
            sync_aggregate=update.sync_aggregate,
            signature_slot=int(update.signature_slot),
        )
        assert not is_finality_update(stripped)
        process_light_client_update(store, stripped, current_slot=sim.slot)
        assert store.best_valid_update is not None
        assert int(store.finalized_header.slot) == base  # no finality proof
        # before the timeout nothing happens; after it, force-apply
        process_light_client_store_force_update(store, base + update_timeout_slots())
        assert int(store.finalized_header.slot) == base
        process_light_client_store_force_update(
            store, base + update_timeout_slots() + 1)
        assert int(store.finalized_header.slot) == \
            int(stripped.attested_header.beacon.slot)
        assert store.best_valid_update is None


# ---------------------------------------------------------------------------
# ops/sync_verify: device path bit-identical to the host path
# ---------------------------------------------------------------------------

class TestOpsParity:
    def _collect_updates(self, slots=16):
        from pos_evolution_tpu.lightclient import build_update
        from pos_evolution_tpu.sim import Simulation
        sim = Simulation(64)
        updates = []
        for _ in range(slots):
            sim.run_slot()
            g = sim.groups[0]
            u = build_update(g.store, sim._get_head(g), archive=sim.block_archive)
            if u is not None:
                updates.append(u)
        return sim, updates

    def test_device_and_host_bit_identical(self):
        from pos_evolution_tpu.lightclient import updates_to_batch
        from pos_evolution_tpu.ops.sync_verify import (
            verify_batch_device,
            verify_batch_host,
        )
        sim, updates = self._collect_updates()
        assert len(updates) >= 8
        genesis = sim.genesis_state
        committees = [genesis.current_sync_committee] * len(updates)
        batch = updates_to_batch(
            updates, committees, bytes(genesis.fork.current_version),
            bytes(genesis.genesis_validators_root))
        # corrupt one signature and one branch so False verdicts are
        # exercised on both paths too
        batch.signatures[1, 0] ^= 0xFF
        if batch.fin_present.any():
            i = int(np.nonzero(batch.fin_present)[0][0])
            batch.fin_branch[i, 0, 0] ^= 1
        host = verify_batch_host(batch)
        dev = verify_batch_device(batch)
        assert set(host) == set(dev)
        for key in host:
            assert host[key].dtype == dev[key].dtype, key
            assert np.array_equal(host[key], dev[key]), key
        # sanity on the verdicts themselves
        assert not host["sig_ok"][1] and host["sig_ok"][0]
        assert (host["participation"][host["sig_ok"]] > 0).all()

    def test_backend_dispatch_routes_to_device(self):
        from pos_evolution_tpu.backend import set_backend
        from pos_evolution_tpu.lightclient import updates_to_batch
        from pos_evolution_tpu.ops.sync_verify import verify_sync_update_batch
        sim, updates = self._collect_updates(slots=6)
        genesis = sim.genesis_state
        committees = [genesis.current_sync_committee] * len(updates)
        batch = updates_to_batch(
            updates, committees, bytes(genesis.fork.current_version),
            bytes(genesis.genesis_validators_root))
        try:
            set_backend("numpy")
            host = verify_sync_update_batch(batch)
            set_backend("jax")
            dev = verify_sync_update_batch(batch)
        finally:
            set_backend("numpy")
        for key in host:
            assert np.array_equal(host[key], dev[key]), key
        assert host["sig_ok"].all()

    def test_weighted_participation(self):
        """Stake weighting: per-lane weights flow into the weight output."""
        from pos_evolution_tpu.lightclient import updates_to_batch
        from pos_evolution_tpu.ops.sync_verify import (
            verify_batch_device,
            verify_batch_host,
        )
        sim, updates = self._collect_updates(slots=4)
        genesis = sim.genesis_state
        committees = [genesis.current_sync_committee] * len(updates)
        lanes = len(genesis.current_sync_committee.pubkeys)
        weights = np.arange(1, lanes + 1, dtype=np.int64)[None, :].repeat(
            len(updates), axis=0)
        batch = updates_to_batch(
            updates, committees, bytes(genesis.fork.current_version),
            bytes(genesis.genesis_validators_root), weights=weights)
        host = verify_batch_host(batch)
        dev = verify_batch_device(batch)
        assert np.array_equal(host["weight"], dev["weight"])
        full = int(np.arange(1, lanes + 1, dtype=np.int64).sum())
        assert (host["weight"] <= full).all() and (host["weight"] > 0).all()


# ---------------------------------------------------------------------------
# Acceptance: checkpoint-synced light client follows a faulted simulation
# ---------------------------------------------------------------------------

class TestAcceptanceE2E:
    def test_light_client_follows_faulted_chain_to_full_node_finality(self):
        """64+-slot faulted run: drops before GST, a sync-committee period
        boundary crossing (minimal period = 64 slots), then exact
        convergence with the full node's finalized head."""
        from pos_evolution_tpu.sim import Simulation, faulty_schedule, lossy_plan
        c = minimal_config()
        gst = 7 * c.slots_per_epoch * c.seconds_per_slot
        plan = lossy_plan(seed=7, drop_p=0.10, gst=gst)
        sim = Simulation(64, schedule=faulty_schedule(64, plan))
        node = sim.attach_light_client()
        sim.run_until_slot(9 * c.slots_per_epoch)  # 72 slots > 64
        sim.flush_light_clients()

        full = sim.store(0)
        assert sim.finalized_epoch() >= 5, "full node must finalize post-GST"
        # same finalized head, exactly
        assert node.finalized_root() == bytes(full.finalized_checkpoint.root)
        assert node.finalized_slot == \
            int(full.blocks[bytes(full.finalized_checkpoint.root)].slot)
        # the run crossed a sync-committee period; the client kept verifying
        assert node.updates_applied > 0 and node.updates_rejected == 0
        # lag metrics recorded every slot and converged
        assert len(node.records) >= 72
        assert node.records[-1]["head_lag"] == 0
        assert node.records[-1]["finality_lag"] == 0
        assert all(r["finality_lag"] >= 0 for r in node.records)

    def test_force_update_substitutes_attested_for_stale_finality_proof(self):
        """During a finality stall every served update re-proves the OLD
        checkpoint; the force-update path must fall back to the attested
        header or the client wedges behind the chain forever."""
        from pos_evolution_tpu.lightclient import (
            bootstrap_from_store,
            build_update,
            initialize_light_client_store,
            is_finality_update,
            process_light_client_store_force_update,
            process_light_client_update,
            update_timeout_slots,
        )
        from pos_evolution_tpu.sim import Simulation
        sim = Simulation(64)
        sim.run_epochs(6)
        genesis = sim.genesis_state
        trusted_root, bootstrap = bootstrap_from_store(sim.store(0))
        store = initialize_light_client_store(
            trusted_root, bootstrap, bytes(genesis.fork.current_version),
            bytes(genesis.genesis_validators_root))
        base = int(store.finalized_header.slot)
        g = sim.groups[0]
        update = build_update(g.store, sim._get_head(g), archive=sim.block_archive)
        # the head's attested state finalizes one step behind the store's
        # own finalized checkpoint: the update's proof is genuinely stale
        assert is_finality_update(update)
        assert int(update.finalized_header.beacon.slot) < base
        # first process legitimately applies to LEARN the next committee
        # (and clears the best-update slot); the second models the stall:
        # no finality progress, so the update is only retained as best
        process_light_client_update(store, update, current_slot=sim.slot)
        assert store.next_sync_committee is not None
        process_light_client_update(store, update, current_slot=sim.slot)
        assert int(store.finalized_header.slot) == base  # no progress: kept
        assert store.best_valid_update is not None
        process_light_client_store_force_update(
            store, base + update_timeout_slots() + 1)
        assert int(store.finalized_header.slot) == \
            int(update.attested_header.beacon.slot)

    def test_client_clock_ticks_while_server_group_crashed(self):
        """A crashed serving group stops serving, but the client is an
        independent process: its per-slot housekeeping (force-update
        timeout, lag records) must keep running through the outage."""
        from pos_evolution_tpu.sim import (
            CrashWindow,
            Simulation,
            chaos_plan,
            faulty_schedule,
        )
        plan = chaos_plan(seed=1, drop_p=0.0, duplicate_p=0.0, reorder_p=0.0,
                          crashes=(CrashWindow(group=0, crash_slot=10,
                                               rejoin_slot=14),))
        sim = Simulation(64, schedule=faulty_schedule(64, plan, n_groups=2))
        node = sim.attach_light_client(group=0)
        sim.run_until_slot(20)
        # one lag record per slot, no gaps across the outage
        assert [r["slot"] for r in node.records] == list(range(21))

    def test_dropped_updates_are_survivable(self):
        """A client whose update feed is heavily lossy pre-GST still
        advances (the updates that do arrive carry finality proofs)."""
        from pos_evolution_tpu.sim import Simulation, faulty_schedule, lossy_plan
        c = minimal_config()
        gst = 3 * c.slots_per_epoch * c.seconds_per_slot
        plan = lossy_plan(seed=3, drop_p=0.5, gst=gst)
        sim = Simulation(64, schedule=faulty_schedule(64, plan))
        node = sim.attach_light_client()
        sim.run_epochs(6)
        assert node.updates_applied < sim.slot  # some updates were dropped
        assert node.finalized_slot > 0  # but finality still advanced
