"""Fleet observability tests (ISSUE 18, DESIGN.md "Fleet observability").

Covers the three legs end to end at unit scale (the mp harness test in
``test_serve_mp.py`` covers the full plane):

- the cross-process metrics pipeline: snapshot write/load/discover,
  torn-file tolerance, the ``FleetAggregator`` merge (worker labels,
  respawn folding, meta freshness), and the ``Histogram.observe_n``
  vs concurrent ``snapshot()`` torn-row race;
- the ``metrics`` RPC served from memory while the circuit breaker is
  OPEN on a fake clock — a backing outage must not blind the fleet;
- end-to-end tracing: seeded deterministic sampling / trace ids, the
  ``SpanBuffer`` append-only flush contract, the client's trace-first
  frame ordering (the byte-scan fast-path contract), and
  ``scripts/trace_merge.py``'s pid lanes + flow arrows;
- per-process event logs: path derivation, discovery, wall-ordered
  merge with lineage;
- the dense phase profiler: slot-wall partition, sampling cadence,
  async charging, and the ``NULL_TIMER`` twin's surface;
- ``scripts/perf_gate.py``: explicit ``--kind`` matching nothing is a
  loud exit 2, ``--list-kinds`` inventories the history;
- the balancer's fleet-metrics health bias.
"""

from __future__ import annotations

import json
import os
import socket
import struct
import sys
import threading
import time

import pytest

from pos_evolution_tpu.config import minimal_config, use_config

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))), "scripts"))


# --- snapshot files -----------------------------------------------------------

def _registry_with(requests):
    from pos_evolution_tpu.telemetry.registry import MetricsRegistry
    reg = MetricsRegistry()
    c = reg.counter("serve_requests_total", "requests by status")
    for status, n in requests.items():
        c.inc(n, method="head", status=status)
    reg.histogram("serve_latency_s", "latency").observe_n(0.002, 5,
                                                          tier="0")
    return reg


class TestSnapshotFiles:
    def test_write_load_discover_roundtrip(self, tmp_path):
        from pos_evolution_tpu.telemetry import fleet
        reg = _registry_with({"ok": 7, "error": 2})
        path = fleet.snapshot_path(tmp_path, worker=3, pid=123)
        assert os.path.basename(path) == "worker3.pid123.metrics.json"
        fleet.write_snapshot(path, reg, worker=3, pid=123, front=1,
                             generation=4)
        blob = fleet.load_snapshot(path)
        assert blob["worker"] == 3 and blob["pid"] == 123
        assert blob["front"] == 1 and blob["generation"] == 4
        assert "serve_requests_total" in blob["registry"]["metrics"]
        # discovery sorts by (worker, pid) regardless of listdir order
        fleet.write_snapshot(fleet.snapshot_path(tmp_path, 0, 999),
                             reg, worker=0, pid=999)
        fleet.write_snapshot(fleet.snapshot_path(tmp_path, 3, 45),
                             reg, worker=3, pid=45)
        names = [os.path.basename(p)
                 for p in fleet.discover_snapshots(tmp_path)]
        assert names == ["worker0.pid999.metrics.json",
                         "worker3.pid45.metrics.json",
                         "worker3.pid123.metrics.json"]

    def test_torn_and_foreign_files_are_skipped(self, tmp_path):
        from pos_evolution_tpu.telemetry import fleet
        torn = tmp_path / "worker0.pid1.metrics.json"
        torn.write_text('{"v": 1, "registry": {"met')  # killed mid-dump
        assert fleet.load_snapshot(torn) is None
        assert fleet.load_snapshot(tmp_path / "absent.json") is None
        (tmp_path / "heartbeat.json").write_text("{}")  # non-snapshot
        assert fleet.discover_snapshots(tmp_path) == [
            str(torn)]  # name matches; load is what rejects it
        agg = fleet.FleetAggregator.from_dir(tmp_path)
        assert agg.snapshots_merged == 0
        assert agg.snapshots_skipped == 1

    def test_wrong_snapshot_version_is_skipped(self, tmp_path):
        from pos_evolution_tpu.telemetry import fleet
        p = tmp_path / "worker0.pid2.metrics.json"
        p.write_text(json.dumps({"v": 999, "worker": 0, "pid": 2,
                                 "registry": {"metrics": {}}}))
        assert fleet.load_snapshot(p) is None


class TestFleetAggregator:
    def _snap(self, tmp_path, worker, pid, requests, **meta):
        from pos_evolution_tpu.telemetry import fleet
        fleet.write_snapshot(
            fleet.snapshot_path(tmp_path, worker, pid),
            _registry_with(requests), worker=worker, pid=pid, **meta)

    def test_worker_labels_totals_and_status_split(self, tmp_path):
        from pos_evolution_tpu.telemetry import fleet
        self._snap(tmp_path, 0, 11, {"ok": 90, "error": 10}, front=0)
        self._snap(tmp_path, 1, 12, {"ok": 50, "shed": 50}, front=1)
        agg = fleet.FleetAggregator.from_dir(tmp_path)
        assert agg.worker_totals("serve_requests_total") == {
            "0": 100, "1": 100}
        assert agg.fleet_total("serve_requests_total") == 200
        by = agg.worker_status_totals("serve_requests_total")
        assert by["0"] == {"ok": 90, "error": 10}
        assert by["1"] == {"ok": 50, "shed": 50}
        assert 'worker="0"' in agg.registry.to_prometheus()
        summ = agg.summary()
        assert summ["requests_by_worker"] == {"0": 100, "1": 100}
        assert summ["snapshots_merged"] == 2

    def test_respawned_incarnations_fold_into_one_worker(self, tmp_path):
        from pos_evolution_tpu.telemetry import fleet
        # the killed pid's last flush + the respawn's fresh counts ADD
        self._snap(tmp_path, 0, 100, {"ok": 40})
        self._snap(tmp_path, 0, 200, {"ok": 2})
        agg = fleet.FleetAggregator.from_dir(tmp_path)
        assert agg.worker_totals("serve_requests_total") == {"0": 42}

    def test_live_blob_does_not_blank_beat_meta(self, tmp_path):
        from pos_evolution_tpu.telemetry import fleet
        self._snap(tmp_path, 0, 11, {"ok": 5}, front=2, generation=7)
        agg = fleet.FleetAggregator.from_dir(tmp_path)
        # the front's own live-registry blob: newer wall, no meta
        agg.add({"v": 1, "worker": 0, "pid": 11, "front": None,
                 "generation": None, "wall": time.time() + 10,
                 "registry": _registry_with({"ok": 1}).snapshot()})
        meta = agg.workers["0"]
        assert meta["front"] == 2 and meta["generation"] == 7


class TestHistogramSnapshotRace:
    def test_observe_n_never_tears_a_snapshot_row(self):
        """N threads batch-observing one histogram value while another
        thread snapshots: every snapshot row must be internally
        consistent (the value always lands in ONE bucket, so any torn
        copy shows bucket_counts[i] != count)."""
        from pos_evolution_tpu.telemetry.registry import MetricsRegistry
        reg = MetricsRegistry()
        h = reg.histogram("h", buckets=(0.001, 1.0))
        stop = threading.Event()
        torn = []

        def snapshotter():
            while not stop.is_set():
                snap = reg.snapshot()
                for row in snap["metrics"]["h"]["series"]:
                    if row["bucket_counts"][1] != row["count"]:
                        torn.append(row)

        def hammer():
            for _ in range(3000):
                h.observe_n(0.5, 3, tier="0")

        snap_t = threading.Thread(target=snapshotter)
        snap_t.start()
        threads = [threading.Thread(target=hammer) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30.0)
        stop.set()
        snap_t.join(timeout=10.0)
        assert not torn, f"torn histogram rows observed: {torn[:3]}"
        assert h.value(tier="0")["count"] == 4 * 3000 * 3


# --- metrics RPC during a backing outage --------------------------------------

def _scrape(addr):
    from pos_evolution_tpu.serve.protocol import recv_frame, send_frame
    with socket.create_connection(addr, timeout=3.0) as s:
        s.settimeout(3.0)
        send_frame(s, {"id": 1, "method": "metrics", "params": {},
                       "deadline_ms": 2500.0, "tier": 0})
        return recv_frame(s)


class TestMetricsRpcDuringOutage:
    def test_metrics_served_from_memory_while_breaker_open(self, tmp_path):
        """The whole point of the admission-exempt scrape: a backing
        outage opens the breaker (fake clock pins it open), and the
        fleet must stay observable anyway."""
        with use_config(minimal_config()):
            from tests.test_serve import _synthetic_view

            from pos_evolution_tpu.serve import (
                ServeChaos,
                ServeClient,
                ServeFront,
                ServingState,
            )
            from pos_evolution_tpu.serve.admission import CircuitBreaker
            from pos_evolution_tpu.telemetry.registry import MetricsRegistry
            eng, root, view = _synthetic_view()
            state = ServingState()
            state.publish(view)
            clock = [100.0]
            chaos = ServeChaos(1)
            front = ServeFront(
                state, scheme=eng.scheme, registry=MetricsRegistry(),
                workers=1, chaos=chaos, metrics_dir=str(tmp_path),
                worker_id=0,
                breaker=CircuitBreaker(failure_threshold=2,
                                       cooldown_s=60.0,
                                       clock=lambda: clock[0]))
            addr = front.start()
            try:
                cli = ServeClient(addr, connections=1, hedge_ms=None,
                                  max_retries=0)
                chaos.fail_backing_for(3600.0)
                params = {"block_root": root.hex(), "samples": [[0, 1]]}
                for _ in range(front.breaker.failure_threshold):
                    assert cli.request("das_cells", params,
                                       deadline_s=0.5).status == "error"
                assert front.breaker.state == front.breaker.OPEN
                resp = _scrape(addr)
                assert resp["status"] == "ok"
                result = resp["result"]
                assert 'worker="0"' in result["prometheus"]
                assert "serve_requests_total" in result["prometheus"]
                assert result["fleet"]["requests_by_worker"]["0"] > 0
                # the fake clock never advanced: still open after serving
                assert front.breaker.state == front.breaker.OPEN
                cli.close()
            finally:
                front.stop()


# --- tracing ------------------------------------------------------------------

class TestTracingDeterminism:
    def test_sample_is_seeded_and_stateless(self):
        from pos_evolution_tpu.telemetry import tracing
        draws = [tracing.sample(7, i, 0.1) for i in range(10_000)]
        assert draws == [tracing.sample(7, i, 0.1)
                        for i in range(10_000)]
        frac = sum(draws) / len(draws)
        assert 0.05 < frac < 0.2
        assert not any(tracing.sample(7, i, 0.0) for i in range(100))
        assert all(tracing.sample(7, i, 1.0) for i in range(100))
        # a different seed samples a different subset
        assert draws != [tracing.sample(8, i, 0.1)
                        for i in range(10_000)]

    def test_trace_id_deterministic_and_distinct(self):
        from pos_evolution_tpu.telemetry import tracing
        ids = {tracing.trace_id(7, i) for i in range(1000)}
        assert len(ids) == 1000
        assert tracing.trace_id(7, 42) == tracing.trace_id(7, 42)
        assert all(len(t) == 16 for t in ids)

    def test_span_buffer_append_only_flush(self, tmp_path):
        from pos_evolution_tpu.telemetry.tracing import (
            SpanBuffer,
            span_filename,
        )
        buf = SpanBuffer(tmp_path, proc="loadgen", max_spans=3)
        buf.add("t1", "client", 100.0, 5.0, status="ok")
        assert buf.flush() == 1
        buf.add("t2", "client", 101.0, 6.0)
        buf.mark("t2", "hedge_sent")
        assert buf.flush() == 2      # only the NEW spans append
        assert buf.flush() == 0
        buf.add("t3", "overflow", 102.0, 1.0)  # 4th span: dropped
        assert buf.summary()["dropped"] == 1
        path = tmp_path / span_filename()
        lines = [json.loads(ln) for ln in
                 path.read_text().splitlines()]
        assert [s["name"] for s in lines] == ["client", "client",
                                              "hedge_sent"]
        assert lines[0]["proc"] == "loadgen"
        assert lines[0]["status"] == "ok"

    def test_record_span_is_noop_without_buffer_or_trace(self, tmp_path):
        from pos_evolution_tpu.telemetry import tracing
        old = tracing.get_buffer()
        try:
            tracing._BUFFER[0] = None
            tracing.record_span("t", "x", 0.0, 1.0)  # no buffer: no-op
            buf = tracing.install_buffer(tmp_path, proc="p")
            tracing.record_span(None, "x", 0.0, 1.0)  # unsampled: no-op
            assert buf.summary()["spans"] == 0
            tracing.record_span("t", "x", 0.0, 1.0)
            assert buf.summary()["spans"] == 1
        finally:
            tracing._BUFFER[0] = old


class TestClientTraceFrame:
    def test_traced_frame_puts_trace_member_first(self):
        """A traced frame must not match the servers' byte-scan fast
        path — the client pins the ``trace`` member in FRONT of the
        envelope (protocol.py's contract)."""
        from pos_evolution_tpu.serve.client import ServeClient
        from pos_evolution_tpu.serve.protocol import send_frame
        srv = socket.socket()
        srv.bind(("127.0.0.1", 0))
        srv.listen(1)
        raw = []

        def serve_one():
            conn, _ = srv.accept()
            with conn:
                hdr = conn.recv(4, socket.MSG_WAITALL)
                (n,) = struct.unpack(">I", hdr)
                payload = conn.recv(n, socket.MSG_WAITALL)
                raw.append(payload)
                req = json.loads(payload)
                send_frame(conn, {"id": req["id"], "status": "ok",
                                  "result": {}})

        t = threading.Thread(target=serve_one)
        t.start()
        try:
            cli = ServeClient(srv.getsockname(), connections=1,
                              hedge_ms=None, max_retries=0)
            res = cli.request("ping", deadline_s=2.0, tier=0,
                              trace="deadbeefdeadbeef")
            assert res.ok
            cli.close()
        finally:
            t.join(timeout=5.0)
            srv.close()
        assert raw and raw[0].startswith(
            b'{"trace":{"id":"deadbeefdeadbeef","s":1}')


class TestTraceMerge:
    def _spans(self, tmp_path):
        rows = [
            # pid 10 = loadgen lane; pid 20 = worker lane
            {"trace": "aa", "name": "client", "t0": 100.0, "dur_ms": 8.0,
             "pid": 10, "proc": "loadgen", "tid": 0, "status": "ok"},
            {"trace": "aa", "name": "service", "t0": 100.002,
             "dur_ms": 5.0, "pid": 20, "proc": "worker0", "tid": 1},
            {"trace": "bb", "name": "client", "t0": 100.01,
             "dur_ms": 1.0, "pid": 10, "proc": "loadgen", "tid": 0},
        ]
        by_pid = {}
        for r in rows:
            by_pid.setdefault(r["pid"], []).append(r)
        for pid, spans in by_pid.items():
            with open(tmp_path / f"spans.{pid}.jsonl", "w") as fh:
                for s in spans:
                    fh.write(json.dumps(s) + "\n")
                if pid == 20:
                    fh.write('{"trace": "cc", "name": "to')  # torn tail

    def test_pid_lanes_flows_and_rebase(self, tmp_path):
        import trace_merge
        self._spans(tmp_path)
        spans = trace_merge.load_directory(tmp_path)
        assert len(spans) == 3  # torn line skipped, never fatal
        merged = trace_merge.merge_chrome(spans)
        evs = merged["traceEvents"]
        lanes = {e["pid"]: e["args"]["name"] for e in evs
                 if e["ph"] == "M"}
        assert lanes == {10: "loadgen", 20: "worker0"}
        slices = [e for e in evs if e["ph"] == "X"]
        assert min(e["ts"] for e in slices) == 0.0  # re-based to t0_min
        # flow arrows only for the trace that crossed processes
        flows = [e for e in evs if e["ph"] in ("s", "t", "f")]
        assert {e["ph"] for e in flows} == {"s", "f"}
        assert len({e["id"] for e in flows}) == 1
        args = {e["args"]["trace"] for e in slices}
        assert args == {"aa", "bb"}

    def test_cli_expect_pids_gate(self, tmp_path, capsys):
        import trace_merge
        self._spans(tmp_path)
        assert trace_merge.main([str(tmp_path), "--expect-pids", "2"]) == 0
        assert os.path.exists(tmp_path / "merged.json")
        assert trace_merge.main([str(tmp_path), "--expect-pids", "3"]) == 1
        out = capsys.readouterr()
        assert "2 processes" in out.out
        assert "did not cross the process boundary" in out.err

    def test_trace_filter(self, tmp_path):
        import trace_merge
        self._spans(tmp_path)
        spans = trace_merge.load_directory(tmp_path, trace="aa")
        assert {s["trace"] for s in spans} == {"aa"}


# --- per-process event logs ---------------------------------------------------

class TestPerProcessEvents:
    def test_path_derivation_and_discovery(self, tmp_path):
        from pos_evolution_tpu.telemetry import (
            discover_per_process,
            per_process_path,
        )
        logical = str(tmp_path / "events.jsonl")
        assert per_process_path(logical, pid=42) == str(
            tmp_path / "events.42.jsonl")
        for pid in (300, 4, 77):
            with open(per_process_path(logical, pid=pid), "w") as fh:
                fh.write(json.dumps({"v": 1, "seq": 0, "type": "x",
                                     "wall": pid}) + "\n")
        (tmp_path / "events.notapid.jsonl").write_text("junk\n")
        found = [os.path.basename(p)
                 for p in discover_per_process(logical)]
        assert found == ["events.4.jsonl", "events.77.jsonl",
                         "events.300.jsonl"]

    def test_merge_orders_by_wall_and_keeps_lineage(self, tmp_path):
        from pos_evolution_tpu.telemetry import (
            EventBus,
            merge_event_files,
            per_process_path,
        )
        logical = str(tmp_path / "events.jsonl")
        with EventBus(per_process_path(logical, pid=1)) as b1:
            b1.emit("a", wall=10.0)
            b1.emit("b", wall=30.0)
        with EventBus(per_process_path(logical, pid=2)) as b2:
            b2.emit("c", wall=20.0)
        out = str(tmp_path / "merged.jsonl")
        merged = merge_event_files(
            [per_process_path(logical, pid=1),
             per_process_path(logical, pid=2)], out_path=out)
        assert [e["type"] for e in merged] == ["a", "c", "b"]
        assert [e["seq"] for e in merged] == [0, 1, 2]
        assert [e["src_pid"] for e in merged] == [1, 2, 1]
        assert merged[2]["src_seq"] == 1
        from pos_evolution_tpu.telemetry import read_jsonl
        assert [e["type"] for e in read_jsonl(out)] == ["a", "c", "b"]

    def test_run_report_auto_merges_per_process_logs(self, tmp_path):
        import run_report
        from pos_evolution_tpu.telemetry import (
            EventBus,
            per_process_path,
        )
        logical = str(tmp_path / "events.jsonl")
        with EventBus(per_process_path(logical, pid=9)) as bus:
            bus.emit("run_start", n_validators=8)
            bus.emit("dense_phase", slot=0, wall_ms=10.0,
                     phases={"vote_pass": 6.0, "epoch_sweep": 3.9},
                     accounted_pct=99.0)
        events, merged_from = run_report.load_events(logical)
        assert len(merged_from) == 1
        report = run_report.build_report(events)
        budget = report["dense_phase_budget"]
        assert budget["sampled_slots"] == 1
        assert budget["accounted_pct"] == 99.0
        md = run_report.to_markdown(report)
        assert "## Dense phase budget" in md
        assert "**99.0%**" in md


# --- dense phase profiler -----------------------------------------------------

class TestPhaseTimer:
    def test_partition_accounts_for_slot_wall(self):
        from pos_evolution_tpu.profiling.phases import (
            DENSE_PHASES,
            PhaseTimer,
        )
        from pos_evolution_tpu.telemetry import Telemetry
        tel = Telemetry()
        pt = PhaseTimer(sample_every=2, registry=tel.registry,
                        bus=tel.bus)
        for slot in range(4):
            pt.begin_slot(slot)
            with pt.phase("vote_pass"):
                time.sleep(0.002)
            with pt.phase("record"):
                time.sleep(0.001)
            pt.end_slot(slot)
        s = pt.summary()
        assert s["slots"] == 4 and s["sampled_slots"] == 2
        assert set(s["phases"]) == {"vote_pass", "record"}
        assert set(s["phases"]) <= set(DENSE_PHASES)
        assert s["accounted_pct"] > 90.0
        assert s["phases"]["vote_pass"]["count"] == 4
        assert s["sampled_phases"]["vote_pass"]["count"] == 2
        # only sampled slots emit events / histogram rows
        evs = tel.bus.of_type("dense_phase")
        assert [e["slot"] for e in evs] == [0, 2]
        assert evs[0]["accounted_pct"] > 90.0
        hist = tel.registry._metrics["dense_phase_ms"]
        row = hist.value(phase="vote_pass")
        assert row["count"] == 2

    def test_reentered_phase_accumulates(self):
        from pos_evolution_tpu.profiling.phases import PhaseTimer
        pt = PhaseTimer(sample_every=1)
        pt.begin_slot(0)
        for _ in range(3):
            with pt.phase("vote_apply"):
                time.sleep(0.001)
        pt.end_slot(0)
        assert pt.summary()["phases"]["vote_apply"]["count"] == 1
        assert pt.summary()["phases"]["vote_apply"]["total_ms"] >= 3.0

    def test_async_charge_stays_out_of_slot_partition(self):
        from pos_evolution_tpu.profiling.phases import PhaseTimer
        pt = PhaseTimer(sample_every=1)
        pt.begin_slot(0)
        with pt.phase("checkpoint_capture"):
            pass
        pt.end_slot(0)
        pt.charge_async("checkpoint_serialize", 0.25)
        s = pt.summary()
        assert "checkpoint_serialize" not in s["phases"]
        assert s["async_phases"]["checkpoint_serialize"]["total_ms"] \
            == 250.0
        # accounted_pct cannot be inflated past 100 by overlap work
        assert s["accounted_pct"] is None or s["accounted_pct"] <= 100.5

    def test_null_timer_twin_surface(self):
        from pos_evolution_tpu.profiling.phases import NULL_TIMER
        assert NULL_TIMER.enabled is False
        NULL_TIMER.begin_slot(0)
        with NULL_TIMER.phase("vote_pass"):
            pass
        NULL_TIMER.fence(None)
        NULL_TIMER.charge_async("x", 1.0)
        NULL_TIMER.end_slot(0)
        assert NULL_TIMER.summary() is None


# --- perf gate kinds ----------------------------------------------------------

class TestPerfGateKinds:
    def _history(self, tmp_path, kinds):
        from pos_evolution_tpu.profiling import history
        path = str(tmp_path / "bench_history.jsonl")
        for kind, counts in kinds:
            history.append_entry(path, {"metric": kind,
                                        "counts": counts}, kind=kind)
        return path

    def test_explicit_kind_matching_nothing_exits_2(self, tmp_path,
                                                    capsys):
        import perf_gate
        hist = self._history(tmp_path, [("bench_merkle", {"x": 1})])
        cand = tmp_path / "cand.json"
        cand.write_text(json.dumps({"counts": {"x": 1}}))
        rc = perf_gate.main(["--candidate", str(cand),
                             "--history", hist,
                             "--kind", "bench_obsx"])  # typo'd kind
        assert rc == 2
        err = capsys.readouterr()
        assert "zero entries of kind 'bench_obsx'" in err.out + err.err

    def test_list_kinds_inventories_history(self, tmp_path, capsys):
        import perf_gate
        hist = self._history(tmp_path, [("bench_obs", {"x": 1}),
                                        ("bench_obs", {"x": 1}),
                                        ("bench_merkle", {"y": 2})])
        assert perf_gate.main(["--history", hist, "--list-kinds"]) == 0
        out = capsys.readouterr().out
        assert "bench_obs" in out and "2" in out
        assert "bench_merkle" in out
        # --candidate still required on the gating path
        assert perf_gate.main != 0  # sanity: callable imported

    def test_matching_kind_still_gates(self, tmp_path):
        import perf_gate
        hist = self._history(tmp_path, [("bench_obs", {"x": 4}),
                                        ("bench_obs", {"x": 4})])
        good = tmp_path / "good.json"
        good.write_text(json.dumps({"counts": {"x": 4}}))
        assert perf_gate.main(["--candidate", str(good),
                               "--history", hist,
                               "--kind", "bench_obs"]) == 0
        bad = tmp_path / "bad.json"
        bad.write_text(json.dumps({"counts": {"x": 40}}))
        assert perf_gate.main(["--candidate", str(bad),
                               "--history", hist,
                               "--kind", "bench_obs"]) == 1


# --- balancer fleet bias ------------------------------------------------------

class TestBalancerMetricsBias:
    def test_error_heavy_worker_is_downweighted(self, tmp_path):
        from pos_evolution_tpu.serve.balancer import Balancer
        from pos_evolution_tpu.telemetry import fleet
        fleet.write_snapshot(fleet.snapshot_path(tmp_path, 0, 1),
                             _registry_with({"ok": 100}), 0, 1)
        fleet.write_snapshot(fleet.snapshot_path(tmp_path, 1, 2),
                             _registry_with({"ok": 40, "error": 60}),
                             1, 2)
        fleet.write_snapshot(fleet.snapshot_path(tmp_path, 2, 3),
                             _registry_with({"error": 8}), 2, 3)
        bal = Balancer(3, metrics_dir=str(tmp_path),
                       metrics_refresh_s=0.0)
        bias = bal._metrics_bias()
        assert bias[0] == 1.0
        assert bias[1] == 0.25  # 60% errors -> floor
        assert 2 not in bias    # < 32 requests: no bias, cold != sick
        assert bal.metrics_refreshes == 1

    def test_shed_is_not_illness(self, tmp_path):
        from pos_evolution_tpu.serve.balancer import Balancer
        from pos_evolution_tpu.telemetry import fleet
        fleet.write_snapshot(fleet.snapshot_path(tmp_path, 0, 1),
                             _registry_with({"ok": 50, "shed": 50}),
                             0, 1)
        bal = Balancer(1, metrics_dir=str(tmp_path),
                       metrics_refresh_s=0.0)
        assert bal._metrics_bias()[0] == 1.0

    def test_no_metrics_dir_means_no_bias(self):
        from pos_evolution_tpu.serve.balancer import Balancer
        bal = Balancer(2)
        assert bal.metrics_dir is None
        assert bal._metrics_bias() == {}  # board-less: uniform pick
