"""Profiling subsystem tests (ISSUE 4): the xplane wire-format parser
against a small checked-in ``*.xplane.pb`` fixture, device-op
attribution, Chrome-trace / collapsed-stack exporters (structural
validity of what Perfetto loads), static cost analysis of the hot-path
kernels, the bench-history robust gate's statistics (including empty /
single-entry histories and a doctored regression), and the
compile-count pin for the ``fused_measure`` traced-captures fix."""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))), "scripts"))

from pos_evolution_tpu.profiling import (  # noqa: E402
    attribution,
    export,
    history,
    xplane,
)

FIXTURE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "fixtures", "mini.xplane.pb")
GOLDEN = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "golden_telemetry.jsonl")


def _fixture_planes():
    with open(FIXTURE, "rb") as fh:
        return xplane.parse_xspace(fh.read())


# -- xplane parser -------------------------------------------------------------

class TestXplaneParser:
    def test_fixture_round_trip(self):
        """parse -> encode -> parse is the identity on the checked-in
        fixture (planes, lines, event-metadata all survive)."""
        planes = _fixture_planes()
        assert [p["name"] for p in planes] == ["/device:TPU:0 (fixture)",
                                               "/host:CPU"]
        assert xplane.parse_xspace(xplane.encode_xspace(planes)) == planes

    def test_fixture_structure(self):
        dev, host = _fixture_planes()
        assert dev["event_metadata"][1].endswith("scatter-add")
        assert [ln["name"] for ln in dev["lines"]] == ["XLA Ops", "XLA Ops#1"]
        assert dev["lines"][0]["timestamp_ns"] == 1_000_000
        assert dev["lines"][0]["events"][1] == {
            "metadata_id": 2, "offset_ps": 5_000_000,
            "duration_ps": 9_000_000_000}
        assert host["lines"][0]["events"][0]["duration_ps"] \
            == 16_000_000_000

    def test_top_table_stability(self):
        """The top-N table is deterministic: device plane first, rows by
        descending total, exact totals."""
        top = xplane.summarize_path(FIXTURE, 2)
        assert list(top) == ["/device:TPU:0 (fixture)", "/host:CPU"]
        assert top["/device:TPU:0 (fixture)"] == [
            {"op": "jit(run)/while/body/jit(aggregate_verify_batch)"
                   "/dot-general", "total_ms": 10.0, "count": 2},
            {"op": "jit(run)/while/body/jit(head_and_weights)/scatter-add",
             "total_ms": 6.0, "count": 2},
        ]
        assert top["/host:CPU"] == [
            {"op": "bench_epoch", "total_ms": 16.0, "count": 1}]

    def test_legacy_aggregate_view(self):
        with open(FIXTURE, "rb") as fh:
            planes = xplane.summarize_xplane(fh.read())
        dev = planes[0]["ops"]
        assert dev["jit(run)/while/body/jit(head_and_weights)/scatter-add"] \
            == [6_000_000_000, 2]

    def test_trace_summary_shim_still_works(self):
        """scripts/trace_summary.py stays a working CLI facade."""
        import trace_summary
        top = trace_summary.summarize_path(FIXTURE, 1)
        assert top["/host:CPU"][0]["op"] == "bench_epoch"

    def test_summarize_path_missing(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            xplane.summarize_path(tmp_path)

    def test_truncated_bytes_raise_valueerror(self):
        """A partially written protobuf (killed writer, full disk) must
        be a loud ValueError — the one exception type ProfiledRegion's
        degrade-don't-die contract is allowed to see — at EVERY
        truncation point, never an IndexError."""
        with open(FIXTURE, "rb") as fh:
            data = fh.read()
        for cut in range(1, len(data)):
            try:
                xplane.parse_xspace(data[:cut])
            except ValueError:
                pass  # loud and typed is the contract


# -- attribution ---------------------------------------------------------------

class TestAttribution:
    def test_innermost_jit(self):
        assert attribution.innermost_jit(
            "jit(run)/while/jit(head_and_weights)/scatter-add") \
            == "head_and_weights"
        assert attribution.innermost_jit("copy-start") is None

    def test_group_by_jit_device_plane_only(self):
        groups = attribution.group_by_jit(_fixture_planes())
        assert groups["head_and_weights"]["total_ms"] == pytest.approx(6.0)
        assert groups["head_and_weights"]["count"] == 2
        assert groups["aggregate_verify_batch"]["total_ms"] \
            == pytest.approx(10.0)
        # host plane excluded when a device plane exists
        assert "unjitted" not in groups

    def test_group_by_jit_host_fallback(self):
        host_only = [p for p in _fixture_planes() if "host" in p["name"]]
        groups = attribution.group_by_jit(host_only)
        assert groups["unjitted"]["total_ms"] == pytest.approx(16.0)

    def test_attribute_to_spans_partitions_totals(self):
        attr = attribution.attribute_to_spans(
            _fixture_planes(), ["aggregate_verify_batch", "nonexistent"])
        assert attr["aggregate_verify_batch"]["total_ms"] \
            == pytest.approx(10.0)
        assert attr["unattributed"]["total_ms"] == pytest.approx(6.5)
        total = sum(v["total_ms"] for v in attr.values())
        assert total == pytest.approx(16.5)  # device plane total preserved


# -- exporters -----------------------------------------------------------------

def _valid_chrome(blob: dict) -> None:
    """Structural trace_event validation: what Perfetto's legacy JSON
    importer requires of the object form."""
    assert isinstance(blob, dict)
    evs = blob["traceEvents"]
    assert isinstance(evs, list) and evs
    json.dumps(blob)  # must be JSON-serializable end to end
    for ev in evs:
        assert isinstance(ev["ph"], str) and ev["ph"] in ("X", "M", "I", "i")
        assert isinstance(ev["name"], str)
        assert isinstance(ev["pid"], int)
        if ev["ph"] == "X":
            assert isinstance(ev["ts"], (int, float))
            assert isinstance(ev["dur"], (int, float)) and ev["dur"] >= 0


class TestChromeTrace:
    def test_golden_events_export(self):
        from pos_evolution_tpu.telemetry import read_jsonl
        events = read_jsonl(GOLDEN)
        blob = export.chrome_trace(events)
        _valid_chrome(blob)
        slices = [e for e in blob["traceEvents"] if e["ph"] == "X"]
        # deliver events carry t + duration_ms -> exact slices
        deliver = [e for e in slices if e["cat"] == "deliver"]
        assert deliver and deliver[0]["ts"] == pytest.approx(12.0 * 1e6)
        assert deliver[0]["dur"] == pytest.approx(18.5 * 1e3)
        # propose has no t of its own: inherits the earliest child t
        propose = [e for e in slices if e["cat"] == "propose"]
        assert propose and propose[0]["ts"] == pytest.approx(12.0 * 1e6)

    def test_device_planes_fold_in(self):
        from pos_evolution_tpu.telemetry import read_jsonl
        blob = export.chrome_trace(read_jsonl(GOLDEN),
                                   device_planes=_fixture_planes())
        _valid_chrome(blob)
        dev = [e for e in blob["traceEvents"]
               if e.get("pid") == export.DEVICE_PID and e["ph"] == "X"]
        assert len(dev) == 6  # 5 device events + 1 host event
        assert any(e["args"]["op_name"].endswith("scatter-add")
                   for e in dev)

    def test_device_event_cap_is_loud(self):
        """max_device_events keeps the longest slices and records the
        drop in a 'truncated' metadata event — never a silent cap."""
        blob = export.chrome_trace([], device_planes=_fixture_planes(),
                                   max_device_events=2)
        _valid_chrome(blob)
        slices = [e for e in blob["traceEvents"] if e["ph"] == "X"]
        assert len(slices) == 2
        # the two longest device events survive (16ms host, 9ms dot)
        assert sorted(e["dur"] for e in slices) == [9000.0, 16000.0]
        trunc = [e for e in blob["traceEvents"] if e["name"] == "truncated"]
        assert trunc and trunc[0]["args"]["dropped_short_events"] == 4

    def test_collapsed_stacks_format(self):
        from pos_evolution_tpu.telemetry import read_jsonl
        lines = export.collapsed_stacks(read_jsonl(GOLDEN))
        assert lines
        for line in lines:
            stack, weight = line.rsplit(" ", 1)
            assert int(weight) >= 1
            assert stack  # frames joined by ';'
        assert any(line.startswith("propose;gossip:block;deliver:on_block")
                   for line in lines)

    def test_device_collapsed_stacks(self):
        lines = export.device_collapsed_stacks(_fixture_planes())
        joined = "\n".join(lines)
        assert "jit(run);while;body;jit(head_and_weights);scatter-add" \
            in joined
        # weights are integer microseconds of the summed durations
        row = [ln for ln in lines if "scatter-add" in ln][0]
        assert int(row.rsplit(" ", 1)[1]) == 6000

    def test_export_cli(self, tmp_path):
        import shutil
        events = tmp_path / "events.jsonl"
        shutil.copy(GOLDEN, events)
        chrome = tmp_path / "trace.json"
        flame = tmp_path / "flame.txt"
        dflame = tmp_path / "flame_dev.txt"
        rc = export.main([str(events), "--chrome", str(chrome),
                          "--flame", str(flame), "--xplane", FIXTURE,
                          "--device-flame", str(dflame)])
        assert rc == 0
        _valid_chrome(json.loads(chrome.read_text()))
        assert flame.read_text().strip()
        assert "scatter-add" in dflame.read_text()


# -- run_report integration ----------------------------------------------------

class TestRunReportProfileFolding:
    def test_top_ops_auto_discovery(self, tmp_path, capsys):
        """run_report picks up top_ops.json next to the event log when
        --top-ops is not given (reports used to silently omit it)."""
        import shutil

        import run_report
        events = tmp_path / "events.jsonl"
        shutil.copy(GOLDEN, events)
        top = {"backend": "cpu",
               "planes": {"/host:CPU": [
                   {"op": "bench_epoch", "total_ms": 16.0, "count": 1}]}}
        (tmp_path / "top_ops.json").write_text(json.dumps(top))
        out = tmp_path / "report.json"
        assert run_report.main([str(events), "--json", str(out),
                                "--markdown", str(tmp_path / "r.md")]) == 0
        report = json.loads(out.read_text())
        assert report["top_device_ops"]["/host:CPU"][0]["op"] \
            == "bench_epoch"

    def test_top_ops_discovered_via_profile_artifacts_event(self, tmp_path):
        """Simulation(profile=<dir>) records where its artifacts landed;
        run_report must find top_ops.json there from the log alone."""
        import shutil

        import run_report
        prof_dir = tmp_path / "prof"
        prof_dir.mkdir()
        top = {"source": "profiled_region",
               "planes": {"/host:CPU": [
                   {"op": "sim_run", "total_ms": 1.0, "count": 1}]}}
        (prof_dir / "top_ops.json").write_text(json.dumps(top))
        events = tmp_path / "logs" / "events.jsonl"
        events.parent.mkdir()
        shutil.copy(GOLDEN, events)
        with open(events, "a") as fh:
            fh.write(json.dumps(
                {"v": 1, "seq": 9999, "type": "profile_artifacts",
                 "dir": str(prof_dir),
                 "files": ["chrome_trace.json", "top_ops.json"]}) + "\n")
        out = tmp_path / "report.json"
        assert run_report.main([str(events), "--json", str(out),
                                "--markdown", str(tmp_path / "r.md")]) == 0
        report = json.loads(out.read_text())
        assert report["top_device_ops"]["/host:CPU"][0]["op"] == "sim_run"

    def test_cost_table_folds_in(self, tmp_path):
        import shutil

        import run_report
        events = tmp_path / "ev.jsonl"
        shutil.copy(GOLDEN, events)
        cost = {"backend": "cpu", "n_validators": 128,
                "kernels": {"epoch.process_epoch_dense":
                            {"flops": 123.0, "bytes_accessed": 456.0}}}
        cpath = tmp_path / "cost.json"
        cpath.write_text(json.dumps(cost))
        out = tmp_path / "report.json"
        md = tmp_path / "r.md"
        assert run_report.main([str(events), "--json", str(out),
                                "--cost", str(cpath),
                                "--markdown", str(md)]) == 0
        report = json.loads(out.read_text())
        assert report["cost_analysis"]["kernels"][
            "epoch.process_epoch_dense"]["flops"] == 123.0
        assert "Static cost analysis" in md.read_text()


# -- bench history + robust gate ----------------------------------------------

class TestHistoryStats:
    def test_append_read_round_trip(self, tmp_path):
        path = tmp_path / "hist.jsonl"
        history.append_entry(path, {"value": 1.0}, kind="bench")
        history.append_entry(path, {"value": 2.0}, kind="bench",
                             top_ops={"p": []})
        entries = history.read_history(path)
        assert [e["emission"]["value"] for e in entries] == [1.0, 2.0]
        assert entries[1]["top_ops"] == {"p": []}
        assert all(e["v"] == history.HISTORY_SCHEMA_VERSION
                   for e in entries)

    def test_torn_tail_tolerated_mid_corruption_raises(self, tmp_path):
        path = tmp_path / "hist.jsonl"
        history.append_entry(path, {"value": 1.0}, kind="bench")
        with open(path, "a") as fh:
            fh.write('{"v": 1, "emission": {"val')  # torn final line
        assert len(history.read_history(path)) == 1
        with open(path, "w") as fh:
            fh.write('not json\n')
            fh.write(json.dumps({"v": 1, "emission": {}}) + "\n")
        with pytest.raises(ValueError, match="corrupt bench-history line"):
            history.read_history(path)

    def test_unknown_schema_version_refused(self, tmp_path):
        path = tmp_path / "hist.jsonl"
        path.write_text('{"v": 99, "emission": {}}\n')
        with pytest.raises(ValueError, match="schema version"):
            history.read_history(path)

    def test_window(self, tmp_path):
        path = tmp_path / "hist.jsonl"
        for i in range(10):
            history.append_entry(path, {"value": i}, kind="bench")
        assert [e["emission"]["value"]
                for e in history.read_history(path, window=3)] == [7, 8, 9]

    def test_robust_band_mad(self):
        # median 10, MAD 1 -> sigma-ish halfwidth k*1.4826
        band = history.robust_band([8, 9, 10, 11, 12], k=2.0, abs_slack=0.0)
        assert band["median"] == 10
        assert band["mad"] == 1
        assert band["hi"] == pytest.approx(10 + 2 * 1.4826)

    def test_robust_band_outlier_resistance(self):
        """One wild outlier widens a stddev band but not the MAD band."""
        band = history.robust_band([10, 10, 10, 10, 1000], k=4.0,
                                   abs_slack=0.0)
        assert band["median"] == 10
        assert band["hi"] == 10  # MAD is still 0

    def test_degenerate_band_gets_abs_slack_floor(self):
        band = history.robust_band([5, 5, 5], k=4.0, abs_slack=4.0)
        assert band["hi"] == 9 and band["lo"] == 1

    def test_band_verdicts_regression_flagged(self):
        series = {"calls_total": [100.0] * 8}
        ok = history.band_verdicts({"calls_total": 103.0}, series,
                                   k=4.0, abs_slack=4.0)
        bad = history.band_verdicts({"calls_total": 160.0}, series,
                                    k=4.0, abs_slack=4.0)
        assert ok[0]["verdict"] == "ok"
        assert bad[0]["verdict"] == "FAIL"

    def test_band_verdicts_skip_without_history(self):
        rows = history.band_verdicts({"new_counter": 5.0}, {}, k=4.0)
        assert rows[0]["verdict"] == "skip"

    def test_drop_does_not_fail_one_sided(self):
        series = {"calls_total": [100.0] * 5}
        rows = history.band_verdicts({"calls_total": 3.0}, series)
        assert rows[0]["verdict"] == "ok"  # vanishing work never gated


class TestHistoryGateCLI:
    def _emission(self, n_calls):
        return {"telemetry": {"counts": {"handler_calls_total": n_calls}},
                "value": 1.25}

    def _write(self, tmp_path, name, obj):
        p = tmp_path / name
        p.write_text(json.dumps(obj))
        return str(p)

    def test_fresh_history_passes_doctored_fails(self, tmp_path, capsys):
        import perf_gate
        hist = tmp_path / "hist.jsonl"
        for _ in range(5):
            history.append_entry(hist, self._emission(100), kind="bench")
        cand = self._write(tmp_path, "cand.json", self._emission(101))
        assert perf_gate.main(["--candidate", cand, "--history", str(hist),
                               "--count-only"]) == 0
        doctored = self._write(tmp_path, "bad.json", self._emission(400))
        assert perf_gate.main(["--candidate", doctored, "--history",
                               str(hist), "--count-only"]) == 1
        assert "FAIL" in capsys.readouterr().out

    def test_empty_history_vacuous_pass(self, tmp_path, capsys):
        import perf_gate
        cand = self._write(tmp_path, "cand.json", self._emission(100))
        hist = tmp_path / "empty.jsonl"
        hist.write_text("")
        assert perf_gate.main(["--candidate", cand, "--history", str(hist),
                               "--count-only"]) == 0
        assert "VACUOUS" in capsys.readouterr().out

    def test_single_entry_band(self, tmp_path):
        """n=1 history: MAD degenerates to 0, the abs_slack floor keeps
        a same-ish candidate passing and a doubled one failing."""
        import perf_gate
        hist = tmp_path / "hist.jsonl"
        history.append_entry(hist, self._emission(100), kind="bench")
        near = self._write(tmp_path, "near.json", self._emission(103))
        far = self._write(tmp_path, "far.json", self._emission(200))
        assert perf_gate.main(["--candidate", near, "--history", str(hist),
                               "--count-only"]) == 0
        assert perf_gate.main(["--candidate", far, "--history", str(hist),
                               "--count-only"]) == 1

    def test_disjoint_namespaces_refused(self, tmp_path, capsys):
        import perf_gate
        hist = tmp_path / "hist.jsonl"
        history.append_entry(hist, self._emission(100), kind="bench")
        cand = self._write(tmp_path, "cand.json",
                           {"counts": {"other_metric": 1}})
        assert perf_gate.main(["--candidate", cand, "--history", str(hist),
                               "--count-only"]) == 2
        assert "incomparable" in capsys.readouterr().out

    def test_candidate_own_entry_excluded_from_band(self, tmp_path,
                                                    capsys):
        """bench.py appends before anyone gates: a regressed emission
        already sitting as the newest history entry must not vouch for
        itself (with [100, 400] in-band, median 250 + MAD slack would
        pass a 400-count candidate)."""
        import perf_gate
        hist = tmp_path / "hist.jsonl"
        history.append_entry(hist, self._emission(100), kind="bench")
        history.append_entry(hist, self._emission(400), kind="bench")
        bad = self._write(tmp_path, "bad.json", self._emission(400))
        assert perf_gate.main(["--candidate", bad, "--history", str(hist),
                               "--count-only"]) == 1
        assert "no self-gating" in capsys.readouterr().out

    def test_strict_timing_uses_relative_slack(self, tmp_path):
        """History mode gates timings with a relative floor, not the
        count-calibrated abs_slack: a 6x regression of a sub-4ms metric
        must fail under --strict-timing."""
        import perf_gate
        hist = tmp_path / "hist.jsonl"
        for _ in range(5):
            history.append_entry(
                hist, {"telemetry": {"counts": {"handler_calls_total": 10}},
                       "head_p50_ms": 0.5}, kind="bench")
        slow = self._write(
            tmp_path, "slow.json",
            {"telemetry": {"counts": {"handler_calls_total": 10}},
             "head_p50_ms": 3.0})
        assert perf_gate.main(["--candidate", slow, "--history", str(hist),
                               "--strict-timing"]) == 1
        same = self._write(
            tmp_path, "same.json",
            {"telemetry": {"counts": {"handler_calls_total": 10}},
             "head_p50_ms": 0.55})
        assert perf_gate.main(["--candidate", same, "--history", str(hist),
                               "--strict-timing"]) == 0

    def test_outlier_quarantine_flags_and_excludes(self, tmp_path, capsys):
        """--max-abs-ratio: a single contaminated history entry (the
        18.7s-style run of CHANGES PR 6) must be flagged LOUDLY and
        excluded from the band. Doctored negative: a candidate that the
        contaminated MAD band would wave through (median dragged +
        widened halfwidth) FAILS against the quarantined band."""
        import perf_gate
        hist = tmp_path / "hist.jsonl"
        for n in (100, 106, 94, 112, 88, 1870):  # 1870 = contamination
            history.append_entry(hist, self._emission(n), kind="bench")
        cand = self._write(tmp_path, "cand.json", self._emission(150))
        # absorbed silently without the flag: 150 < 103 + 4*1.4826*MAD(9)
        assert perf_gate.main(["--candidate", cand, "--history", str(hist),
                               "--count-only"]) == 0
        assert "QUARANTINE" not in capsys.readouterr().out
        # with quarantine: loud flag, clean band, regression caught
        assert perf_gate.main(["--candidate", cand, "--history", str(hist),
                               "--count-only", "--max-abs-ratio", "4"]) == 1
        out = capsys.readouterr().out
        assert "[QUARANTINE]" in out and "1870" in out
        assert "excluded from the band" in out
        # an in-band candidate still passes with quarantine on
        ok = self._write(tmp_path, "ok.json", self._emission(104))
        assert perf_gate.main(["--candidate", ok, "--history", str(hist),
                               "--count-only", "--max-abs-ratio", "4"]) == 0

    def test_quarantine_needs_three_entries(self, tmp_path, capsys):
        """Two wildly different entries are a level shift, not an
        outlier — n < 3 series must pass through unquarantined."""
        import perf_gate
        hist = tmp_path / "hist.jsonl"
        for n in (100, 1870):
            history.append_entry(hist, self._emission(n), kind="bench")
        cand = self._write(tmp_path, "cand.json", self._emission(101))
        assert perf_gate.main(["--candidate", cand, "--history", str(hist),
                               "--count-only", "--max-abs-ratio", "4"]) == 0
        assert "QUARANTINE" not in capsys.readouterr().out

    def test_quarantine_series_unit(self):
        import perf_gate
        import io
        out = io.StringIO()
        series = {"a": [10.0, 10.0, 10.0, 500.0], "b": [0.0, 0.0, 0.0],
                  "short": [1.0, 99.0], "sparse": [0.0, 0.0, 0.0, 2.0]}
        cleaned = perf_gate.quarantine_series(series, 8.0, out)
        assert cleaned["a"] == [10.0, 10.0, 10.0]
        assert cleaned["b"] == [0.0, 0.0, 0.0]      # all-zero: no flags
        assert cleaned["short"] == [1.0, 99.0]      # n<3 untouched
        # sparse counters toggling 0 <-> small are NOT contamination
        assert cleaned["sparse"] == [0.0, 0.0, 0.0, 2.0]
        assert "[QUARANTINE] a:" in out.getvalue()
        assert "sparse" not in out.getvalue()

    def test_quarantine_mutually_inconsistent_series_is_loud(self):
        """When leave-one-out implicates EVERY entry there is no clean
        core to band against — the raw series is kept but the operator
        must be told loudly, not silently passed through."""
        import io

        import perf_gate
        out = io.StringIO()
        series = {"w": [1.0, 100.0, 10000.0]}
        cleaned = perf_gate.quarantine_series(series, 8.0, out)
        assert cleaned["w"] == [1.0, 100.0, 10000.0]
        assert "[QUARANTINE] w: series is mutually inconsistent" \
            in out.getvalue()

    def test_kindless_entry_refuses_not_crashes(self, tmp_path, capsys):
        """A hand-seeded entry with no 'kind' field must hit the
        deliberate mixed-kind exit 2, not a sorted() TypeError."""
        import perf_gate
        hist = tmp_path / "hist.jsonl"
        history.append_entry(hist, self._emission(100), kind="bench")
        with open(hist, "a") as fh:
            fh.write(json.dumps({"v": 1, "emission": self._emission(90)})
                     + "\n")
        cand = self._write(tmp_path, "cand.json", self._emission(101))
        assert perf_gate.main(["--candidate", cand, "--history", str(hist),
                               "--count-only"]) == 2
        assert "MIXED" in capsys.readouterr().out

    def test_mixed_kinds_refused_without_kind_flag(self, tmp_path, capsys):
        """bench and bench_all share the history file AND the count keys
        at different magnitudes — a band over the mixture gates nothing
        honestly, so mixed kinds require an explicit --kind."""
        import perf_gate
        hist = tmp_path / "hist.jsonl"
        for _ in range(3):
            history.append_entry(hist, self._emission(100), kind="bench")
            history.append_entry(hist, self._emission(9000),
                                 kind="bench_all")
        cand = self._write(tmp_path, "cand.json", self._emission(101))
        assert perf_gate.main(["--candidate", cand, "--history", str(hist),
                               "--count-only"]) == 2
        assert "MIXED" in capsys.readouterr().out
        # --kind selects the candidate's own family: passes against the
        # bench band, and the bench_all entries no longer widen it
        assert perf_gate.main(["--candidate", cand, "--history", str(hist),
                               "--kind", "bench", "--count-only"]) == 0
        big = self._write(tmp_path, "big.json", self._emission(9000))
        assert perf_gate.main(["--candidate", big, "--history", str(hist),
                               "--kind", "bench", "--count-only"]) == 1

    def test_window_limits_band(self, tmp_path):
        """Only the trailing --window entries shape the band: after a
        legitimate step-change, old history ages out."""
        import perf_gate
        hist = tmp_path / "hist.jsonl"
        for _ in range(10):
            history.append_entry(hist, self._emission(10), kind="bench")
        for _ in range(5):
            history.append_entry(hist, self._emission(100), kind="bench")
        cand = self._write(tmp_path, "cand.json", self._emission(102))
        assert perf_gate.main(["--candidate", cand, "--history", str(hist),
                               "--window", "5", "--count-only"]) == 0


# -- cost analysis + live capture (jax required) -------------------------------

jax = pytest.importorskip("jax")


class TestCostAnalysis:
    def test_hot_path_table(self):
        from pos_evolution_tpu.profiling import cost
        table = cost.analyze_hot_paths(n=128, capacity=16)
        assert table["backend"] == jax.default_backend()
        kernels = table["kernels"]
        for name in ("aggregation.aggregate_verify_batch",
                     "forkchoice.head_and_weights",
                     "forkchoice.head_from_buckets",
                     "epoch.process_epoch_dense",
                     "sync_verify.merkle_walk",
                     "shuffle.swap_or_not"):
            assert name in kernels
            row = kernels[name]
            assert "error" not in row, f"{name}: {row}"
            assert row.get("flops", 0) > 0
            assert row.get("bytes_accessed", 0) > 0
        # memory_analysis leg (present on CPU/TPU backends here)
        agg = kernels["aggregation.aggregate_verify_batch"]
        assert agg.get("argument_bytes", 0) > 0
        assert agg.get("peak_bytes", 0) >= agg.get("output_bytes", 0)


class TestProfiledRegion:
    def test_capture_attribute_emit(self, tmp_path):
        import numpy as np

        import jax.numpy as jnp

        from pos_evolution_tpu.telemetry import Telemetry
        tel = Telemetry()

        @jax.jit
        def work(x):
            return (x @ x).sum()

        x = jnp.ones((256, 256))
        np.asarray(work(x))  # compile outside the region
        with attribution.ProfiledRegion(
                "test_region", telemetry=tel,
                trace_dir=tmp_path / "trace") as prof:
            tel.bus.emit("handler", handler="work_handler", duration_ms=1.0)
            np.asarray(work(x))
        assert prof.error is None, prof.error
        assert prof.planes, "trace produced no planes"
        assert prof.top_ops
        profile_events = tel.bus.of_type("profile")
        assert len(profile_events) == 1
        assert profile_events[0]["name"] == "test_region"
        assert "attribution" in profile_events[0]
        # the region's own TraceAnnotation slice envelops every op it
        # dispatched: counting it would double the table on CPU planes
        assert "test_region" not in prof.attribution
        assert "test_region" not in prof.by_jit
        # explicit trace_dir is kept on disk
        assert (tmp_path / "trace").exists()

    def test_degrades_without_killing_region(self):
        """A profiling failure must not raise out of the region body."""
        ran = []
        import unittest.mock as mock
        with mock.patch.object(jax.profiler, "start_trace",
                               side_effect=RuntimeError("boom")):
            with attribution.ProfiledRegion("broken") as prof:
                ran.append(True)
        assert ran and prof.error is not None
        assert prof.top_ops == {}


class TestFusedMeasureCaptures:
    def test_compile_count_unchanged_by_captures(self):
        """The constant-folding fix (pass the fork-choice tables as
        traced captures instead of closures) must not change how many
        XLA backend compiles a measurement costs — pinned via the
        telemetry jaxrt recompile counter."""
        import numpy as np

        import jax.numpy as jnp

        from pos_evolution_tpu.ops.forkchoice import (
            DenseStore, head_and_weights,
        )
        from pos_evolution_tpu.telemetry import MetricsRegistry, jaxrt
        from pos_evolution_tpu.utils.benchtime import (
            checksum_tree, fused_measure,
        )

        capacity, n = 16, 64
        rng = np.random.default_rng(0)
        store = DenseStore(
            parent=jnp.asarray(np.arange(-1, capacity - 1, dtype=np.int32)),
            slot=jnp.arange(capacity, dtype=jnp.int32),
            rank=jnp.asarray(rng.permutation(capacity).astype(np.int32)),
            real=jnp.ones(capacity, bool),
            leaf_viable=jnp.ones(capacity, bool),
            justified_idx=jnp.int32(0),
            msg_block=jnp.asarray(
                rng.integers(0, capacity, n).astype(np.int32)),
            msg_epoch=jnp.zeros(n, jnp.int64),
            weight=jnp.asarray(np.full(n, 32, np.int64)),
            boost_idx=jnp.int32(capacity - 1),
            boost_amount=jnp.int64(7),
        )

        def closure_body(salt, acc):
            st = store._replace(
                msg_epoch=store.msg_epoch.at[0].set(salt.astype(jnp.int64)))
            h, w = head_and_weights(st, capacity)
            return acc + h.astype(jnp.int32) + checksum_tree(w)

        def captured_body(salt, acc, st0):
            st = st0._replace(
                msg_epoch=st0.msg_epoch.at[0].set(salt.astype(jnp.int64)))
            h, w = head_and_weights(st, capacity)
            return acc + h.astype(jnp.int32) + checksum_tree(w)

        reg = MetricsRegistry()
        was = jaxrt.current()
        jaxrt.install(reg)
        try:
            def compiles():
                return reg.counter("jax_backend_compiles_total").value()

            c0 = compiles()
            t_closure = fused_measure(closure_body, entropy=5, reps=1)
            c1 = compiles()
            t_captured = fused_measure(captured_body, entropy=5, reps=1,
                                       captures=store)
            c2 = compiles()
        finally:
            jaxrt.install(was)
        assert t_closure > 0 and t_captured > 0
        closure_compiles = c1 - c0
        captured_compiles = c2 - c1
        assert closure_compiles >= 1
        assert captured_compiles == closure_compiles, (
            f"captures changed compile count: "
            f"{closure_compiles} -> {captured_compiles}")


class TestSimulationProfile:
    @pytest.mark.usefixtures("minimal_cfg")
    def test_profiled_sim_writes_artifacts(self, tmp_path):
        from pos_evolution_tpu.sim import Simulation
        from pos_evolution_tpu.telemetry import Telemetry

        tel = Telemetry()
        sim = Simulation(16, telemetry=tel, profile=tmp_path / "prof")
        sim.run_until_slot(3)
        assert sim.slot == 4  # the profiled segment ran the sim
        chrome = tmp_path / "prof" / "chrome_trace.json"
        assert chrome.exists()
        _valid_chrome(json.loads(chrome.read_text()))
        assert (tmp_path / "prof" / "flame.txt").read_text().strip()
        profile_events = tel.bus.of_type("profile")
        assert len(profile_events) == 1
        # second run segment is NOT re-profiled (single capture contract)
        sim.run_until_slot(4)
        assert len(tel.bus.of_type("profile")) == 1
