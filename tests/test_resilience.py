"""ISSUE 10: self-healing long runs — atomic checksummed
autocheckpoints (sync + async), supervised auto-resume with SIGKILL
injection, corruption detection/quarantine/rollback, degraded-mesh
resume, goodput reporting, and the bench_resilience perf gate."""

import json
import os
import signal
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

from pos_evolution_tpu.config import minimal_config, use_config
from pos_evolution_tpu.resilience import (
    AutoCheckpoint,
    CheckpointCorruption,
    CheckpointManager,
    FingerprintMismatch,
    IntegrityError,
    backoff_delay,
    scan_columns,
    state_digest,
    supervise,
)

jax = pytest.importorskip("jax")

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_REPO, "scripts"))


def _payload_path(mgr, step, name="payload.bin"):
    return os.path.join(mgr._step_dir(step), name)


# --- CheckpointManager --------------------------------------------------------


class TestCheckpointManager:
    def test_roundtrip_and_retention(self, tmp_path):
        mgr = CheckpointManager(tmp_path, retain=2)
        for step, blob in ((4, b"a" * 100), (8, b"b" * 100),
                           (12, b"c" * 100)):
            mgr.save(step, blob)
        assert mgr.steps() == [8, 12]  # oldest GC'd
        step, payloads = mgr.latest_valid()
        assert step == 12 and payloads["payload.bin"] == b"c" * 100

    def test_async_callable_payload_and_stats(self, tmp_path):
        mgr = CheckpointManager(tmp_path, retain=4, async_mode=True)
        mgr.save(1, {"payload.bin": lambda: b"lazy" * 1000})
        mgr.save(2, b"eager")
        mgr.drain()
        assert mgr.load(1)["payload.bin"] == b"lazy" * 1000
        s = mgr.stats()
        assert s["saves"] == 2 and s["background_s"] > 0
        mgr.close()

    def test_async_worker_error_surfaces(self, tmp_path):
        mgr = CheckpointManager(tmp_path, async_mode=True)

        def boom():
            raise ValueError("serialize died")
        mgr.save(1, {"payload.bin": boom})
        with pytest.raises(RuntimeError, match="background checkpoint"):
            mgr.save(2, b"x", wait=True)
        mgr.close()

    def test_truncated_payload_refused_and_rolled_past(self, tmp_path):
        mgr = CheckpointManager(tmp_path, retain=4)
        mgr.save(4, b"good" * 64)
        mgr.save(8, b"newer" * 64)
        p = _payload_path(mgr, 8)
        with open(p, "rb") as fh:
            data = fh.read()
        with open(p, "wb") as fh:
            fh.write(data[: len(data) // 2])  # torn write
        with pytest.raises(CheckpointCorruption, match="truncated"):
            mgr.validate(8)
        step, payloads = mgr.latest_valid()
        assert step == 4 and payloads["payload.bin"] == b"good" * 64
        # the torn step is quarantined as evidence, not deleted
        assert mgr.steps() == [4]
        assert os.path.isdir(os.path.join(str(tmp_path), "quarantine",
                                          "step_00000008"))

    def test_bit_flip_refused(self, tmp_path):
        mgr = CheckpointManager(tmp_path)
        mgr.save(4, b"\x00" * 256)
        p = _payload_path(mgr, 4)
        with open(p, "r+b") as fh:
            fh.seek(128)
            fh.write(b"\x01")  # single bit flip, length unchanged
        with pytest.raises(CheckpointCorruption, match="checksum"):
            mgr.load(4)

    def test_forged_checksum_quarantined_not_loaded(self, tmp_path):
        """The doctored negative: an attacker (or a bug) rewriting the
        manifest checksum must not smuggle altered bytes into a resume —
        the recomputed payload hash disagrees with the forged one."""
        mgr = CheckpointManager(tmp_path)
        mgr.save(4, b"truth")
        mgr.save(8, b"newer-truth")
        mpath = os.path.join(mgr._step_dir(8), "manifest.json")
        manifest = json.load(open(mpath))
        manifest["files"]["payload.bin"]["sha256"] = "f" * 64
        json.dump(manifest, open(mpath, "w"))
        step, _ = mgr.latest_valid()
        assert step == 4
        assert 8 not in mgr.steps()  # quarantined
        assert mgr.stats()["quarantined"] == 1

    def test_missing_manifest_refused(self, tmp_path):
        mgr = CheckpointManager(tmp_path)
        mgr.save(4, b"x")
        os.remove(os.path.join(mgr._step_dir(4), "manifest.json"))
        with pytest.raises(CheckpointCorruption, match="no manifest"):
            mgr.load(4)

    def test_fingerprint_mismatch_refused_without_quarantine(self,
                                                             tmp_path):
        """A checkpoint from a different run shape is REFUSED but kept:
        it is somebody's good checkpoint, just not this run's."""
        CheckpointManager(tmp_path, fingerprint={"cfg": "aaaa"}).save(4,
                                                                      b"x")
        other = CheckpointManager(tmp_path, fingerprint={"cfg": "bbbb"})
        with pytest.raises(FingerprintMismatch):
            other.validate(4)
        assert other.latest_valid() is None
        assert other.steps() == [4]  # still there, NOT quarantined

    def test_resave_same_step_never_loses_the_durable_copy(self, tmp_path):
        mgr = CheckpointManager(tmp_path)
        mgr.save(4, b"first")
        mgr.save(4, b"second")  # re-save (the finish() at slot N case)
        assert mgr.load(4)["payload.bin"] == b"second"
        assert not [n for n in os.listdir(tmp_path)
                    if n.startswith(".old-")]
        # kill between displace and rename: the displaced previous copy
        # must be RESTORED by the next manager start, not lost
        displaced = os.path.join(str(tmp_path), ".old-step_00000004-999")
        os.replace(mgr._step_dir(4), displaced)
        assert CheckpointManager(tmp_path).latest_valid()[0] == 4

    def test_kill_mid_write_leaves_previous_step(self, tmp_path):
        """Simulated preemption inside a staged write: the tmp dir is
        invisible to steps() and swept on the next manager start."""
        mgr = CheckpointManager(tmp_path)
        mgr.save(4, b"committed")
        tmp = os.path.join(str(tmp_path), ".tmp-step_00000008-99999")
        os.makedirs(tmp)
        with open(os.path.join(tmp, "payload.bin"), "wb") as fh:
            fh.write(b"half-writ")  # no manifest: the kill point
        assert mgr.steps() == [4]
        mgr2 = CheckpointManager(tmp_path)
        assert not os.path.exists(tmp)  # swept
        assert mgr2.latest_valid()[0] == 4


# --- heartbeat + backoff ------------------------------------------------------


class TestHeartbeatAndBackoff:
    def test_beat_roundtrip_and_age(self, tmp_path):
        from pos_evolution_tpu.utils.watchdog import Heartbeat, read_heartbeat
        p = str(tmp_path / "hb.json")
        assert read_heartbeat(p) is None
        hb = Heartbeat(p)
        hb.beat(slot=17)
        out = read_heartbeat(p)
        assert out["payload"]["slot"] == 17
        assert out["age_s"] < 5.0

    def test_backoff_caps_and_is_deterministic(self):
        assert backoff_delay(0, 1.0, 30.0, 0.25, seed=1) == 0.0
        a = backoff_delay(3, 1.0, 30.0, 0.25, seed=1)
        b = backoff_delay(3, 1.0, 30.0, 0.25, seed=1)
        assert a == b  # same (seed, failures) -> same jitter
        assert 4.0 <= a <= 5.0  # base * 2**2 * (1 + [0, .25))
        assert backoff_delay(30, 1.0, 30.0, 0.0, seed=1) == 30.0  # cap


# --- supervise() over real child processes ------------------------------------


class TestSupervisor:
    def _script(self, tmp_path, body) -> list:
        path = tmp_path / "child.py"
        path.write_text(textwrap.dedent(body))
        return [sys.executable, str(path)]

    def test_crash_then_success(self, tmp_path):
        argv = self._script(tmp_path, f"""
            import os, sys
            marker = {str(tmp_path / 'once')!r}
            if not os.path.exists(marker):
                open(marker, 'w').close()
                sys.exit(3)       # first attempt crashes
            sys.exit(0)
        """)
        summary = supervise(lambda attempt: argv, max_failures=3,
                            backoff_s=0.01, poll_s=0.02)
        assert summary["ok"] and summary["attempts"] == 2
        (i,) = summary["interruptions"]
        assert i["reason"] == "crash" and i["exit_code"] == 3

    def test_hang_detected_and_killed(self, tmp_path):
        hb_path = str(tmp_path / "hb.json")
        # first attempt beats once then hangs forever; the resumed
        # attempt exits clean
        argv = self._script(tmp_path, f"""
            import json, os, sys, time
            sys.path.insert(0, {_REPO!r})
            from pos_evolution_tpu.utils.watchdog import Heartbeat
            marker = {str(tmp_path / 'hung_once')!r}
            hb = Heartbeat({hb_path!r})
            hb.beat(slot=1)
            if not os.path.exists(marker):
                open(marker, 'w').close()
                time.sleep(600)   # wedged
            sys.exit(0)
        """)
        t0 = time.time()
        summary = supervise(lambda attempt: argv, heartbeat_path=hb_path,
                            hang_timeout_s=1.0, max_failures=3,
                            backoff_s=0.01, poll_s=0.05)
        assert summary["ok"] and summary["attempts"] == 2
        assert summary["interruptions"][0]["reason"] == "hang"
        assert summary["interruptions"][0]["exit_code"] == -signal.SIGKILL
        assert time.time() - t0 < 60  # killed, not waited out

    def test_gives_up_loudly_after_n_failures(self, tmp_path):
        from pos_evolution_tpu.resilience import SupervisorGaveUp
        argv = self._script(tmp_path, "import sys; sys.exit(7)")
        with pytest.raises(SupervisorGaveUp) as ei:
            supervise(lambda attempt: argv, max_failures=2,
                      backoff_s=0.01, poll_s=0.02)
        assert ei.value.summary["attempts"] == 2
        assert not ei.value.summary["ok"]


# --- driver autocheckpointing (spec level) ------------------------------------


@pytest.mark.usefixtures("minimal_cfg")
class TestSimulationAutocheckpoint:
    def test_autocheckpoint_resume_bit_identical_to_twin(self, tmp_path):
        from pos_evolution_tpu.sim import Simulation
        d = str(tmp_path / "ckpt")
        sim = Simulation(32, autocheckpoint=(4, d))
        sim.run_epochs(1)
        sim.finish_autocheckpoint()
        resumed = Simulation.resume_latest(d)
        twin = Simulation(32)
        twin.run_epochs(1)
        assert resumed.slot == twin.slot
        assert state_digest(resumed) == state_digest(twin)
        resumed.run_epochs(2)
        twin.run_epochs(2)
        assert state_digest(resumed) == state_digest(twin)

    def test_resume_skips_torn_newest_step(self, tmp_path):
        """The supervisor contract of the satellite: a kill mid-write
        (or post-write corruption) of the NEWEST step must roll the
        resume back to the previous valid one, loudly."""
        from pos_evolution_tpu.sim import Simulation
        d = str(tmp_path / "ckpt")
        sim = Simulation(32, autocheckpoint=(4, d))
        sim.run_epochs(2)
        sim.finish_autocheckpoint()
        mgr = CheckpointManager(d)
        steps = mgr.steps()
        assert len(steps) >= 2
        newest = steps[-1]
        p = _payload_path(mgr, newest)
        with open(p, "r+b") as fh:  # truncate = torn write
            fh.truncate(os.path.getsize(p) // 2)
        resumed = Simulation.resume_latest(d)
        assert resumed.slot == steps[-2]
        assert newest not in CheckpointManager(d).steps()  # quarantined

    def test_resume_refuses_when_all_steps_corrupt(self, tmp_path):
        from pos_evolution_tpu.sim import Simulation
        d = str(tmp_path / "ckpt")
        sim = Simulation(32, autocheckpoint=(8, d))
        sim.run_epochs(1)
        sim.finish_autocheckpoint()
        mgr = CheckpointManager(d)
        for step in mgr.steps():
            p = _payload_path(mgr, step)
            with open(p, "r+b") as fh:
                fh.truncate(10)
        with pytest.raises(FileNotFoundError, match="no valid checkpoint"):
            Simulation.resume_latest(d)

    def test_config_fingerprint_mismatch_refuses(self, tmp_path):
        """A checkpoint taken under one protocol config must not resume
        under another (same failure mode as resuming a mainnet store
        with minimal constants: silent nonsense)."""
        from pos_evolution_tpu.config import mainnet_config
        from pos_evolution_tpu.sim import Simulation
        d = str(tmp_path / "ckpt")
        sim = Simulation(16, autocheckpoint=(4, d))
        sim.run_epochs(1)
        sim.finish_autocheckpoint()
        with use_config(mainnet_config()):
            with pytest.raises(FileNotFoundError):
                Simulation.resume_latest(d)
        # NOT quarantined: it is a good checkpoint for the right config
        assert CheckpointManager(d).steps()
        assert Simulation.resume_latest(d).slot == sim.slot


# --- driver autocheckpointing (dense, sharded, cross-mesh) --------------------


class TestDenseAutocheckpoint:
    @pytest.mark.mesh8
    def test_kill_resume_on_degraded_mesh_bit_identical(self, tmp_path):
        """Checkpoint on 2x2, 'lose' half the devices, resume on 1x2
        and finish — bit-identical to an uninterrupted single-device
        twin (the device-loss path of PR 9's resume-across-mesh)."""
        from pos_evolution_tpu.parallel.sharded import make_mesh
        from pos_evolution_tpu.sim.dense_driver import DenseSimulation
        cfg = minimal_config()
        d = str(tmp_path / "ckpt")
        sim = DenseSimulation(64, cfg=cfg, mesh=make_mesh(4, 2),
                              verify_aggregates=False, check_walk_every=0,
                              autocheckpoint=(4, d))
        sim.run_epochs(2)
        sim.finish_autocheckpoint()
        resumed = DenseSimulation.resume_latest(d, mesh=make_mesh(2, 1))
        twin = DenseSimulation(64, cfg=cfg, mesh=None,
                               verify_aggregates=False, check_walk_every=0)
        twin.run_epochs(2)
        assert state_digest(resumed) == state_digest(twin)
        resumed.run_epochs(4)
        twin.run_epochs(4)
        assert state_digest(resumed) == state_digest(twin)

    def test_torn_dense_checkpoint_refused(self, tmp_path):
        """The corrupt-checkpoint satellite on the dense backend: a
        bit-flipped npz payload must refuse with a checksum error and
        the resume must land on the previous valid step."""
        from pos_evolution_tpu.sim.dense_driver import DenseSimulation
        cfg = minimal_config()
        d = str(tmp_path / "ckpt")
        sim = DenseSimulation(32, cfg=cfg, verify_aggregates=False,
                              check_walk_every=0, autocheckpoint=(4, d))
        sim.run_epochs(1)
        sim.finish_autocheckpoint()
        mgr = CheckpointManager(d)
        steps = mgr.steps()
        p = _payload_path(mgr, steps[-1])
        with open(p, "r+b") as fh:
            fh.seek(os.path.getsize(p) // 2)
            fh.write(b"\xff\xff")
        with pytest.raises(CheckpointCorruption, match="checksum"):
            mgr.load(steps[-1])
        resumed = DenseSimulation.resume_latest(d)
        assert resumed.slot == steps[-2]


# --- integrity guard: detect -> quarantine -> rollback -> replay --------------


class TestIntegrityGuard:
    def test_scan_columns_flags_nan_and_oob(self):
        findings = scan_columns(
            {"weights": np.array([1.0, np.nan, np.inf]),
             "balance": np.array([5, -3], dtype=np.int64),
             "msg_block": np.array([0, 7], dtype=np.int32)},
            n_blocks=4)
        text = "; ".join(findings)
        assert "2 non-finite" in text
        assert "negative balance" in text
        assert "outside the 4-entry block table" in text

    def test_rollback_replay_bit_identical_to_twin(self, tmp_path):
        """The full recovery loop, in-process: corrupt the dense state
        mid-run -> the guard trips -> the newest checkpoint is
        quarantined -> resume from the last good step -> replay to the
        end — final state bit-identical to an uninterrupted twin."""
        from pos_evolution_tpu.sim.dense_driver import DenseSimulation
        cfg = minimal_config()
        d = str(tmp_path / "ckpt")
        spec = AutoCheckpoint(every_n_slots=4, dir=d, guard_every=4,
                              retain=8)
        sim = DenseSimulation(32, cfg=cfg, verify_aggregates=False,
                              check_walk_every=0, autocheckpoint=spec)
        target = 2 * cfg.slots_per_epoch
        poisoned_at = None
        with pytest.raises(IntegrityError) as ei:
            while sim.slot < target:
                sim.run_slot()
                if sim.slot == 9 and poisoned_at is None:
                    # memory corruption between audits: a vote pointer
                    # wanders outside the block table
                    poisoned_at = sim.slot
                    sim.msg_block = sim.msg_block.at[3].set(10_000)
        assert "msg_block" in str(ei.value)
        mgr = CheckpointManager(d)
        assert mgr.stats()["quarantined"] == 0  # fresh manager view
        assert os.path.isdir(os.path.join(d, "quarantine"))
        good = mgr.steps()[-1]
        assert good <= 8  # the post-poison step is out of the sequence
        resumed = DenseSimulation.resume_latest(d)
        assert resumed.slot == good
        twin = DenseSimulation(32, cfg=cfg, verify_aggregates=False,
                               check_walk_every=0)
        while twin.slot < target:
            twin.run_slot()
        while resumed.slot < target:
            resumed.run_slot()
        assert state_digest(resumed) == state_digest(twin)

    @pytest.mark.usefixtures("minimal_cfg")
    def test_spec_driver_guard_catches_resident_corruption(self):
        from pos_evolution_tpu.resilience import IntegrityGuard
        from pos_evolution_tpu.sim import Simulation
        sim = Simulation(32)
        sim.run_epochs(1)
        guard = IntegrityGuard(every_n_slots=1)
        assert guard.check(sim) == []
        # clobber a store invariant: finality ahead of justification
        from pos_evolution_tpu.specs.containers import Checkpoint
        sim.groups[0].store.finalized_checkpoint = Checkpoint(
            epoch=9, root=bytes(32))
        findings = guard.check(sim)
        assert any("ahead of justified" in f for f in findings)


# --- the supervised SIGKILL end-to-end (satellite 4) --------------------------


@pytest.mark.mesh8
class TestKillMidRunSupervised:
    def _run(self, tmp_path, tag, extra):
        out = tmp_path / f"bench_{tag}.json"
        argv = [sys.executable,
                os.path.join(_REPO, "scripts", "resilient_run.py"),
                "--validators", "64", "--epochs", "2",
                "--ckpt-dir", str(tmp_path / f"ckpt_{tag}"),
                "--every", "4", "--backoff", "0.05",
                "--json", str(out), *extra]
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)  # the parent sets the child's devices
        proc = subprocess.run(argv, env=env, capture_output=True,
                              text=True, timeout=600)
        assert proc.returncode == 0, proc.stderr[-2000:]
        return json.load(open(out))

    def test_sigkill_between_epochs_resumes_bit_identical(self, tmp_path):
        """SIGKILL a supervised 64-validator SHARDED run between epochs
        (slot 10 of 16), auto-resume onto a DEGRADED mesh (2x2 -> 1x2),
        finish, and pin bit-identity of the final state against an
        uninterrupted twin."""
        killed = self._run(
            tmp_path, "killed",
            ["--sharded", "2x2", "--degraded-sharded", "1x2",
             "--crash-at-slot", "10",
             "--events", str(tmp_path / "events.jsonl")])
        assert killed["attempts"] == 2
        assert killed["interruptions"] == 1
        assert killed["interruption_reasons"] == ["crash"]
        assert killed["resumed_on_degraded_mesh"] == [1, 2]
        assert killed["replayed_slots"] >= 1  # slot 10 back to step 8
        twin = self._run(tmp_path, "twin", ["--sharded", "2x2"])
        assert twin["attempts"] == 1 and twin["interruptions"] == 0
        assert killed["state_digest"] == twin["state_digest"]
        assert killed["final_slot"] == twin["final_slot"]
        # async autocheckpointing overhead is measured and bounded
        assert twin["ckpt_overhead_pct"] < 10.0, twin
        # the events log reconstructs the story offline
        import run_report
        from pos_evolution_tpu.telemetry import read_jsonl
        report = run_report.build_report(
            read_jsonl(str(tmp_path / "events.jsonl")))
        res = report["resilience"]
        assert res["checkpoints_saved"] >= 2
        assert len(res["interruptions"]) == 1
        assert res["resumes"] and res["resumes"][0]["step"] == 8
        md = run_report.to_markdown(report)
        assert "## Resilience" in md
        assert "effective goodput" in md


# --- run_report + perf gate ---------------------------------------------------


class TestResilienceReport:
    def _events(self):
        seq = [0]

        def ev(type_, **f):
            seq[0] += 1
            return {"v": 1, "seq": seq[0], "type": type_, **f}
        return [
            ev("checkpoint_saved", slot=8, step=8, async_mode=True,
               blocked_ms=12.5),
            ev("supervisor_interruption", attempt=0, reason="crash",
               exit_code=-9, wall_s=4.2, last_heartbeat={"slot": 10}),
            ev("run_resumed", step=8, slot=8, dir="/tmp/x"),
            ev("checkpoint_saved", slot=12, step=12, async_mode=True,
               blocked_ms=11.0),
            ev("checkpoint_quarantined", step=16, reason="checksum"),
            ev("integrity_violation", slot=14, findings=["boom"]),
            ev("checkpoint_final", slot=16, saves=3, bytes=1000,
               loop_blocked_s=0.02, blocked_s=0.03, background_s=0.4),
            ev("run_segment", wall_s=9.0, final_slot=16),
            ev("goodput", attempts=2, interruptions=1, replayed_slots=2,
               final_slot=16, goodput_pct=88.9, ckpt_overhead_pct=2.0,
               total_wall_s=13.0),
        ]

    def test_build_report_resilience_section(self):
        import run_report
        rep = run_report.build_report(self._events())
        res = rep["resilience"]
        assert res["checkpoints_saved"] == 2
        assert res["replayed_slots"] == 2
        assert res["interruptions"][0]["reason"] == "crash"
        assert res["quarantined_checkpoints"][0]["step"] == 16
        assert res["integrity_violations"][0]["slot"] == 14
        assert res["goodput"]["goodput_pct"] == 88.9
        md = run_report.to_markdown(rep)
        assert "## Resilience" in md
        assert "quarantined checkpoint" in md
        assert "integrity violation" in md

    def test_no_resilience_events_no_section(self):
        import run_report
        rep = run_report.build_report(
            [{"v": 1, "seq": 0, "type": "slot", "slot": 1}])
        assert "resilience" not in rep
        assert "## Resilience" not in run_report.to_markdown(rep)


class TestBenchResilienceGate:
    def _emission(self, blocked=0.2, interruptions=1):
        return {"metric": "resilient_run", "driver": "sim",
                "attempts": interruptions + 1,
                "interruptions": interruptions,
                "replayed_slots": 2, "final_slot": 16,
                "goodput_pct": 88.9,
                "ckpt_blocked_s": blocked, "ckpt_background_s": 1.0,
                "ckpt_overhead_pct": 100.0 * blocked / 9.0,
                "run_wall_s": 9.0, "total_wall_s": 13.0,
                "counts": {"attempts": interruptions + 1,
                           "interruptions": interruptions,
                           "replayed_slots": 2, "ckpt_saves": 3}}

    def test_gate_passes_real_fails_doctored_overhead(self, tmp_path):
        import perf_gate

        from pos_evolution_tpu.profiling import history
        hist = tmp_path / "hist.jsonl"
        for _ in range(3):
            history.append_entry(hist, self._emission(),
                                 kind="bench_resilience")
        cand = tmp_path / "cand.json"
        cand.write_text(json.dumps(self._emission(blocked=0.21)))
        assert perf_gate.main(["--candidate", str(cand),
                               "--history", str(hist),
                               "--kind", "bench_resilience",
                               "--strict-timing"]) == 0
        slow = tmp_path / "slow.json"
        slow.write_text(json.dumps(self._emission(blocked=2.0)))
        assert perf_gate.main(["--candidate", str(slow),
                               "--history", str(hist),
                               "--kind", "bench_resilience",
                               "--strict-timing"]) == 1

    def test_gate_fails_on_more_interruptions(self, tmp_path):
        import perf_gate

        from pos_evolution_tpu.profiling import history
        hist = tmp_path / "hist.jsonl"
        for _ in range(3):
            history.append_entry(hist, self._emission(),
                                 kind="bench_resilience")
        worse = tmp_path / "worse.json"
        worse.write_text(json.dumps(self._emission(interruptions=30)))
        assert perf_gate.main(["--candidate", str(worse),
                               "--history", str(hist),
                               "--kind", "bench_resilience"]) == 1


class TestRefuseUnlessVirginStore:
    def _args(self, d):
        import resilient_run
        return resilient_run.build_parser().parse_args(
            ["--ckpt-dir", str(d)])

    def test_empty_store_allows_fresh_start(self, tmp_path, capsys):
        import resilient_run
        resilient_run._refuse_unless_virgin_store(self._args(tmp_path))

    def test_refused_or_quarantined_steps_block_fresh_start(self,
                                                            tmp_path):
        """A store whose steps were all refused (wrong config) or
        quarantined (corruption) must NOT silently restart from genesis
        and exit 0 — the refuse-loudly contract."""
        import resilient_run
        CheckpointManager(tmp_path, fingerprint={"cfg": "aa"}).save(4,
                                                                    b"x")
        with pytest.raises(SystemExit, match="refusing"):
            resilient_run._refuse_unless_virgin_store(self._args(tmp_path))
        CheckpointManager(tmp_path).quarantine(4, reason="test")
        with pytest.raises(SystemExit, match="quarantined"):
            resilient_run._refuse_unless_virgin_store(self._args(tmp_path))


class TestEventBusAppendMode:
    def test_append_continues_seq_past_previous_attempt(self, tmp_path):
        from pos_evolution_tpu.telemetry import read_jsonl
        from pos_evolution_tpu.telemetry.events import EventBus
        p = str(tmp_path / "events.jsonl")
        with EventBus(p) as bus:
            bus.emit("slot", slot=1)
            bus.emit("slot", slot=2)
        with EventBus(p, append=True) as bus:
            bus.emit("slot", slot=3)
        events = read_jsonl(p)
        assert [e["slot"] for e in events] == [1, 2, 3]
        assert [e["seq"] for e in events] == [0, 1, 2]

    def test_append_truncates_torn_tail_log_stays_readable(self, tmp_path):
        """A writer killed mid-line leaves a torn tail; the resumed
        attempt must TRUNCATE it (not newline-terminate it into fatal
        mid-log corruption) so every later read_jsonl still works."""
        from pos_evolution_tpu.telemetry import read_jsonl
        from pos_evolution_tpu.telemetry.events import EventBus
        p = str(tmp_path / "events.jsonl")
        with EventBus(p) as bus:
            bus.emit("slot", slot=1)
        with open(p, "a") as fh:
            fh.write('{"v": 1, "seq": 1, "type": "slot", "sl')  # killed
        with EventBus(p, append=True) as bus:
            bus.emit("slot", slot=9)
        events = read_jsonl(p)  # must NOT raise mid-log corruption
        assert [e.get("slot") for e in events] == [1, 9]


# --- atomic snapshot writes (satellite 1) -------------------------------------


class TestAtomicSnapshotWrites:
    def test_atomic_write_bytes_no_partial_on_failure(self, tmp_path):
        from pos_evolution_tpu.utils.snapshot import atomic_write_bytes
        p = str(tmp_path / "blob.bin")
        atomic_write_bytes(p, b"first")
        assert open(p, "rb").read() == b"first"
        atomic_write_bytes(p, b"second")
        assert open(p, "rb").read() == b"second"
        assert not [f for f in os.listdir(tmp_path) if ".tmp." in f]

    @pytest.mark.usefixtures("minimal_cfg")
    def test_save_simulation_path_is_atomic_and_loadable(self, tmp_path):
        from pos_evolution_tpu.sim import Simulation
        from pos_evolution_tpu.utils.snapshot import save_simulation
        sim = Simulation(16)
        sim.run_epochs(1)
        p = str(tmp_path / "sim.ckpt")
        data = save_simulation(sim, path=p)
        assert open(p, "rb").read() == data
        back = Simulation.resume(data)
        assert state_digest(back) == state_digest(sim)

    def test_save_dense_goes_through_atomic_path(self, tmp_path):
        from pos_evolution_tpu.ops.epoch import densify
        from pos_evolution_tpu.specs.genesis import make_genesis
        from pos_evolution_tpu.utils.snapshot import load_dense, save_dense
        with use_config(minimal_config()):
            state, _ = make_genesis(16)
            reg = densify(state)
        p = str(tmp_path / "reg.npz")
        save_dense(p, reg)
        back = load_dense(p)
        assert np.array_equal(np.asarray(reg.balance),
                              np.asarray(back.balance))
        assert not [f for f in os.listdir(tmp_path) if ".tmp." in f]


# --- chaos bundle incremental flush (satellite 2) -----------------------------


class TestChaosIncrementalBundles:
    @pytest.mark.usefixtures("minimal_cfg")
    def test_crashed_episode_leaves_replayable_bundle(self, tmp_path):
        import chaos_fuzz
        cfg = chaos_fuzz.episode_config(5, 0, 64, 16)
        inflight = str(tmp_path / "inflight_ep0")

        class _Die(Exception):
            pass

        # die deterministically mid-episode (the in-process stand-in
        # for a preemption: run_episode never reaches its return)
        from pos_evolution_tpu.sim import driver as drv
        real_run_slot = drv.Simulation.run_slot

        def dying_run_slot(self):
            real_run_slot(self)
            if self.slot >= 6:
                raise _Die("preempted")
        drv.Simulation.run_slot = dying_run_slot
        try:
            with pytest.raises(_Die):
                chaos_fuzz.run_episode(cfg, bundle_dir=inflight)
        finally:
            drv.Simulation.run_slot = real_run_slot
        # the incremental flush survived the death
        for name in ("config.json", "checkpoint.bin", "events.jsonl"):
            p = os.path.join(inflight, name)
            assert os.path.exists(p) and os.path.getsize(p) > 0, name
        # and the partial bundle replays to completion
        out = chaos_fuzz.replay_bundle(inflight)
        assert out["match"] is None  # no recorded verdict on a partial
        assert out["replayed"] == []
