"""Telemetry subsystem tests (ISSUE 3): MetricsRegistry semantics and
export formats, EventBus span lineage across a faulted multi-group run,
fault-attribution events agreeing with the FaultPlan's seeded decisions,
the debug-gated StoreInvariantChecker wiring, run_report on a golden
JSONL fixture, and the perf-regression gate's exit behavior."""

import json
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))), "scripts"))

from pos_evolution_tpu.config import minimal_config  # noqa: E402
from pos_evolution_tpu.telemetry import (  # noqa: E402
    SCHEMA_VERSION,
    EventBus,
    MetricsRegistry,
    Telemetry,
    emit_global,
    read_jsonl,
    set_global,
)

pytestmark = pytest.mark.usefixtures("minimal_cfg")

GOLDEN = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "golden_telemetry.jsonl")


# -- registry ------------------------------------------------------------------

class TestMetricsRegistry:
    def test_counter_labels_and_values(self):
        reg = MetricsRegistry()
        c = reg.counter("requests_total", "help text")
        c.inc()
        c.inc(2, method="get")
        c.inc(method="get")
        assert c.value() == 1
        assert c.value(method="get") == 3
        assert reg.counter("requests_total") is c  # get-or-create

    def test_counter_rejects_negative(self):
        with pytest.raises(AssertionError):
            MetricsRegistry().counter("c").inc(-1)

    def test_kind_conflict_is_loud(self):
        reg = MetricsRegistry()
        reg.counter("x")
        with pytest.raises(AssertionError):
            reg.gauge("x")

    def test_gauge_set_and_inc(self):
        g = MetricsRegistry().gauge("depth")
        g.set(5, queue="a")
        g.inc(2, queue="a")
        g.set(-3)
        assert g.value(queue="a") == 7
        assert g.value() == -3

    def test_histogram_buckets_sum_count(self):
        h = MetricsRegistry().histogram("lat", buckets=(0.1, 1.0, 10.0))
        for v in (0.05, 0.5, 0.5, 5.0, 50.0):
            h.observe(v)
        row = h.value()
        assert row["count"] == 5
        assert row["sum"] == pytest.approx(56.05)
        assert row["bucket_counts"] == [1, 2, 1]  # 50.0 -> +Inf only

    def test_prometheus_text_format(self):
        reg = MetricsRegistry()
        reg.counter("hits_total", "hits").inc(3, route="/x")
        reg.gauge("depth").set(2)
        reg.histogram("lat", buckets=(1.0,)).observe(0.5)
        text = reg.to_prometheus()
        assert "# TYPE hits_total counter" in text
        assert 'hits_total{route="/x"} 3' in text
        assert "# HELP hits_total hits" in text
        assert "# TYPE depth gauge" in text
        assert "depth 2" in text
        assert 'lat_bucket{le="1.0"} 1' in text
        assert 'lat_bucket{le="+Inf"} 1' in text
        assert "lat_count 1" in text

    def test_json_export_and_counts(self):
        reg = MetricsRegistry()
        reg.counter("a_total").inc(2, k="v")
        reg.histogram("h", buckets=(1.0,)).observe(0.5)
        reg.gauge("g").set(9)
        blob = reg.to_json()
        assert blob["a_total"]["kind"] == "counter"
        assert blob["a_total"]["series"][0] == {"labels": {"k": "v"},
                                                "value": 2}
        counts = reg.counts()
        assert counts == {"a_total;k=v": 2, "h;stat=count": 1}
        json.dumps(blob)  # must be serializable as-is


# -- event bus -----------------------------------------------------------------

class TestEventBus:
    def test_envelope_and_seq(self):
        bus = EventBus()
        e0 = bus.emit("a", x=1)
        e1 = bus.emit("b", span="s1", parent="s0")
        assert e0 == {"v": SCHEMA_VERSION, "seq": 0, "type": "a", "x": 1}
        assert e1["seq"] == 1 and e1["span"] == "s1" and e1["parent"] == "s0"
        assert bus.of_type("a") == [e0]

    def test_jsonl_roundtrip_and_torn_tail(self, tmp_path):
        path = tmp_path / "ev.jsonl"
        with EventBus(path) as bus:
            bus.emit("a", x=1)
            bus.emit("b", y=2)
        with open(path, "a") as fh:
            fh.write('{"v": 1, "seq": 99, "type": "torn"')  # killed mid-write
        events = read_jsonl(path)
        assert [e["type"] for e in events] == ["a", "b"]

    def test_midfile_corruption_raises_with_line_number(self, tmp_path):
        """Only the FINAL line may be torn; corruption mid-log must be
        loud — silently dropping the suffix would present a truncated
        run as a complete one."""
        path = tmp_path / "ev.jsonl"
        path.write_text('{"v": 1, "seq": 0, "type": "a"}\n'
                        '{"v": 1, "seq": 1, "ty\n'
                        '{"v": 1, "seq": 2, "type": "c"}\n')
        with pytest.raises(ValueError, match=":2: corrupt"):
            read_jsonl(path)

    def test_unknown_schema_version_raises(self, tmp_path):
        path = tmp_path / "ev.jsonl"
        path.write_text('{"v": 999, "seq": 0, "type": "future"}\n')
        with pytest.raises(ValueError, match="schema version"):
            read_jsonl(path)


# -- driver integration: spans, faults, invariants -----------------------------

def _faulted_sim(telemetry=None, n_groups=2, epochs=4, record_log=True):
    from pos_evolution_tpu.sim import (
        CrashWindow,
        FaultPlan,
        Simulation,
        faulty_schedule,
    )
    c = minimal_config()
    spe = c.slots_per_epoch
    plan = FaultPlan(
        seed=7, drop_p=0.15, duplicate_p=0.05, reorder_p=0.1,
        gst=3 * spe * c.seconds_per_slot, record_log=record_log,
        crashes=(CrashWindow(group=1, crash_slot=spe, rejoin_slot=2 * spe),))
    sim = Simulation(32, schedule=faulty_schedule(32, plan, n_groups=n_groups),
                     telemetry=telemetry)
    sim.run_epochs(epochs)
    return sim, plan


class TestDriverTelemetry:
    def test_span_parent_child_integrity(self):
        """Every parent referenced by any event of a faulted multi-group
        run must exist as an emitted span: propose/attest roots, gossip
        edges, per-group deliveries."""
        tel = Telemetry()
        sim, plan = _faulted_sim(tel)
        events = tel.bus.events
        spans = {e["span"] for e in events if e.get("span")}
        parents = {e["parent"] for e in events if e.get("parent")}
        assert parents, "expected span lineage in a telemetry run"
        assert parents <= spans, f"orphan parents: {parents - spans}"
        for e in events:
            if e["type"] == "deliver" and e.get("span"):
                assert e["parent"] in spans
                assert e["parent"].rsplit("/", 1)[0] in spans  # root span

    def test_fault_events_match_plan_decisions_exactly(self):
        tel = Telemetry()
        sim, plan = _faulted_sim(tel)
        from collections import Counter
        by_event = Counter((e["action"], e["kind"])
                           for e in tel.bus.of_type("fault"))
        by_plan = Counter((e["action"], e["kind"]) for e in plan.log)
        assert by_event == by_plan and by_plan, \
            "fault attribution must mirror the plan's seeded decisions"

    def test_fault_event_carries_replayable_hash_inputs(self):
        """The (seed, tag, slot, src, msg_id, dst, u, threshold) payload
        must let a consumer REPLAY the decision: drawing the recorded
        identity through FaultPlan._unit reproduces u below threshold."""
        tel = Telemetry()
        sim, plan = _faulted_sim(tel)
        idx_of = {"drop": 0, "reorder": 1, "duplicate": 3}
        checked = 0
        for e in tel.bus.of_type("fault"):
            key = (e["tag"], e["slot"], e["src"], e["msg_id"], e["dst"])
            u = plan._unit(idx_of[e["action"]], *key)
            assert u == e["u"] and u < e["threshold"]
            checked += 1
        assert checked > 0

    def test_telemetry_does_not_perturb_the_run(self):
        """Attaching a bus/registry must not change a single per-slot
        metric — observability is read-only by construction."""
        ref, _ = _faulted_sim(None)
        tel = Telemetry(debug=True)
        sim, _ = _faulted_sim(tel)
        assert sim.metrics == ref.metrics

    def test_metrics_entries_superset_of_legacy_keys(self):
        sim, _ = _faulted_sim(None, epochs=1)
        legacy = {"slot", "head", "head_slot", "justified_epoch",
                  "finalized_epoch", "n_blocks", "equivocators"}
        rich = {"participation", "justification_bits", "n_latest_messages",
                "head_root"}
        for rec in sim.metrics:
            assert legacy | rich <= set(rec)
            assert rec["head"] == rec["head_root"][:8]

    def test_checkpoint_resume_with_telemetry_stays_bit_identical(self):
        from pos_evolution_tpu.sim import Simulation
        ref, _ = _faulted_sim(None, epochs=4)
        sim, plan = _faulted_sim(None, epochs=2)
        data = sim.checkpoint()
        from pos_evolution_tpu.sim import FaultPlan, CrashWindow, faulty_schedule
        c = minimal_config()
        spe = c.slots_per_epoch
        plan2 = FaultPlan(
            seed=7, drop_p=0.15, duplicate_p=0.05, reorder_p=0.1,
            gst=3 * spe * c.seconds_per_slot,
            crashes=(CrashWindow(group=1, crash_slot=spe,
                                 rejoin_slot=2 * spe),))
        tel = Telemetry()
        back = Simulation.resume(
            data, schedule=faulty_schedule(32, plan2, n_groups=2),
            telemetry=tel)
        back.run_epochs(4)
        assert back.metrics == ref.metrics
        assert tel.bus.of_type("slot"), "resumed run must keep recording"

    def test_resume_with_reused_schedule_reclaims_fault_sink(self):
        """Resuming with the ORIGINAL schedule object (the documented
        contract — schedules hold callables) must re-point the plan's
        fault sink at the NEW bus, not leak events onto the dead run's,
        and the resumed run_start must describe the checkpointed state."""
        from pos_evolution_tpu.sim import (
            FaultPlan,
            Simulation,
            faulty_schedule,
        )
        c = minimal_config()
        plan = FaultPlan(seed=3, drop_p=0.2,
                         gst=3 * c.slots_per_epoch * c.seconds_per_slot)
        sched = faulty_schedule(32, plan, n_groups=2)
        tel_a = Telemetry()
        sim = Simulation(32, schedule=sched, telemetry=tel_a)
        sim.run_epochs(2)
        assert plan.sink is tel_a.bus
        data = sim.checkpoint()
        n_a = len(tel_a.bus.events)
        tel_b = Telemetry()
        back = Simulation.resume(data, schedule=sched, telemetry=tel_b)
        assert plan.sink is tel_b.bus
        back.run_epochs(4)
        assert tel_b.bus.of_type("fault"), \
            "post-resume fault events must land on the new bus"
        assert len(tel_a.bus.events) == n_a, \
            "the dead run's bus must not keep growing"
        (start,) = tel_b.bus.of_type("run_start")
        assert start["resumed_at_slot"] == sim.slot
        # and resuming with NO telemetry must CLEAR the stale sink, not
        # keep appending to the (possibly closed) previous bus
        n_b = len(tel_b.bus.events)
        back2 = Simulation.resume(data, schedule=sched)
        assert plan.sink is None
        back2.run_epochs(3)
        assert len(tel_b.bus.events) == n_b

    def test_mutating_failed_handler_is_caught_debug_gated(self):
        """A deliberately store-mutating FAILING handler must surface as
        an invariant_violation event when telemetry.debug is on — the
        pos-evolution.md:1041 contract, enforced at the driver's own
        call sites."""
        import pos_evolution_tpu.sim.driver as drv
        orig = drv.fc.on_attestation

        def dirty_on_attestation(store, att, is_from_block=False):
            store.time += 1  # mutate BEFORE failing: the forbidden move
            raise AssertionError("dirty handler")

        tel = Telemetry(debug=True)
        try:
            drv.fc.on_attestation = dirty_on_attestation
            sim, _ = _faulted_sim(tel, epochs=1)
        finally:
            drv.fc.on_attestation = orig
        violations = tel.bus.of_type("invariant_violation")
        assert violations, "mutating failed handler must be flagged"
        assert violations[0]["handler"] == "dirty_on_attestation"
        assert any(g.invariants.violations for g in sim.groups)

    def test_debug_off_skips_invariant_checker(self):
        tel = Telemetry(debug=False)
        sim, _ = _faulted_sim(tel, epochs=1)
        assert all(g.invariants is None for g in sim.groups)


# -- global sink (resident degradation, watchdog incidents) --------------------

class TestGlobalSink:
    def test_emit_global_noop_without_install(self):
        set_global(None)
        assert emit_global("degradation", reason="x") is None

    def test_watchdog_incident_event(self):
        from pos_evolution_tpu.utils.watchdog import Watchdog
        tel = Telemetry().install_global()
        try:
            wd = Watchdog(path=None, tag="t")
            assert wd.step("boom", lambda: 1 / 0, default="d") == "d"
        finally:
            set_global(None)
        (ev,) = tel.bus.of_type("watchdog_incident")
        assert ev["step"] == "boom" and ev["tag"] == "t"
        assert "ZeroDivisionError" in ev["error"]

    def test_resident_degradation_event(self):
        pytest.importorskip("jax")
        from pos_evolution_tpu.sim import Simulation
        tel = Telemetry().install_global()
        try:
            sim = Simulation(32, accelerated_forkchoice=True)
            sim.run_until_slot(2)
            sim.groups[0].resident._degrade("test-injected")
        finally:
            set_global(None)
        (ev,) = tel.bus.of_type("degradation")
        assert ev["component"] == "resident_forkchoice"
        assert ev["reason"] == "test-injected"
        assert ev["fallback"] == "host_spec_walk"


# -- jax runtime telemetry -----------------------------------------------------

class TestJaxRuntime:
    def test_compile_events_and_explicit_hooks(self):
        jax = pytest.importorskip("jax")
        import jax.numpy as jnp

        from pos_evolution_tpu.telemetry import jaxrt
        reg = MetricsRegistry()
        jaxrt.install(reg)
        try:
            @jax.jit
            def f(x):
                return x * 2 + 1

            np.asarray(f(jnp.arange(7)))  # unique shape -> fresh compile
            jaxrt.record_dispatch(site="test")
            jaxrt.record_transfer(128, direction="d2h", site="test")
        finally:
            jaxrt.install(None)
        counts = reg.counts()
        assert counts.get("jax_backend_compiles_total", 0) >= 1
        assert counts["jax_dispatches_total;site=test"] == 1
        assert counts["jax_transfer_bytes_total;direction=d2h;site=test"] == 128
        # detached: further events must not land anywhere
        n = dict(counts)
        jaxrt.record_dispatch(site="test")
        assert reg.counts() == n


# -- HandlerTimer satellites ---------------------------------------------------

class TestHandlerTimerHardening:
    def test_summary_tolerates_empty_samples(self):
        from pos_evolution_tpu.utils.metrics import HandlerTimer
        t = HandlerTimer()
        t.samples["never_hit"]  # defaultdict: registered, no samples
        s = t.summary()
        assert s["never_hit"]["count"] == 0
        assert np.isnan(s["never_hit"]["p50_ms"])
        assert np.isnan(s["never_hit"]["p95_ms"])
        assert s["never_hit"]["total_s"] == 0.0

    def test_reset_drops_warmup_samples(self):
        from pos_evolution_tpu.utils.metrics import HandlerTimer
        t = HandlerTimer()
        with t.track("h"):
            pass
        t.reset()
        assert t.summary() == {}
        with t.track("h"):
            pass
        assert t.summary()["h"]["count"] == 1


# -- run_report on the golden fixture ------------------------------------------

class TestRunReport:
    def test_golden_fixture_report(self):
        from run_report import build_report, to_markdown
        events = read_jsonl(GOLDEN)
        report = build_report(events)
        fin = report["finality"]
        assert fin["final_justified_epoch"] == 3
        assert fin["final_finalized_epoch"] == 2
        assert fin["advances"] == [
            {"slot": 24, "finalized_epoch": 1},
            {"slot": 32, "finalized_epoch": 2}]
        assert report["faults"]["counts"] == {
            "drop": {"block": 1}, "reorder": {"attestation": 1}}
        eff = report["faults"]["effects"]
        assert eff["gossip_edges"] == 4
        assert eff["undelivered_gossip_edges"] == 1  # the dropped block
        assert eff["handler_rejects"] == {"on_attestation": 1}
        assert eff["invariant_violations"] == 1
        assert eff["crashes"] == [
            {"group": 1, "slot": 8, "lost_in_flight": 3}]
        assert eff["rejoins"] == [
            {"group": 1, "slot": 16, "sync_checkpoint_epoch": 1}]
        assert eff["degradations"] == [
            {"component": "resident_forkchoice",
             "reason": "divergence self-check at query 128"}]
        assert report["handlers"]["get_head"] == {
            "count": 2, "p50_ms": 2.0, "p95_ms": 2.675, "total_ms": 4.0}
        assert report["handlers"]["on_block"]["count"] == 1
        assert report["light_clients"]["0"]["final_head_lag"] == 1
        md = to_markdown(report)
        assert "## Finality timeline" in md and "| get_head | 2 |" in md

    def test_handler_percentiles_match_numpy(self):
        """The dependency-free percentile must agree with np.percentile
        (linear interpolation) on the fixture's durations."""
        from run_report import _percentile
        xs = [0.4, 0.8, 1.25, 2.75, 18.5]
        for q in (50, 95):
            assert _percentile(xs, q) == pytest.approx(
                float(np.percentile(xs, q)))

    def test_cli_writes_json_and_markdown(self, tmp_path):
        from run_report import main
        out_json = tmp_path / "r.json"
        out_md = tmp_path / "r.md"
        assert main([GOLDEN, "--json", str(out_json),
                     "--markdown", str(out_md)]) == 0
        report = json.loads(out_json.read_text())
        assert report["n_events"] == 25
        assert out_md.read_text().startswith("# Run report")

    def test_report_reconstructs_live_run_without_simulation(self, tmp_path):
        """Acceptance: a faulted multi-group run's JSONL alone yields the
        finality timeline, handler percentiles, and per-fault-type counts
        matching the plan's actual decisions exactly."""
        from collections import Counter

        from run_report import build_report
        path = tmp_path / "events.jsonl"
        tel = Telemetry.to_file(path)
        sim, plan = _faulted_sim(tel, epochs=4)
        tel.close()
        report = build_report(read_jsonl(path))
        assert report["finality"]["final_finalized_epoch"] == \
            sim.finalized_epoch()
        assert [r["finalized_epoch"] for r in report["finality"]["timeline"]] \
            == [m["finalized_epoch"] for m in sim.metrics]
        by_plan: dict = {}
        for e in plan.log:
            by_plan.setdefault(e["action"], Counter())[e["kind"]] += 1
        got = {a: Counter(k) for a, k in report["faults"]["counts"].items()}
        for action, kinds in by_plan.items():
            assert got.get(action, Counter()) == kinds, action
        deliver_counts = Counter(
            e["handler"] for e in read_jsonl(path) if e["type"] == "deliver")
        for handler, n in deliver_counts.items():
            assert report["handlers"][handler]["count"] == n


# -- perf gate -----------------------------------------------------------------

class TestPerfGate:
    def _bench_emission(self, recompiles):
        return {"metric": "m", "value": 1.0, "unit": "s",
                "telemetry": {"counts": {
                    "jax_backend_compiles_total": recompiles,
                    "jax_dispatches_total;site=fused_measure": 12}}}

    def test_real_emission_passes(self, tmp_path):
        from perf_gate import main
        base = tmp_path / "base.json"
        cand = tmp_path / "cand.json"
        base.write_text(json.dumps(self._bench_emission(8)))
        cand.write_text(json.dumps(self._bench_emission(8)))
        assert main(["--candidate", str(cand), "--baseline", str(base),
                     "--count-only"]) == 0

    def test_doctored_inflated_recompiles_fail(self, tmp_path):
        from perf_gate import main
        base = tmp_path / "base.json"
        cand = tmp_path / "cand.json"
        base.write_text(json.dumps(self._bench_emission(8)))
        cand.write_text(json.dumps(self._bench_emission(64)))
        assert main(["--candidate", str(cand), "--baseline", str(base),
                     "--count-only"]) == 1

    def test_vacuous_pass_when_baseline_has_no_counts(self, tmp_path):
        from perf_gate import main
        base = tmp_path / "base.json"
        cand = tmp_path / "cand.json"
        base.write_text(json.dumps({"metric": "m", "value": 1.0}))
        cand.write_text(json.dumps(self._bench_emission(8)))
        assert main(["--candidate", str(cand), "--baseline", str(base),
                     "--count-only"]) == 0

    def test_run_report_handler_counts_are_gateable(self, tmp_path):
        from perf_gate import extract_counts, gate
        report = {"handlers": {"on_block": {"count": 68, "p50_ms": 17.9}}}
        assert extract_counts(report) == {
            "handler_calls_total;handler=on_block": 68}
        doctored = {"handlers": {"on_block": {"count": 204}}}
        assert gate(report, doctored, 1.25, 4.0) == 1
        assert gate(report, report, 1.25, 4.0) == 0

    def test_registry_counts_aggregate_over_status_label(self):
        """A registry counts() emission (status-labelled) must intersect
        a run-report emission on the per-handler aggregate."""
        from perf_gate import extract_counts, gate
        registry_shaped = {"counts": {
            "handler_calls_total;handler=on_block;status=accept": 60,
            "handler_calls_total;handler=on_block;status=reject": 8}}
        assert extract_counts(registry_shaped)[
            "handler_calls_total;handler=on_block"] == 68
        report = {"handlers": {"on_block": {"count": 68}}}
        assert gate(registry_shaped, report, 1.25, 4.0) == 0
        inflated = {"handlers": {"on_block": {"count": 204}}}
        assert gate(registry_shaped, inflated, 1.25, 4.0) == 1

    def test_disjoint_count_namespaces_refuse_to_gate(self):
        """A bench emission vs a run report share no count keys: that is
        an incomparable pair (exit 2), NOT a vacuous pass — a real
        regression must not ship behind a namespace mismatch."""
        from perf_gate import gate
        bench = self._bench_emission(8)
        report = {"handlers": {"on_block": {"count": 68}}}
        assert gate(bench, report, 1.25, 4.0) == 2

    def test_timing_report_only_unless_strict(self, tmp_path):
        from perf_gate import gate
        base = {"config2": {"ms": 10.0}, "telemetry": {"counts": {"c": 1}}}
        cand = {"config2": {"ms": 100.0}, "telemetry": {"counts": {"c": 1}}}
        assert gate(base, cand, 1.25, 4.0, count_only=False) == 0
        assert gate(base, cand, 1.25, 4.0, count_only=False,
                    strict_timing=True) == 1

    def test_missing_baseline_is_usage_error(self, tmp_path):
        from perf_gate import main
        cand = tmp_path / "cand.json"
        cand.write_text("{}")
        assert main(["--candidate", str(cand),
                     "--baseline", str(tmp_path / "nope.json")]) == 2
