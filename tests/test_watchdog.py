"""Watchdog tests (utils/watchdog.py): supervised steps, bounded retries,
timeouts, and commit-on-arrival partial results surviving step death."""

import json
import time

import pytest

from pos_evolution_tpu.utils.watchdog import (
    Watchdog,
    WatchdogTimeout,
    _call_with_timeout,
)


class TestSteps:
    def test_success_records_and_returns(self, tmp_path):
        p = str(tmp_path / "wd.json")
        wd = Watchdog(path=p, tag="t")
        assert wd.step("add", lambda a, b: a + b, 2, 3) == 5
        on_disk = json.load(open(p))
        assert on_disk["completed"]["add"] == 5
        assert on_disk["incidents"] == []
        assert on_disk["tag"] == "t"

    def test_failure_records_incident_and_returns_default(self, tmp_path):
        p = str(tmp_path / "wd.json")
        wd = Watchdog(path=p)

        def boom():
            raise ValueError("kaput")

        assert wd.step("bad", boom, default="fallback") == "fallback"
        assert wd.failed("bad")
        on_disk = json.load(open(p))
        assert "bad" not in on_disk["completed"]
        assert on_disk["incidents"][0]["step"] == "bad"
        assert "kaput" in on_disk["incidents"][0]["error"]

    def test_retries_with_backoff_then_succeeds(self):
        wd = Watchdog(backoff_s=0.01)
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise RuntimeError("transient")
            return "ok"

        assert wd.step("flaky", flaky, retries=3) == "ok"
        assert len(calls) == 3
        assert len(wd.incidents) == 2          # the two failed attempts
        assert not wd.failed("flaky")

    def test_commit_on_arrival_survives_later_death(self, tmp_path):
        """The round-5 failure mode: step N dies after steps 1..N-1
        completed — their results must already be on disk."""
        p = str(tmp_path / "wd.json")
        wd = Watchdog(path=p)
        wd.step("chunk_0", lambda: 11)
        wd.step("chunk_1", lambda: 22)
        with pytest.raises(KeyboardInterrupt):
            # simulated kill: escapes step() entirely, no commit happens
            wd.step("chunk_2", _raise_interrupt)
        on_disk = json.load(open(p))
        assert on_disk["completed"] == {"chunk_0": 11, "chunk_1": 22}

    def test_atomic_commit_never_leaves_partial_file(self, tmp_path):
        p = str(tmp_path / "wd.json")
        wd = Watchdog(path=p)
        for i in range(20):
            wd.step(f"s{i}", lambda i=i: i)
            json.load(open(p))                 # parseable after every commit


def _raise_interrupt():
    raise KeyboardInterrupt


class TestTimeout:
    def test_timeout_raises_and_is_recorded(self):
        wd = Watchdog(timeout_s=0.2)
        t0 = time.time()
        out = wd.step("sleepy", time.sleep, 30, default="dead")
        assert out == "dead"
        assert time.time() - t0 < 5
        assert "WatchdogTimeout" in wd.incidents[0]["error"]

    def test_timeout_cleared_after_step(self):
        wd = Watchdog(timeout_s=0.2)
        wd.step("sleepy", time.sleep, 30)
        # a later slow-but-under-budget step must not inherit the alarm
        assert wd.step("fine", lambda: time.sleep(0.05) or "ok",
                       timeout_s=10) == "ok"

    def test_no_timeout_passthrough(self):
        assert _call_with_timeout(lambda: 7, (), {}, None) == 7

    def test_nested_watchdogs_defer_to_outer_timer(self):
        """A nested Watchdog (bench_all's config3b step runs a script
        with its own) must neither clobber the outer SIGALRM timer nor
        swallow the outer timeout as an inner incident."""
        outer = Watchdog(timeout_s=0.3)
        inner = Watchdog(timeout_s=60)       # would mask outer if armed

        def outer_step():
            # inner step sleeps past the OUTER budget; the timeout must
            # surface as the OUTER step's incident, not the inner's
            return inner.step("inner", time.sleep, 30, default="inner-dead")

        assert outer.step("outer", outer_step, default="outer-dead") == \
            "outer-dead"
        assert [i["step"] for i in outer.incidents] == ["outer"]
        assert inner.incidents == []

    def test_timeout_exception_type(self):
        with pytest.raises(WatchdogTimeout):
            _call_with_timeout(time.sleep, (30,), {}, 0.1)
