"""Differential tests: JAX/XLA kernels vs the NumPy/spec oracle
(SURVEY.md §4.4b: identical inputs must give bit-identical outputs).
"""

import numpy as np
import pytest

from pos_evolution_tpu.config import minimal_config, use_config

jax = pytest.importorskip("jax")


class TestSha256Device:
    def test_single_block_matches_hashlib(self):
        import hashlib
        from pos_evolution_tpu.ops.sha256 import sha256_words, words_to_digest
        msg = b"\xab" * 37
        padded = bytearray(64)
        padded[:37] = msg
        padded[37] = 0x80
        padded[62:64] = (37 * 8).to_bytes(2, "big")
        words = np.frombuffer(bytes(padded), dtype=">u4").astype(np.uint32)
        out = sha256_words(jax.numpy.asarray(words[None, :]))
        assert words_to_digest(np.asarray(out)[0]) == hashlib.sha256(msg).digest()

    def test_pair_words_matches_merkle_combiner(self):
        import hashlib
        from pos_evolution_tpu.ops.sha256 import sha256_pair_words, words_to_digest
        left = np.frombuffer(b"\x01" * 32, dtype=">u4").astype(np.uint32)
        right = np.frombuffer(b"\x02" * 32, dtype=">u4").astype(np.uint32)
        out = sha256_pair_words(jax.numpy.asarray(left[None]),
                                jax.numpy.asarray(right[None]))
        assert words_to_digest(np.asarray(out)[0]) == \
            hashlib.sha256(b"\x01" * 32 + b"\x02" * 32).digest()


class TestShuffleDevice:
    @pytest.mark.parametrize("n,rounds", [(64, 10), (100, 90), (2048, 90)])
    def test_matches_numpy_backend(self, n, rounds):
        from pos_evolution_tpu.backend.numpy_backend import shuffle_permutation
        from pos_evolution_tpu.ops.shuffle import shuffle_permutation_jax
        seed = bytes(range(32))
        got = np.asarray(shuffle_permutation_jax(seed, n, rounds)).astype(np.uint64)
        want = shuffle_permutation(seed, n, rounds)
        assert np.array_equal(got, want)

    def test_matches_scalar_spec(self, minimal_cfg):
        from pos_evolution_tpu.ops.shuffle import shuffle_permutation_jax
        from pos_evolution_tpu.specs.helpers import compute_shuffled_index
        seed = b"\x5a" * 32
        got = np.asarray(shuffle_permutation_jax(seed, 64, minimal_cfg.shuffle_round_count))
        want = [compute_shuffled_index(i, 64, seed) for i in range(64)]
        assert got.tolist() == want

    def test_is_permutation(self):
        from pos_evolution_tpu.ops.shuffle import shuffle_permutation_jax
        got = np.asarray(shuffle_permutation_jax(b"\x07" * 32, 1000, 90))
        assert sorted(got.tolist()) == list(range(1000))


def _random_dense_state(n=128, seed=0, epoch=9):
    """A spec BeaconState with adversarially varied registry columns."""
    from pos_evolution_tpu.specs.genesis import make_genesis
    rng = np.random.default_rng(seed)
    state, _ = make_genesis(n)
    gwei = 10**9
    state.slot = (epoch + 1) * minimal_config().slots_per_epoch - 1
    reg = state.validators
    state.balances = rng.integers(16 * gwei, 40 * gwei, n).astype(np.uint64)
    reg.effective_balance = (np.minimum(state.balances // gwei, 32) * gwei).astype(np.uint64)
    reg.slashed = rng.random(n) < 0.05
    # a few exited / not-yet-active validators
    reg.exit_epoch[rng.random(n) < 0.05] = epoch - 1
    reg.activation_epoch[rng.random(n) < 0.05] = epoch + 2
    # slashed validators about to hit the proportional penalty sweep
    half = minimal_config().epochs_per_slashings_vector // 2
    sweep = rng.random(n) < 0.03
    reg.slashed |= sweep
    reg.withdrawable_epoch[sweep] = epoch + half
    state.previous_epoch_participation = rng.integers(0, 8, n).astype(np.uint8)
    state.current_epoch_participation = rng.integers(0, 8, n).astype(np.uint8)
    state.inactivity_scores = rng.integers(0, 50, n).astype(np.uint64)
    state.justification_bits = rng.random(4) < 0.5
    state.slashings[:] = rng.integers(0, 64 * gwei, state.slashings.shape[0])
    from pos_evolution_tpu.specs.containers import Checkpoint
    state.previous_justified_checkpoint = Checkpoint(epoch=epoch - 2, root=b"\x02" * 32)
    state.current_justified_checkpoint = Checkpoint(epoch=epoch - 1, root=b"\x01" * 32)
    state.finalized_checkpoint = Checkpoint(epoch=epoch - 3, root=b"\x03" * 32)
    state.block_roots = rng.integers(0, 255, state.block_roots.shape).astype(np.uint8)
    return state


class TestDenseEpochDifferential:
    """process_epoch_dense must be bit-identical to the spec pipeline."""

    @pytest.mark.parametrize("seed", [0, 1, 2, 3])
    def test_matches_spec_pipeline(self, minimal_cfg, seed):
        from pos_evolution_tpu.ops.epoch import densify, process_epoch_dense
        from pos_evolution_tpu.specs import epoch as spec_epoch
        from pos_evolution_tpu.specs.helpers import get_current_epoch

        state = _random_dense_state(n=128, seed=seed)
        dense = densify(state)
        current_epoch = get_current_epoch(state)
        bits_before = state.justification_bits.copy()
        prev_j = int(state.previous_justified_checkpoint.epoch)
        cur_j = int(state.current_justified_checkpoint.epoch)
        fin_before = int(state.finalized_checkpoint.epoch)
        slashings_sum = int(state.slashings.sum())

        # --- spec pipeline (mutates the state) ---
        spec_epoch.process_justification_and_finalization(state)
        spec_epoch.process_inactivity_updates(state)
        spec_epoch.process_rewards_and_penalties(state)
        spec_epoch.process_slashings(state)
        spec_epoch.process_effective_balance_updates(state)
        spec_epoch.process_participation_flag_updates(state)

        # --- dense kernel ---
        out = process_epoch_dense(dense, current_epoch, fin_before,
                                  jax.numpy.asarray(bits_before),
                                  prev_j, cur_j, slashings_sum, minimal_cfg)
        reg = out.registry

        assert np.array_equal(np.asarray(reg.balance),
                              state.balances.astype(np.int64)), "balances diverge"
        assert np.array_equal(np.asarray(reg.effective_balance),
                              state.validators.effective_balance.astype(np.int64))
        assert np.array_equal(np.asarray(reg.inactivity_scores),
                              state.inactivity_scores.astype(np.int64))
        assert np.array_equal(np.asarray(reg.prev_flags),
                              state.previous_epoch_participation)
        assert np.array_equal(np.asarray(out.new_justification_bits),
                              state.justification_bits)
        fin = int(out.finalize_epoch)
        expect_fin = int(state.finalized_checkpoint.epoch)
        if fin >= 0:
            assert fin == expect_fin
        else:
            assert expect_fin == fin_before

    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_registry_churn_matches_spec(self, minimal_cfg, seed):
        """Device churn (eligibility/ejection/dequeue) must be bit-identical
        to the spec's sequential process_registry_updates loop."""
        from pos_evolution_tpu.ops.epoch import (densify, densify_eligibility,
                                                 registry_churn_dense)
        from pos_evolution_tpu.specs.epoch import process_registry_updates
        from pos_evolution_tpu.specs.genesis import make_genesis
        from pos_evolution_tpu.specs.containers import Checkpoint

        rng = np.random.default_rng(seed)
        n = 96
        state, _ = make_genesis(n)
        c = minimal_cfg
        reg = state.validators
        # ejectable validators (low effective balance)
        reg.effective_balance[rng.random(n) < 0.15] = c.ejection_balance
        # fresh deposits waiting for eligibility marking
        fresh = rng.random(n) < 0.1
        reg.activation_eligibility_epoch[fresh] = 2**64 - 1
        reg.activation_epoch[fresh] = 2**64 - 1
        # a queue of validators already eligible, awaiting activation
        queued = rng.random(n) < 0.2
        reg.activation_eligibility_epoch[queued] = rng.integers(1, 4, queued.sum())
        reg.activation_epoch[queued] = 2**64 - 1
        # some validators already exiting (occupying the exit queue)
        exiting = rng.random(n) < 0.1
        reg.exit_epoch[exiting] = rng.integers(12, 15, exiting.sum())
        state.slot = 10 * c.slots_per_epoch - 1
        state.finalized_checkpoint = Checkpoint(epoch=5, root=b"\x05" * 32)

        dense = densify(state)
        elig = densify_eligibility(state)
        out = registry_churn_dense(dense, elig, 9, 5, c)
        process_registry_updates(state)

        def far_to_sentinel(a):
            a = a.astype(np.uint64)
            return np.where(a == np.uint64(2**64 - 1), np.uint64(2**62),
                            a).astype(np.int64)

        for field, col in (("activation_eligibility_epoch", out.activation_eligibility_epoch),
                           ("activation_epoch", out.activation_epoch),
                           ("exit_epoch", out.exit_epoch),
                           ("withdrawable_epoch", out.withdrawable_epoch)):
            want = far_to_sentinel(getattr(state.validators, field))
            got = np.asarray(col)
            assert np.array_equal(got, want), \
                f"{field} diverges (seed {seed}): {got[:12]} vs {want[:12]}"

    def test_justification_thresholds(self, minimal_cfg):
        """2/3 boundary must behave identically at the exact threshold."""
        from pos_evolution_tpu.ops.epoch import densify, process_epoch_dense
        state = _random_dense_state(n=60, seed=7)
        gwei = 10**9
        # all active, equal balances; exactly 40/60 target-participating
        reg = state.validators
        reg.slashed[:] = False
        reg.exit_epoch[:] = 2**64 - 1
        reg.activation_epoch[:] = 0
        reg.effective_balance[:] = 32 * gwei
        state.balances[:] = 32 * gwei
        state.previous_epoch_participation[:] = 0
        state.previous_epoch_participation[:40] = 0b010  # timely target
        state.current_epoch_participation[:] = 0
        dense = densify(state)
        out = process_epoch_dense(dense, 9, 6,
                                  jax.numpy.asarray(np.zeros(4, dtype=bool)),
                                  7, 8, 0, minimal_cfg)
        assert bool(out.justify_prev)   # 40*3 >= 60*2
        out2 = process_epoch_dense(
            dense._replace(prev_flags=dense.prev_flags.at[39].set(0)),
            9, 6, jax.numpy.asarray(np.zeros(4, dtype=bool)), 7, 8, 0, minimal_cfg)
        assert not bool(out2.justify_prev)  # 39*3 < 60*2
