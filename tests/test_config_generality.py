"""Config generality: the protocol machinery must not hard-code either
preset — chains run and finalize under varied slot/committee/shuffle
parameters (the reference's constants are knobs, SURVEY.md §5 config).
"""

import numpy as np
import pytest

from pos_evolution_tpu.config import minimal_config, use_config
from pos_evolution_tpu.ssz import hash_tree_root


VARIANTS = {
    "wide-slots": dict(slots_per_epoch=4, target_committee_size=8,
                       max_committees_per_slot=2),
    "many-rounds": dict(shuffle_round_count=30),
    "small-history": dict(slots_per_historical_root=32,
                          epochs_per_historical_vector=32,
                          epochs_per_slashings_vector=32),
    "odd-boost": dict(proposer_score_boost_percent=33,
                      safe_slots_to_update_justified=1),
}


@pytest.mark.parametrize("name", sorted(VARIANTS))
def test_chain_finalizes_under_config_variant(name):
    cfg = minimal_config().replace(name=name, **VARIANTS[name])
    with use_config(cfg):
        from pos_evolution_tpu.sim import Simulation
        sim = Simulation(48)
        sim.run_epochs(5)
        m = sim.metrics[-1]
        assert m["head_slot"] == 5 * cfg.slots_per_epoch
        assert m["justified_epoch"] >= 3, (name, m)
        assert m["finalized_epoch"] >= 2, (name, m)


@pytest.mark.parametrize("name", sorted(VARIANTS))
def test_backends_agree_under_config_variant(name):
    jax = pytest.importorskip("jax")
    cfg = minimal_config().replace(name=name, **VARIANTS[name])
    with use_config(cfg):
        from pos_evolution_tpu.backend import set_backend
        from pos_evolution_tpu.specs.genesis import make_genesis
        from pos_evolution_tpu.specs.transition import state_transition
        from pos_evolution_tpu.specs.validator import (
            attest_all_committees, build_block,
        )

        def run(backend):
            set_backend(backend)
            try:
                state, _ = make_genesis(48)
                atts = []
                for slot in range(1, 3 * cfg.slots_per_epoch + 1):
                    sb = build_block(state, slot, attestations=atts)
                    state_transition(state, sb, True)
                    atts = attest_all_committees(
                        state, slot, hash_tree_root(sb.message))
                return hash_tree_root(state)
            finally:
                set_backend("numpy")

        assert run("numpy") == run("jax"), name
