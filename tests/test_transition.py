"""State-transition tests (L2): executable-spec unit tests per SURVEY.md §4.1.

Covers: genesis sanity, empty/attesting block transitions, the honest chain
reaching justification + finalization (the SURVEY.md §7 step-2 exit
criterion), the 4-case finalization rule, hysteresis, deposits, slashings,
and the slashable-attestation truth table.
"""

import numpy as np
import pytest

from pos_evolution_tpu.config import minimal_config, use_config
from pos_evolution_tpu.specs.containers import (
    AttestationData, BeaconState, Checkpoint,
)
from pos_evolution_tpu.specs.deposits import build_deposit_data, build_deposit_tree
from pos_evolution_tpu.specs.genesis import make_genesis, validator_secret_key
from pos_evolution_tpu.specs.helpers import (
    get_current_epoch,
    is_slashable_attestation_data,
)
from pos_evolution_tpu.specs.epoch import (
    process_effective_balance_updates,
    weigh_justification_and_finalization,
)
from pos_evolution_tpu.specs.transition import process_deposit, state_transition
from pos_evolution_tpu.specs.validator import attest_all_committees, build_block
from pos_evolution_tpu.ssz import hash_tree_root

pytestmark = pytest.mark.usefixtures("minimal_cfg")


@pytest.fixture(scope="module")
def chain():
    """Run a 6-epoch honest chain once; several tests inspect it."""
    with use_config(minimal_config()) as c:
        state, anchor = make_genesis(64)
        genesis_root = hash_tree_root(state)
        atts = []
        snapshots = {}
        for slot in range(1, 6 * c.slots_per_epoch + 1):
            sb = build_block(state, slot, attestations=atts)
            state_transition(state, sb, True)
            atts = attest_all_committees(state, slot, hash_tree_root(sb.message))
            if slot % c.slots_per_epoch == 0:
                snapshots[slot // c.slots_per_epoch] = (
                    int(state.current_justified_checkpoint.epoch),
                    int(state.finalized_checkpoint.epoch),
                )
        return {"state": state, "snapshots": snapshots, "genesis_root": genesis_root}


class TestGenesis:
    def test_genesis_active_set(self):
        state, anchor = make_genesis(64)
        assert len(state.validators) == 64
        assert (state.validators.activation_epoch == 0).all()
        assert bytes(anchor.state_root) == hash_tree_root(state)

    def test_genesis_root_deterministic(self):
        s1, _ = make_genesis(32)
        s2, _ = make_genesis(32)
        assert hash_tree_root(s1) == hash_tree_root(s2)


class TestChainProgress:
    def test_empty_block_applies(self):
        state, _ = make_genesis(64)
        sb = build_block(state, 1)
        state_transition(state, sb, True)
        assert int(state.slot) == 1

    def test_chain_justifies_and_finalizes(self, chain):
        snaps = chain["snapshots"]
        # First possible justification is epoch 2; after that it should track
        # current-1, and finalization should trail justification by one.
        assert snaps[3][0] >= 2, f"no justification by epoch 3: {snaps}"
        assert snaps[4][1] >= 2, f"no finalization by epoch 4: {snaps}"
        assert snaps[6] == (5, 4), f"steady-state j/f wrong: {snaps[6]}"

    def test_wrong_state_root_rejected(self):
        state, _ = make_genesis(64)
        sb = build_block(state, 1)
        sb.message.state_root = b"\x42" * 32
        with pytest.raises(AssertionError):
            state_transition(state.copy(), sb, True)

    def test_bad_signature_rejected(self):
        state, _ = make_genesis(64)
        sb = build_block(state, 1)
        sb.signature = b"\x99" * 96
        with pytest.raises(AssertionError):
            state_transition(state.copy(), sb, True)


def _stub_state_for_weigh(epoch: int, bits) -> BeaconState:
    """Minimal state to drive weigh_justification_and_finalization."""
    state, _ = make_genesis(16)
    # epoch processing runs at the last slot of the epoch (pos-evolution.md:415)
    state.slot = (epoch + 1) * minimal_config().slots_per_epoch - 1
    rng = np.random.default_rng(epoch)
    state.block_roots = rng.integers(0, 255, size=state.block_roots.shape).astype(np.uint8)
    state.justification_bits = np.array(bits, dtype=bool)
    return state


class TestFinalizationCases:
    """The 4-case 2-finalization rule (pos-evolution.md:842-851)."""

    def test_case_rule_234_with_4th_source(self):
        # bits are shifted inside weigh_...: pre [1,1,1,0] -> post [_,1,1,1]
        state = _stub_state_for_weigh(10, [1, 1, 1, 0])
        old_prev = Checkpoint(epoch=7, root=b"\x07" * 32)
        state.previous_justified_checkpoint = old_prev
        state.current_justified_checkpoint = Checkpoint(epoch=8, root=b"\x08" * 32)
        # no new justification this epoch (balances below 2/3)
        weigh_justification_and_finalization(state, 90, 10, 10)
        assert state.finalized_checkpoint == old_prev

    def test_case_rule_12_current_source(self):
        state = _stub_state_for_weigh(10, [1, 1, 0, 0])
        cur = Checkpoint(epoch=9, root=b"\x09" * 32)
        state.previous_justified_checkpoint = Checkpoint(epoch=8, root=b"\x08" * 32)
        state.current_justified_checkpoint = cur
        # current epoch justifies: bits[0] set
        weigh_justification_and_finalization(state, 90, 10, 90)
        assert state.finalized_checkpoint == cur

    def test_no_finalization_on_gap(self):
        state = _stub_state_for_weigh(10, [0, 0, 0, 0])
        state.previous_justified_checkpoint = Checkpoint(epoch=3, root=b"\x03" * 32)
        state.current_justified_checkpoint = Checkpoint(epoch=4, root=b"\x04" * 32)
        pre_final = state.finalized_checkpoint.copy()
        weigh_justification_and_finalization(state, 90, 10, 10)
        assert state.finalized_checkpoint == pre_final

    def test_justification_threshold_is_two_thirds(self):
        state = _stub_state_for_weigh(10, [0, 0, 0, 0])
        pre = state.current_justified_checkpoint.copy()
        # exactly below 2/3: 59/90 < 2/3
        weigh_justification_and_finalization(state, 90, 59, 59)
        assert state.current_justified_checkpoint == pre
        # exactly 2/3: 60*3 >= 90*2 justifies previous epoch
        state2 = _stub_state_for_weigh(10, [0, 0, 0, 0])
        weigh_justification_and_finalization(state2, 90, 60, 0)
        assert int(state2.current_justified_checkpoint.epoch) == 9
        assert state2.justification_bits[1]


class TestHysteresis:
    """pos-evolution.md:114-133: ±0.25/+1.25 ETH thresholds."""

    def test_small_dip_does_not_update(self):
        state, _ = make_genesis(8)
        gwei = 10**9
        state.balances[0] = 32 * gwei - gwei // 4  # dip 0.25, not below threshold
        process_effective_balance_updates(state)
        assert int(state.validators.effective_balance[0]) == 32 * gwei

    def test_big_dip_updates_down(self):
        state, _ = make_genesis(8)
        gwei = 10**9
        state.balances[0] = 31 * gwei  # 32 - 1.0 < 32 - 0.25 threshold
        process_effective_balance_updates(state)
        assert int(state.validators.effective_balance[0]) == 31 * gwei

    def test_upward_requires_crossing(self):
        state, _ = make_genesis(8)
        gwei = 10**9
        state.validators.effective_balance[0] = 30 * gwei
        state.balances[0] = 31 * gwei  # +1.0 ETH, below the +1.25 threshold
        process_effective_balance_updates(state)
        assert int(state.validators.effective_balance[0]) == 30 * gwei
        state.balances[0] = 31 * gwei + gwei // 2  # +1.5 crosses
        process_effective_balance_updates(state)
        assert int(state.validators.effective_balance[0]) == 31 * gwei


class TestDeposits:
    def test_new_validator_deposit(self):
        state, _ = make_genesis(8)
        gwei = 10**9
        data = build_deposit_data(sk=1000, withdrawal_credentials=b"\x00" * 32,
                                  amount=32 * gwei)
        root, deposits = build_deposit_tree([data])
        state.eth1_data.deposit_root = root
        state.eth1_data.deposit_count = 9
        state.eth1_deposit_index = 0
        # tree index 0 == state.eth1_deposit_index
        process_deposit(state, deposits[0])
        assert len(state.validators) == 9
        assert int(state.balances[-1]) == 32 * gwei
        assert state.validators[8].activation_epoch == 2**64 - 1  # not yet active

    def test_topup_existing_validator(self):
        state, _ = make_genesis(8)
        gwei = 10**9
        data = build_deposit_data(sk=validator_secret_key(3),
                                  withdrawal_credentials=b"\x00" * 32,
                                  amount=1 * gwei)
        root, deposits = build_deposit_tree([data])
        state.eth1_data.deposit_root = root
        state.eth1_deposit_index = 0
        before = int(state.balances[3])
        process_deposit(state, deposits[0])
        assert len(state.validators) == 8
        assert int(state.balances[3]) == before + gwei

    def test_invalid_proof_rejected(self):
        state, _ = make_genesis(8)
        data = build_deposit_data(sk=1000, withdrawal_credentials=b"\x00" * 32,
                                  amount=32 * 10**9)
        root, deposits = build_deposit_tree([data])
        state.eth1_data.deposit_root = b"\xaa" * 32
        state.eth1_deposit_index = 0
        with pytest.raises(AssertionError):
            process_deposit(state, deposits[0])


class TestSlashableAttestationData:
    """Truth table for pos-evolution.md:1134-1143."""

    def _data(self, source_epoch, target_epoch, tag=0):
        return AttestationData(
            slot=0, index=tag,
            beacon_block_root=bytes([tag]) * 32,
            source=Checkpoint(epoch=source_epoch, root=b"\x01" * 32),
            target=Checkpoint(epoch=target_epoch, root=bytes([tag + 1]) * 32),
        )

    def test_double_vote(self):
        d1 = self._data(2, 5, tag=0)
        d2 = self._data(2, 5, tag=7)
        assert is_slashable_attestation_data(d1, d2)

    def test_surround_vote(self):
        outer = self._data(1, 6)
        inner = self._data(2, 5, tag=3)
        assert is_slashable_attestation_data(outer, inner)
        assert not is_slashable_attestation_data(inner, outer)

    def test_identical_not_slashable(self):
        d = self._data(2, 5)
        assert not is_slashable_attestation_data(d, d.copy())

    def test_disjoint_not_slashable(self):
        d1 = self._data(2, 3)
        d2 = self._data(3, 4, tag=5)
        assert not is_slashable_attestation_data(d1, d2)
