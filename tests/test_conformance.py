"""Conformance tests against INDEPENDENT implementations and external
anchors — breaking the self-generated golden-vector circularity
(pos-evolution.md:9-11: the pyspec's whole testing story is producing
vectors checked by independent implementations).

This environment has no network egress, so the real
``ethereum/consensus-specs`` vector tarballs are unreachable; the
strongest available substitutes, used here:

1. **hashlib** (OpenSSL) as a genuinely external SHA-256 implementation:
   the zero-hash chain and merkle trees are recomputed from raw hashlib
   calls, never through the package's hashing layer.
2. **From-spec reimplementations written in this file**: a standalone
   SSZ merkleizer/serializer built directly from the SSZ spec rules, and
   the swap-or-not shuffle transcribed from the reference document's own
   pyspec listing (pos-evolution.md:513-535), both deliberately
   structured differently from the package code they check.
3. **Externally standardized BLS12-381 constants and algebra**: the
   IETF/ZCash curve parameters (q, r, generators) are spec constants;
   conformance asserts the mathematical properties every correct
   implementation must satisfy (generators on curve and of order r,
   pairing bilinearity and non-degeneracy) rather than trusting any
   in-repo implementation.
"""

import hashlib

import numpy as np
import pytest

from pos_evolution_tpu.specs import containers as C
from pos_evolution_tpu.specs.helpers import compute_shuffled_index
from pos_evolution_tpu.ssz import (
    Bitlist,
    ZERO_HASHES,
    hash_tree_root,
    serialize,
    uint64,
)
from pos_evolution_tpu.ssz import List as SSZList


# --- independent SSZ implementation (from the SSZ spec rules) -----------------


def _h(a: bytes, b: bytes) -> bytes:
    return hashlib.sha256(a + b).digest()


def _merkleize(chunks: list, limit: int | None = None) -> bytes:
    """Binary merkle tree over 32-byte chunks, zero-chunk padded to the
    next power of two of ``limit`` (or chunk count)."""
    count = max(len(chunks), 1)
    width = 1
    while width < (limit if limit is not None else count):
        width *= 2
    padded = chunks + [b"\x00" * 32] * (width - len(chunks))
    while len(padded) > 1:
        padded = [_h(padded[i], padded[i + 1]) for i in range(0, len(padded), 2)]
    return padded[0]


def _pack(data: bytes) -> list:
    if not data:
        return []
    if len(data) % 32:
        data = data + b"\x00" * (32 - len(data) % 32)
    return [data[i:i + 32] for i in range(0, len(data), 32)]


def _mix_len(root: bytes, n: int) -> bytes:
    return _h(root, n.to_bytes(32, "little"))


def _htr_uint64(v: int) -> bytes:
    return v.to_bytes(8, "little") + b"\x00" * 24


def _htr_bool(v: bool) -> bytes:
    return bytes([1 if v else 0]) + b"\x00" * 31


def _htr_bytes(v: bytes) -> bytes:
    return _merkleize(_pack(v), (len(v) + 31) // 32)


def _htr_checkpoint(epoch: int, root: bytes) -> bytes:
    return _merkleize([_htr_uint64(epoch), _htr_bytes(root)])


class TestSSZAgainstIndependentImpl:
    def test_zero_hash_chain_vs_hashlib(self):
        z = b"\x00" * 32
        for level in range(len(ZERO_HASHES)):
            assert bytes(ZERO_HASHES[level]) == z
            z = hashlib.sha256(z + z).digest()

    def test_uint64_and_bool(self):
        for v in (0, 1, 2**64 - 1, 0xDEADBEEF):
            assert hash_tree_root(v, uint64) == _htr_uint64(v)

    def test_checkpoint(self):
        cp = C.Checkpoint(epoch=7, root=b"\x42" * 32)
        assert hash_tree_root(cp) == _htr_checkpoint(7, b"\x42" * 32)
        # fixed-size container serialization = field concatenation
        assert serialize(cp) == (7).to_bytes(8, "little") + b"\x42" * 32

    def test_attestation_data(self):
        ad = C.AttestationData(
            slot=3, index=5, beacon_block_root=b"\x01" * 32,
            source=C.Checkpoint(epoch=1, root=b"\x02" * 32),
            target=C.Checkpoint(epoch=2, root=b"\x03" * 32))
        want = _merkleize([
            _htr_uint64(3), _htr_uint64(5), _htr_bytes(b"\x01" * 32),
            _htr_checkpoint(1, b"\x02" * 32), _htr_checkpoint(2, b"\x03" * 32),
        ])
        assert hash_tree_root(ad) == want

    def test_validator_container(self):
        v = C.Validator(
            pubkey=b"\xaa" * 48, withdrawal_credentials=b"\xbb" * 32,
            effective_balance=32 * 10**9, slashed=True,
            activation_eligibility_epoch=1, activation_epoch=2,
            exit_epoch=3, withdrawable_epoch=4)
        want = _merkleize([
            _htr_bytes(b"\xaa" * 48), _htr_bytes(b"\xbb" * 32),
            _htr_uint64(32 * 10**9), _htr_bool(True),
            _htr_uint64(1), _htr_uint64(2), _htr_uint64(3), _htr_uint64(4),
        ])
        assert hash_tree_root(v) == want

    def test_list_of_uint64(self):
        limit = 100
        values = [5, 6, 7]
        sed = SSZList(uint64, limit)
        packed = _pack(b"".join(v.to_bytes(8, "little") for v in values))
        want = _mix_len(_merkleize(packed, (limit * 8 + 31) // 32), len(values))
        assert hash_tree_root(values, sed) == want

    def test_bitlist(self):
        limit = 40
        bits = [True, False, True, True, False, False, True, False, True]
        sed = Bitlist(limit)
        byts = bytearray((len(bits) + 7) // 8)
        for i, b in enumerate(bits):
            if b:
                byts[i // 8] |= 1 << (i % 8)
        want = _mix_len(_merkleize(_pack(bytes(byts)), (limit + 255) // 256),
                        len(bits))
        assert hash_tree_root(bits, sed) == want
        # serialization appends the length-delimiter bit
        ser = bytearray(byts)
        ser[len(bits) // 8] |= 1 << (len(bits) % 8)
        assert serialize(bits, sed) == bytes(ser)


# --- shuffle transcribed from the reference listing ---------------------------


def _shuffle_from_reference(index: int, index_count: int, seed: bytes,
                            rounds: int) -> int:
    """Verbatim transcription of pos-evolution.md:511-533."""
    assert index < index_count
    for current_round in range(rounds):
        pivot = int.from_bytes(
            hashlib.sha256(seed + bytes([current_round])).digest()[0:8],
            "little") % index_count
        flip = (pivot + index_count - index) % index_count
        position = max(index, flip)
        source = hashlib.sha256(
            seed + bytes([current_round])
            + (position // 256).to_bytes(4, "little")).digest()
        byte = source[(position % 256) // 8]
        bit = (byte >> (position % 8)) % 2
        index = flip if bit else index
    return index


class TestShuffleAgainstReferenceListing:
    def test_scalar_helper_matches(self, minimal_cfg):
        seed = hashlib.sha256(b"conformance-seed").digest()
        n = 173
        rounds = minimal_cfg.shuffle_round_count
        got = [int(compute_shuffled_index(i, n, seed)) for i in range(n)]
        want = [_shuffle_from_reference(i, n, seed, rounds) for i in range(n)]
        assert got == want
        assert sorted(got) == list(range(n))  # it is a permutation

    def test_device_permutation_matches(self, minimal_cfg):
        from pos_evolution_tpu.ops.shuffle import shuffle_permutation_jax
        seed = hashlib.sha256(b"device-conformance").digest()
        n = 128
        rounds = minimal_cfg.shuffle_round_count
        perm = np.asarray(shuffle_permutation_jax(seed, n, rounds))
        want = [_shuffle_from_reference(i, n, seed, rounds) for i in range(n)]
        assert perm.tolist() == want


# --- BLS12-381 against the external standard ----------------------------------


class TestBLSAgainstStandard:
    """The curve parameters are fixed by the external standard (ZCash
    BLS12-381 / IETF ciphersuites); any correct implementation must
    reproduce these algebraic facts about them."""

    def test_field_and_group_orders(self):
        from pos_evolution_tpu.crypto import bls12_381 as b
        # q prime of 381 bits, r prime of 255 bits, r | q^12 - 1 (embedding
        # degree 12), and the curve orders: #E(Fq) = h1 * r
        assert b.Q.bit_length() == 381
        assert b.R.bit_length() == 255
        assert pow(2, b.Q - 1, b.Q) == 1 and pow(2, b.R - 1, b.R) == 1
        assert (b.Q**12 - 1) % b.R == 0
        for k in (1, 2, 3, 4, 6):
            assert (b.Q**k - 1) % b.R != 0, "embedding degree must be 12"
        # BLS parametrization: r = x^4 - x^2 + 1, q = (x-1)^2/3 * r + x
        x = -b.BLS_X
        assert b.R == x**4 - x**2 + 1
        assert b.Q == (x - 1)**2 // 3 * b.R + x

    def test_generators_on_curve_and_order(self):
        from pos_evolution_tpu.crypto import bls12_381 as b
        assert b.g1_on_curve(b.G1_GEN)
        assert b.g2_on_curve(b.G2_GEN)
        assert b.ec_mul(b.G1_GEN, b.R) is None
        assert b.ec_mul(b.G2_GEN, b.R) is None

    def test_pairing_bilinear_nondegenerate(self):
        from pos_evolution_tpu.crypto import bls12_381 as b
        a, c = 6, 11
        e_ab = b.pairing(b.ec_mul(b.G1_GEN, a), b.ec_mul(b.G2_GEN, c))
        e_11 = b.pairing(b.G1_GEN, b.G2_GEN)
        assert not e_11.is_one()          # non-degeneracy
        assert e_ab == e_11.pow(a * c)    # bilinearity
