"""Fork-choice tests (L4): store handlers, HLMD-GHOST head, boost,
equivocation discounting, handler atomicity (SURVEY.md §4.2).
"""

import copy

import numpy as np
import pytest

from pos_evolution_tpu.config import cfg
from pos_evolution_tpu.specs import forkchoice as fc
from pos_evolution_tpu.specs.containers import AttesterSlashing
from pos_evolution_tpu.specs.genesis import make_genesis
from pos_evolution_tpu.specs.helpers import get_indexed_attestation
from pos_evolution_tpu.specs.validator import (
    advance_state_to_slot,
    attest_all_committees,
    build_block,
    make_committee_attestation,
)
from pos_evolution_tpu.ssz import hash_tree_root

pytestmark = pytest.mark.usefixtures("minimal_cfg")


def tick_to_slot(store, slot, offset=0):
    fc.on_tick(store, store.genesis_time + slot * cfg().seconds_per_slot + offset)


def new_store(n_validators=64):
    state, anchor = make_genesis(n_validators)
    store = fc.get_forkchoice_store(state, anchor)
    return store, state, hash_tree_root(anchor)


class TestStoreInit:
    def test_init(self):
        store, state, anchor_root = new_store(16)
        assert anchor_root in store.blocks
        assert anchor_root in store.block_states
        assert int(store.justified_checkpoint.epoch) == 0
        assert fc.get_head(store) == anchor_root

    def test_anchor_mismatch_rejected(self):
        state, anchor = make_genesis(16)
        anchor.state_root = b"\x01" * 32
        with pytest.raises(AssertionError):
            fc.get_forkchoice_store(state, anchor)


class TestOnBlock:
    def test_chain_head_follows_blocks(self):
        store, state, anchor_root = new_store(32)
        parent_state = state
        for slot in range(1, 4):
            tick_to_slot(store, slot)
            sb = build_block(parent_state, slot)
            fc.on_block(store, sb)
            parent_state = store.block_states[hash_tree_root(sb.message)]
            assert fc.get_head(store) == hash_tree_root(sb.message)

    def test_future_block_rejected(self):
        store, state, _ = new_store(32)
        sb = build_block(state, 2)
        tick_to_slot(store, 1)
        with pytest.raises(AssertionError):
            fc.on_block(store, sb)

    def test_unknown_parent_rejected(self):
        store, state, _ = new_store(32)
        sb = build_block(state, 1)
        sb.message.parent_root = b"\x55" * 32
        tick_to_slot(store, 1)
        with pytest.raises(AssertionError):
            fc.on_block(store, sb)

    def test_atomicity_on_invalid_block(self):
        """pos-evolution.md:1041: failed handlers must not modify the store."""
        store, state, _ = new_store(32)
        tick_to_slot(store, 1)
        sb = build_block(state, 1)
        sb.signature = b"\x13" * 96  # breaks verify_block_signature mid-handler
        blocks_before = dict(store.blocks)
        lm_before = dict(store.latest_messages)
        jc_before = copy.deepcopy(store.justified_checkpoint)
        with pytest.raises(AssertionError):
            fc.on_block(store, sb)
        assert store.blocks == blocks_before
        assert store.latest_messages == lm_before
        assert store.justified_checkpoint == jc_before


class TestForksAndWeights:
    def _two_children(self, store, state):
        """Two competing blocks at slot 1; returns (root_a, root_b, states)."""
        tick_to_slot(store, 1)
        sb_a = build_block(state, 1, graffiti=b"\x0a" * 32)
        sb_b = build_block(state, 1, graffiti=b"\x0b" * 32)
        fc.on_block(store, sb_a)
        fc.on_block(store, sb_b)
        ra, rb = hash_tree_root(sb_a.message), hash_tree_root(sb_b.message)
        return ra, rb, store.block_states[ra], store.block_states[rb]

    def test_lexicographic_tiebreak_without_votes(self):
        store, state, _ = new_store(32)
        # avoid proposer boost deciding the tie: deliver after the interval
        tick_to_slot(store, 1, offset=cfg().seconds_per_slot // cfg().intervals_per_slot)
        sb_a = build_block(state, 1, graffiti=b"\x0a" * 32)
        sb_b = build_block(state, 1, graffiti=b"\x0b" * 32)
        fc.on_block(store, sb_a)
        fc.on_block(store, sb_b)
        ra, rb = hash_tree_root(sb_a.message), hash_tree_root(sb_b.message)
        assert fc.get_head(store) == max(ra, rb)

    def test_votes_decide_head(self):
        store, state, _ = new_store(64)
        tick_to_slot(store, 1, offset=cfg().seconds_per_slot)  # no boost
        sb_a = build_block(state, 1, graffiti=b"\x0a" * 32)
        sb_b = build_block(state, 1, graffiti=b"\x0b" * 32)
        fc.on_block(store, sb_a)
        fc.on_block(store, sb_b)
        ra, rb = hash_tree_root(sb_a.message), hash_tree_root(sb_b.message)
        loser, winner = sorted([ra, rb])
        # attest to the lexicographically-smaller block; votes must beat tie-break
        win_state = store.block_states[winner if winner == ra else ra]
        state_a = store.block_states[ra]
        att = make_committee_attestation(state_a if loser == ra else store.block_states[rb],
                                         1, 0, loser)
        tick_to_slot(store, 2)
        fc.on_attestation(store, att)
        assert fc.get_head(store) == loser

    def test_proposer_boost_sways_head(self):
        """Timely block gets W/4 committee weight (pos-evolution.md:1355)."""
        store, state, _ = new_store(64)
        tick_to_slot(store, 1, offset=cfg().seconds_per_slot)
        sb_a = build_block(state, 1, graffiti=b"\x0a" * 32)
        fc.on_block(store, sb_a)
        ra = hash_tree_root(sb_a.message)
        # competing block at slot 2 arrives timely -> gets boost
        tick_to_slot(store, 2, offset=0)
        sb_c = build_block(state, 2, graffiti=b"\x0c" * 32)
        fc.on_block(store, sb_c)
        rc = hash_tree_root(sb_c.message)
        assert store.proposer_boost_root == rc
        assert fc.get_head(store) == rc
        # boost resets on the next slot; without votes, tie-break decides
        tick_to_slot(store, 3)
        assert store.proposer_boost_root == b"\x00" * 32
        assert fc.get_head(store) == max(ra, rc)


class TestOnAttestation:
    def test_latest_messages_updated(self):
        store, state, _ = new_store(32)
        tick_to_slot(store, 1)
        sb = build_block(state, 1)
        fc.on_block(store, sb)
        root = hash_tree_root(sb.message)
        post = store.block_states[root]
        att = make_committee_attestation(post, 1, 0, root)
        tick_to_slot(store, 2)
        fc.on_attestation(store, att)
        idx = get_indexed_attestation(post, att)
        for i in np.asarray(idx.attesting_indices):
            assert store.latest_messages[int(i)].root == root

    def test_same_slot_attestation_rejected_off_wire(self):
        store, state, _ = new_store(32)
        tick_to_slot(store, 1)
        sb = build_block(state, 1)
        fc.on_block(store, sb)
        root = hash_tree_root(sb.message)
        att = make_committee_attestation(store.block_states[root], 1, 0, root)
        with pytest.raises(AssertionError):
            fc.on_attestation(store, att)  # current slot, not from block
        fc.on_attestation(store, att, is_from_block=True)  # allowed from block

    def test_bad_signature_rejected(self):
        store, state, _ = new_store(32)
        tick_to_slot(store, 1)
        sb = build_block(state, 1)
        fc.on_block(store, sb)
        root = hash_tree_root(sb.message)
        att = make_committee_attestation(store.block_states[root], 1, 0, root)
        att.signature = b"\x77" * 96
        tick_to_slot(store, 2)
        lm_before = dict(store.latest_messages)
        with pytest.raises(AssertionError):
            fc.on_attestation(store, att)
        assert store.latest_messages == lm_before


class TestEquivocationDiscounting:
    def test_slashing_removes_weight(self):
        """pos-evolution.md:1435-1461: equivocators lose fork-choice weight."""
        store, state, _ = new_store(64)
        tick_to_slot(store, 1, offset=cfg().seconds_per_slot)
        sb_a = build_block(state, 1, graffiti=b"\x0a" * 32)
        sb_b = build_block(state, 1, graffiti=b"\x0b" * 32)
        fc.on_block(store, sb_a)
        fc.on_block(store, sb_b)
        ra, rb = hash_tree_root(sb_a.message), hash_tree_root(sb_b.message)
        loser, winner = sorted([ra, rb])
        state_of = {ra: store.block_states[ra], rb: store.block_states[rb]}

        # Committee 0 votes for the smaller-root block -> it becomes head.
        att1 = make_committee_attestation(state_of[loser], 1, 0, loser)
        tick_to_slot(store, 2)
        fc.on_attestation(store, att1)
        assert fc.get_head(store) == loser

        # Same committee equivocates: also votes for the other fork.
        att2 = make_committee_attestation(state_of[winner], 1, 0, winner)
        idx1 = get_indexed_attestation(state_of[loser], att1)
        idx2 = get_indexed_attestation(state_of[winner], att2)
        slashing = AttesterSlashing(attestation_1=idx1, attestation_2=idx2)
        fc.on_attester_slashing(store, slashing)
        assert store.equivocating_indices == set(
            int(i) for i in np.asarray(idx1.attesting_indices))
        # Their weight is discounted -> tie-break decides again.
        assert fc.get_head(store) == winner

    def test_equivocators_never_rejoin_lmd(self):
        store, state, _ = new_store(64)
        tick_to_slot(store, 1)
        sb = build_block(state, 1)
        fc.on_block(store, sb)
        root = hash_tree_root(sb.message)
        post = store.block_states[root]
        store.equivocating_indices.add(5)
        att = make_committee_attestation(post, 1, 0, root)
        tick_to_slot(store, 2)
        fc.on_attestation(store, att)
        assert 5 not in store.latest_messages


class TestDeepChains:
    def test_filtered_tree_beyond_recursion_limit(self):
        """get_filtered_block_tree must survive chains far longer than
        Python's recursion limit (long-running simulations)."""
        from pos_evolution_tpu.specs.containers import BeaconBlock
        state, anchor = make_genesis(16)
        store = fc.get_forkchoice_store(state, anchor)
        anchor_root = hash_tree_root(anchor)
        # synthetic 5000-block chain: headers only; leaf viability needs a
        # state just for the tip
        parent = anchor_root
        leaf_state = store.block_states[anchor_root]
        for slot in range(1, 5001):
            blk = BeaconBlock(slot=slot, proposer_index=0, parent_root=parent,
                              state_root=bytes(8) + slot.to_bytes(8, "little") + bytes(16))
            root = hash_tree_root(blk)
            store.blocks[root] = blk
            parent = root
        store.block_states[parent] = leaf_state
        tree = fc.get_filtered_block_tree(store)
        assert len(tree) == 5001
        head = fc.get_head(store)
        assert int(store.blocks[head].slot) == 5000


class TestPruning:
    def test_prune_keeps_canonical_chain(self):
        from pos_evolution_tpu.sim import Simulation
        sim = Simulation(64)
        sim.run_epochs(5)
        store = sim.store()
        assert sim.finalized_epoch() >= 3
        head_before = fc.get_head(store)
        n_before = len(store.blocks)
        dropped = fc.prune_store(store)
        assert dropped > 0
        assert len(store.blocks) == n_before - dropped
        assert fc.get_head(store) == head_before
        # the store still processes new blocks after pruning
        slot = fc.get_current_slot(store) + 1
        fc.on_tick(store, store.genesis_time + slot * cfg().seconds_per_slot)
        sb = build_block(store.block_states[head_before], slot)
        fc.on_block(store, sb)
        assert fc.get_head(store) == hash_tree_root(sb.message)


class TestCommitteeAssignment:
    def test_every_validator_has_exactly_one_duty(self):
        from pos_evolution_tpu.specs.genesis import make_genesis
        from pos_evolution_tpu.specs.validator import get_committee_assignment
        state, _ = make_genesis(32)
        seen_slots = {}
        for v in range(32):
            duty = get_committee_assignment(state, 0, v)
            assert duty is not None, f"validator {v} has no duty"
            committee, index, slot = duty
            assert v in committee
            seen_slots[v] = slot
        # committees partition the epoch: 32 validators over 8 slots
        assert len(set(seen_slots.values())) == cfg().slots_per_epoch


class TestOnTick:
    def test_best_justified_promoted_at_epoch_boundary(self):
        store, state, _ = new_store(32)
        c = cfg()
        from pos_evolution_tpu.specs.containers import Checkpoint
        # fabricate a better justified checkpoint on the finalized chain
        anchor_root = fc.get_head(store)
        store.best_justified_checkpoint = Checkpoint(epoch=1, root=anchor_root)
        # mid-epoch tick: no promotion
        tick_to_slot(store, c.slots_per_epoch - 1)
        assert int(store.justified_checkpoint.epoch) == 0
        # epoch boundary: promoted
        tick_to_slot(store, c.slots_per_epoch)
        assert int(store.justified_checkpoint.epoch) == 1
