"""KZG polynomial-commitment subsystem tests (kzg/, DESIGN.md §23).

Pins, in order: the Fr Montgomery engine against the pure-int oracle
(host AND jitted device twin, bit-identical), the batched NTT/INTT
(roundtrip, evaluation-on-domain oracle, host<->device identity, the
backend seam's stats), the G1 commitment path (naive-MSM oracle, wire
binding, engine-wide memo), single-blob cell proofs with corrupted-cell
/ wrong-commitment / malformed-proof rejection, the aggregated
committee multiproof with its forged-cell soundness negatives, the
``hash_to_g2`` disk cache knob, the DasServer aggregate serving path
(one pairing verdict per served block, proof-bytes accounting, cache
reuse, corruption attribution), and the checkpoint/resume scheme
fingerprint refusal.  The device commitment MSM differential is
``slow``-marked: its one-time XLA CPU compile dominates (~4 min), the
not-slow NTT differential carries the tier-1 host<->device bit.
"""

import os

import numpy as np
import pytest

from pos_evolution_tpu.crypto import bls12_381 as bls
from pos_evolution_tpu.kzg import aggregate, curve, fr, ntt
from pos_evolution_tpu.kzg.scheme import KzgCellScheme
from pos_evolution_tpu.kzg.setup import trusted_setup

pytestmark = pytest.mark.usefixtures("minimal_cfg")

R = fr.MODULUS


def _rand_ints(rng, n):
    return [int.from_bytes(rng.bytes(32), "little") % R for _ in range(n)]


# --- Fr Montgomery engine -----------------------------------------------------

class TestFrField:
    def test_encode_decode_roundtrip(self):
        rng = np.random.default_rng(0)
        xs = _rand_ints(rng, 64) + [0, 1, R - 1]
        assert fr.decode(fr.encode(xs)) == xs

    def test_host_ops_match_int_oracle(self):
        rng = np.random.default_rng(1)
        xs, ys = _rand_ints(rng, 32), _rand_ints(rng, 32)
        a, b = fr.encode(xs), fr.encode(ys)
        assert fr.decode(fr.mont_mul(a, b)) == \
            [x * y % R for x, y in zip(xs, ys)]
        assert fr.decode(fr.mont_add(a, b)) == \
            [(x + y) % R for x, y in zip(xs, ys)]
        assert fr.decode(fr.mont_sub(a, b)) == \
            [(x - y) % R for x, y in zip(xs, ys)]
        assert fr.decode(fr.mont_neg(a)) == [(-x) % R for x in xs]

    def test_batch_inv_matches_fermat(self):
        rng = np.random.default_rng(2)
        xs = _rand_ints(rng, 16)
        xs = [x or 1 for x in xs]
        inv = fr.decode(fr.batch_inv(fr.encode(xs)))
        assert inv == [pow(x, R - 2, R) for x in xs]
        assert all(x * v % R == 1 for x, v in zip(xs, inv))

    def test_device_twin_bit_identical(self):
        """Every device field op reproduces the host limbs digit for
        digit on randomized lazy-domain inputs."""
        jax = pytest.importorskip("jax")
        jnp = jax.numpy
        dev = fr.device_ops()
        rng = np.random.default_rng(3)
        xs, ys = _rand_ints(rng, 24), _rand_ints(rng, 24)
        a, b = fr.encode(xs), fr.encode(ys)
        aj = jnp.asarray(a.astype(np.int32))
        bj = jnp.asarray(b.astype(np.int32))
        for name, host in (("mul", fr.mont_mul), ("add", fr.mont_add),
                           ("sub", fr.mont_sub)):
            got = np.asarray(dev[name](aj, bj)).astype(np.int64)
            np.testing.assert_array_equal(got, host(a, b), err_msg=name)
        got_canon = np.asarray(dev["canon"](aj)).astype(np.int64)
        np.testing.assert_array_equal(got_canon, fr.mont_canon(a))
        inv = np.asarray(dev["inv"](aj)).astype(np.int64)
        assert fr.decode(fr.mont_canon(inv)) == \
            [pow(x, R - 2, R) for x in xs]


# --- NTT ----------------------------------------------------------------------

class TestNtt:
    @pytest.mark.parametrize("n", [1, 2, 8, 64])
    def test_roundtrip(self, n):
        rng = np.random.default_rng(n)
        xs = _rand_ints(rng, n)
        enc = fr.encode(xs)
        back = ntt.ntt_host(ntt.ntt_host(enc), inverse=True)
        assert fr.decode(back) == xs

    def test_forward_is_evaluation_on_domain(self):
        """The convention every consumer relies on: forward NTT of
        coefficients = evaluations at domain(n)[i], pure-int oracle."""
        n = 16
        rng = np.random.default_rng(7)
        coeffs = _rand_ints(rng, n)
        evals = fr.decode(ntt.ntt_host(fr.encode(coeffs)))
        dom = ntt.domain(n)
        for i in (0, 1, 5, n - 1):
            want = sum(c * pow(dom[i], j, R) for j, c in enumerate(coeffs))
            assert evals[i] == want % R

    def test_host_device_bit_identical(self):
        pytest.importorskip("jax")
        rng = np.random.default_rng(11)
        for n in (8, 64):
            enc = fr.encode(_rand_ints(rng, n))
            for inverse in (False, True):
                h = ntt.ntt_host(enc, inverse)
                d = ntt.ntt_device(enc, inverse)
                np.testing.assert_array_equal(d, h,
                                              err_msg=f"n={n} inv={inverse}")

    def test_backend_seam_and_stats(self):
        from pos_evolution_tpu.backend import set_backend
        rng = np.random.default_rng(13)
        enc = fr.encode(_rand_ints(rng, 8))
        ntt.reset_stats()
        try:
            set_backend("numpy")
            out_h = ntt.ntt(enc)
            assert ntt.stats()["host_ntts"] == 1
            set_backend("jax")
            out_d = ntt.ntt(enc)
            s = ntt.stats()
            assert s["device_ntts"] + s["fallback_host"] == 1
            np.testing.assert_array_equal(out_d, out_h)
        finally:
            set_backend("numpy")
            ntt.reset_stats()


# --- commitment path ----------------------------------------------------------

class TestCommit:
    def test_lincomb_matches_naive_oracle(self):
        setup = trusted_setup(8, seed=5)
        rng = np.random.default_rng(17)
        scalars = _rand_ints(rng, 8)
        got = curve.g1_lincomb(setup.powers_g1, scalars)
        acc = None
        for p, s in zip(setup.powers_g1, scalars):
            acc = bls.ec_add(acc, bls.ec_mul(p, s))
        assert got == acc
        assert curve.g1_lincomb(setup.powers_g1, [0] * 8) is None

    def test_setup_is_deterministic_and_on_curve(self):
        a = trusted_setup(4, seed=9)
        b = trusted_setup(4, seed=9)
        assert a.powers_g1 == b.powers_g1
        assert trusted_setup(4, seed=10).powers_g1 != a.powers_g1
        assert all(bls.g1_on_curve(p) for p in a.powers_g1)

    def test_commit_wire_binding_and_memo(self):
        from pos_evolution_tpu.config import cfg
        s = KzgCellScheme()
        n_cells, m, _n = s.geometry()
        rng = np.random.default_rng(19)
        grid = rng.integers(0, 256, (n_cells, cfg().das_cell_bytes),
                            dtype=np.uint8)
        point, comp, coeffs, wire = s.commit_full(grid)
        assert s.commit(grid) == wire == s.wire_bind(comp)
        assert len(wire) == 32 and len(comp) == 48
        assert bls.g1_decompress(comp) == point
        assert len(s._memo) == 1          # second commit hit the memo
        # the evaluations really are the blob bytes: decode via INTT
        evals = fr.decode(ntt.ntt_host(fr.encode(list(coeffs))))
        assert evals[0] == s.cell_values(grid[0])[0]


# --- single-blob proofs (CellCommitmentScheme contract) -----------------------

class TestCellProofs:
    @pytest.fixture()
    def blob(self):
        from pos_evolution_tpu.config import cfg
        s = KzgCellScheme()
        n_cells, _m, _n = s.geometry()
        rng = np.random.default_rng(23)
        grid = rng.integers(0, 256, (n_cells, cfg().das_cell_bytes),
                            dtype=np.uint8)
        return s, grid, s.commit(grid)

    def test_honest_proof_verifies(self, blob):
        s, grid, wire = blob
        idx = [0, 3, 7]
        proof = s.prove_cells(grid, idx)
        assert s.verify_cells(wire, grid[idx], idx, proof)

    def test_corrupted_cell_rejected(self, blob):
        s, grid, wire = blob
        idx = [0, 3, 7]
        proof = s.prove_cells(grid, idx)
        bad = grid[idx].copy()
        bad[1, 0] ^= 0x01
        assert not s.verify_cells(wire, bad, idx, proof)

    def test_wrong_commitment_rejected(self, blob):
        s, grid, wire = blob
        idx = [2, 5]
        proof = s.prove_cells(grid, idx)
        assert not s.verify_cells(b"\x00" * 32, grid[idx], idx, proof)

    def test_malformed_proof_rejected(self, blob):
        s, grid, wire = blob
        idx = [1]
        proof = s.prove_cells(grid, idx)
        assert not s.verify_cells(wire, grid[idx], idx, [])
        assert not s.verify_cells(wire, grid[idx], idx,
                                  [b"not-the-tag"] + proof[1:])
        garbled = proof[:-1] + [b"\xff" * 48]
        assert not s.verify_cells(wire, grid[idx], idx, garbled)


# --- aggregated committee multiproofs -----------------------------------------

class TestAggregate:
    @pytest.fixture()
    def committee(self):
        from pos_evolution_tpu.config import cfg
        s = KzgCellScheme()
        n_cells, _m, _n = s.geometry()
        rng = np.random.default_rng(29)
        grids = [rng.integers(0, 256, (n_cells, cfg().das_cell_bytes),
                              dtype=np.uint8) for _ in range(2)]
        wires = [s.commit(g) for g in grids]
        samples = [(0, 0), (0, 5), (1, 2), (1, n_cells - 1)]
        cells = [grids[b][c] for b, c in samples]
        proof = s.prove_aggregate(grids, samples)
        return s, grids, wires, samples, cells, proof

    def test_honest_aggregate_verifies(self, committee):
        s, grids, wires, samples, cells, proof = committee
        assert s.verify_aggregate(wires, samples, cells, proof)
        # the aggregation win itself: one opening for the whole set
        assert s.proof_n_bytes(proof) == 48 * (len(grids) + 2)

    def test_forged_cell_in_aggregate_rejected(self, committee):
        """The soundness bit: an attacker serving one corrupted cell
        inside an otherwise-honest aggregate cannot pass the pairing
        check, whichever cell it is."""
        s, grids, wires, samples, cells, proof = committee
        for j in range(len(cells)):
            forged = [c.copy() for c in cells]
            forged[j] = forged[j].copy()
            forged[j][0] ^= 0xA5
            assert not s.verify_aggregate(wires, samples, forged, proof)

    def test_swapped_samples_rejected(self, committee):
        s, grids, wires, samples, cells, proof = committee
        swapped = [samples[1], samples[0]] + samples[2:]
        assert not s.verify_aggregate(wires, swapped, cells, proof)

    def test_tampered_proof_points_rejected(self, committee):
        s, grids, wires, samples, cells, proof = committee
        for key in ("w", "wp"):
            bad = dict(proof)
            bad[key] = bytes(proof["points"][0])
            assert not s.verify_aggregate(wires, samples, cells, bad)
        bad = dict(proof)
        bad["points"] = list(proof["points"][::-1])
        assert not s.verify_aggregate(wires, samples, cells, bad)

    def test_wrong_wire_commitment_rejected(self, committee):
        s, grids, wires, samples, cells, proof = committee
        assert not s.verify_aggregate([wires[1], wires[0]], samples,
                                      cells, proof)

    def test_proof_encoding_roundtrip(self, committee):
        s, grids, wires, samples, cells, proof = committee
        parts = s.encode_proof(proof)
        assert s.decode_proof(parts) == proof
        with pytest.raises(ValueError):
            s.decode_proof(parts[1:])


# --- device commitment MSM (compile-dominated differential) -------------------

@pytest.mark.slow
class TestDeviceMsm:
    def test_commit_host_device_bit_identical(self):
        pytest.importorskip("jax")
        from pos_evolution_tpu.backend import set_backend
        from pos_evolution_tpu.config import cfg
        rng = np.random.default_rng(31)
        s = KzgCellScheme()
        n_cells, _m, _n = s.geometry()
        grids = [rng.integers(0, 256, (n_cells, cfg().das_cell_bytes),
                              dtype=np.uint8) for _ in range(2)]
        try:
            set_backend("numpy")
            host = [KzgCellScheme().commit(g) for g in grids]
            set_backend("jax")
            dev = [KzgCellScheme().commit(g) for g in grids]
        finally:
            set_backend("numpy")
        assert host == dev


# --- hash_to_g2 disk cache (POS_G2_CACHE_DIR knob) ----------------------------

class TestG2DiskCache:
    def test_cache_hit_corruption_and_dst_keying(self, tmp_path,
                                                 monkeypatch):
        msg = b"g2-cache-test"
        ref = bls.hash_to_g2(msg)            # knob unset: no disk IO
        assert not list(tmp_path.iterdir())
        monkeypatch.setenv("POS_G2_CACHE_DIR", str(tmp_path))
        assert bls.hash_to_g2(msg) == ref    # miss -> compute + store
        files = list(tmp_path.iterdir())
        assert len(files) == 1 and files[0].suffix == ".bin"
        assert bls.hash_to_g2(msg) == ref    # hit -> loaded point
        # dst participates in both the hash derivation and the cache key
        other = bls.hash_to_g2(msg, dst=b"other-dst")
        assert other != ref and len(list(tmp_path.iterdir())) == 2
        # corruption fails closed into recomputation
        for f in tmp_path.iterdir():
            f.write_bytes(b"\x00" * 192)
        assert bls.hash_to_g2(msg) == ref
        for f in tmp_path.iterdir():
            f.write_bytes(b"short")
        assert bls.hash_to_g2(msg, dst=b"other-dst") == other


# --- aggregate serving (DasServer + checkpoint fingerprint) -------------------

class TestKzgServing:
    def test_serve_samples_aggregate_path(self):
        from pos_evolution_tpu.das import (
            BlobEngine,
            DasServer,
            SamplingClientPopulation,
        )
        from pos_evolution_tpu.telemetry.registry import MetricsRegistry
        eng = BlobEngine(scheme="kzg", seed=4)
        grids, coms, _ = eng.build_for(2, b"\x07" * 32)

        class _FakeSidecar:
            def __init__(self, cells, commitment):
                self.cells = cells
                self.commitment = commitment

        sidecars = [_FakeSidecar(g, co) for g, co in zip(grids, coms)]
        registry = MetricsRegistry()
        server = DasServer(eng.scheme, registry=registry)
        pop = SamplingClientPopulation(500, samples_per_client=4, seed=1)
        s1 = server.serve_samples(b"\x07" * 32, sidecars, pop)
        assert s1["scheme"] == "kzg" and s1["aggregated"]
        assert s1["failed"] == 0 and s1["clients_all_ok"] == 500
        assert s1["samples"] == 2000
        # ONE aggregate proof for the whole block's sampled set, so the
        # per-sample wire cost collapses vs the 128-byte merkle branch
        assert s1["proof_bytes"] == s1["proof_bytes_per_sample"] * 2000
        assert s1["proof_bytes_per_sample"] * 4 <= 128
        # same block again: the aggregate comes straight from the cache
        s2 = server.serve_samples(b"\x07" * 32, sidecars, pop)
        assert s2["cache_hits"] > 0 and s2["failed"] == 0
        counts = registry.counts()
        assert counts["das_aggregate_proofs_total"] >= 1
        assert counts["das_aggregate_proof_bytes_total"] >= s1["proof_bytes"]
        # a corrupted served cell fails the single pairing verdict and
        # is attributed to every sampling client
        bad = np.asarray(grids[0]).copy()
        bad[:, 0] ^= 0xFF
        sidecars[0].cells = bad
        s3 = DasServer(eng.scheme, registry=registry).serve_samples(
            b"\x08" * 32, sidecars, pop)
        assert s3["failed"] > 0 and s3["clients_all_ok"] == 0

    def test_das_aggregate_rpc_and_loadgen_verify(self):
        from pos_evolution_tpu.config import cfg
        from pos_evolution_tpu.das import BlobEngine
        from pos_evolution_tpu.serve import (
            ServeClient,
            ServeFront,
            ServeView,
            ServingState,
        )
        from pos_evolution_tpu.serve.loadgen import LoadGenerator
        from pos_evolution_tpu.telemetry.registry import MetricsRegistry
        eng = BlobEngine(scheme="kzg", seed=4)
        root = b"\x07" * 32
        grids, coms, _ = eng.build_for(2, root)

        class _Sidecar:
            def __init__(self, cells, commitment):
                self.cells, self.commitment = cells, commitment

        view = ServeView(
            slot=2, head_root=root, head_slot=2,
            justified_epoch=0, justified_root=b"\x00" * 32,
            finalized_epoch=0, finalized_root=b"\x00" * 32,
            update_ssz=b"\x01\x02", update_root=b"\x03" * 32,
            sidecars={root: [_Sidecar(g, c)
                             for g, c in zip(grids, coms)]},
            n_cells=2 * cfg().das_cells_per_blob, scheme="kzg")
        state = ServingState()
        state.publish(view)
        front = ServeFront(state, scheme=eng.scheme,
                           registry=MetricsRegistry(), workers=2)
        addr = front.start()
        try:
            cli = ServeClient(addr, connections=2)
            # the head summary advertises the scheme: remote loadgen
            # clients pick das_aggregate vs das_cells from it
            head = cli.request("head", deadline_s=2.0)
            assert head.ok and head.result["scheme"] == "kzg"
            res = cli.request("das_aggregate", {
                "block_root": root.hex(),
                "samples": [[0, 1], [1, 3], [0, 1], [1, 15]]},
                deadline_s=5.0)
            assert res.ok, res.error
            r = res.result
            assert r["scheme"] == "kzg" and r["blobs_opened"] == 2
            assert r["samples"] == [[0, 1], [1, 3], [1, 15]]  # canonical
            assert r["proof_bytes"] == 48 * 4
            lg = LoadGenerator.__new__(LoadGenerator)
            lg._agg_memo = {}
            assert lg._verify_agg_many([r]) == (1, 0)
            # a tampered served cell fails the client-side pairing check
            forged = dict(r)
            forged["cells"] = list(r["cells"])
            forged["cells"][0] = bytes(
                b ^ 0xA5 for b in bytes.fromhex(r["cells"][0])).hex()
            assert lg._verify_agg_many([forged]) == (0, 1)
            # per-cell branch method is honestly refused on a kzg view
            cells_res = cli.request("das_cells", {
                "block_root": root.hex(), "samples": [[0, 1]]},
                deadline_s=2.0)
            assert cells_res.status == "error"
            assert "das_aggregate" in cells_res.error
            cli.close()
        finally:
            front.stop()

    def test_resume_refuses_scheme_mismatch(self):
        from pos_evolution_tpu.das import BlobEngine
        from pos_evolution_tpu.sim import Simulation
        sim = Simulation(16, das=BlobEngine(n_blobs=1, scheme="kzg"))
        sim.run_until_slot(3)
        assert sim.das.describe()["scheme"] == "kzg"
        blob = sim.checkpoint()
        # the scheme name is part of the engine fingerprint: resuming a
        # kzg chain with a merkle engine must refuse loudly
        with pytest.raises(ValueError, match="does not match"):
            Simulation.resume(blob, das=BlobEngine(n_blobs=1,
                                                   scheme="merkle"))
        twin = Simulation.resume(blob, das=sim.das)
        twin.run_until_slot(5)
        sim.run_until_slot(5)
        from pos_evolution_tpu.specs import forkchoice as fc
        assert fc.get_head(twin.store()) == fc.get_head(sim.store())
