"""Protocol-variant tests (L7; SURVEY.md §2.9): propose-vote-merge family,
view-merge, Goldfish (expiry/VRF/subsampling/sleepy joining/confirmation),
RLMD-GHOST eta-expiry, SSF single-slot finality, and the avalanche attack
on vanilla GHOST (§2.10).
"""

import numpy as np
import pytest

from pos_evolution_tpu.models import (
    PVMAdversary,
    PVMSimulation,
    SSFSimulation,
    goldfish,
    is_ack_slashable,
    lmd,
    rlmd,
)
from pos_evolution_tpu.models.pvm import (
    GENESIS_ROOT,
    HeadVote,
    PVMBlock,
    View,
    ghost_head,
    vanilla_ghost_head,
)
from pos_evolution_tpu.models.ssf import Acknowledgment, FFGVote, SSFCheckpoint


class TestPVMTemplate:
    def test_lmd_honest_chain_grows(self):
        sim = PVMSimulation(lmd(16))
        sim.run_slots(10)
        chains = [sim.chain_of(v) for v in range(16)]
        assert all(c == chains[0] for c in chains), "honest views diverged"
        assert len(chains[0]) == 11  # genesis + one block per slot

    def test_view_merge_aligns_voters(self):
        """pos-evolution.md:1540: with synchrony and an honest proposer the
        merged view makes every honest validator vote for the proposal."""
        sim = PVMSimulation(rlmd(12, eta=4))
        for _ in range(6):
            sim.run_slot()
            last = sim.log[-1]
            assert last["votes"] == 12
        # all votes each slot were unanimous for that slot's proposal
        v0 = sim.validators[0].view
        for (validator, slot), root in v0.votes.items():
            blk = v0.blocks[root]
            assert blk.slot == slot, "vote was not for the slot's proposal"

    def test_rlmd_expiry_window(self):
        """Only votes from the last eta slots count (pos-evolution.md:1585)."""
        view = View()
        b1 = PVMBlock(slot=1, parent=GENESIS_ROOT, proposer=0)
        b2 = PVMBlock(slot=1, parent=GENESIS_ROOT, proposer=1)
        view.add_block(b1)
        view.add_block(b2)
        # 3 old votes for b1 at slot 1; 1 fresh vote for b2 at slot 5
        for v in range(3):
            view.add_vote(HeadVote(slot=1, block_root=b1.root, validator=v))
        view.add_vote(HeadVote(slot=5, block_root=b2.root, validator=9))
        # eta = inf: b1's 3 old votes win
        assert ghost_head(view, 6, None) == b1.root
        # eta = 2 at slot 6: only slots 4-5 count -> b2 wins
        assert ghost_head(view, 6, 2) == b2.root

    def test_goldfish_is_eta_one(self):
        """Goldfish == RLMD with eta = 1 (pos-evolution.md:1585)."""
        view = View()
        b1 = PVMBlock(slot=1, parent=GENESIS_ROOT, proposer=0)
        b2 = PVMBlock(slot=1, parent=GENESIS_ROOT, proposer=1)
        view.add_block(b1)
        view.add_block(b2)
        for v in range(5):
            view.add_vote(HeadVote(slot=3, block_root=b1.root, validator=v))
        view.add_vote(HeadVote(slot=4, block_root=b2.root, validator=7))
        # at slot 5 with eta=1 only slot-4 votes count
        assert ghost_head(view, 5, 1) == b2.root

    def test_equivocating_votes_discounted(self):
        """Fork-choice discounting (pos-evolution.md:1411): equivocators
        lose all weight."""
        view = View()
        b1 = PVMBlock(slot=1, parent=GENESIS_ROOT, proposer=0)
        b2 = PVMBlock(slot=1, parent=GENESIS_ROOT, proposer=1)
        view.add_block(b1)
        view.add_block(b2)
        view.add_vote(HeadVote(slot=2, block_root=b1.root, validator=5))
        view.add_vote(HeadVote(slot=2, block_root=b2.root, validator=5))  # equivocates
        view.add_vote(HeadVote(slot=2, block_root=b2.root, validator=6))
        assert 5 in view.equivocators
        assert ghost_head(view, 3, None) == b2.root


class TestGoldfish:
    def test_honest_run_with_vrf_leaders(self):
        sim = PVMSimulation(goldfish(16))
        sim.run_slots(10)
        chains = [sim.chain_of(v) for v in range(16)]
        assert all(c == chains[0] for c in chains)
        assert len(chains[0]) == 11

    def test_kappa_deep_confirmation(self):
        sim = PVMSimulation(goldfish(16, kappa=3))
        sim.run_slots(10)
        confirmed = sim.confirmed_ledger(0)
        blk = sim.validators[0].view.blocks[confirmed]
        assert blk.slot <= sim.slot - 3
        # confirmed prefix is on every validator's canonical chain
        for v in range(16):
            assert confirmed in sim.chain_of(v)

    def test_fast_confirmation_full_participation(self):
        """3/4 rule fast-confirms the slot's proposal (pos-evolution.md:
        1562-1569)."""
        sim = PVMSimulation(goldfish(16, fast_confirm=True))
        sim.run_slots(5)
        root = sim.fast_confirmed.get(0)
        assert root is not None
        assert sim.validators[0].view.blocks[root].slot >= 4

    def test_no_fast_confirm_below_threshold(self):
        adv = PVMAdversary(asleep=lambda t, v: v < 6)  # 10/16 < 3/4 awake
        sim = PVMSimulation(goldfish(16, fast_confirm=True), adv)
        sim.run_slots(5)
        assert sim.fast_confirmed.get(15) is None

    def test_sleepy_join_dreamy_then_awake(self):
        """asleep -> dreamy -> awake joining (pos-evolution.md:1547);
        under half-honest-awake the chain keeps growing and rejoiners
        converge."""
        asleep_until = 6
        adv = PVMAdversary(asleep=lambda t, v: v >= 10 and t < asleep_until)
        sim = PVMSimulation(goldfish(16), adv)
        sim.run_slots(12)
        # sleeper rejoined and agrees with the always-awake validators
        assert sim.chain_of(15) == sim.chain_of(0)
        assert len(sim.chain_of(0)) >= 11

    def test_subsampling_still_progresses(self):
        sim = PVMSimulation(goldfish(32, subsample_rate=0.5))
        sim.run_slots(8)
        assert len(sim.chain_of(0)) == 9
        total_votes = sum(e["votes"] for e in sim.log)
        assert total_votes < 32 * 8  # strictly subsampled

    def test_one_async_slot_is_survivable_for_liveness(self):
        """A fully-async slot halts that slot's progress but the chain
        resumes — the *safety* brittleness (pos-evolution.md:1579-1583) is
        exactly why RLMD generalizes the expiry."""
        adv = PVMAdversary(drop_proposal=lambda t, v: t == 4,
                           drop_votes=lambda t, v: t == 4)
        sim = PVMSimulation(goldfish(16), adv)
        sim.run_slots(10)
        assert len(sim.chain_of(0)) >= 10


class TestRLMDAsynchronyTolerance:
    """pos-evolution.md:1600: RLMD-GHOST tolerates asynchronous periods
    shorter than eta - 1 slots; Goldfish (eta = 1) cannot tolerate even one
    (:1579-1583)."""

    def _fork_after_async(self, eta, async_slots):
        """Honest votes anchor chain A at slot 5; then `async_slots` slots
        with no honest votes; the adversary proposes chain B and one fresh
        vote. Does A survive the fork choice at the end?"""
        view = View()
        a1 = PVMBlock(slot=1, parent=GENESIS_ROOT, proposer=0)
        b1 = PVMBlock(slot=1, parent=GENESIS_ROOT, proposer=1)
        view.add_block(a1)
        view.add_block(b1)
        for v in range(8):  # strong honest support for A at slot 5
            view.add_vote(HeadVote(slot=5, block_root=a1.root, validator=v))
        # asynchronous gap: slots 6 .. 5+async_slots produce nothing honest;
        # at the end the adversary votes once for B
        decision_slot = 6 + async_slots
        view.add_vote(HeadVote(slot=decision_slot - 1, block_root=b1.root,
                               validator=99))
        return ghost_head(view, decision_slot, eta) == a1.root

    def test_eta_window_bounds_tolerance(self):
        # eta = 6: a 3-slot async gap (< eta - 1) keeps chain A canonical
        assert self._fork_after_async(eta=6, async_slots=3)
        # the same gap kills Goldfish (eta = 1): old votes expired,
        # the single adversarial fresh vote wins
        assert not self._fork_after_async(eta=1, async_slots=3)
        # and RLMD with a gap >= eta also loses the anchor
        assert not self._fork_after_async(eta=3, async_slots=4)


class TestSSF:
    def test_single_slot_finality_under_synchrony(self):
        """pos-evolution.md:1637: honest proposer + synchrony + honest
        supermajority => the proposal justifies and (via acknowledgments,
        :1646) finalizes within its own slot."""
        sim = SSFSimulation(16)
        sim.run_slots(6)
        assert sim.max_finalized_slot() >= 5
        # every slot's proposal finalized
        assert len(sim.finalized) >= 6

    def test_no_finality_without_supermajority(self):
        adv = PVMAdversary(asleep=lambda t, v: v < 6)  # 10/16 < 2/3... 10*3=30<32
        sim = SSFSimulation(16, adversary=adv)
        sim.run_slots(5)
        assert sim.max_finalized_slot() == 0

    def test_finalized_chain_is_prefix_of_available(self):
        """Prefix property (pos-evolution.md:1188)."""
        sim = SSFSimulation(16)
        sim.run_slots(6)
        chain = sim.chain_of(0)
        for blk in sim.finalized_blocks():
            assert blk in chain

    def test_ack_surround_slashing_truth_table(self):
        cp = SSFCheckpoint(block=b"\x01" * 32, slot=5)
        ack = Acknowledgment(checkpoint=cp, slot=5, validator=3)
        surround = FFGVote(source=SSFCheckpoint(b"\x00" * 32, 4),
                           target=SSFCheckpoint(b"\x02" * 32, 7), validator=3)
        inside = FFGVote(source=SSFCheckpoint(b"\x00" * 32, 5),
                         target=SSFCheckpoint(b"\x02" * 32, 6), validator=3)
        other = FFGVote(source=SSFCheckpoint(b"\x00" * 32, 4),
                        target=SSFCheckpoint(b"\x02" * 32, 7), validator=4)
        assert is_ack_slashable(ack, surround)
        assert not is_ack_slashable(ack, inside)   # source not before ack slot
        assert not is_ack_slashable(ack, other)    # different validator

    def test_honest_run_has_no_slashings(self):
        sim = SSFSimulation(12)
        sim.run_slots(5)
        assert sim.detect_ack_slashings() == []


class TestAvalancheAttack:
    """pos-evolution.md:1469-1501: withheld flat subtree + equivocation
    reuse displaces honest chains under vanilla (block-count) GHOST; LMD
    vote weighting + discounting kills the attack."""

    def _honest_chain(self, view, length, start_slot=1, parent=GENESIS_ROOT):
        roots = []
        for i in range(length):
            b = PVMBlock(slot=start_slot + i, parent=parent, proposer=100 + i)
            view.add_block(b)
            parent = b.root
            roots.append(b.root)
        return roots

    def test_withheld_subtree_displaces_honest_chain(self):
        view = View()
        honest = self._honest_chain(view, 6)
        # adversary releases k=7 withheld blocks: chain g->A1->A2 with a
        # flat wide subtree under A2 (pos-evolution.md:1489-1495)
        a1 = PVMBlock(slot=1, parent=GENESIS_ROOT, proposer=0, salt=1)
        a2 = PVMBlock(slot=2, parent=a1.root, proposer=1, salt=1)
        view.add_block(a1)
        view.add_block(a2)
        for k in range(5):
            view.add_block(PVMBlock(slot=3 + k, parent=a2.root,
                                    proposer=2 + k, salt=1))
        head = vanilla_ghost_head(view)
        assert not view.is_ancestor(honest[0], head), "honest chain survived"
        assert view.is_ancestor(a1.root, head)

    def test_equivocation_reuse_displaces_again(self):
        view = View()
        a1 = PVMBlock(slot=1, parent=GENESIS_ROOT, proposer=0, salt=1)
        a2 = PVMBlock(slot=2, parent=a1.root, proposer=1, salt=1)
        view.add_block(a1)
        view.add_block(a2)
        for k in range(5):
            view.add_block(PVMBlock(slot=3 + k, parent=a2.root,
                                    proposer=2 + k, salt=1))
        # honest validators now build on the adversary's tip
        honest_new = self._honest_chain(view, 3, start_slot=8, parent=a2.root)
        # adversary REUSES blocks 3..6 as equivocations (same proposer+slot,
        # different parent) deeper in its own chain
        deep_parent = a2.root
        for k in range(4):
            eq = PVMBlock(slot=3 + k, parent=deep_parent, proposer=2 + k, salt=2)
            view.add_block(eq)
            deep_parent = eq.root
        head = vanilla_ghost_head(view)
        assert not view.is_ancestor(honest_new[0], head), \
            "honest blocks survived the reuse round"

    def test_lmd_with_discounting_defeats_avalanche(self):
        """pos-evolution.md:1501: under the vote-based LMD rule with
        equivocation discounting the withheld blocks carry no weight."""
        view = View()
        honest = self._honest_chain(view, 6)
        # honest validators actually voted for their chain
        for v in range(8):
            view.add_vote(HeadVote(slot=6, block_root=honest[-1], validator=v))
        a1 = PVMBlock(slot=1, parent=GENESIS_ROOT, proposer=0, salt=1)
        a2 = PVMBlock(slot=2, parent=a1.root, proposer=1, salt=1)
        view.add_block(a1)
        view.add_block(a2)
        for k in range(5):
            view.add_block(PVMBlock(slot=3 + k, parent=a2.root,
                                    proposer=2 + k, salt=1))
        head = ghost_head(view, 7, None)
        assert view.is_ancestor(honest[0], head), "LMD failed to hold the chain"
