"""Simulation-driver tests (L6): honest runs, sleepy validators, partitions."""

import numpy as np
import pytest

from pos_evolution_tpu.config import minimal_config, use_config
from pos_evolution_tpu.sim import Schedule, Simulation

pytestmark = pytest.mark.usefixtures("minimal_cfg")


class TestHonestRun:
    def test_finalizes(self):
        sim = Simulation(64)
        sim.run_epochs(5)
        assert sim.finalized_epoch() >= 3
        assert sim.justified_epoch() >= 4

    def test_one_block_per_slot(self):
        sim = Simulation(64)
        sim.run_epochs(2)
        # anchor + one block per slot 1..16
        assert sim.metrics[-1]["n_blocks"] == 2 * 8 + 1

    def test_metrics_recorded(self):
        sim = Simulation(32)
        sim.run_until_slot(4)
        assert [m["slot"] for m in sim.metrics] == [0, 1, 2, 3, 4]
        assert all("head" in m and "finalized_epoch" in m for m in sim.metrics)

    def test_handler_tracing(self):
        """SURVEY.md §5: per-handler timing (on_block/on_attestation/
        get_head) collected during the run."""
        sim = Simulation(32)
        sim.run_until_slot(6)
        s = sim.trace_summary()
        for handler in ("get_head", "on_block", "on_attestation"):
            assert handler in s and s[handler]["count"] > 0
            assert s[handler]["p50_ms"] >= 0

    def test_pack_dedups_on_canonical_chain_and_prunes_expired(self):
        """Operation-pool semantics (r5 scale_demo catch): a proposer must
        not re-pack attestations already included on ITS OWN chain —
        re-packing starves fresh attestations once committees x window
        exceed max_attestations and delays justification at scale — while
        attestations included only on a losing fork stay packable, and
        expired pool entries are pruned."""
        from pos_evolution_tpu.ssz import hash_tree_root
        sim = Simulation(64)
        sim.run_until_slot(4)
        # deliver the slot-4 gossip (as the slot-5 proposer's tick would)
        # so the pool holds attestations not yet included in any block
        sim._tick_all(sim.slot_start(sim.slot))
        group = sim.groups[0]
        head = sim._get_head(group)
        assert group.pool, "pool should hold gossiped attestations"
        assert group.block_atts, "block-carried attestations must be tracked"
        # every attestation on the canonical chain is excluded from packing
        onchain = set()
        for roots in group.block_atts.values():
            onchain.update(roots)
        packed = sim._pack_attestations(group, sim.slot, head)
        packed_roots = {hash_tree_root(a) for a in packed}
        assert packed_roots.isdisjoint(onchain)
        assert packed, "fresh pool attestations should be packable"
        # fork-insensitivity: inclusion recorded on a NON-canonical block
        # does not block packing on the head chain
        victim = next(iter(packed_roots))
        group.block_atts[b"\xaa" * 32] = [victim]   # losing-fork block
        still = {hash_tree_root(a)
                 for a in sim._pack_attestations(group, sim.slot, head)}
        assert victim in still
        # ...but inclusion on the head block itself does
        group.block_atts.setdefault(head, []).append(victim)
        gone = {hash_tree_root(a)
                for a in sim._pack_attestations(group, sim.slot, head)}
        assert victim not in gone
        # pruning: far-future pack drops everything expired from the pool
        horizon = sim.slot + sim.cfg.slots_per_epoch + 1
        sim._pack_attestations(group, horizon, head)
        assert not group.pool

    @pytest.mark.slow
    def test_mainnet_justification_timing(self):
        """Mainnet config, honest run: the genesis guard skips the first
        two boundaries, first justification lands at the end of epoch 2
        (justified == 2 after 3 epochs, finalized still 0) — the timing
        scale_demo.py asserts at 64K validators."""
        from pos_evolution_tpu.config import mainnet_config
        with use_config(mainnet_config()):
            sim = Simulation(64)
            sim.run_epochs(3)
            assert sim.justified_epoch() == 2
            assert sim.finalized_epoch() == 0


class TestAcceleratedForkChoice:
    def test_accelerated_run_matches_spec_run(self):
        """Device fork choice inside the driver reproduces the spec run
        head-for-head (SURVEY.md §4.4b)."""
        pytest.importorskip("jax")
        fast = Simulation(64, accelerated_forkchoice=True)
        fast.run_epochs(2)
        ref = Simulation(64)
        ref.run_epochs(2)
        assert [m["head"] for m in fast.metrics] == [m["head"] for m in ref.metrics]

    def test_fully_accelerated_driver_matches_numpy_driver(self):
        """The whole driver under the jax backend (device epoch sweeps +
        device churn + device fork choice) reproduces the numpy run."""
        pytest.importorskip("jax")
        from pos_evolution_tpu.backend import set_backend
        ref = Simulation(64)
        ref.run_epochs(3)
        set_backend("jax")
        try:
            fast = Simulation(64, accelerated_forkchoice=True)
            fast.run_epochs(3)
        finally:
            set_backend("numpy")
        assert [m["head"] for m in fast.metrics] == [m["head"] for m in ref.metrics]
        assert fast.metrics[-1]["finalized_epoch"] == ref.metrics[-1]["finalized_epoch"]


class TestSleepyValidators:
    def test_minority_asleep_still_finalizes(self):
        """Dynamic availability: < 1/3 asleep must not stop finality
        (pos-evolution.md:1184-1190 with beta_1 = 33%)."""
        asleep = set(range(12))  # 12/64 < 1/3 offline

        sched = Schedule(n_validators=64,
                         awake=lambda r, v: v not in asleep)
        sim = Simulation(64, schedule=sched)
        sim.run_epochs(5)
        assert sim.finalized_epoch() >= 2

    def test_supermajority_asleep_halts_finality(self):
        """> 1/3 asleep: the finalized chain must stall (plausible liveness
        needs > 2/3 honest-and-awake, pos-evolution.md:243)."""
        asleep = set(range(28))  # 28/64 > 1/3 offline
        sched = Schedule(n_validators=64,
                         awake=lambda r, v: v not in asleep)
        sim = Simulation(64, schedule=sched)
        sim.run_epochs(4)
        assert sim.finalized_epoch() == 0

    def test_wakeup_recovers_finality(self):
        """Sleepy validators waking after 'GAT' lets finality catch up
        (pos-evolution.md:199, 1186)."""
        c = minimal_config()
        gat_round = 2 * c.slots_per_epoch * c.intervals_per_slot
        asleep = set(range(28))
        sched = Schedule(
            n_validators=64,
            awake=lambda r, v: (v not in asleep) or r >= gat_round)
        sim = Simulation(64, schedule=sched)
        sim.run_epochs(6)
        assert sim.finalized_epoch() >= 3


@pytest.mark.slow
class TestRealBLSEndToEnd:
    """The crypto seam carries REAL BLS12-381 signatures end to end
    (pos-evolution.md:165,717): genesis keys, proposer/randao/attestation
    signing, aggregate verification in on_block/on_attestation, and
    finalization, all through ``set_bls_backend(NativeBLS)`` — no FakeBLS
    anywhere in the run. ~50 ms per native pairing verify keeps this to a
    small scale (VERDICT r3 item 5)."""

    def test_sim_epoch_finalizes_with_native_bls(self):
        from pos_evolution_tpu.crypto import native_bls
        if not native_bls.available():
            # Attempt the build for real instead of guessing from
            # compiler presence (the old heuristic hard-failed boxes where
            # g++ exists but is broken, and silently skipped ones where
            # the compiler hides behind a nonstandard name). Fail ONLY on
            # a nonzero make exit — with the captured diagnostic — so the
            # only real-crypto e2e cannot evaporate unexplained (VERDICT
            # r4 weak #2); anything short of a failed compile is a skip
            # with the observed reason.
            import os
            import subprocess
            native_dir = os.path.join(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                "native")
            try:
                proc = subprocess.run(
                    ["make", "-C", native_dir], capture_output=True,
                    text=True, timeout=600)
            except FileNotFoundError:
                pytest.skip("native BLS unavailable: no `make` on PATH")
            except subprocess.TimeoutExpired:
                pytest.skip("native BLS unavailable: `make -C native` "
                            "timed out after 600s")
            if proc.returncode != 0:
                diag = (proc.stdout + "\n" + proc.stderr).strip()
                # "compiler missing" is an environment limitation, not a
                # build regression — decide by checking the compiler make
                # would use, NOT by pattern-matching the output (a missing
                # *header* also says 'No such file or directory', and that
                # one IS a regression that must fail loudly)
                import shutil
                cxx = os.environ.get("CXX", "g++")
                if not (shutil.which(cxx) or shutil.which("c++")
                        or shutil.which("clang++")):
                    pytest.skip("native BLS unavailable: no C++ compiler "
                                f"on PATH (make said: {diag[-300:]})")
                pytest.fail("native BLS build failed (make -C native, "
                            f"exit {proc.returncode}):\n{diag[-2000:]}")
            # build succeeded: clear the cached load failure and retry
            native_bls._load.cache_clear()
            if not native_bls.available():
                pytest.skip("native BLS unavailable: make succeeded but "
                            f"the library did not load from "
                            f"{native_bls._LIB_PATH}")
        from pos_evolution_tpu.crypto.bls import (
            bls, get_bls_backend, set_bls_backend)
        from pos_evolution_tpu.crypto.native_bls import NativeBLS

        prior_backend = get_bls_backend()
        set_bls_backend(NativeBLS)
        try:
            # Dispatch really is native: a known-answer check against the
            # exact Python oracle, not FakeBLS's XOR scheme.
            from pos_evolution_tpu.crypto.bls12_381 import PyBLS
            assert bls.SkToPk(1) == PyBLS.SkToPk(1)
            assert len(bls.Sign(1, b"m")) == 96

            sim = Simulation(16)
            sim.run_epochs(4)
            # Real pairing checks passed in every handler on the way here;
            # a single forged/fake signature would have thrown in on_block.
            assert sim.justified_epoch() >= 3
            assert sim.finalized_epoch() >= 2
            assert sim.metrics[-1]["n_blocks"] == 4 * 8 + 1
        finally:
            set_bls_backend(prior_backend)
