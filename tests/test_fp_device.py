"""Differential tests: device limb-vector Fp arithmetic (ops/fp.py) vs
exact Python integers — the base layer of the BLS12-381 pairing kernel
(SURVEY.md §2.7 N1). Randomized batches plus adversarial boundary values
(0, 1, p-1, p, 2p-1, values with long FFF... carry ripples).
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from pos_evolution_tpu.crypto.bls12_381 import Q as P_INT  # noqa: E402
from pos_evolution_tpu.ops import fp  # noqa: E402


def rand_residues(rng, n, bound=None):
    """n random values in [0, bound) as (ints, limb array)."""
    bound = bound if bound is not None else 2 * P_INT
    vals = [int.from_bytes(rng.bytes(48), "big") % bound for _ in range(n)]
    arr = np.stack([fp.to_limbs(v) for v in vals])
    return vals, jax.numpy.asarray(arr)


EDGE = [0, 1, 2, P_INT - 1, P_INT, P_INT + 1, 2 * P_INT - 1,
        (1 << 372) - 1, ((1 << 384) - 1) % (2 * P_INT)]


class TestLimbCodec:
    def test_roundtrip(self):
        rng = np.random.default_rng(0)
        for v in EDGE + [int.from_bytes(rng.bytes(48), "big") % (2 * P_INT)
                         for _ in range(20)]:
            assert fp.from_limbs(fp.to_limbs(v)) == v

    def test_carry_norm_ripple(self):
        """The pathological all-FFF ripple that defeats bounded local
        folding must resolve exactly through the lookahead."""
        import jax.numpy as jnp
        x = np.full(32, fp.MASK, dtype=np.int32)
        x[0] = fp.MASK + 1  # forces a carry that ripples through every limb
        got = np.asarray(fp.carry_norm(jnp.asarray(x)[None, :], 33))[0]
        assert fp.from_limbs(got) == fp.from_limbs(x)

    def test_carry_norm_large_digits(self):
        """Digit sums up to 2^29 (the conv-column bound); the top digit is
        kept small so the value honours the out_len contract, as every
        real convolution output does (4p^2 < 2^768)."""
        import jax.numpy as jnp
        rng = np.random.default_rng(1)
        x = rng.integers(0, 2**29, (8, 63), dtype=np.int64).astype(np.int32)
        x[:, -1] = rng.integers(0, 2**19, 8)
        got = np.asarray(fp.carry_norm(jnp.asarray(x), 64))
        for i in range(8):
            assert fp.from_limbs(got[i]) == fp.from_limbs(x[i])
            assert got[i].max() <= fp.MASK


class TestFieldOps:
    @pytest.mark.parametrize("seed", [0, 1])
    def test_mul_matches_python(self, seed):
        rng = np.random.default_rng(seed)
        va, a = rand_residues(rng, 64)
        vb, b = rand_residues(rng, 64)
        got = np.asarray(fp.modmul_jit(a, b))
        for i in range(64):
            assert fp.from_limbs(got[i]) % P_INT == (va[i] * vb[i]) % P_INT
            assert fp.from_limbs(got[i]) < 2 * P_INT

    def test_mul_edge_values(self):
        import jax.numpy as jnp
        vals = EDGE
        arr = jnp.asarray(np.stack([fp.to_limbs(v) for v in vals]))
        n = len(vals)
        got = np.asarray(fp.modmul_jit(arr[:, None, :].repeat(n, 1).reshape(n * n, -1),
                                       arr[None, :, :].repeat(n, 0).reshape(n * n, -1)))
        k = 0
        for va in vals:
            for vb in vals:
                assert fp.from_limbs(got[k]) % P_INT == (va * vb) % P_INT, (va, vb)
                assert fp.from_limbs(got[k]) < 2 * P_INT
                k += 1

    @pytest.mark.parametrize("op,pyop", [
        ("modadd", lambda a, b: a + b),
        ("modsub", lambda a, b: a - b),
    ])
    def test_add_sub(self, op, pyop):
        rng = np.random.default_rng(7)
        va, a = rand_residues(rng, 64)
        vb, b = rand_residues(rng, 64)
        got = np.asarray(jax.jit(getattr(fp, op))(a, b))
        for i in range(64):
            assert fp.from_limbs(got[i]) % P_INT == pyop(va[i], vb[i]) % P_INT
            assert fp.from_limbs(got[i]) < 2 * P_INT

    def test_neg_canon_eq(self):
        rng = np.random.default_rng(9)
        va, a = rand_residues(rng, 16)
        neg = np.asarray(jax.jit(fp.modneg)(a))
        for i in range(16):
            assert fp.from_limbs(neg[i]) % P_INT == (-va[i]) % P_INT
        can = np.asarray(jax.jit(fp.canon)(a))
        for i in range(16):
            assert fp.from_limbs(can[i]) == va[i] % P_INT
        # eq across non-canonical representatives: v and v + p compare equal
        vplus = jax.numpy.asarray(np.stack(
            [fp.to_limbs((v % P_INT) + P_INT) for v in va]))
        assert np.asarray(jax.jit(fp.eq)(a, vplus)).all()

    def test_inv(self):
        rng = np.random.default_rng(3)
        va, a = rand_residues(rng, 8)
        got = np.asarray(fp.modinv_jit(a))
        for i in range(8):
            inv = fp.from_limbs(got[i]) % P_INT
            assert (inv * va[i]) % P_INT == 1 if va[i] % P_INT != 0 else inv == 0

    def test_inv_zero(self):
        import jax.numpy as jnp
        z = jnp.asarray(fp.ZERO)[None, :]
        assert fp.from_limbs(np.asarray(fp.modinv_jit(z))[0]) % P_INT == 0

    def test_long_chain_stays_reduced(self):
        """1000 chained muls/adds keep residues in [0, 2p) and match
        Python — guards against bound-tracking mistakes accumulating."""
        import jax.numpy as jnp
        rng = np.random.default_rng(11)
        v, x = rand_residues(rng, 4)
        acc_v = [1] * 4
        acc = jnp.asarray(np.stack([fp.to_limbs(1)] * 4))

        @jax.jit
        def step(acc, x):
            return fp.modadd(fp.modmul(acc, x), x)

        for _ in range(1000):
            acc = step(acc, x)
            acc_v = [(a * b + b) % P_INT for a, b in zip(acc_v, v)]
        got = np.asarray(acc)
        for i in range(4):
            assert fp.from_limbs(got[i]) % P_INT == acc_v[i]
            assert fp.from_limbs(got[i]) < 2 * P_INT
