"""Golden-vector conformance (SURVEY.md §4.5): fixed scenarios produce
pinned hash_tree_root / digest values, mirroring the pyspec -> client-team
test-vector pipeline (pos-evolution.md:9). Any semantic drift in SSZ,
state transition, shuffling, or committee assignment trips these.

Regenerate intentionally with:
    python tests/test_golden_vectors.py --regen
"""

import hashlib
import json
import os

import numpy as np
import pytest

from pos_evolution_tpu.config import minimal_config, use_config

VECTOR_FILE = os.path.join(os.path.dirname(__file__), "golden_vectors.json")


def compute_vectors() -> dict:
    with use_config(minimal_config()):
        from pos_evolution_tpu.specs.genesis import make_genesis
        from pos_evolution_tpu.specs.helpers import (
            get_beacon_committee, get_beacon_proposer_index,
            get_shuffled_permutation,
        )
        from pos_evolution_tpu.specs.transition import state_transition
        from pos_evolution_tpu.specs.validator import (
            attest_all_committees, build_block,
        )
        from pos_evolution_tpu.ssz import hash_tree_root, serialize

        out = {}
        state, anchor = make_genesis(64)
        out["genesis_state_root"] = hash_tree_root(state).hex()
        out["genesis_block_root"] = hash_tree_root(anchor).hex()

        sb1 = build_block(state, 1)
        state_transition(state, sb1, True)
        out["state_root_after_block_1"] = hash_tree_root(state).hex()

        atts = attest_all_committees(state, 1, hash_tree_root(sb1.message))
        sb2 = build_block(state, 2, attestations=atts)
        state_transition(state, sb2, True)
        out["state_root_after_block_2"] = hash_tree_root(state).hex()
        out["state_ssz_digest_after_block_2"] = hashlib.sha256(
            serialize(state)).hexdigest()

        # run to the end of epoch 2 (first possible justification)
        for slot in range(3, 3 * 8 + 1):
            atts_prev = attest_all_committees(
                state, slot - 1, state.block_roots[(slot - 1) % 64].tobytes())
            sb = build_block(state, slot, attestations=atts_prev)
            state_transition(state, sb, True)
        out["state_root_epoch_3"] = hash_tree_root(state).hex()
        out["justified_epoch_3"] = int(state.current_justified_checkpoint.epoch)

        perm = get_shuffled_permutation(b"\x21" * 32, 4096)
        out["shuffle_4096_digest"] = hashlib.sha256(
            np.asarray(perm, dtype=np.uint64).tobytes()).hexdigest()

        fresh, _ = make_genesis(64)
        committee = get_beacon_committee(fresh, 3, 1)
        out["committee_slot3_idx1"] = [int(v) for v in committee]
        out["proposer_slot_0"] = int(get_beacon_proposer_index(fresh))
        return out


@pytest.mark.skipif(not os.path.exists(VECTOR_FILE),
                    reason="golden vectors not generated")
def test_golden_vectors_stable():
    with open(VECTOR_FILE) as f:
        want = json.load(f)
    got = compute_vectors()
    mismatches = {k: (want[k], got[k]) for k in want if got.get(k) != want[k]}
    assert not mismatches, f"golden vectors drifted: {mismatches}"


if __name__ == "__main__":
    import sys
    if "--regen" in sys.argv:
        with open(VECTOR_FILE, "w") as f:
            json.dump(compute_vectors(), f, indent=1)
        print(f"wrote {VECTOR_FILE}")
