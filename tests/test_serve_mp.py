"""Multi-process serving plane tests (serve/shm, serve/workers,
serve/balancer, utils/singleflight.ProcessFlight).

Covers, inside-out:

- the shared-memory view board: encode/decode fidelity, the
  publish-once / attach-many seqlock contract, health slots and the
  supervisor's tombstone;
- the cross-process build lease: leader election, spool handoff to
  waiters, dead-leader takeover;
- the 8-process stampede pin: however many processes miss the same
  (block, blob) keys at once, the backing build runs once per key —
  ``sum(leads) == n_keys`` across the whole pool;
- ``WorkerPool`` supervision end-to-end with real spawn children:
  a SIGKILL'd worker is detected as a crash and respawned, a wedged
  worker (heartbeats stop) is detected as a hang and respawned, and
  respawns land on the board's current view generation;
- the balancer's health-biased weighting, including the tombstone's
  routing effect (a cleared front drops to the probe trickle).

Everything here runs on the CPU-pinned test mesh; worker children are
real ``spawn`` processes (the plane's production start method).
"""

from __future__ import annotations

import dataclasses
import json
import multiprocessing
import os
import socket
import subprocess
import sys
import tempfile
import time

import numpy as np
import pytest

from pos_evolution_tpu.config import cfg, minimal_config, use_config
from pos_evolution_tpu.serve.shm import (
    LEASE_BUILDING,
    LEASE_DONE,
    ShmViewBoard,
    decode_view,
    encode_view,
    lease_digest,
)
from pos_evolution_tpu.serve.state import ServeView
from pos_evolution_tpu.utils.singleflight import ProcessFlight

_CTX = multiprocessing.get_context("spawn")


def _tiny_view(slot: int = 7, n_blobs: int = 2) -> ServeView:
    root = bytes([slot % 256]) * 32
    sidecars = [_Sidecar(np.full((8, 40), i + 1, dtype=np.uint8),
                         bytes([i]) * 32) for i in range(n_blobs)]
    return ServeView(
        slot=slot, head_root=root, head_slot=slot,
        justified_epoch=1, justified_root=b"\x01" * 32,
        finalized_epoch=0, finalized_root=b"\x02" * 32,
        update_ssz=b"\x5a" * 64, update_root=b"\x03" * 32,
        sidecars={root: sidecars}, n_cells=16)


class _Sidecar:
    def __init__(self, cells, commitment):
        self.cells = cells
        self.commitment = commitment


def _board(tmp, **kw):
    lock_path = os.path.join(tmp, "board.lock")
    return ShmViewBoard.create(lock_path, **kw), lock_path


# --- shared-memory view board -------------------------------------------------

class TestShmViewBoard:
    def test_encode_decode_roundtrip(self):
        view = _tiny_view()
        out = decode_view(encode_view(view))
        assert out.slot == view.slot
        assert out.head_root == view.head_root
        assert out.update_ssz == view.update_ssz
        assert out.update_root == view.update_root
        assert out.n_cells == view.n_cells
        (root, cars), = out.sidecars.items()
        assert root == view.head_root
        for got, want in zip(cars, view.sidecars[view.head_root]):
            assert got.commitment == want.commitment
            np.testing.assert_array_equal(got.cells, want.cells)

    def test_publish_once_attach_many(self):
        with tempfile.TemporaryDirectory() as tmp:
            board, lock_path = _board(tmp)
            try:
                assert board.current() == (0, None)
                g1 = board.publish(_tiny_view(slot=7))
                reader = ShmViewBoard.attach(board.name, lock_path)
                try:
                    gen, view = reader.current()
                    assert gen == g1 and view.slot == 7
                    # same generation decodes once: the cache is hit
                    assert reader.current()[1] is view
                    g2 = board.publish(_tiny_view(slot=8))
                    assert g2 > g1
                    gen, view = reader.current()
                    assert gen == g2 and view.slot == 8
                finally:
                    reader.close()
            finally:
                board.close()

    def test_health_slots_and_tombstone(self):
        with tempfile.TemporaryDirectory() as tmp:
            board, _ = _board(tmp, n_fronts=4)
            try:
                board.write_health(1, generation=6, brownout=True,
                                   depth=3, requests=42, shed=2)
                (row,) = board.read_health()
                assert row["front"] == 1 and row["pid"] == os.getpid()
                assert row["brownout"] and row["depth"] == 3
                assert row["requests"] == 42 and row["shed"] == 2
                assert row["age_s"] < 2.0
                # the supervisor's tombstone: the slot vanishes from
                # routing immediately, no staleness window
                board.clear_health(1)
                assert board.read_health() == []
            finally:
                board.close()


# --- build lease --------------------------------------------------------------

def _built(n: int = 4) -> dict:
    return {c: (np.full(6, c, dtype=np.uint8),
                np.full((2, 3), c, dtype=np.uint8)) for c in range(n)}


class TestBuildLease:
    def test_leader_spools_then_waiters_absorb(self):
        with tempfile.TemporaryDirectory() as tmp:
            board, _ = _board(tmp)
            try:
                digest = lease_digest(("proofs", b"\x07" * 32, 0))
                role, slot = board.lease_acquire(digest)
                assert role == "lead" and slot >= 0
                built = _built()
                board.spool_write(digest, built)
                board.lease_done(slot, digest)
                role2, slot2 = board.lease_acquire(digest)
                assert role2 == "done" and slot2 == slot
                got = board.spool_read(digest)
                assert set(got) == set(built)
                for c in built:
                    np.testing.assert_array_equal(got[c][0], built[c][0])
                    np.testing.assert_array_equal(got[c][1], built[c][1])
            finally:
                board.close()

    def test_live_leader_makes_waiters(self):
        with tempfile.TemporaryDirectory() as tmp:
            board, _ = _board(tmp)
            try:
                digest = lease_digest(("proofs", b"\x08" * 32, 1))
                role, slot = board.lease_acquire(digest)
                assert role == "lead"
                # this process IS the live leader: a second claimant
                # must wait, not build
                assert board.lease_acquire(digest) == ("wait", slot)
                assert board.lease_state(slot, digest) == (
                    LEASE_BUILDING, os.getpid())
            finally:
                board.close()

    def test_dead_leader_takeover(self):
        with tempfile.TemporaryDirectory() as tmp:
            board, _ = _board(tmp)
            try:
                # a real dead pid: spawn-and-reap a child
                proc = subprocess.run([sys.executable, "-c", "pass"])
                dead = subprocess.Popen([sys.executable, "-c", "pass"])
                dead_pid = dead.pid
                dead.wait()
                assert proc.returncode == 0
                digest = lease_digest(("proofs", b"\x09" * 32, 2))
                board._write_lease(0, digest, LEASE_BUILDING, dead_pid)
                role, slot = board.lease_acquire(digest)
                assert (role, slot) == ("lead", 0)
            finally:
                board.close()


# --- 8-process stampede -------------------------------------------------------

def _stampede_child(board_name: str, lock_path: str, barrier,
                    out_path: str, n_keys: int) -> None:
    """Spawn entry: rendezvous with 7 siblings, then miss every key at
    once. Builds are tiny and deterministic so waiters can check the
    absorbed values."""
    board = ShmViewBoard.attach(board_name, lock_path)
    flight = ProcessFlight(board, timeout_s=60.0)
    results = {}
    try:
        barrier.wait(60.0)
        for k in range(n_keys):
            built = flight.do(("stampede", k),
                              lambda k=k: _built(4 + k))
            results[k] = int(sum(int(v[0][0]) for v in built.values()))
        payload = {"leads": flight.leads,
                   "cross_waits": flight.cross_waits,
                   "fallbacks": flight.fallbacks,
                   "results": results}
    finally:
        board.close()
    with open(out_path, "w") as f:
        json.dump(payload, f)


class TestEightProcessStampede:
    def test_builds_once_per_key_across_eight_processes(self):
        n_procs, n_keys = 8, 2
        with tempfile.TemporaryDirectory() as tmp:
            board, lock_path = _board(tmp)
            barrier = _CTX.Barrier(n_procs)
            outs = [os.path.join(tmp, f"p{i}.json")
                    for i in range(n_procs)]
            procs = [_CTX.Process(target=_stampede_child,
                                  args=(board.name, lock_path, barrier,
                                        outs[i], n_keys))
                     for i in range(n_procs)]
            try:
                for p in procs:
                    p.start()
                for p in procs:
                    p.join(120.0)
                    assert p.exitcode == 0
                reports = []
                for path in outs:
                    with open(path) as f:
                        reports.append(json.load(f))
            finally:
                for p in procs:
                    if p.is_alive():
                        p.kill()
                board.close()
            # THE stampede pin: one build per key across the entire
            # process pool — every other (process, key) pair absorbed
            # the leader's spool
            assert sum(r["leads"] for r in reports) == n_keys
            assert sum(r["fallbacks"] for r in reports) == 0
            assert (sum(r["cross_waits"] for r in reports)
                    == n_procs * n_keys - n_keys)
            # and every process saw the same built values
            want = {str(k): (4 + k) * (3 + k) // 2 for k in range(n_keys)}
            for r in reports:
                assert r["results"] == want


# --- WorkerPool supervision ---------------------------------------------------

def _free_ports(n: int) -> list[int]:
    ports, socks = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


class TestWorkerPoolSupervision:
    def test_crash_and_hang_detection_respawn_on_current_generation(self):
        from pos_evolution_tpu.serve.workers import WorkerPool, worker_spec

        with use_config(minimal_config()), \
                tempfile.TemporaryDirectory() as tmp:
            board, lock_path = _board(tmp, n_fronts=4)
            board.publish(_tiny_view(slot=1))
            (port,) = _free_ports(1)
            cfg_dict = dataclasses.asdict(cfg())
            # worker 1 wedges shortly after ready: its beat thread goes
            # silent inside the window while the process stays alive —
            # exactly what hang detection exists to catch. The window is
            # short enough that the RESPAWNED child is outside it (a
            # still-open window would wedge every respawn into parking)
            wedge_at = time.time() + 3.0
            specs = [
                worker_spec(0, port, board.name, lock_path, tmp,
                            threads=1, config=cfg_dict),
                worker_spec(1, port, board.name, lock_path, tmp,
                            threads=1, config=cfg_dict,
                            chaos={"wedge_windows":
                                   [(wedge_at, wedge_at + 2.5)]}),
            ]
            pool = WorkerPool(specs, board, hang_timeout_s=1.5,
                              backoff_s=0.1, backoff_cap_s=0.5)
            try:
                pool.start()
                assert pool.wait_ready(60.0), "pool never became ready"
                killed = pool.kill_worker(0)
                assert killed is not None
                deadline = time.monotonic() + 60.0
                while time.monotonic() < deadline:
                    reasons = {i["reason"] for i in pool.interruptions}
                    rows = pool.worker_rows()
                    if {"crash", "hang"} <= reasons \
                            and all(r["alive"] for r in rows) \
                            and all(r["restarts"] >= 1 for r in rows):
                        break
                    time.sleep(0.1)
                reasons = [i["reason"] for i in pool.interruptions]
                assert "crash" in reasons, reasons
                assert "hang" in reasons, reasons
                by_worker = {i["worker"]: i["reason"]
                             for i in pool.interruptions}
                assert by_worker.get(0) == "crash"
                assert by_worker.get(1) == "hang"
                rows = pool.worker_rows()
                assert all(r["alive"] for r in rows), rows
                assert all(r["restarts"] >= 1 for r in rows), rows
                # respawns serve the CURRENT published view: advance the
                # generation and require both children to converge on it
                gen = board.publish(_tiny_view(slot=2))
                deadline = time.monotonic() + 30.0
                while time.monotonic() < deadline:
                    rows = pool.worker_rows()
                    if all(r["generation"] == gen for r in rows):
                        break
                    time.sleep(0.1)
                assert all(r["generation"] == gen
                           for r in pool.worker_rows()), \
                    (gen, pool.worker_rows())
            finally:
                pool.stop()
                board.close()


# --- balancer weighting -------------------------------------------------------

class TestBalancerWeighting:
    def _shares(self, bal, n: int = 2000) -> list[float]:
        counts = [0] * bal.n_fronts
        for i in range(n):
            counts[bal.pick((i + 0.5) / n)] += 1
        return [c / n for c in counts]

    def test_health_bias_and_tombstone_trickle(self):
        from pos_evolution_tpu.serve.balancer import Balancer

        with tempfile.TemporaryDirectory() as tmp:
            board, _ = _board(tmp, n_fronts=4)
            try:
                for slot in (0, 1, 2, 3):
                    board.write_health(slot, generation=2)
                bal = Balancer(2, board=board,
                               slot_map=[[0, 1], [2, 3]],
                               refresh_s=0.0)
                shares = self._shares(bal)
                assert abs(shares[0] - 0.5) < 0.05, shares
                # front 1 browns out entirely: it keeps a reduced share
                # (brownout is degradation, not death)
                board.write_health(2, generation=2, brownout=True)
                board.write_health(3, generation=2, brownout=True)
                shares = self._shares(bal)
                assert shares[0] > 0.65, shares
                assert shares[1] > 0.1, shares
                # both its workers die and the supervisor tombstones
                # them: the front drops to the probe trickle at once
                board.clear_health(2)
                board.clear_health(3)
                shares = self._shares(bal)
                assert shares[1] < 0.1, shares
                assert shares[0] > 0.9, shares
            finally:
                board.close()

    def test_no_board_is_uniform(self):
        from pos_evolution_tpu.serve.balancer import Balancer

        bal = Balancer(4)
        shares = self._shares(bal)
        assert all(abs(s - 0.25) < 0.02 for s in shares), shares


# --- end-to-end scenario (heavy: full mp plane under chaos) -------------------

@pytest.mark.slow
class TestMpScenario:
    def test_chaos_scenario_verdict_ok(self):
        from pos_evolution_tpu.serve.harness import run_mp_scenario

        with use_config(minimal_config()):
            result = run_mp_scenario(
                arrivals=12000, rate=6000.0, seed=11, kills=1,
                wedges=1, fd_exhaust_n=32)
        verdict = result["verdict"]
        assert verdict["ok"], verdict
        assert verdict["interactive_goodput_pct"] >= 99.0
        assert verdict["lost"] == 0 or \
            result["load"]["lost_by_reason"], "losses must carry reasons"
