"""Device flight recorder tests (ISSUE 19): the compile-provenance
ledger's span context and attribution math, the jaxrt listener
lifecycle (install / swap / detach / reattach), the CPU host-RSS
memory-watermark fallback, shard-skew probing, the armed dense run
end-to-end (>=95% named attribution on fresh compiles), perf_diff's
doctored-regression attribution ranking, obs_top's snapshot render,
and the trace_summary deprecation shim pin."""

import importlib
import json
import os
import sys
import warnings

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))), "scripts"))

from pos_evolution_tpu.config import mainnet_config  # noqa: E402
from pos_evolution_tpu.profiling import ledger  # noqa: E402
from pos_evolution_tpu.telemetry import (  # noqa: E402
    MetricsRegistry,
    Telemetry,
)
from pos_evolution_tpu.telemetry import jaxrt  # noqa: E402
from pos_evolution_tpu.telemetry.device import (  # noqa: E402
    DeviceMemorySampler,
    FlightRecorder,
    host_rss_bytes,
    shard_completion_times,
)

FIXTURE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "fixtures", "mini.xplane.pb")

BACKEND_EVT = "/jax/core/compile/backend_compile_duration"
TRACE_EVT = "/jax/core/compile/jaxpr_trace_duration"


@pytest.fixture
def jaxrt_state():
    """Save/restore the process-global jaxrt wiring so these tests can
    swap registries and ledgers without leaking into the rest of the
    suite (listener registration itself is irrevocable and shared)."""
    saved = dict(jaxrt._STATE)
    yield jaxrt._STATE
    jaxrt._STATE.update(saved)


def _cfg(slots_per_epoch=8):
    return mainnet_config().replace(slots_per_epoch=slots_per_epoch,
                                    max_committees_per_slot=4)


# -- span context / provenance -------------------------------------------------

class TestSpanContext:
    def test_phase_push_pop_nests(self):
        assert ledger.current_phase() is None
        prev = ledger.push_phase("vote_pass")
        inner = ledger.push_phase("epoch_sweep")
        assert ledger.current_phase() == "epoch_sweep"
        ledger.pop_phase(inner)
        assert ledger.current_phase() == "vote_pass"
        ledger.pop_phase(prev)
        assert ledger.current_phase() is None

    def test_function_scope_restores_outer(self):
        with ledger.function_scope("outer"):
            with ledger.function_scope("inner"):
                assert ledger.current_function() == "inner"
            assert ledger.current_function() == "outer"
        assert ledger.current_function() is None

    def test_provenance_precedence(self):
        """function_scope > inline:<phase> > region > '?'."""
        assert ledger.provenance("backend_compile_duration") == \
            ("backend_compile", "?", "?")
        prev_r = ledger.push_region("ad_hoc_block")
        assert ledger.provenance("backend_compile_duration")[1] == \
            "ad_hoc_block"
        prev_p = ledger.push_phase("head")
        assert ledger.provenance("backend_compile_duration") == \
            ("backend_compile", "inline:head", "head")
        with ledger.function_scope("sharded:votes"):
            assert ledger.provenance("backend_compile_duration") == \
                ("backend_compile", "sharded:votes", "head")
        ledger.pop_phase(prev_p)
        ledger.pop_region(prev_r)

    def test_unknown_stage_passes_through(self):
        stage, _, _ = ledger.provenance("weird_duration")
        assert stage == "weird_duration"

    def test_phase_block_sets_context(self):
        """profiling/phases.py pushes the phase slot on enter/exit."""
        from pos_evolution_tpu.profiling.phases import PhaseTimer
        pt = PhaseTimer(sample_every=1)
        pt.begin_slot(0)
        with pt.phase("epoch_sweep"):
            assert ledger.current_phase() == "epoch_sweep"
        assert ledger.current_phase() is None
        pt.end_slot(0)

    def test_profiled_region_sets_region(self, monkeypatch):
        import jax
        from pos_evolution_tpu.profiling.attribution import ProfiledRegion

        def _refuse(*a, **kw):
            raise RuntimeError("no tracing in this test")
        # force the degrade path: the region must set the span context
        # even when the jax profiler can't start (and starting a real
        # trace here would cost seconds for nothing)
        monkeypatch.setattr(jax.profiler, "start_trace", _refuse)
        with ProfiledRegion("bench_epoch") as prof:
            assert ledger.current_region() == "bench_epoch"
        assert prof.error is not None
        assert ledger.current_region() is None


# -- CompileLedger -------------------------------------------------------------

class TestCompileLedger:
    def test_rows_and_attribution(self):
        led = ledger.CompileLedger()
        prev = ledger.push_phase("epoch_sweep")
        led.on_duration(BACKEND_EVT, 0.25)
        led.on_duration(BACKEND_EVT, 0.05)
        led.on_duration(TRACE_EVT, 0.01)
        ledger.pop_phase(prev)
        led.on_duration(BACKEND_EVT, 0.40)  # no context: '?' row
        rows = led.rows()
        assert rows[0] == {"stage": "backend_compile", "phase": "?",
                           "function": "?", "count": 1, "seconds": 0.4}
        named = [r for r in rows if r["phase"] == "epoch_sweep"]
        assert {r["stage"] for r in named} == {"backend_compile", "trace"}
        attr = led.attribution()
        assert attr == {"backend_compiles": 3, "seen": 3, "named": 2,
                        "named_pct": 66.67}

    def test_attribution_against_listener_total(self):
        """With ``total`` from the registry counter, unledgered compiles
        (fired before attach) dilute named_pct — the acceptance bar is
        measured against the full listener count."""
        led = ledger.CompileLedger()
        prev = ledger.push_phase("head")
        led.on_duration(BACKEND_EVT, 0.1)
        ledger.pop_phase(prev)
        assert led.attribution(total=2)["named_pct"] == 50.0
        assert led.attribution(total=0)["named_pct"] is None

    def test_registry_counter_rides_along(self):
        reg = MetricsRegistry()
        led = ledger.CompileLedger(registry=reg)
        with ledger.function_scope("sharded:epoch"):
            led.on_duration(BACKEND_EVT, 0.2)
        counts = reg.counts()
        key = ("jax_compiles_by_provenance_total;function=sharded:epoch;"
               "phase=?;stage=backend_compile")
        assert counts.get(key) == 1


# -- jaxrt lifecycle (satellite c) ---------------------------------------------

class TestJaxrtLifecycle:
    def test_install_swap_detach_reattach(self, jaxrt_state):
        """Counters land in whichever registry is installed *now*;
        detaching stops the flow without unregistering the listeners;
        reattach resumes it."""
        reg1, reg2 = MetricsRegistry(), MetricsRegistry()
        jaxrt.install(reg1)
        jaxrt._on_duration(BACKEND_EVT, 0.1)
        assert reg1.counts().get("jax_backend_compiles_total") == 1

        jaxrt.install(reg2)  # swap: last install wins
        jaxrt._on_duration(BACKEND_EVT, 0.1)
        assert reg1.counts().get("jax_backend_compiles_total") == 1
        assert reg2.counts().get("jax_backend_compiles_total") == 1

        jaxrt.install(None)  # detach
        assert jaxrt.current() is None
        jaxrt._on_duration(BACKEND_EVT, 0.1)
        jaxrt._on_event("/jax/some/event")
        assert reg2.counts().get("jax_backend_compiles_total") == 1

        jaxrt.install(reg1)  # reattach
        jaxrt._on_duration(TRACE_EVT, 0.1)
        assert reg1.counts().get("jax_traces_total") == 1

    def test_detached_record_helpers_are_noops(self, jaxrt_state):
        """The no-jax / no-registry degradation path: every explicit
        hook must be a silent no-op, never a crash."""
        jaxrt.install(None)
        jaxrt.attach_ledger(None)
        jaxrt.record_dispatch(3, site="x")
        jaxrt.record_transfer(1024, direction="d2h", site="x")
        jaxrt.record_donation(1024, site="x", armed=False)
        jaxrt._on_duration(BACKEND_EVT, 0.1)
        jaxrt._on_event("/jax/any")

    def test_ledger_attach_is_independent_of_registry(self, jaxrt_state):
        """A ledger without a registry still accumulates rows."""
        jaxrt.install(None)
        led = ledger.CompileLedger()
        jaxrt.attach_ledger(led)
        assert jaxrt.current_ledger() is led
        jaxrt._on_duration(BACKEND_EVT, 0.1)
        assert led.attribution()["seen"] == 1
        jaxrt.attach_ledger(None)
        jaxrt._on_duration(BACKEND_EVT, 0.1)
        assert led.attribution()["seen"] == 1

    def test_broken_ledger_never_kills_the_listener(self, jaxrt_state):
        class Bomb:
            def on_duration(self, event, duration):
                raise RuntimeError("boom")
        reg = MetricsRegistry()
        jaxrt.install(reg)
        jaxrt.attach_ledger(Bomb())
        jaxrt._on_duration(BACKEND_EVT, 0.1)  # must not raise
        assert reg.counts().get("jax_backend_compiles_total") == 1

    def test_transfer_charges_active_phase_separately(self, jaxrt_state):
        """Phase attribution lives in jax_transfer_bytes_by_phase_total;
        the site-keyed jax_transfer_bytes_total keys are a pinned
        contract and must not grow a phase label."""
        reg = MetricsRegistry()
        jaxrt.install(reg)
        jaxrt.record_transfer(100, direction="d2h", site="ckpt")
        prev = ledger.push_phase("checkpoint")
        jaxrt.record_transfer(28, direction="d2h", site="ckpt")
        ledger.pop_phase(prev)
        counts = reg.counts()
        assert counts[
            "jax_transfer_bytes_total;direction=d2h;site=ckpt"] == 128
        assert counts["jax_transfer_bytes_by_phase_total;direction=d2h;"
                      "phase=checkpoint"] == 28
        assert not any("phase" in k and k.startswith(
            "jax_transfer_bytes_total") for k in counts)

    def test_donation_counter_armed_pair(self, jaxrt_state):
        reg = MetricsRegistry()
        jaxrt.install(reg)
        jaxrt.record_donation(1000, site="epoch_step", armed=True)
        jaxrt.record_donation(24, site="epoch_step", armed=False)
        counts = reg.counts()
        assert counts[
            "jax_donation_bytes_total;armed=1;site=epoch_step"] == 1000
        assert counts[
            "jax_donation_bytes_total;armed=0;site=epoch_step"] == 24

    def test_host_gather_records_d2h_bytes(self, jaxrt_state):
        import jax.numpy as jnp
        from pos_evolution_tpu.parallel import sharded
        reg = MetricsRegistry()
        jaxrt.install(reg)
        out = sharded.host_gather({"a": jnp.zeros(8, jnp.float32),
                                   "b": jnp.zeros((2, 4), jnp.int32)})
        assert isinstance(out["a"], np.ndarray)
        assert reg.counts()[
            "jax_transfer_bytes_total;direction=d2h;site=host_gather"] == 64


# -- memory watermarks ---------------------------------------------------------

class TestDeviceMemorySampler:
    def test_cpu_fallback_is_host_rss(self):
        """jax CPU devices return memory_stats() = None, so the sampler
        must fall back to /proc/self/statm and label it honestly."""
        rss = host_rss_bytes()
        if rss is None:
            pytest.skip("no /proc/self/statm on this platform")
        sampler = DeviceMemorySampler()
        rows = sampler.sample(site="slot", slot=0)
        assert sampler.source in ("host_rss", "memory_stats")
        if sampler.source == "host_rss":
            assert rows == [{"device": "host", "platform": "host_rss",
                             "bytes_in_use": rows[0]["bytes_in_use"]}]
            assert rows[0]["bytes_in_use"] > 0

    def test_gauges_events_and_peaks(self):
        reg = MetricsRegistry()
        events = []

        class Bus:
            def emit(self, type_, **kw):
                events.append({"type": type_, **kw})
        sampler = DeviceMemorySampler(registry=reg, bus=Bus())
        sampler.sample(site="slot", slot=0)
        sampler.sample(site="epoch", slot=7)
        wm = sampler.watermark()
        assert wm["samples"] == 2 and wm["source"] is not None
        assert all(v > 0 for v in wm["peak_bytes"].values())
        assert [e["site"] for e in events] == ["slot", "epoch"]
        assert events[1]["slot"] == 7 and events[1]["rows"]
        series = reg.snapshot()["metrics"]["device_memory_bytes"]["series"]
        stats = {row["labels"]["stat"] for row in series}
        assert {"bytes_in_use", "peak_bytes_in_use"} <= stats

    def test_curve_stays_bounded(self):
        sampler = DeviceMemorySampler(curve_cap=8)
        for i in range(64):
            sampler.sample(site="slot", slot=i)
        assert len(sampler.curve) < 8
        assert sampler.watermark()["curve_stride"] > 1
        # endpoints survive decimation
        assert sampler.curve[0]["slot"] == 0

    def test_sampler_never_raises_with_broken_sinks(self):
        class Bomb:
            def emit(self, *a, **kw):
                raise RuntimeError("closed")

            def gauge(self, *a, **kw):
                raise RuntimeError("closed")
        sampler = DeviceMemorySampler(registry=Bomb(), bus=Bomb())
        assert sampler.sample(site="slot") is not None


# -- shard skew ----------------------------------------------------------------

class TestShardSkew:
    def test_single_device_array_one_row(self):
        import jax.numpy as jnp
        rows = shard_completion_times(jnp.arange(16))
        assert len(rows) >= 1
        assert all(r["ms"] >= 0 for r in rows)

    def test_host_array_is_empty(self):
        assert shard_completion_times(np.arange(4)) == []
        assert shard_completion_times(None) == []

    def test_probe_accumulates_and_emits(self):
        import jax.numpy as jnp
        reg = MetricsRegistry()
        events = []

        class Bus:
            def emit(self, type_, **kw):
                events.append({"type": type_, **kw})
        fr = FlightRecorder(registry=reg, bus=Bus(), memory=False,
                            ledger=False)
        fr.probe_skew("vote_pass", jnp.arange(8), slot=0)
        fr.probe_skew("vote_pass", jnp.arange(8), slot=16)
        table = fr.skew_table()
        assert table and table[0]["phase"] == "vote_pass"
        assert table[0]["probes"] == 2
        assert table[0]["max_ms"] >= table[0]["mean_ms"] >= 0
        skew_events = [e for e in events if e["type"] == "shard_skew"]
        assert [e["slot"] for e in skew_events] == [0, 16]
        assert all(e["spread_ms"] >= 0 for e in skew_events)

    @pytest.mark.mesh8
    def test_sharded_array_names_every_device(self):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec
        from pos_evolution_tpu.parallel.collectives import SHARD_AXIS
        from pos_evolution_tpu.parallel.sharded import make_mesh
        mesh = make_mesh(8, 2)
        arr = jax.device_put(
            jnp.arange(64, dtype=jnp.float32),
            NamedSharding(mesh, PartitionSpec(SHARD_AXIS)))
        rows = shard_completion_times(arr)
        assert len(rows) == 8
        assert len({r["device"] for r in rows}) == 8


# -- flight recorder lifecycle + armed dense run -------------------------------

class TestFlightRecorder:
    def test_should_probe_cadence(self):
        fr = FlightRecorder(sample_every=16, memory=False, ledger=False,
                            skew=False)
        assert [s for s in range(64) if fr.should_probe(s)] == [0, 16, 32, 48]

    def test_install_detach(self, jaxrt_state):
        tel = Telemetry()
        fr = FlightRecorder(telemetry=tel)
        assert not fr.installed
        fr.install()
        assert fr.installed
        assert jaxrt.current() is tel.registry
        assert jaxrt.current_ledger() is fr.ledger
        fr.detach()
        assert not fr.installed
        assert jaxrt.current_ledger() is None

    def test_detach_spares_a_newer_ledger(self, jaxrt_state):
        """detach() only removes *its own* ledger — a second recorder
        installed later must not be torn down by the first's cleanup."""
        fr1 = FlightRecorder(registry=MetricsRegistry())
        fr2 = FlightRecorder(registry=MetricsRegistry())
        fr1.install()
        fr2.install()
        fr1.detach()
        assert jaxrt.current_ledger() is fr2.ledger
        fr2.detach()

    def test_armed_dense_run_end_to_end(self, jaxrt_state, tmp_path):
        """The tentpole, in one assertion pile: an armed CPU run
        produces named compile attribution, memory samples with an
        honest source label, skew probes, a device section in the sim
        summary, and an artifact run_report renders as '## Device'.

        256 validators / shuffle_rounds=6 deliberately matches the
        test_dense_chaos shapes so a full-suite run reuses the op
        cache; standalone, the fresh compiles exercise the ledger."""
        from pos_evolution_tpu.sim.dense_driver import DenseSimulation
        events_path = tmp_path / "events.jsonl"
        tel = Telemetry.to_file(str(events_path))
        fr = FlightRecorder(telemetry=tel, sample_every=8)
        sim = DenseSimulation(256, cfg=_cfg(), mesh=None, seed=3,
                              shuffle_rounds=6, check_walk_every=0,
                              telemetry=tel, phase_profile=8,
                              flight_recorder=fr)
        assert not fr.installed  # arming is lazy: first run_slot
        sim.run_epochs(2)
        assert fr.installed
        summary = sim.summary()
        dev = summary["device"]

        # memory watermarks with an honest source label
        assert dev["memory"]["samples"] > 0
        assert dev["memory"]["source"] in ("memory_stats", "host_rss")
        assert all(v > 0 for v in dev["memory"]["peak_bytes"].values())

        # compile attribution: every ledgered backend compile from this
        # run is named (the sim compiles inside phase blocks); measured
        # against the listener total the bar is >=95% only when this
        # test ran with fresh shapes, so assert on the ledger's own rows
        attr = dev["compile_ledger"]["attribution"]
        if attr["seen"]:
            assert attr["named"] == attr["seen"]
            assert all(r["phase"] != "?"
                       for r in dev["compile_ledger"]["rows"]
                       if r["stage"] == "backend_compile")

        # skew probes ran at the fenced cadence
        assert dev["shard_skew"]["probes"] > 0
        phases = {r["phase"] for r in dev["shard_skew"]["table"]}
        assert "vote_pass" in phases and "epoch_sweep" in phases

        # events landed on the bus
        types = {e["type"] for e in tel.bus.events}
        assert "device_memory" in types and "shard_skew" in types

        # artifact -> run_report device section
        artifact = tmp_path / "run.device_ledger.json"
        fr.write_artifact(str(artifact))
        import run_report
        tel.bus.close()
        found = run_report.discover_device_ledger(str(events_path))
        assert found == str(artifact)
        with open(artifact) as fh:
            doc = json.load(fh)
        report = run_report.build_report(
            list(run_report.read_jsonl(str(events_path))),
            device_ledger=doc)
        assert report["device"]["memory"]["samples"] == \
            dev["memory"]["samples"]
        md = run_report.to_markdown(report)
        assert "## Device" in md
        assert "watermark" in md
        fr.detach()

    def test_unarmed_run_has_no_device_section(self):
        from pos_evolution_tpu.sim.dense_driver import DenseSimulation
        # same shapes as the armed run above: the op cache is warm
        sim = DenseSimulation(256, cfg=_cfg(), mesh=None, seed=3,
                              shuffle_rounds=6, check_walk_every=0)
        sim.run_epochs(1)
        assert "device" not in sim.summary()


# -- perf_diff -----------------------------------------------------------------

class TestPerfDiff:
    def _emission(self, sweep_ms, compiles=8):
        return {"walls": {"steady_ms": 40.0 + sweep_ms},
                "phases": {"vote_pass": {"total_ms": 30.0},
                           "epoch_sweep": {"total_ms": sweep_ms},
                           "record": {"total_ms": 2.0}},
                "counts": {"jax_backend_compiles_total": compiles},
                "device": {"compile_ledger": {"rows": [
                    {"stage": "backend_compile",
                     "function": "inline:epoch_sweep",
                     "phase": "epoch_sweep", "count": compiles,
                     "seconds": 0.5}]}}}

    def test_doctored_x10_phase_ranks_first(self):
        """The CI negative: multiply one phase x10 and perf_diff must
        name it as the top attribution with ~100% of the wall delta."""
        import perf_diff
        d = perf_diff.diff(self._emission(10.0), self._emission(100.0))
        assert d["top_phase"] == "epoch_sweep"
        assert d["phases"][0]["ratio"] == 10.0
        assert d["phases"][0]["wall_share_pct"] == 100.0
        assert d["wall"]["delta_ms"] == 90.0
        text = perf_diff.render(d)
        assert "top attribution: epoch_sweep" in text

    def test_counter_and_ledger_deltas_rank(self):
        import perf_diff
        d = perf_diff.diff(self._emission(10.0, compiles=8),
                           self._emission(10.0, compiles=64))
        assert d["counters"][0]["counter"] == "jax_backend_compiles_total"
        assert d["counters"][0]["ratio"] == 8.0
        led = d["compile_ledger"][0]
        assert led["function"] == "inline:epoch_sweep"
        assert led["delta"] == 56

    def test_event_log_side(self, tmp_path):
        import perf_diff
        path = tmp_path / "ev.jsonl"
        with open(path, "w") as fh:
            for seq, (slot, ms) in enumerate(((0, 5.0), (8, 7.0))):
                fh.write(json.dumps({
                    "v": 1, "seq": seq,
                    "type": "dense_phase", "slot": slot,
                    "wall_ms": ms + 1.0,
                    "phases": {"vote_pass": ms}}) + "\n")
        side = perf_diff.load_side(str(path))
        assert side["phases"] == {"vote_pass": 12.0}
        assert side["wall_ms"] == 14.0

    def test_history_mode_cli(self, tmp_path, capsys):
        import perf_diff
        hist = tmp_path / "bench_history.jsonl"
        with open(hist, "w") as fh:
            for seq, ms in enumerate((10.0, 100.0)):
                fh.write(json.dumps({"v": 1, "seq": seq,
                                     "kind": "bench_obs",
                                     "emission": self._emission(ms)}) + "\n")
        assert perf_diff.main(["--history", str(hist),
                               "--kind", "bench_obs"]) == 0
        out = capsys.readouterr().out
        assert "top attribution: epoch_sweep" in out

    def test_gate_failure_prints_attribution(self, tmp_path, capsys):
        """perf_gate's FAIL path must append the perf_diff table so CI
        logs carry the culprit, while the exit code stays 1."""
        from perf_gate import main
        base, cand = self._emission(10.0, 8), self._emission(100.0, 64)
        bp, cp = tmp_path / "b.json", tmp_path / "c.json"
        bp.write_text(json.dumps(base))
        cp.write_text(json.dumps(cand))
        assert main(["--candidate", str(cp), "--baseline", str(bp),
                     "--count-only"]) == 1
        out = capsys.readouterr().out
        assert "PERF GATE: FAIL" in out
        assert "attribution (scripts/perf_diff.py)" in out
        assert "top attribution: epoch_sweep" in out


# -- obs_top -------------------------------------------------------------------

class TestObsTop:
    def test_once_snapshot_renders_everything(self, tmp_path):
        import obs_top
        from pos_evolution_tpu.utils.watchdog import Heartbeat
        rundir = tmp_path
        Heartbeat(str(rundir / "worker0.hb")).beat(
            slot=96, justified_epoch=11, finalized_epoch=10)
        fr = FlightRecorder(registry=MetricsRegistry())
        fr.ledger.on_duration(BACKEND_EVT, 0.3)
        fr.sample_memory(site="slot", slot=96)
        fr.write_artifact(str(rundir / "run.device_ledger.json"))
        events = rundir / "ev.jsonl"
        with open(events, "w") as fh:
            fh.write(json.dumps({"type": "slot", "slot": 96}) + "\n")
        snap = obs_top.collect(str(rundir), events=str(events))
        text = obs_top.render(snap)
        assert "slot 96" in text
        assert "justified 11" in text and "lag 1" in text
        assert "worker0.hb" in text
        assert "hbm watermark" in text
        assert "compiles: " in text

    def test_empty_dir_degrades_politely(self, tmp_path):
        import obs_top
        snap = obs_top.collect(str(tmp_path))
        assert "nothing to show yet" in obs_top.render(snap)

    def test_torn_event_tail_is_skipped(self, tmp_path):
        import obs_top
        path = tmp_path / "ev.jsonl"
        with open(path, "w") as fh:
            fh.write(json.dumps({"type": "slot", "slot": 5}) + "\n")
            fh.write('{"type": "slot", "slot"')  # torn final line
        out = obs_top._tail_events(str(path))
        assert out["slot"]["slot"] == 5


# -- trace_summary deprecation shim (satellite b) ------------------------------

class TestTraceSummaryDeprecation:
    def test_import_warns_and_still_forwards(self):
        """The fold-into-run_report contract: importing the old script
        emits DeprecationWarning, but summarize_path keeps forwarding to
        profiling.xplane byte-for-byte."""
        sys.modules.pop("trace_summary", None)
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            trace_summary = importlib.import_module("trace_summary")
        assert any(issubclass(w.category, DeprecationWarning)
                   for w in caught)
        from pos_evolution_tpu.profiling import xplane
        assert trace_summary.summarize_path(FIXTURE, 2) == \
            xplane.summarize_path(FIXTURE, 2)

    def test_cli_still_prints_same_json(self, capsys):
        import trace_summary
        assert trace_summary.main([FIXTURE, "1"]) == 0
        out, err = capsys.readouterr()
        assert "deprecated" in err
        top = json.loads(out)
        assert top["/host:CPU"][0]["op"] == "bench_epoch"

    def test_cli_no_args_is_usage_error(self, capsys):
        import trace_summary
        assert trace_summary.main([]) == 2

    def test_run_report_xplane_flag_took_over(self, tmp_path):
        """run_report --xplane produces the same top-ops table the old
        CLI printed (the fold-in, not a fork)."""
        import run_report
        events = tmp_path / "ev.jsonl"
        events.write_text(json.dumps(
            {"v": 1, "seq": 0, "type": "run_meta", "slot": 0}) + "\n")
        out = tmp_path / "report.json"
        rc = run_report.main([str(events), "--xplane", FIXTURE,
                              "--top-n", "1", "--json", str(out)])
        assert rc == 0
        with open(out) as fh:
            report = json.load(fh)
        assert report["top_device_ops"]["/host:CPU"][0]["op"] == \
            "bench_epoch"
