"""ExecutionBackend dispatch tests (layer LB): the spec layer must produce
bit-identical states under the numpy and jax backends (SURVEY.md §4.4b —
"identical spec-level inputs must give bit-identical justification/
finalization/head outputs").
"""

import numpy as np
import pytest

from pos_evolution_tpu.backend import get_backend, set_backend
from pos_evolution_tpu.specs.genesis import make_genesis
from pos_evolution_tpu.specs.transition import state_transition
from pos_evolution_tpu.specs.validator import attest_all_committees, build_block
from pos_evolution_tpu.ssz import hash_tree_root

jax = pytest.importorskip("jax")

pytestmark = pytest.mark.usefixtures("minimal_cfg")


@pytest.fixture(autouse=True)
def _restore_backend():
    yield
    set_backend("numpy")


def _run_chain(n_epochs: int):
    state, _ = make_genesis(64)
    atts = []
    roots = []
    for slot in range(1, n_epochs * 8 + 1):
        sb = build_block(state, slot, attestations=atts)
        state_transition(state, sb, True)
        atts = attest_all_committees(state, slot, hash_tree_root(sb.message))
        if slot % 8 == 0:
            roots.append(hash_tree_root(state).hex())
    return state, roots


class TestBackendParity:
    def test_chain_identical_across_backends(self):
        set_backend("numpy")
        state_np, roots_np = _run_chain(4)
        set_backend("jax")
        assert get_backend().name == "jax"
        state_jax, roots_jax = _run_chain(4)
        assert roots_np == roots_jax, "per-epoch state roots diverged"
        assert int(state_jax.finalized_checkpoint.epoch) >= 2
        assert state_np.finalized_checkpoint == state_jax.finalized_checkpoint

    def test_shuffle_identical_across_backends(self):
        from pos_evolution_tpu.specs.helpers import get_shuffled_permutation
        seed = b"\x3c" * 32
        set_backend("numpy")
        p_np = np.asarray(get_shuffled_permutation(seed, 500))
        set_backend("jax")
        p_jax = np.asarray(get_shuffled_permutation(seed, 500))
        assert np.array_equal(p_np, p_jax)

    def test_churn_state_identical_across_backends(self):
        """Full process_epoch on a state with ejections, fresh deposits, a
        waiting activation queue, and an occupied exit queue must be
        bit-identical under both backends."""
        from pos_evolution_tpu.specs.containers import Checkpoint
        from pos_evolution_tpu.specs.epoch import process_epoch

        def churny_state():
            rng = np.random.default_rng(11)
            state, _ = make_genesis(96)
            c = minimal_config()
            reg = state.validators
            reg.effective_balance[rng.random(96) < 0.15] = c.ejection_balance
            fresh = rng.random(96) < 0.1
            reg.activation_eligibility_epoch[fresh] = 2**64 - 1
            reg.activation_epoch[fresh] = 2**64 - 1
            queued = rng.random(96) < 0.2
            reg.activation_eligibility_epoch[queued] = rng.integers(1, 4, queued.sum())
            reg.activation_epoch[queued] = 2**64 - 1
            exiting = rng.random(96) < 0.1
            reg.exit_epoch[exiting] = rng.integers(12, 15, exiting.sum())
            state.slot = 10 * c.slots_per_epoch - 1
            state.finalized_checkpoint = Checkpoint(epoch=5, root=b"\x05" * 32)
            state.block_roots = rng.integers(
                0, 255, state.block_roots.shape).astype(np.uint8)
            return state

        from pos_evolution_tpu.config import minimal_config
        set_backend("numpy")
        s_np = churny_state()
        process_epoch(s_np)
        set_backend("jax")
        s_jax = churny_state()
        process_epoch(s_jax)
        for col in ("activation_eligibility_epoch", "activation_epoch",
                    "exit_epoch", "withdrawable_epoch", "effective_balance"):
            assert np.array_equal(getattr(s_np.validators, col),
                                  getattr(s_jax.validators, col)), col
        assert hash_tree_root(s_np) == hash_tree_root(s_jax)

    def test_accelerated_epoch_flag(self):
        import pos_evolution_tpu.backend.jax_backend as jb
        import pos_evolution_tpu.backend.numpy_backend as nb
        assert getattr(jb, "accelerated_epoch", False)
        assert not getattr(nb, "accelerated_epoch", False)
