"""Differential tests: batched device preamble (ops/g2prep.py) vs the
exact Python oracle — decompression, Fq2 sqrt, sign canonicalization,
hash-to-G2 with cofactor clearing, and the twist Jacobian arithmetic.

Slow tier: the sqrt/cofactor ladders are 380-760-step scans whose bodies
compile for minutes on XLA:CPU (cheap on TPU). `pytest -m ""` runs them.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

pytestmark = pytest.mark.slow

from pos_evolution_tpu.crypto import bls12_381 as o  # noqa: E402
from pos_evolution_tpu.ops import fp  # noqa: E402
from pos_evolution_tpu.ops import g2prep as gp  # noqa: E402

jnp = jax.numpy


def fq2_of(limbs2):
    return o.Fq2(fp.from_limbs(limbs2[0]), fp.from_limbs(limbs2[1]))


class TestDecompress:
    def test_g1_batch_matches_oracle(self):
        ks = (1, 7, 12345, 0xFEED)
        comp = [o.g1_compress(o.ec_mul(o.G1_GEN, k)) for k in ks]
        xs, signs = [], []
        for d in comp:
            bits = int.from_bytes(d, "big")
            signs.append(bool(bits & (1 << 381)))
            xs.append(fp.to_limbs(bits & ((1 << 381) - 1)))
        pts, ok = gp.g1_decompress_batch(
            jnp.asarray(np.stack(xs)), jnp.asarray(signs))
        assert np.asarray(ok).all()
        for i, d in enumerate(comp):
            ox, oy = o.g1_decompress(d)
            assert fp.from_limbs(np.asarray(pts)[i, 0]) == ox
            assert fp.from_limbs(np.asarray(pts)[i, 1]) == oy

    def test_g1_invalid_x_flagged(self):
        # x with no curve point: find one by scanning
        x = 1
        while True:
            y2 = (pow(x, 3, o.Q) + 4) % o.Q
            if pow(y2, (o.Q - 1) // 2, o.Q) != 1:
                break
            x += 1
        pts, ok = gp.g1_decompress_batch(
            jnp.asarray(fp.to_limbs(x)[None]), jnp.asarray([False]))
        assert not bool(np.asarray(ok)[0])

    def test_g2_batch_matches_oracle(self):
        sigs = [o.g2_compress(o.ec_mul(o.hash_to_g2(bytes([i]) * 32), 5 + i))
                for i in range(4)]
        xl, sg, inf, bad = gp.g2_compressed_to_limbs(
            np.stack([np.frombuffer(s, np.uint8) for s in sigs]))
        assert not inf.any()
        assert not bad.any()
        pts, ok = gp.g2_decompress_batch(jnp.asarray(xl), jnp.asarray(sg))
        assert np.asarray(ok).all()
        for i, s in enumerate(sigs):
            X, Y = o.g2_decompress(s)
            p = np.asarray(pts)[i]
            assert fq2_of(p[0]) == X
            assert fq2_of(p[1]) == Y


class TestHashToG2:
    def test_batch_matches_oracle(self):
        msgs = [bytes([i]) * 32 for i in range(4)]
        aff = np.asarray(gp.hash_to_g2_batch(msgs))
        for i, m in enumerate(msgs):
            X, Y = o.hash_to_g2(m)
            assert fq2_of(aff[i, 0]) == X
            assert fq2_of(aff[i, 1]) == Y

    def test_candidate_picks_match_oracle_ctr(self):
        # the host Legendre scan picks the same ctr the oracle's
        # try-and-increment settles on (same x candidate)
        msgs = [bytes([7, i]) for i in range(8)]
        xs, picks = gp.hash_to_g2_candidates(msgs)
        for i, m in enumerate(msgs):
            X, _ = o.hash_to_g2(m)
            # the oracle's point derives from the picked candidate after
            # cofactor clearing; recompute its pre-clearing x directly
            import hashlib
            ctr = int(picks[i])
            seed = hashlib.sha256(b"blsg2" + m + ctr.to_bytes(4, "little"))
            d0 = seed.digest()
            d1 = hashlib.sha256(d0).digest()
            d2 = hashlib.sha256(d1).digest()
            assert fp.from_limbs(xs[i, 0]) == int.from_bytes(
                d0 + d1[:16], "big") % o.Q
            assert fp.from_limbs(xs[i, 1]) == int.from_bytes(
                d1[16:] + d2, "big") % o.Q


class TestTwistArithmetic:
    def test_scalar_mult_matches_oracle(self):
        q = o.hash_to_g2(b"twist-arith")
        for k in (1, 5, 2**63 + 5):
            enc = np.stack([
                np.stack([fp.to_limbs(q[0].a), fp.to_limbs(q[0].b)]),
                np.stack([fp.to_limbs(q[1].a), fp.to_limbs(q[1].b)]),
            ])[None]
            # pad every schedule to 64 bits so the scan compiles ONCE
            # across the k sweep (leading zeros double infinity: no-op)
            bits = np.array([(k >> (63 - j)) & 1 for j in range(64)],
                            dtype=bool)
            jac = gp.g2_mul_static(jnp.asarray(enc), bits)
            aff, inf = gp.g2_jac_to_affine(jac)
            want = o.ec_mul(q, k)
            assert not bool(np.asarray(inf)[0])
            a = np.asarray(aff)[0]
            assert fq2_of(a[0]) == want[0] and fq2_of(a[1]) == want[1]

    def test_scalar_batch_data_bits(self):
        q = o.hash_to_g2(b"twist-batch")
        ks = [3, 10, 77]
        nbits = 8
        enc = np.stack([
            np.stack([fp.to_limbs(q[0].a), fp.to_limbs(q[0].b)]),
            np.stack([fp.to_limbs(q[1].a), fp.to_limbs(q[1].b)]),
        ])
        encs = jnp.asarray(np.stack([enc] * len(ks)))
        bits = np.zeros((len(ks), nbits), dtype=bool)
        for i, k in enumerate(ks):
            bits[i] = [(k >> (nbits - 1 - j)) & 1 for j in range(nbits)]
        aff, inf = gp.g2_jac_to_affine(
            gp.g2_mul_scalar_batch(encs, jnp.asarray(bits)))
        for i, k in enumerate(ks):
            want = o.ec_mul(q, k)
            a = np.asarray(aff)[i]
            assert not bool(np.asarray(inf)[i])
            assert fq2_of(a[0]) == want[0] and fq2_of(a[1]) == want[1]

    def test_add_cancellation_and_inf(self):
        q = o.hash_to_g2(b"twist-inf")
        enc = np.stack([
            np.stack([fp.to_limbs(q[0].a), fp.to_limbs(q[0].b)]),
            np.stack([fp.to_limbs(q[1].a), fp.to_limbs(q[1].b)]),
        ])[None]
        pj = gp.g2_affine_to_jac(jnp.asarray(enc))
        neg = jnp.concatenate(
            [pj[:, 0:1], fp.modneg(pj[:, 1:2]), pj[:, 2:3]], axis=1)
        _, inf = gp.g2_jac_to_affine(gp.g2_add_jac(pj, neg))
        assert bool(np.asarray(inf)[0])
        # inf + P = P
        zero = jnp.zeros_like(pj)
        aff, inf2 = gp.g2_jac_to_affine(gp.g2_add_jac(zero, pj))
        assert not bool(np.asarray(inf2)[0])
        a = np.asarray(aff)[0]
        assert fq2_of(a[0]) == q[0] and fq2_of(a[1]) == q[1]
