"""Serving-tier tests (pos_evolution_tpu/serve/, DESIGN.md §19).

Covers, roughly inside-out:

- the wire protocol (framing, oversize/garbage refusal);
- single-flight stampede suppression, including the ``DasServer``
  proof-path regression: concurrent misses on a new block run the
  backing-scheme branch build ONCE per (block, blob), not once per
  requester;
- admission control (deadline-derived shedding with honest retry-after),
  brownout hysteresis, and the circuit breaker — all on fake clocks;
- the hardened ``LRUCache`` under thread hammering;
- the client library's hedge / retry-after / deadline machinery against
  a deliberately stalling fake server;
- the socket front end-to-end: correct proofs under concurrency, honest
  rejections, deadline propagation, chaos (stalls, wipes, backing
  outage, slow-loris), and the run report's "Serving" section.
"""

from __future__ import annotations

import json
import socket
import struct
import threading
import time

import numpy as np
import pytest

from pos_evolution_tpu.config import minimal_config, use_config


# --- protocol -----------------------------------------------------------------

class TestProtocol:
    def test_round_trip_and_pipelining(self):
        from pos_evolution_tpu.serve.protocol import recv_frame, send_frame
        a, b = socket.socketpair()
        try:
            send_frame(a, {"id": 1, "method": "ping"})
            send_frame(a, {"id": 2, "params": {"x": [1, 2]}})
            assert recv_frame(b) == {"id": 1, "method": "ping"}
            assert recv_frame(b) == {"id": 2, "params": {"x": [1, 2]}}
            a.close()
            assert recv_frame(b) is None  # clean EOF
        finally:
            b.close()

    def test_oversize_and_garbage_refused(self):
        from pos_evolution_tpu.serve.protocol import (
            MAX_FRAME_BYTES,
            ProtocolError,
            recv_frame,
        )
        a, b = socket.socketpair()
        try:
            a.sendall(struct.pack(">I", MAX_FRAME_BYTES + 1))
            with pytest.raises(ProtocolError):
                recv_frame(b)
        finally:
            a.close(), b.close()
        a, b = socket.socketpair()
        try:
            a.sendall(struct.pack(">I", 3) + b"{{{")
            with pytest.raises(ProtocolError):
                recv_frame(b)
        finally:
            a.close(), b.close()


# --- single-flight ------------------------------------------------------------

class TestSingleFlight:
    def test_concurrent_callers_build_once(self):
        from pos_evolution_tpu.utils.singleflight import SingleFlight
        sf = SingleFlight()
        builds, results = [], []
        gate = threading.Event()

        def build():
            gate.wait(2.0)
            builds.append(1)
            return 42

        threads = [threading.Thread(
            target=lambda: results.append(sf.do("k", build)))
            for _ in range(8)]
        for t in threads:
            t.start()
        time.sleep(0.05)  # let every caller join the flight
        gate.set()
        for t in threads:
            t.join(timeout=5.0)
        assert builds == [1]
        assert results == [42] * 8
        assert sf.leads == 1 and sf.waits == 7

    def test_exception_shared_and_flight_cleared(self):
        from pos_evolution_tpu.utils.singleflight import SingleFlight
        sf = SingleFlight()
        with pytest.raises(ValueError):
            sf.do("k", lambda: (_ for _ in ()).throw(ValueError("boom")))
        # the failed flight is gone: a later call builds fresh
        assert sf.do("k", lambda: 7) == 7


# --- admission / brownout / breaker -------------------------------------------

class TestAdmission:
    def _queue(self, ema_s: float, workers: int = 2, **kw):
        from pos_evolution_tpu.serve.admission import (
            AdmissionQueue,
            ServiceEstimator,
        )
        est = ServiceEstimator(initial_s=ema_s, alpha=0.5)
        return AdmissionQueue(workers, estimator=est, **kw)

    def test_admits_and_priority_order(self):
        q = self._queue(0.001)
        assert q.offer("bulk1", 1, budget_s=1.0) is None
        assert q.offer("int1", 0, budget_s=1.0) is None
        assert q.offer("bulk2", 1, budget_s=1.0) is None
        # interactive pops strictly first, then bulk FIFO
        assert q.take(0.1) == "int1"
        assert q.take(0.1) == "bulk1"
        assert q.take(0.1) == "bulk2"

    def test_deadline_derived_shed_with_honest_retry_after(self):
        # EMA 50ms, 1 worker: 3 queued bulk items project 150ms of wait
        q = self._queue(0.05, workers=1)
        for i in range(3):
            assert q.offer(i, 1, budget_s=10.0) is None
        verdict = q.offer("late", 1, budget_s=0.1)  # 100ms budget < 150ms
        assert verdict is not None and verdict["reason"] == "deadline"
        assert verdict["retry_after_ms"] >= 100.0  # the projected wait
        assert q.shed["deadline"] == 1
        # a patient request (10s budget) still gets in
        assert q.offer("patient", 1, budget_s=10.0) is None

    def test_depth_cap_and_brownout_shed(self):
        q = self._queue(0.0001, max_depth=2)
        assert q.offer("a", 1, budget_s=5.0) is None
        assert q.offer("b", 1, budget_s=5.0) is None
        assert q.offer("c", 1, budget_s=5.0)["reason"] == "depth"
        # brownout sheds BULK outright but interactive still enters
        assert q.offer("d", 1, budget_s=5.0,
                       brownout=True)["reason"] == "brownout"
        assert q.offer("i", 0, budget_s=5.0, brownout=True) is None

    def test_bulk_waits_behind_interactive(self):
        q = self._queue(0.01, workers=1)
        for i in range(4):
            q.offer(f"i{i}", 0, budget_s=10.0)
        # bulk's projected wait includes the interactive backlog
        assert q.projected_wait_s(1) == pytest.approx(0.04)
        assert q.projected_wait_s(0) == pytest.approx(0.04)


class TestBrownout:
    def test_hysteresis(self):
        from pos_evolution_tpu.serve.admission import BrownoutController
        clock = [0.0]
        b = BrownoutController(enter_wait_s=0.1, exit_wait_s=0.02,
                               exit_streak=3, clock=lambda: clock[0])
        assert not b.observe_interactive_wait(0.05)
        assert b.observe_interactive_wait(0.2)      # enter
        assert b.observe_interactive_wait(0.01)     # calm 1
        assert b.observe_interactive_wait(0.05)     # not calm -> reset
        for _ in range(2):
            assert b.observe_interactive_wait(0.01)
        assert not b.observe_interactive_wait(0.01)  # calm 3 -> exit
        assert [t["state"] for t in b.transitions] == ["brownout",
                                                       "normal"]


class TestCircuitBreaker:
    def test_abandoned_probe_frees_the_slot(self):
        # a probe whose deadline expires mid-handler reaches no verdict;
        # without abandon() the breaker would wedge half-open forever
        from pos_evolution_tpu.serve.admission import CircuitBreaker
        clock = [0.0]
        cb = CircuitBreaker(failure_threshold=1, cooldown_s=1.0,
                            clock=lambda: clock[0])
        cb.record_failure()
        clock[0] = 2.0
        assert cb.allow()[0]        # the half-open probe slot
        assert not cb.allow()[0]    # held
        cb.abandon()                # probe expired without a verdict
        assert cb.allow()[0]        # the NEXT caller can probe
        cb.record_success()
        assert cb.state == cb.CLOSED

    def test_trip_halfopen_probe(self):
        from pos_evolution_tpu.serve.admission import CircuitBreaker
        clock = [0.0]
        cb = CircuitBreaker(failure_threshold=3, cooldown_s=1.0,
                            clock=lambda: clock[0])
        for _ in range(3):
            assert cb.allow()[0]
            cb.record_failure()
        assert cb.state == cb.OPEN
        ok, retry = cb.allow()
        assert not ok and retry == pytest.approx(1.0)
        clock[0] = 1.5  # cooldown over -> half-open, ONE probe slot
        ok1, _ = cb.allow()
        ok2, _ = cb.allow()
        assert ok1 and not ok2
        cb.record_failure()  # probe fails -> reopen
        assert cb.state == cb.OPEN
        clock[0] = 3.0
        assert cb.allow()[0]
        cb.record_success()  # probe succeeds -> closed
        assert cb.state == cb.CLOSED


# --- hardened LRU -------------------------------------------------------------

class TestLRUCacheConcurrency:
    def test_hit_rate_guarded_before_any_lookup(self):
        from pos_evolution_tpu.das import LRUCache
        assert LRUCache(4).hit_rate == 0.0

    def test_thread_hammer_keeps_invariants(self):
        from pos_evolution_tpu.das import LRUCache
        from pos_evolution_tpu.das.server import _MISS
        lru = LRUCache(32)
        errors = []

        def hammer(tid):
            try:
                for i in range(2000):
                    k = (tid * 7 + i) % 64
                    if lru.get(k) is _MISS:
                        lru.put(k, k)
                    if i % 500 == 499:
                        lru.clear()
            except Exception as e:  # corruption surfaces as exceptions
                errors.append(e)

        threads = [threading.Thread(target=hammer, args=(t,))
                   for t in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30.0)
        assert not errors
        assert len(lru) <= 32
        assert lru.hits + lru.misses == lru.lookups == 8 * 2000


# --- DasServer proof-path single-flight (the stampede regression) -------------

class _CountingScheme:
    """Wraps a scheme, counting backing branch builds."""

    def __init__(self, inner):
        self._inner = inner
        self.branch_calls = 0
        self._lock = threading.Lock()

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def branches(self, cells, indices):
        with self._lock:
            self.branch_calls += 1
        return self._inner.branches(cells, indices)


class TestServeSamplesSingleFlight:
    def test_new_block_miss_populates_once_under_concurrency(self):
        with use_config(minimal_config()):
            from pos_evolution_tpu.das import (
                BlobEngine,
                DasServer,
                SamplingClientPopulation,
            )
            eng = BlobEngine(seed=4)
            grids, coms, _ = eng.build_for(2, b"\x07" * 32)

            class _Sidecar:
                def __init__(self, cells, commitment):
                    self.cells, self.commitment = cells, commitment

            sidecars = [_Sidecar(g, c) for g, c in zip(grids, coms)]
            scheme = _CountingScheme(eng.scheme)
            server = DasServer(scheme, registry=None)
            n_threads = 8
            pops = [SamplingClientPopulation(400, samples_per_client=4,
                                             seed=s)
                    for s in range(n_threads)]
            gate = threading.Event()
            summaries, errors = [], []

            def serve(pop):
                gate.wait(5.0)
                try:
                    # cfg() is thread-local: each serving thread enters
                    # the same config the sidecars were built under
                    with use_config(minimal_config()):
                        summaries.append(server.serve_samples(
                            b"\x09" * 32, sidecars, pop))
                except Exception as e:
                    errors.append(e)

            threads = [threading.Thread(target=serve, args=(p,))
                       for p in pops]
            for t in threads:
                t.start()
            gate.set()
            for t in threads:
                t.join(timeout=60.0)
            assert not errors
            assert len(summaries) == n_threads
            # THE regression contract: one backing build per (block,
            # blob), however many threads missed concurrently
            assert server.scheme_builds == len(sidecars)
            assert scheme.branch_calls == len(sidecars)
            assert all(s["failed"] == 0 for s in summaries)
            # a later serve of the same block is all cache hits
            s2 = server.serve_samples(b"\x09" * 32, sidecars, pops[0])
            assert s2["cache_misses"] == 0
            assert server.scheme_builds == len(sidecars)


# --- client vs a deliberately stalling fake server ----------------------------

class _FakeServer:
    """Protocol-speaking server with a scripted per-request behavior
    queue: "ok", "stall" (never answer), ("slow", s), ("shed", ms)."""

    def __init__(self, script):
        self.script = list(script)
        self._lock = threading.Lock()
        self.seen = 0
        self.request_conns: list[int] = []  # id(sock) per request seen
        self.lst = socket.socket()
        self.lst.bind(("127.0.0.1", 0))
        self.lst.listen(16)
        self.addr = self.lst.getsockname()
        self._stop = threading.Event()
        threading.Thread(target=self._accept, daemon=True).start()

    def _accept(self):
        while not self._stop.is_set():
            try:
                sock, _ = self.lst.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(sock,),
                             daemon=True).start()

    def _serve(self, sock):
        from pos_evolution_tpu.serve.protocol import recv_frame, send_frame
        while not self._stop.is_set():
            try:
                req = recv_frame(sock)
            except Exception:
                return
            if req is None:
                return
            with self._lock:
                self.seen += 1
                self.request_conns.append(id(sock))
                step = (self.script.pop(0) if self.script else "ok")
            if step == "stall":
                continue  # never answer THIS request
            if isinstance(step, tuple) and step[0] == "slow":
                time.sleep(step[1])
                step = "ok"
            if isinstance(step, tuple) and step[0] == "shed":
                send_frame(sock, {"id": req["id"], "status": "shed",
                                  "reason": "depth",
                                  "retry_after_ms": step[1]})
                continue
            send_frame(sock, {"id": req["id"], "status": "ok",
                              "result": {"pong": True}})

    def close(self):
        self._stop.set()
        self.lst.close()


class TestClientRetryHedgeDeadline:
    def test_hedge_rescues_a_stalled_worker(self):
        from pos_evolution_tpu.serve import ServeClient
        srv = _FakeServer(["stall", "ok"])
        try:
            cli = ServeClient(srv.addr, connections=2, hedge_ms=30.0)
            res = cli.request("ping", deadline_s=2.0, tier=0)
            assert res.ok and res.result == {"pong": True}
            assert res.hedges == 1  # the duplicate won
            # ...and it went down a DIFFERENT connection than the
            # primary: a same-socket duplicate would inherit the stall
            assert len(set(srv.request_conns)) == 2
            cli.close()
        finally:
            srv.close()

    def test_retry_after_path_after_a_shed(self):
        from pos_evolution_tpu.serve import ServeClient
        srv = _FakeServer([("shed", 40.0), "ok"])
        try:
            cli = ServeClient(srv.addr, connections=1, hedge_ms=None)
            t0 = time.monotonic()
            res = cli.request("ping", deadline_s=2.0, tier=1)
            elapsed = time.monotonic() - t0
            assert res.ok and res.retries >= 1
            assert elapsed >= 0.04  # honored the server's retry-after
            cli.close()
        finally:
            srv.close()

    def test_shed_beyond_budget_returns_honestly(self):
        from pos_evolution_tpu.serve import ServeClient
        srv = _FakeServer([("shed", 5000.0)])
        try:
            cli = ServeClient(srv.addr, connections=1, hedge_ms=None)
            res = cli.request("ping", deadline_s=0.3, tier=1)
            # retry-after exceeds the budget: the client gives up NOW
            # with the server's verdict instead of sleeping past its own
            # deadline
            assert res.status == "shed" and res.reason == "depth"
            cli.close()
        finally:
            srv.close()

    def test_deadline_bounds_a_fully_stalled_server(self):
        from pos_evolution_tpu.serve import ServeClient
        srv = _FakeServer(["stall"] * 20)
        try:
            cli = ServeClient(srv.addr, connections=2, hedge_ms=50.0,
                              max_retries=1)
            t0 = time.monotonic()
            res = cli.request("ping", deadline_s=0.4, tier=0)
            elapsed = time.monotonic() - t0
            assert res.status == "timeout"
            assert elapsed < 2.0  # bounded by the budget, not by hope
            cli.close()
        finally:
            srv.close()


# --- the socket front end-to-end ----------------------------------------------

def _synthetic_view():
    from pos_evolution_tpu.config import cfg
    from pos_evolution_tpu.das import BlobEngine
    from pos_evolution_tpu.serve import ServeView
    eng = BlobEngine(seed=4)
    grids, coms, _ = eng.build_for(2, b"\x07" * 32)

    class _Sidecar:
        def __init__(self, cells, commitment):
            self.cells, self.commitment = cells, commitment

    root = b"\x07" * 32
    view = ServeView(
        slot=2, head_root=root, head_slot=2,
        justified_epoch=0, justified_root=b"\x00" * 32,
        finalized_epoch=0, finalized_root=b"\x00" * 32,
        update_ssz=b"\x01\x02", update_root=b"\x03" * 32,
        sidecars={root: [_Sidecar(g, c) for g, c in zip(grids, coms)]},
        n_cells=2 * cfg().das_cells_per_blob)
    return eng, root, view


class TestServeFrontE2E:
    def _front(self, **kw):
        from pos_evolution_tpu.serve import ServeFront, ServingState
        from pos_evolution_tpu.telemetry.registry import MetricsRegistry
        eng, root, view = _synthetic_view()
        state = ServingState()
        state.publish(view)
        front = ServeFront(state, scheme=eng.scheme,
                           registry=MetricsRegistry(), **kw)
        addr = front.start()
        return front, addr, root, state, view

    def test_served_cells_verify_and_errors_are_honest(self):
        with use_config(minimal_config()):
            from pos_evolution_tpu.serve import ServeClient
            from pos_evolution_tpu.serve.loadgen import LoadGenerator
            front, addr, root, _state, view = self._front(workers=2)
            try:
                cli = ServeClient(addr, connections=2)
                res = cli.request("das_cells", {
                    "block_root": root.hex(),
                    "samples": [[0, 1], [1, 3], [0, 1], [1, 15]]},
                    deadline_s=2.0)
                assert res.ok
                lg = LoadGenerator.__new__(LoadGenerator)
                assert lg._verify_bulk(res.result)
                # unknown method and unknown block are honest errors
                assert cli.request("nope", deadline_s=1.0).status == \
                    "error"
                bad = cli.request("das_cells", {
                    "block_root": "ab" * 32, "samples": [[0, 0]]},
                    deadline_s=1.0)
                assert bad.status == "error"
                assert "not in the serving window" in bad.error
                # out-of-range sample is refused, not crashed into
                oob = cli.request("das_cells", {
                    "block_root": root.hex(),
                    "samples": [[0, 9999]]}, deadline_s=1.0)
                assert oob.status == "error"
                cli.close()
            finally:
                front.stop()

    def test_expired_deadline_is_refused_before_work(self):
        # raw protocol (the client library would refuse to even send an
        # expired request): deadline_ms=0 means expired AT arrival by
        # construction — the worker must answer an honest timeout
        # without ever touching the backing store
        with use_config(minimal_config()):
            from pos_evolution_tpu.serve.protocol import (
                recv_frame,
                send_frame,
            )
            front, addr, root, _state, _view = self._front(workers=1)
            try:
                sock = socket.create_connection(addr, timeout=5.0)
                send_frame(sock, {"id": 1, "method": "das_cells",
                                  "params": {"block_root": root.hex(),
                                             "samples": [[0, 0]]},
                                  "deadline_ms": 0.0})
                resp = recv_frame(sock)
                assert resp["status"] == "timeout"
                assert front.summary()["by_status"].get("timeout") == 1
                assert front.das.scheme_builds == 0  # no work was done
                sock.close()
            finally:
                front.stop()

    def test_hostile_frames_neither_kill_the_reader_nor_trip_breaker(self):
        with use_config(minimal_config()):
            from pos_evolution_tpu.serve.protocol import (
                recv_frame,
                send_frame,
            )
            front, addr, root, _state, _view = self._front(workers=1)
            try:
                sock = socket.create_connection(addr, timeout=5.0)
                # non-numeric deadline falls back to the default budget
                send_frame(sock, {"id": 1, "method": "head",
                                  "deadline_ms": None})
                assert recv_frame(sock)["status"] == "ok"
                # unhashable method is an honest error, not a dead reader
                send_frame(sock, {"id": 2, "method": []})
                assert recv_frame(sock)["status"] == "error"
                # client-side garbage params must NOT count against the
                # backing store: breaker stays closed past its threshold
                for i in range(front.breaker.failure_threshold + 2):
                    send_frame(sock, {"id": 10 + i,
                                      "method": "das_cells",
                                      "params": {"block_root": "zz",
                                                 "samples": [[0, 0]]}})
                    assert recv_frame(sock)["status"] == "error"
                assert front.breaker.state == front.breaker.CLOSED
                # an oversize sample list is an honest refusal (the
                # response would outgrow the frame cap), never a dead
                # worker
                from pos_evolution_tpu.serve.server import (
                    MAX_SAMPLES_PER_REQUEST,
                )
                send_frame(sock, {"id": 50, "method": "das_cells",
                                  "params": {
                                      "block_root": root.hex(),
                                      "samples": [[0, 0]] * (
                                          MAX_SAMPLES_PER_REQUEST + 1)}})
                big = recv_frame(sock)
                assert big["status"] == "error"
                assert "cap" in big["error"]
                # the same connection still serves real work
                send_frame(sock, {"id": 99, "method": "das_cells",
                                  "params": {"block_root": root.hex(),
                                             "samples": [[0, 1]]}})
                assert recv_frame(sock)["status"] == "ok"
                sock.close()
            finally:
                front.stop()

    def test_dead_connections_are_pruned(self):
        with use_config(minimal_config()):
            front, addr, _root, _state, _view = self._front(workers=1)
            try:
                for _ in range(6):
                    socket.create_connection(addr, timeout=5.0).close()
                deadline = time.monotonic() + 5.0
                while time.monotonic() < deadline:
                    # a fresh accept prunes the dead entries
                    probe = socket.create_connection(addr, timeout=5.0)
                    with front._conn_lock:
                        n = len(front._conns)
                    probe.close()
                    if n <= 2:
                        break
                    time.sleep(0.05)
                else:
                    pytest.fail(f"dead connections never pruned "
                                f"({n} retained)")
            finally:
                front.stop()

    def test_nan_deadline_cannot_bypass_admission(self):
        # NaN/Infinity are valid JSON numbers to json.loads: they must
        # fall back to the DEFAULT budget, not sail past every
        # `now >= expires_at` comparison forever
        with use_config(minimal_config()):
            from pos_evolution_tpu.serve.protocol import (
                recv_frame,
                send_frame,
            )
            front, addr, root, _state, _view = self._front(workers=1)
            try:
                sock = socket.create_connection(addr, timeout=5.0)
                for bad in (float("nan"), float("inf")):
                    send_frame(sock, {"id": 1, "method": "head",
                                      "deadline_ms": bad})
                    assert recv_frame(sock)["status"] == "ok"
                # the admitted item carries a finite expiry
                item = ({"id": 9, "method": "head"}, None, 0.0,
                        front.default_deadline_ms, 0)
                assert front.queue.offer(item, 0,
                                         float("nan")) is None or True
                sock.close()
            finally:
                front.stop()

    def test_unpublished_view_is_unavailable_not_a_breaker_trip(self):
        with use_config(minimal_config()):
            from pos_evolution_tpu.serve import (
                ServeClient,
                ServeFront,
                ServingState,
            )
            eng, root, _view = _synthetic_view()
            front = ServeFront(ServingState(), scheme=eng.scheme,
                               workers=1)  # nothing published yet
            addr = front.start()
            try:
                cli = ServeClient(addr, connections=1, hedge_ms=None,
                                  max_retries=0)
                for _ in range(front.breaker.failure_threshold + 2):
                    res = cli.request(
                        "das_cells",
                        {"block_root": root.hex(), "samples": [[0, 0]]},
                        deadline_s=0.5)
                    assert res.status == "unavailable"
                    assert "no serving view" in (res.reason or "")
                # not-ready is not a backing verdict: breaker closed
                assert front.breaker.state == front.breaker.CLOSED
                cli.close()
            finally:
                front.stop()

    def test_brownout_sheds_bulk_keeps_interactive(self):
        with use_config(minimal_config()):
            from pos_evolution_tpu.serve import ServeClient
            front, addr, root, _state, _view = self._front(workers=2)
            try:
                front.brownout.active = True  # force the state machine
                cli = ServeClient(addr, connections=1, hedge_ms=None,
                                  max_retries=0)
                bulk = cli.request("das_cells", {
                    "block_root": root.hex(), "samples": [[0, 0]]},
                    deadline_s=0.2)
                assert bulk.status == "shed"
                assert bulk.reason == "brownout"
                head = cli.request("head", deadline_s=1.0, tier=0)
                assert head.ok and head.result["head_slot"] == 2
                assert front.queue.shed["brownout"] == 1
                cli.close()
            finally:
                front.stop()

    def test_breaker_opens_on_backing_outage_and_recovers(self):
        with use_config(minimal_config()):
            from pos_evolution_tpu.serve import ServeChaos, ServeClient
            from pos_evolution_tpu.serve.admission import CircuitBreaker
            chaos = ServeChaos(1)
            front, addr, root, _state, _view = self._front(
                workers=1, chaos=chaos,
                breaker=CircuitBreaker(failure_threshold=2,
                                       cooldown_s=0.2))
            try:
                cli = ServeClient(addr, connections=1, hedge_ms=None,
                                  max_retries=0)
                chaos.fail_backing_for(0.4)
                params = {"block_root": root.hex(), "samples": [[0, 2]]}
                statuses = [cli.request("das_cells", params,
                                        deadline_s=0.5).status
                            for _ in range(4)]
                assert statuses[:2] == ["error", "error"]  # tripping
                assert "unavailable" in statuses[2:]  # open = honest
                # interactive is untouched by a backing outage
                assert cli.request("head", deadline_s=1.0,
                                   tier=0).ok
                # after the outage + cooldown the half-open probe closes
                deadline = time.monotonic() + 5.0
                while time.monotonic() < deadline:
                    if cli.request("das_cells", params,
                                   deadline_s=0.5).ok:
                        break
                    time.sleep(0.05)
                else:
                    pytest.fail("breaker never recovered")
                assert front.breaker.state == front.breaker.CLOSED
                cli.close()
            finally:
                front.stop()

    def test_slow_loris_is_closed_while_real_traffic_flows(self):
        with use_config(minimal_config()):
            from pos_evolution_tpu.serve import (
                ServeClient,
                SlowLorisSwarm,
            )
            front, addr, root, _state, _view = self._front(
                workers=2, read_timeout_s=0.15)
            try:
                swarm = SlowLorisSwarm(addr, n=4, dribble_s=0.3)
                swarm.start()
                cli = ServeClient(addr, connections=2)
                oks = sum(cli.request("head", deadline_s=1.0,
                                      tier=0).ok
                          for _ in range(20))
                assert oks == 20  # the swarm never cost a worker
                deadline = time.monotonic() + 5.0
                while (front.slow_loris_closed < 4
                       and time.monotonic() < deadline):
                    time.sleep(0.05)
                assert front.slow_loris_closed >= 4
                swarm.stop()
                cli.close()
            finally:
                front.stop()

    def test_cache_wipe_on_publish_then_stampede_rebuild_once(self):
        with use_config(minimal_config()):
            from pos_evolution_tpu.serve import ServeChaos, ServeClient
            chaos = ServeChaos(3, wipe_prob=1.0)
            front, addr, root, state, view = self._front(
                workers=4, chaos=chaos)
            try:
                cli = ServeClient(addr, connections=4)
                params = {"block_root": root.hex(),
                          "samples": [[0, c] for c in range(8)]}
                assert cli.request("das_cells", params,
                                   deadline_s=2.0).ok
                builds_before = front.das.scheme_builds
                state.publish(view)  # block boundary -> chaos wipes
                assert len(front.das.proof_cache) == 0
                # concurrent stampede on the wiped cache
                results = []
                threads = [threading.Thread(
                    target=lambda: results.append(cli.request(
                        "das_cells", params, deadline_s=3.0)))
                    for _ in range(6)]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join(timeout=30.0)
                assert all(r.ok for r in results)
                # blob 0 was rebuilt exactly once, not once per caller
                assert front.das.scheme_builds == builds_before + 1
                assert any(e["kind"] == "cache_wipe"
                           for e in chaos.log)
                cli.close()
            finally:
                front.stop()


# --- load generator + driver attach + report ----------------------------------

class TestLoadgen:
    def test_arrival_patterns_deterministic_and_shaped(self):
        from pos_evolution_tpu.serve import arrival_times
        for pattern in ("uniform", "diurnal", "bursty", "hotspot"):
            a = arrival_times(pattern, 500, 1000.0, seed=5)
            b = arrival_times(pattern, 500, 1000.0, seed=5)
            assert np.array_equal(a, b), pattern
            assert a.shape == (500,) and (np.diff(a) >= 0).all()
        assert not np.array_equal(
            arrival_times("uniform", 500, 1000.0, seed=5),
            arrival_times("uniform", 500, 1000.0, seed=6))
        # a 10x burst window densifies arrivals inside it
        t = arrival_times("uniform", 2000, 1000.0, seed=5,
                          burst_windows=((0.5, 1.0, 10.0),))
        inside = ((t >= 0.5) & (t < 1.0)).sum()
        before = ((t >= 0.0) & (t < 0.5)).sum()
        assert inside > 2 * before
        # stacking a window on the BURSTY pattern multiplies rates (the
        # thinning peak is the product, not the max): the same n then
        # arrives strictly sooner — with the capped-acceptance bug the
        # on-phase-inside-window rate silently saturated and the span
        # barely moved
        base = arrival_times("bursty", 3000, 1000.0, seed=5)
        stacked = arrival_times("bursty", 3000, 1000.0, seed=5,
                                burst_windows=((0.0, 1.0, 4.0),))
        assert stacked[-1] < base[-1] * 0.8

    def test_mini_open_loop_run_all_verified(self):
        with use_config(minimal_config()):
            from pos_evolution_tpu.serve import (
                LoadGenerator,
                ServeFront,
                ServingState,
            )
            from pos_evolution_tpu.telemetry.registry import (
                MetricsRegistry,
            )
            eng, root, view = _synthetic_view()
            state = ServingState()
            state.publish(view)
            front = ServeFront(state, scheme=eng.scheme,
                               registry=MetricsRegistry(), workers=2)
            addr = front.start()
            try:
                def targets():
                    v = state.current()
                    return {"roots": [r.hex() for r in v.sidecars],
                            "n_cells": v.n_cells,
                            "n_blobs": {r.hex(): len(s)
                                        for r, s in v.sidecars.items()}}
                lg = LoadGenerator(addr, 400, 2000.0, pattern="hotspot",
                                   seed=11, client_threads=16,
                                   targets_fn=targets)
                summary = lg.run()
                assert summary["arrivals"] == 400
                assert summary["verify_failures"] == 0
                assert summary["verified_proofs"] > 0
                tiers = summary["tiers"]
                assert tiers["bulk"]["by_status"].get("ok", 0) > 0
                assert tiers["interactive"]["by_status"].get("ok",
                                                             0) > 0
            finally:
                front.stop()

    def test_head_summary_advertises_discovery_targets(self):
        with use_config(minimal_config()):
            _eng, root, view = _synthetic_view()
            h = view.head_summary()
            assert h["n_cells"] == view.n_cells
            assert h["das_blobs"] == {root.hex(): len(view.sidecars[root])}
            assert h["das_roots"] == [root.hex()]

    def test_remote_discovery_drives_an_unknown_front(self):
        """ISSUE 13 satellite / ROADMAP item 3 remainder: the generator
        learns its bulk targets from the front's OWN head + finality
        RPCs (``discover_targets``) — no in-process introspection — and
        every served proof still verifies."""
        with use_config(minimal_config()):
            from pos_evolution_tpu.serve import (
                LoadGenerator,
                ServeFront,
                ServingState,
            )
            from pos_evolution_tpu.telemetry.registry import (
                MetricsRegistry,
            )
            eng, _root, view = _synthetic_view()
            state = ServingState()
            state.publish(view)
            front = ServeFront(state, scheme=eng.scheme,
                               registry=MetricsRegistry(), workers=2)
            addr = front.start()
            try:
                lg = LoadGenerator(addr, 300, 2000.0, pattern="uniform",
                                   seed=11, client_threads=16,
                                   discover=True)
                summary = lg.run()
                assert summary["verify_failures"] == 0
                assert summary["verified_proofs"] > 0
                disc = summary["remote_discovery"]
                assert disc["discoveries"] >= 1
                # discovery really came over the wire: the targets_fn
                # resolves the published view's roots and geometry
                targets = lg.targets_fn()
                assert targets["roots"] == [r.hex() for r in view.sidecars]
                assert targets["n_cells"] == view.n_cells
                assert targets["finalized_epoch"] == view.finalized_epoch
            finally:
                front.stop()

    def test_discovery_survives_a_dead_front(self):
        """A failed poll keeps the last-known targets and counts a
        failure — the generator degrades, it does not crash."""
        from pos_evolution_tpu.serve import ServeClient
        from pos_evolution_tpu.serve.loadgen import discover_targets
        cli = ServeClient(("127.0.0.1", 9), connections=1,
                          hedge_ms=None)   # discard port: nothing listens
        stats: dict = {}
        fn = discover_targets(cli, refresh_s=0.0, deadline_s=0.2,
                              stats=stats)
        out = fn()
        assert out == {"roots": [], "n_cells": 0, "n_blobs": {}}
        assert stats["failures"] >= 1 and stats["discoveries"] == 0
        cli.close()


class TestDriverServeAttach:
    def test_simulation_publishes_views(self):
        with use_config(minimal_config()):
            from pos_evolution_tpu.sim import Simulation
            sim = Simulation(32, das=True, serve=True)
            sim.run_epochs(1)
            views = sim.serving_state.views
            assert len(views) == sim.slot
            last = views[-1]
            assert last.sidecars, "DAS window never carried sidecars"
            assert last.update_root is not None
            assert last.n_cells == 2 * sim.cfg.das_cells_per_blob
            # the published update bytes re-hash to the advertised root
            from pos_evolution_tpu.lightclient.containers import (
                LightClientUpdate,
            )
            from pos_evolution_tpu.ssz import deserialize, hash_tree_root
            obj = deserialize(last.update_ssz, LightClientUpdate)
            assert bytes(hash_tree_root(obj)) == last.update_root


class TestServingReport:
    def test_report_section_from_events(self, tmp_path):
        import os
        import sys
        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))), "scripts"))
        import run_report as sys_path_report

        from pos_evolution_tpu.telemetry import EventBus
        path = tmp_path / "ev.jsonl"
        with EventBus(path) as bus:
            bus.emit("serve_attach", workers=4, pattern="bursty",
                     arrivals=1000, rate=500.0,
                     chaos={"seed": 1})
            bus.emit("serve_summary",
                     server={"workers": 4, "requests_total": 1000,
                             "by_status": {"ok": 950, "shed": 50},
                             "shed_rate": 0.05,
                             "shed_by_reason": {"deadline": 50,
                                                "depth": 0,
                                                "brownout": 0},
                             "brownout_transitions": 2,
                             "breaker_state": "closed",
                             "breaker_transitions": 0,
                             "singleflight": {"leads": 8, "waits": 40},
                             "scheme_builds": 8,
                             "proof_cache": {"hits": 900, "misses": 100,
                                             "hit_rate": 0.9},
                             "slow_loris_closed": 4,
                             "chaos_stalls": 2},
                     load={"pattern": "bursty", "arrivals": 1000,
                           "rate": 500.0, "wall_s": 2.0,
                           "tiers": {"interactive": {
                               "arrivals": 300, "goodput_pct": 99.0,
                               "shed_pct": 0.0, "p50_ms": 1.0,
                               "p99_ms": 9.0, "p999_ms": 20.0},
                               "bulk": {
                               "arrivals": 700, "goodput_pct": 92.0,
                               "shed_pct": 7.1, "p50_ms": 2.0,
                               "p99_ms": 30.0, "p999_ms": 80.0}},
                           "hedges": 12, "retries": 30,
                           "verified_proofs": 640,
                           "verify_failures": 0},
                     chaos={"injections": {"cache_wipe": 3}},
                     slo_ms=50.0, slo_ok=True)
        from pos_evolution_tpu.telemetry import read_jsonl
        events = read_jsonl(path)
        report = sys_path_report.build_report(events)
        s = report["serving"]
        assert s["arrivals"] == 1000
        assert s["shed_rate"] == 0.05
        assert s["verified_proofs"] == 640
        assert s["slo_ok"] is True
        assert s["tiers"]["interactive"]["p999_ms"] == 20.0
        md = sys_path_report.to_markdown(report)
        assert "## Serving" in md
        assert "p999" in md
        assert "verified proofs" in md
        assert "honest rejections" in md
        assert json.dumps(report)  # JSON-serializable end to end
