"""Chaos fuzzing pipeline tests (scripts/chaos_fuzz.py).

Tier-1 smoke: two fixed-seed episodes of random adversary x fault
compositions run clean under the full monitor stack. The doctored
negative (forced conflicting finalized checkpoints with no equivocation
behind them) must trip the ``AccountableSafetyMonitor`` as a
``protocol_violation``, write a complete repro bundle, replay to the
same violation from ``Simulation.resume`` + seeds, and shrink to a
strictly smaller composition. Longer fuzz sweeps are ``slow``.
"""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))), "scripts"))

import chaos_fuzz  # noqa: E402

pytestmark = pytest.mark.usefixtures("minimal_cfg")


class TestEpisodeComposition:
    def test_pure_function_of_seed_and_episode(self):
        a = chaos_fuzz.episode_config(7, 3)
        b = chaos_fuzz.episode_config(7, 3)
        assert a == b
        assert a != chaos_fuzz.episode_config(7, 4)
        assert a != chaos_fuzz.episode_config(8, 3)

    def test_controlled_sets_disjoint_and_below_one_third(self):
        for ep in range(12):
            cfg = chaos_fuzz.episode_config(1, ep)
            seen = set()
            for strat in cfg["adversaries"]:
                s = set(strat["controlled"])
                assert not (s & seen), "controlled sets overlap"
                seen |= s
            assert 3 * len(seen) < cfg["n_validators"]

    def test_crash_windows_spare_the_donor_group(self):
        for ep in range(20):
            cfg = chaos_fuzz.episode_config(2, ep)
            for w in cfg["faults"]["crashes"]:
                assert w["group"] == 1  # group 0 is the checkpoint donor


class TestChaosSmoke:
    def test_two_fixed_seed_episodes_clean(self, tmp_path):
        """The tier-1 smoke: two seeded episodes, full monitor stack,
        zero violations, no bundles, no watchdog incidents."""
        summary = chaos_fuzz.fuzz(
            episodes=2, seed=5, n_validators=64, n_slots=16,
            out_dir=str(tmp_path))
        assert summary["episodes"] == 2
        assert summary["violating"] == 0
        assert summary["incidents"] == 0
        assert summary["bundles"] == []
        # clean episodes leave no event logs behind
        assert not [f for f in os.listdir(tmp_path)
                    if f.endswith(".events.jsonl")]

    def test_serve_episode_composes_traffic_with_chaos(self, tmp_path):
        """ISSUE 13 satellite: a --serve episode attaches a live
        ServeFront + remote-discovery open-loop loadgen to the faulted
        adversarial run; the verdict carries the SLO/goodput outcome
        and every served proof verified."""
        cfg = chaos_fuzz.episode_config(2, 0, 32, 10, serve=True)
        cfg["serve"].update(arrivals=250, rate=400.0)
        result = chaos_fuzz.run_episode(cfg)
        serve = result["serve"]
        assert serve["verify_failures"] == 0
        assert serve["verified_proofs"] > 0
        assert serve["remote_discovery"]["discoveries"] >= 1
        assert "slo_ok" in serve and "interactive_goodput_pct" in serve

    @pytest.mark.slow
    def test_fuzz_sweep_clean(self, tmp_path):
        """Wider sweep over compositions (the real fuzzing workload),
        long enough (8 epochs, GST at 1/3) that the liveness monitor is
        ARMED for the tail epochs — a stalled composition would flag."""
        from pos_evolution_tpu.config import cfg
        c = cfg()
        summary = chaos_fuzz.fuzz(
            episodes=6, seed=0, n_validators=64,
            n_slots=8 * c.slots_per_epoch, out_dir=str(tmp_path))
        assert summary["violating"] == 0
        assert summary["incidents"] == 0
        # the bound arithmetic the sweep relies on: monitors must be
        # armed before the episode ends
        ep = chaos_fuzz.episode_config(0, 0, 64, 8 * c.slots_per_epoch)
        sec_per_epoch = c.seconds_per_slot * c.slots_per_epoch
        armed = -(-int(ep["faults"]["gst"]) // sec_per_epoch)
        assert (armed + ep["monitors"]["liveness_bound_epochs"]
                < 8), "liveness monitor never arms inside the sweep"


class TestDoctoredNegative:
    @pytest.fixture(scope="class")
    def doctored(self, tmp_path_factory):
        """One doctored episode, bundle + shrink included (class-scoped:
        the replay/shrink assertions reuse the same run)."""
        from pos_evolution_tpu.config import minimal_config, use_config
        out = tmp_path_factory.mktemp("chaos_doctor")
        with use_config(minimal_config()):
            summary = chaos_fuzz.fuzz(
                episodes=1, seed=5, n_validators=64, n_slots=16,
                out_dir=str(out), doctor=True)
        return summary, out

    def test_trips_safety_monitor_loudly(self, doctored):
        summary, _ = doctored
        assert summary["violating"] == 1
        (bundle,) = summary["bundles"]
        violations = json.load(open(os.path.join(bundle, "violations.json")))
        v = violations[0]
        assert v["monitor"] == "accountable_safety"
        # no equivocation behind the forged conflict -> the evidence set
        # CANNOT reach 1/3: a genuine (non-accountable) safety break
        assert v["kind"] == "protocol_violation"
        assert 3 * v["slashable_stake"] < v["total_stake"]

    def test_bundle_is_complete(self, doctored):
        _, out = doctored
        bundle = os.path.join(str(out), "bundle_ep0")
        for name in ("config.json", "checkpoint.bin", "violations.json",
                     "events.jsonl", "shrink.json", "config.min.json"):
            path = os.path.join(bundle, name)
            assert os.path.exists(path), f"bundle missing {name}"
            assert os.path.getsize(path) > 0

    def test_replay_reproduces_violation(self, doctored):
        summary, _ = doctored
        out = chaos_fuzz.replay_bundle(summary["bundles"][0])
        assert out["match"], (out["replayed"], out["recorded"])

    def test_run_report_property_audit_section(self, doctored):
        """The bundle's event log folds into the run report's property
        audit: the violation row (slot, evidence size, stake) and the
        repro-bundle path both surface, in JSON and markdown."""
        import run_report
        summary, _ = doctored
        bundle = summary["bundles"][0]
        events_path = os.path.join(bundle, "events.jsonl")
        events = run_report.read_jsonl(events_path)
        assert run_report.discover_bundle(events_path) == bundle
        report = run_report.build_report(events, bundle=bundle)
        audit = report["property_audit"]
        assert audit["clean"] is False
        assert audit["repro_bundle"] == bundle
        (v,) = audit["violations"]
        assert v["monitor"] == "accountable_safety"
        assert v["kind"] == "protocol_violation"
        assert v["slot"] is not None and v["evidence_size"] > 0
        kinds = [m["kind"] for m in audit["monitors"]]
        assert "AccountableSafetyMonitor" in kinds
        md = run_report.to_markdown(report)
        assert "## Property audit" in md
        assert "protocol_violation" in md and bundle in md

    def test_run_report_clean_audit(self):
        """A monitor-free log must NOT claim the properties held — there
        was no audit; a monitored clean log may."""
        import run_report
        report = run_report.build_report(
            [{"v": 1, "type": "slot", "slot": 1, "finalized_epoch": 0}])
        audit = report["property_audit"]
        assert audit["clean"] is True and audit["violations"] == []
        assert "nothing was audited" in run_report.to_markdown(report)
        monitored = run_report.build_report([
            {"v": 1, "type": "monitor_attach",
             "monitors": [{"kind": "AccountableSafetyMonitor"}],
             "adversaries": []},
            {"v": 1, "type": "slot", "slot": 1, "finalized_epoch": 0}])
        assert "all properties held" in run_report.to_markdown(monitored)

    def test_run_report_violation_keys_survive(self):
        """The structured JSON must keep the conflict's identifying keys
        (groups / epochs / roots), not just the free-text detail."""
        import run_report
        report = run_report.build_report([
            {"v": 1, "type": "monitor", "slot": 9,
             "monitor": "accountable_safety", "kind": "protocol_violation",
             "checkpoint": "finalized", "groups": [0, 1], "epochs": [1, 1],
             "roots": ["0d0d", "0e0e"], "evidence_size": 7,
             "slashable_stake": 224, "total_stake": 2048, "detail": "x"}])
        (v,) = report["property_audit"]["violations"]
        assert v["groups"] == [0, 1]
        assert v["epochs"] == [1, 1]
        assert v["roots"] == ["0d0d", "0e0e"]

    def test_shrink_strictly_reduces(self, doctored):
        summary, _ = doctored
        bundle = summary["bundles"][0]
        shrink = json.load(open(os.path.join(bundle, "shrink.json")))
        assert shrink["after"] < shrink["before"]
        minimized = json.load(open(os.path.join(bundle, "config.min.json")))
        original = json.load(open(os.path.join(bundle, "config.json")))
        assert (len(chaos_fuzz._components(minimized))
                < len(chaos_fuzz._components(original)))
        # the minimized composition still violates
        result = chaos_fuzz.run_episode(minimized)
        assert chaos_fuzz._same_violation(
            result["violations"],
            json.load(open(os.path.join(bundle, "violations.json")))[0])
