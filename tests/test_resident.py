"""Differential tests for the persistent device fork-choice store
(ops/resident.py): the resident incremental mirror must equal the spec
walk AND the full-rescan dense kernel at every query, across handler
sequences including forks, boost, equivocation slashing, capacity growth
and checkpoint movement (SURVEY.md §4.4b; pos-evolution.md:298,762 run
get_head on every duty, which is exactly the query this path serves).
"""

import numpy as np
import pytest

from pos_evolution_tpu.config import cfg
from pos_evolution_tpu.specs import forkchoice as fc
from pos_evolution_tpu.specs.genesis import make_genesis
from pos_evolution_tpu.specs.validator import build_block, make_committee_attestation
from pos_evolution_tpu.ssz import hash_tree_root

jax = pytest.importorskip("jax")

from pos_evolution_tpu.ops.forkchoice import get_head_dense  # noqa: E402
from pos_evolution_tpu.ops.resident import ResidentForkChoice  # noqa: E402

pytestmark = pytest.mark.usefixtures("minimal_cfg")


def tick_to_slot(store, slot, offset=0):
    fc.on_tick(store, store.genesis_time + slot * cfg().seconds_per_slot + offset)


def assert_triple_equal(resident, store, context=""):
    """spec walk == rescan kernel == resident incremental head."""
    want = fc.get_head(store)
    assert get_head_dense(store) == want, f"rescan diverged {context}"
    assert resident.head(store) == want, f"resident diverged {context}"


class TestResidentHandlers:
    def test_fork_votes_boost_and_slashing(self):
        from pos_evolution_tpu.specs.containers import AttesterSlashing
        from pos_evolution_tpu.specs.helpers import get_indexed_attestation

        state, anchor = make_genesis(64)
        store = fc.get_forkchoice_store(state, anchor)
        resident = ResidentForkChoice(store)
        tick_to_slot(store, 1, offset=cfg().seconds_per_slot)
        sb_a = build_block(state, 1, graffiti=b"\x0a" * 32)
        sb_b = build_block(state, 1, graffiti=b"\x0b" * 32)
        for sb in (sb_a, sb_b):
            fc.on_block(store, sb)
            resident.note_block(store, hash_tree_root(sb.message))
        assert_triple_equal(resident, store, "after fork blocks")

        ra, rb = hash_tree_root(sb_a.message), hash_tree_root(sb_b.message)
        loser, winner = sorted([ra, rb])
        st = {ra: store.block_states[ra], rb: store.block_states[rb]}
        att1 = make_committee_attestation(st[loser], 1, 0, loser)
        tick_to_slot(store, 2)
        idx = fc.on_attestation(store, att1)
        resident.note_attestation(idx, int(att1.data.target.epoch), loser)
        assert_triple_equal(resident, store, "after vote for loser")
        assert resident.head(store) == loser

        # equivocation: the same committee votes the other fork; slashing
        # evidence discounts them -> tie-break flips to the winner root
        att2 = make_committee_attestation(st[winner], 1, 0, winner)
        slashing = AttesterSlashing(
            attestation_1=get_indexed_attestation(st[loser], att1),
            attestation_2=get_indexed_attestation(st[winner], att2))
        fc.on_attester_slashing(store, slashing)
        evil = (set(int(i) for i in np.asarray(slashing.attestation_1.attesting_indices))
                & set(int(i) for i in np.asarray(slashing.attestation_2.attesting_indices)))
        resident.note_slashing(evil)
        assert_triple_equal(resident, store, "after slashing")
        assert resident.head(store) == winner

        # a discounted validator's future vote must not land (:1438)
        tick_to_slot(store, 3)
        att3 = make_committee_attestation(st[loser], 2, 0, loser)
        try:
            idx3 = fc.on_attestation(store, att3)
            resident.note_attestation(idx3, int(att3.data.target.epoch), loser)
        except AssertionError:
            pass
        assert_triple_equal(resident, store, "after post-slashing vote")

    def test_boost_rides_host_scalars(self):
        state, anchor = make_genesis(64)
        store = fc.get_forkchoice_store(state, anchor)
        resident = ResidentForkChoice(store)
        tick_to_slot(store, 1, offset=cfg().seconds_per_slot)
        sb_a = build_block(state, 1, graffiti=b"\x0a" * 32)
        fc.on_block(store, sb_a)
        resident.note_block(store, hash_tree_root(sb_a.message))
        # timely block at slot 2 earns the boost (pos-evolution.md:1020-1024)
        tick_to_slot(store, 2, offset=0)
        sb_c = build_block(state, 2, graffiti=b"\x0c" * 32)
        fc.on_block(store, sb_c)
        resident.note_block(store, hash_tree_root(sb_c.message))
        assert store.proposer_boost_root == hash_tree_root(sb_c.message)
        assert_triple_equal(resident, store, "with live boost")
        # boost resets on the next slot tick (:942-944)
        tick_to_slot(store, 3)
        assert store.proposer_boost_root == b"\x00" * 32
        assert_triple_equal(resident, store, "after boost reset")

    def test_capacity_growth_rebuild(self):
        """Exceeding the initial capacity triggers a transparent rebuild."""
        state, anchor = make_genesis(32)
        store = fc.get_forkchoice_store(state, anchor)
        resident = ResidentForkChoice(store, capacity=4)
        parent_state = state
        for slot in range(1, 10):
            tick_to_slot(store, slot)
            sb = build_block(parent_state, slot)
            fc.on_block(store, sb)
            root = hash_tree_root(sb.message)
            resident.note_block(store, root)
            parent_state = store.block_states[root]
            assert_triple_equal(resident, store, f"slot {slot}")
        assert resident.capacity >= 10


class TestResidentInSimulation:
    @pytest.mark.parametrize("seed", [0, 1])
    def test_sleepy_fuzz_triple_differential(self, seed):
        """Random sleepy schedules; the sim's resident head must equal
        both oracles on the principal view at every slot — across epoch
        boundaries (weight rebuilds) and justification movement."""
        from pos_evolution_tpu.sim import Schedule, Simulation
        rng = np.random.default_rng(seed)
        sleep_table = rng.random((200, 64)) < 0.25
        sched = Schedule(
            n_validators=64,
            awake=lambda r, v: not sleep_table[min(r, 199), v])
        sim = Simulation(64, schedule=sched, accelerated_forkchoice=True)
        for _ in range(2 * cfg().slots_per_epoch):
            sim.run_slot()
            group = sim.groups[0]
            store = group.store
            want = fc.get_head(store)
            assert group.resident.head(store) == want, \
                f"divergence at slot {sim.slot - 1} (seed {seed})"
        assert sim.metrics[-1]["n_blocks"] > 1  # chain actually grew

    def test_accelerated_sim_with_faults_and_crash_restart(self):
        """The resident path composes with the fault layer: drops plus a
        crash-restart (the rejoiner gets a fresh resident mirror of its
        synced anchor) stay head-for-head with the spec walk."""
        from pos_evolution_tpu.config import minimal_config
        from pos_evolution_tpu.sim import (
            CrashWindow, FaultPlan, Simulation, faulty_schedule,
        )
        spe = minimal_config().slots_per_epoch
        # duplicate_p included deliberately: redelivered blocks must not
        # double-append resident rows (gossip dedup in _process_block)
        plan = FaultPlan(seed=3, drop_p=0.1, duplicate_p=0.15,
                         reorder_p=0.1,
                         crashes=(CrashWindow(1, spe, 2 * spe),))
        sim = Simulation(64, schedule=faulty_schedule(64, plan, n_groups=2),
                         accelerated_forkchoice=True)
        for _ in range(4 * spe):
            sim.run_slot()
            for group in sim.groups:
                if group.crashed:
                    continue
                assert group.resident.head(group.store) == \
                    fc.get_head(group.store), f"slot {sim.slot - 1}"
                assert not group.resident.degraded

    def test_finalizes_and_no_rebuild_between_epochs(self):
        """Honest run: epochs finalize through the resident path, and head
        queries between rebuild events do not re-densify (the round-2
        missing-integration complaint: no per-query host rebuild)."""
        from pos_evolution_tpu.sim import Simulation
        sim = Simulation(64, accelerated_forkchoice=True)
        resident = sim.groups[0].resident
        calls = {"n": 0}
        orig = resident.rebuild

        def counting_rebuild(store):
            calls["n"] += 1
            return orig(store)

        resident.rebuild = counting_rebuild
        sim.run_epochs(4)
        assert sim.finalized_epoch() >= 1
        # rebuild events: epoch rollovers + justified/finalized movement +
        # capacity doublings — far fewer than head queries
        n_queries = sim.trace_summary()["get_head"]["count"]
        assert calls["n"] < n_queries / 3, \
            f"{calls['n']} rebuilds for {n_queries} head queries"


class TestGracefulDegradation:
    """The resident path is an optimization, never a truth source: device
    errors and self-check divergences drop to the host spec walk and keep
    the run alive (ISSUE 1 tentpole part 4)."""

    def _store_with_chain(self, slots=3):
        state, anchor = make_genesis(32)
        store = fc.get_forkchoice_store(state, anchor)
        parent_state = state
        for slot in range(1, slots + 1):
            tick_to_slot(store, slot)
            sb = build_block(parent_state, slot)
            fc.on_block(store, sb)
            parent_state = store.block_states[hash_tree_root(sb.message)]
        return store

    def test_device_error_falls_back_to_spec_head(self):
        store = self._store_with_chain()
        resident = ResidentForkChoice(store)

        def boom(store_arg):
            raise RuntimeError("XLA compile OOM")

        resident._device_head = boom
        assert resident.head(store) == fc.get_head(store)
        assert resident.degraded
        assert "OOM" in resident.incidents[0]
        # and it STAYS on the host path, still correct
        assert resident.head(store) == fc.get_head(store)

    def test_divergence_self_check_catches_corruption(self):
        # a two-child fork with no votes: the head is decided purely by
        # the lexicographic tie-break, which the device encodes as ranks
        state, anchor = make_genesis(32)
        store = fc.get_forkchoice_store(state, anchor)
        tick_to_slot(store, 1, offset=cfg().seconds_per_slot)
        for g in (b"\x0a", b"\x0b"):
            fc.on_block(store, build_block(state, 1, graffiti=g * 32))
        resident = ResidentForkChoice(store, selfcheck_every=1)
        # corrupt the mirror: invert the rank order so the device descent
        # resolves the tie toward the wrong child
        import jax.numpy as jnp
        resident.rank = jnp.asarray(
            np.max(np.asarray(resident.rank)) - np.asarray(resident.rank))
        want = fc.get_head(store)
        got = resident.head(store)
        assert got == want, "self-check must answer with the spec head"
        assert resident.degraded
        assert "divergence" in resident.incidents[0]

    def test_degraded_handlers_are_noops_and_run_continues(self):
        store = self._store_with_chain(2)
        resident = ResidentForkChoice(store)
        resident._degrade("test-injected")
        state = store.block_states[fc.get_head(store)]
        tick_to_slot(store, 3)
        sb = build_block(state, 3)
        fc.on_block(store, sb)
        resident.note_block(store, hash_tree_root(sb.message))  # no-op, no crash
        resident.note_attestation(np.arange(4), 0, hash_tree_root(sb.message))
        resident.note_slashing([1, 2])
        assert resident.head(store) == fc.get_head(store)

    def test_selfcheck_period_counts_fresh_queries(self):
        """The periodic audit runs every Nth FRESH computation against
        the vectorized host walk (ISSUE 9: repeated identical queries
        answer from the memo — no device work, nothing new to audit;
        ``get_head_host`` replaced the pure-Python spec walk, which cost
        tens of seconds per audit at 64K validators)."""
        store = self._store_with_chain()
        resident = ResidentForkChoice(store, selfcheck_every=4)
        walk_calls = {"n": 0}
        import pos_evolution_tpu.ops.forkchoice as ofc
        real = ofc.get_head_host

        def counting(store_arg):
            walk_calls["n"] += 1
            return real(store_arg)

        ofc.get_head_host, _saved = counting, ofc.get_head_host
        try:
            # memoized repeats: one fresh computation, never audited
            for _ in range(8):
                resident.head(store)
            assert resident._head_queries == 1
            assert walk_calls["n"] == 0
            # fresh computations (a new landed vote batch each time)
            tip = list(store.blocks.keys())[-1]
            for i in range(7):
                resident.note_attestation(np.array([i]), 1 + i, tip)
                resident.head(store)
        finally:
            ofc.get_head_host = _saved
        assert resident._head_queries == 8
        assert walk_calls["n"] == 2            # fresh queries 4 and 8
        assert not resident.degraded

    def test_healthy_sim_never_degrades(self):
        from pos_evolution_tpu.sim import Simulation
        sim = Simulation(64, accelerated_forkchoice=True)
        sim.run_epochs(2)
        assert not sim.groups[0].resident.degraded
        assert sim.groups[0].resident.incidents == []
