"""ISSUE 20: the whole paper under attack at mainnet scale — the
ProtocolVariant seam inside the dense driver. Expiry-windowed /
supermajority-link / acknowledgment tallies over the sharded message
columns, the per-slot SSF gadget and Goldfish/RLMD confirmation as
full-participation audits, the committee-targeted multi-slot ex-ante
reorg with proposer boost, variant-fingerprinted checkpoints with loud
cross-variant refusal, DAS + light-client riders on the dense loop,
the variant-aware monitor (with its doctored negative), and spec⇄dense
variant parity through the seam."""

import os
import sys

import numpy as np
import pytest

pytest.importorskip("jax")

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))), "scripts"))

VARIANTS = ("gasper", "goldfish", "rlmd", "ssf")


@pytest.fixture(scope="module", autouse=True)
def _release_compiled_kernels():
    """This module compiles variant-tally/vote-pass kernels for many
    distinct (n, mesh, variant) shapes no later test file reuses;
    leaving them cached measurably slows the rest of the suite."""
    yield
    import gc

    import jax
    jax.clear_caches()
    gc.collect()


def _mesh(pods, shard):
    from pos_evolution_tpu.parallel.sharded import make_mesh
    return make_mesh(pods * shard, pods)


def _cfg(slots_per_epoch=8):
    from pos_evolution_tpu.config import mainnet_config
    return mainnet_config().replace(slots_per_epoch=slots_per_epoch,
                                    max_committees_per_slot=4)


def _sim(n=1024, variant=None, mesh=None, seed=11, **kw):
    from pos_evolution_tpu.sim.dense_driver import DenseSimulation
    kw.setdefault("verify_aggregates", False)
    kw.setdefault("check_walk_every", 4)
    return DenseSimulation(n, cfg=_cfg(), mesh=mesh, seed=seed,
                           variant=variant, **kw)


def _exante(n, frac=0.40, fork_slot=2, span=2):
    from pos_evolution_tpu.sim.dense_adversary import DenseExAnteReorg
    return DenseExAnteReorg(controlled=np.arange(int(n * frac)),
                            fork_slot=fork_slot, span=span)


# --- the tally kernels (sharded vs host oracle) --------------------------------


class TestVariantTallies:
    def test_windowed_and_ack_tallies_match_host_oracles_on_mesh(self):
        from pos_evolution_tpu.sim.dense_variants import (
            slot_ack_tally,
            slot_vote_tally,
            variant_tally_parity,
        )
        sim = _sim(n=2048, variant="ssf", mesh=_mesh(2, 4))
        for _ in range(6):
            sim.run_slot()
        s = sim.slot
        assert variant_tally_parity(sim, 0, s)
        # the two reductions agree where the window is one slot: both
        # count exactly this slot's latest votes
        assert np.array_equal(slot_vote_tally(sim, 0, s),
                              slot_ack_tally(sim, 0, s))
        # whole-table sanity: the slot's tally sums to the stake that
        # voted this slot
        ms = np.asarray(sim.views[0].msg_slot)[: sim.n]
        eb = np.asarray(sim.views[0].registry.effective_balance)[: sim.n]
        assert slot_vote_tally(sim, 0, s).sum() == eb[ms == s].sum()

    def test_expiry_kernel_twin_matches_sharded(self):
        import jax.numpy as jnp

        from pos_evolution_tpu.parallel.partition import shard_leaf, spec_for
        from pos_evolution_tpu.parallel.sharded import expiry_mask_for
        from pos_evolution_tpu.sim.dense_variants import expiry_kernel
        mesh = _mesh(2, 4)
        rng = np.random.default_rng(0)
        mb = rng.integers(-1, 50, 4096).astype(np.int32)
        ms = rng.integers(0, 20, 4096).astype(np.int64)
        dev = expiry_mask_for(mesh)(
            shard_leaf(mesh, spec_for("messages/msg_block"), mb),
            shard_leaf(mesh, spec_for("messages/msg_slot"), ms),
            jnp.int64(5), jnp.int64(9))
        host = expiry_kernel()(jnp.asarray(mb), jnp.asarray(ms),
                               jnp.int64(5), jnp.int64(9))
        assert np.array_equal(np.asarray(dev), np.asarray(host))
        assert (np.asarray(host) == np.where(
            (ms >= 5) & (ms <= 9), mb, -1)).all()


# --- honest runs per variant ---------------------------------------------------


class TestHonestVariantRuns:
    @pytest.mark.parametrize("variant", VARIANTS)
    def test_honest_run_head_parity_and_decisions(self, variant):
        sim = _sim(n=768, variant=variant)
        sim.run_epochs(2)
        s = sim.summary()
        assert s["resident_head_equals_spec_walk"]
        assert s["variant"] == variant
        if variant == "ssf":
            # justifies and finalizes every post-warmup slot: in-slot
            # finality is the point of the gadget
            st = s["variant_state"]
            assert st["finalizations"][0] >= sim.slot - 2
        elif variant != "gasper":
            assert s["variant_decisions"] > 0

    def test_gasper_variant_is_bit_identical_to_pre_variant_driver(self):
        # DenseGasper must reproduce the variant=None driver exactly
        a = _sim(n=768, variant=None, seed=3)
        b = _sim(n=768, variant="gasper", seed=3)
        a.run_epochs(2)
        b.run_epochs(2)
        assert a.view_heads[0] == b.view_heads[0]
        assert a.metrics == b.metrics

    @pytest.mark.parametrize("variant", ("goldfish", "ssf"))
    def test_single_device_vs_mesh_bit_identical(self, variant):
        a = _sim(n=2048, variant=variant, seed=9)
        b = _sim(n=2048, variant=variant, seed=9, mesh=_mesh(2, 4))
        for _ in range(10):
            a.run_slot()
            b.run_slot()
            assert a.view_heads[0] == b.view_heads[0], a.slot
        assert a.variant.decisions == b.variant.decisions
        assert a.summary()["resident_head_equals_spec_walk"]
        assert b.summary()["resident_head_equals_spec_walk"]

    def test_rlmd_admit_gate_rejects_stale_votes(self):
        from pos_evolution_tpu.sim.dense_adversary import VoteBatch

        class _Bus:
            def __init__(self):
                self.events = []

            def emit(self, type_, **f):
                self.events.append({"type": type_, **f})

        class _Tel:
            bus = _Bus()
        tel = _Tel()
        sim = _sim(n=512, variant="rlmd", telemetry=tel)
        for _ in range(6):
            sim.run_slot()
        tgt = sim._head(0)
        mask = np.zeros(sim.n, dtype=bool)
        mask[:64] = True
        before = np.asarray(sim.views[0].msg_slot).copy()
        # cast three slots ago: outside the admit window, must not land
        stale = VoteBatch(mask, tgt, sim.slot // sim.S,
                          slot=sim.slot - 3)
        landed = sim._deliver_batch(0, stale, sim.slot + 1,
                                    (sim.slot + 1) // sim.S)
        assert not landed.any()
        assert np.array_equal(before, np.asarray(sim.views[0].msg_slot))
        assert any(e["type"] == "dense_fault" and e.get("expired")
                   for e in tel.bus.events)

    def test_full_participation_duty_is_everyone(self):
        sim = _sim(n=512, variant="goldfish")
        assert sim.duty_mask(3).all()
        g = _sim(n=512, variant="gasper")
        g.run_slot()   # committee assignment exists only post-shuffle
        assert g.duty_mask(3).sum() == 512 // g.S


# --- the ex-ante reorg matrix --------------------------------------------------


class TestExAnteReorg:
    def _verdict(self, variant, boost, n=2000, seed=3):
        adv = _exante(n)
        sim = _sim(n=n, seed=seed, adversaries=[adv],
                   variant={"kind": variant, "boost_percent": boost})
        sim.run_epochs(2)
        head = sim._head(0)
        assert adv.priv and adv.released
        assert sim.summary()["resident_head_equals_spec_walk"]
        return sim._descends(head, adv.priv[0])

    def test_gasper_without_boost_reorged(self):
        assert self._verdict("gasper", 0)

    def test_gasper_with_boost_defended(self):
        assert not self._verdict("gasper", 40)

    @pytest.mark.parametrize("variant", ("goldfish", "rlmd", "ssf"))
    def test_full_participation_structurally_defends(self, variant):
        # the banked multi-committee votes collapse to one
        # latest-message stamp against everyone re-voting per slot
        assert not self._verdict(variant, 0)

    def test_withheld_votes_inert_until_release(self):
        n = 2000
        adv = _exante(n, fork_slot=2, span=2)
        sim = _sim(n=n, seed=3, adversaries=[adv],
                   variant={"kind": "gasper", "boost_percent": 0})
        for _ in range(3):
            sim.run_slot()
        # bank is open: votes sit in the table but the head ignores the
        # invisible block entirely
        assert adv.priv and not adv.released
        mb = np.asarray(sim.views[0].msg_block)
        assert (mb == adv.priv[0]).any()
        assert not sim.views[0].vis_host[adv.priv[0]]
        assert not sim._descends(sim._head(0), adv.priv[0])


# --- SSF accountable safety at exactly one third -------------------------------


class TestSsfAccountableSafety:
    def test_splitvoter_double_finality_exactly_one_third(self):
        from pos_evolution_tpu.sim.dense_adversary import DenseSplitVoter
        from pos_evolution_tpu.sim.dense_monitors import (
            default_dense_monitors,
        )
        from pos_evolution_tpu.sim.faults import DenseFaultPlan
        n = 1200
        sim = _sim(n=n, variant="ssf", n_groups=2, seed=5,
                   fault_plan=DenseFaultPlan(partition="full"),
                   adversaries=[DenseSplitVoter(
                       controlled=np.arange(n // 3))],
                   monitors=default_dense_monitors())
        sim.run_epochs(2)
        adf = [v for v in sim.monitor_violations
               if v["kind"] == "accountable_double_finality"]
        assert adf, "conflicting SSF finalizations must be priced"
        v = adf[0]
        assert 3 * v["slashable_stake"] == v["total_stake"]
        assert v["rule"] == "ssf"
        # both views finalized every slot through their own gadget
        st = sim.summary()["variant_state"]
        assert all(f > 0 for f in st["finalizations"])

    def test_doctored_ssf_double_finality_is_protocol_violation(self):
        from pos_evolution_tpu.sim.dense_monitors import (
            default_dense_monitors,
        )
        from pos_evolution_tpu.sim.faults import DenseFaultPlan
        sim = _sim(n=600, variant="ssf", n_groups=2, seed=5,
                   fault_plan=DenseFaultPlan(partition="delay"),
                   monitors=default_dense_monitors())
        sim.run_epochs(1)
        assert sim.variant.doctor(sim, sim.slot)
        viols = []
        for mon in sim.monitors:
            viols += mon.on_slot_end(sim, sim.slot)
        kinds = {v["kind"] for v in viols}
        # forged conflicting finality with NO double-vote evidence:
        # caught, and classified as a genuine protocol violation
        assert "protocol_violation" in kinds
        assert "accountable_double_finality" not in kinds

    def test_doctored_goldfish_confirmation_divergence(self):
        from pos_evolution_tpu.sim.dense_monitors import (
            default_dense_monitors,
        )
        from pos_evolution_tpu.sim.faults import DenseFaultPlan
        sim = _sim(n=600, variant="goldfish", n_groups=2, seed=5,
                   fault_plan=DenseFaultPlan(partition="delay"),
                   monitors=default_dense_monitors())
        sim.run_epochs(1)
        assert sim.variant.doctor(sim, sim.slot)
        viols = []
        for mon in sim.monitors:
            viols += mon.on_slot_end(sim, sim.slot)
        assert "confirmation_divergence" in {v["kind"] for v in viols}


# --- variant-fingerprinted checkpoints -----------------------------------------


class TestVariantCheckpoints:
    @pytest.mark.parametrize("variant", VARIANTS)
    def test_mid_attack_cross_mesh_resume_bit_identical(self, variant):
        from pos_evolution_tpu.sim.dense_driver import DenseSimulation
        n = 2048
        a = _sim(n=n, variant=variant, mesh=_mesh(2, 4), seed=9,
                 adversaries=[_exante(n)])
        for _ in range(3):           # bank open, nothing released yet
            a.run_slot()
        data = a.checkpoint()
        b = DenseSimulation.resume(data, mesh=_mesh(4, 2),
                                   expect_variant=variant)
        for _ in range(7):           # through release and beyond
            a.run_slot()
            b.run_slot()
            assert a.view_heads[0] == b.view_heads[0], a.slot
        assert a.variant.state_meta() == b.variant.state_meta()
        assert np.array_equal(np.asarray(a.views[0].msg_slot),
                              np.asarray(b.views[0].msg_slot))

    def test_cross_variant_resume_refuses_loudly(self):
        from pos_evolution_tpu.sim.dense_driver import DenseSimulation
        sim = _sim(n=512, variant="ssf")
        sim.run_slot()
        data = sim.checkpoint()
        with pytest.raises(ValueError, match="refusing to resume"):
            DenseSimulation.resume(data, expect_variant="goldfish")
        # matching expectation (or none) passes
        DenseSimulation.resume(data, expect_variant="ssf")
        DenseSimulation.resume(data)

    def test_riders_ride_the_checkpoint(self):
        from pos_evolution_tpu.das.dense_rider import DenseDasRider
        from pos_evolution_tpu.lightclient.population import (
            DenseLightClientPopulation,
        )
        from pos_evolution_tpu.sim.dense_driver import DenseSimulation
        sim = _sim(n=512, variant="goldfish",
                   riders=(DenseDasRider(scheme="merkle", n_clients=8),
                           DenseLightClientPopulation(n_clients=16)))
        for _ in range(5):
            sim.run_slot()
        data = sim.checkpoint()
        back = DenseSimulation.resume(data)
        assert [r.describe() for r in back.riders] == \
            [r.describe() for r in sim.riders]
        assert back.riders[0].state_meta() == sim.riders[0].state_meta()
        assert np.array_equal(back.riders[1].head_slot,
                              sim.riders[1].head_slot)
        for _ in range(4):
            sim.run_slot()
            back.run_slot()
        assert back.riders[0].stats() == sim.riders[0].stats()
        assert back.riders[1].stats() == sim.riders[1].stats()


# --- the DAS / light-client riders ---------------------------------------------


class TestDenseRiders:
    @pytest.mark.parametrize("scheme", ("merkle", "kzg"))
    def test_das_rider_builds_verifies_and_samples(self, scheme):
        from pos_evolution_tpu.config import use_config
        from pos_evolution_tpu.das.dense_rider import DenseDasRider
        with use_config(_cfg()):
            rider = DenseDasRider(scheme=scheme, n_blobs=1, n_clients=8,
                                  samples_per_client=2)
            sim = _sim(n=512, variant="gasper", riders=(rider,))
            for _ in range(4):
                sim.run_slot()
        st = rider.stats()
        assert st["sidecars_built"] >= 4
        assert st["sidecars_verified"] > 0
        assert st["sidecar_failures"] == 0
        assert st["samples_drawn"] > 0 and st["sample_misses"] == 0
        assert sim.summary()["workload"]["das"] == st

    def test_lightclients_follow_each_variants_own_decision(self):
        from pos_evolution_tpu.lightclient.population import (
            DenseLightClientPopulation,
        )
        heads = {}
        for variant in ("goldfish", "ssf"):
            pop = DenseLightClientPopulation(n_clients=32, seed=4)
            sim = _sim(n=768, variant=variant, riders=(pop,))
            sim.run_epochs(2)
            st = pop.stats()
            assert st["clients_synced"] == 32
            assert st["updates_applied"] > 0
            heads[variant] = st["max_head_slot"]
            # a zero-lag client tracks the newest decision; laggards
            # trail by at most their drawn lag
            dec = sim.variant.latest_decision(sim, 0)
            assert dec is not None
            assert st["max_head_slot"] == dec[0]
        assert heads["ssf"] >= heads["goldfish"]


# --- spec <-> dense variant parity (satellite 4) -------------------------------


class TestSpecDenseVariantParity:
    @pytest.mark.parametrize("variant", ("goldfish", "rlmd", "ssf"))
    def test_dense_decision_stream_matches_dense_twin(self, variant):
        """Twin honest runs through the seam: the per-slot head and
        finality/confirmation decision streams must be bit-identical
        between the single-device and the sharded instantiation of the
        SAME variant policy — the dense half of the spec⇄dense parity
        artifact (the 64K leg runs in scripts/variant_matrix.py)."""
        a = _sim(n=1536, variant=variant, seed=13)
        b = _sim(n=1536, variant=variant, seed=13, mesh=_mesh(4, 2))
        heads_a, heads_b = [], []
        for _ in range(12):
            a.run_slot()
            b.run_slot()
            heads_a.append(a.view_heads[0])
            heads_b.append(b.view_heads[0])
        assert heads_a == heads_b
        assert a.variant.decisions == b.variant.decisions
        assert a.variant.state_meta() == b.variant.state_meta()


class TestDenseMatrix:
    """scripts/variant_matrix.py --dense: cell configs are pure and
    pinned, the verdict logic encodes the paper's claims, bundles
    replay byte-stably, and the bench emission gates."""

    def test_cell_config_pure_and_pinned(self):
        import variant_matrix as vm
        a = vm.dense_cell_config("exante", "gasper_boost", 2112)
        b = vm.dense_cell_config("exante", "gasper_boost", 2112)
        assert a == b
        assert a["variant"] == {"kind": "gasper", "boost_percent": 40}
        assert a["adversaries"][0]["controlled"] == [[0, int(2112 * .40)]]
        kinds = {r["kind"] for r in a["workload"]["riders"]}
        assert kinds == {"das", "lightclient"}
        # both commitment schemes are exercised across the matrix
        schemes = {vm.dense_cell_config("exante", c, 2112)["workload"]
                   ["riders"][0]["scheme"]
                   for c in vm.DENSE_CELLS["exante"]}
        assert schemes == {"merkle", "kzg"}

    def test_every_dense_cell_is_pinned(self):
        import variant_matrix as vm
        for scenario, cells in vm.DENSE_CELLS.items():
            for cell in cells:
                assert (scenario, cell) in vm.EXPECTED_DENSE, (
                    scenario, cell)

    def test_splitvoter_ssf_cell_verdict_and_replay(self, tmp_path):
        import variant_matrix as vm
        cfgd = vm.dense_cell_config("splitvoter", "ssf", 384)
        # SSF double-finalizes per slot: two epochs already carry the
        # full verdict (the 4-epoch cell runs in CI and the artifact)
        cfgd["n_epochs"] = 2
        result = vm.run_dense_cell(cfgd)
        v = result["verdict"]
        assert v["matches_expectation"] is True
        assert v["ssf_double_finality"] and v["ssf_exact_third"]
        assert v["confirmation_diverged"] is False
        assert v["workload"]["das"]["sidecar_failures"] == 0
        assert v["workload"]["das"]["sample_misses"] == 0
        bundle = vm.write_dense_bundle(str(tmp_path), cfgd, result, None)
        out = vm.replay_dense_bundle(bundle)
        assert out["match"] is True

    def test_exante_verdict_diverges_gasper_vs_goldfish(self):
        import variant_matrix as vm
        gasper = vm.run_dense_cell(
            vm.dense_cell_config("exante", "gasper", 2112))
        goldfish = vm.run_dense_cell(
            vm.dense_cell_config("exante", "goldfish", 2112))
        assert gasper["verdict"]["reorged"] is True
        assert goldfish["verdict"]["reorged"] is False
        assert gasper["verdict"]["matches_expectation"] is True
        assert goldfish["verdict"]["matches_expectation"] is True

    def test_bench_dense_emission_shape(self):
        import variant_matrix as vm
        rows = [{"scenario": "exante", "cell": "ssf", "wall_s": 1.5,
                 "slots_run": 16, "attack_succeeded": False},
                {"scenario": "splitvoter", "cell": "ssf", "wall_s": 9.0,
                 "slots_run": 32, "attack_succeeded": True}]
        em = vm.bench_dense_emission(rows)
        assert em["metric"] == "bench_dense_variants"
        assert em["ssf"] == {"wall_s": 1.5}
        assert em["counts"] == {"ssf.slots_run": 16,
                                "ssf.attack_succeeded": 0}
