"""Host-side g2prep paths that need no device ladder compiles (fast tier):
wire-format canonicality validation and the hash-to-G2 oracle fallback."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from pos_evolution_tpu.crypto import bls12_381 as o  # noqa: E402
from pos_evolution_tpu.ops import fp  # noqa: E402
from pos_evolution_tpu.ops import g2prep as gp  # noqa: E402
from pos_evolution_tpu.ops.pairing import g2_affine_encode  # noqa: E402


def _sig_bytes(k: int = 5) -> np.ndarray:
    return np.frombuffer(o.g2_compress(o.ec_mul(o.G2_GEN, k)), np.uint8)


class TestCompressedCanonicality:
    def test_valid_row_passes(self):
        xl, sg, inf, bad = gp.g2_compressed_to_limbs(_sig_bytes())
        assert not inf[0] and not bad[0]
        X, _ = o.g2_decompress(_sig_bytes().tobytes())
        assert fp.from_limbs(xl[0, 0]) == X.a
        assert fp.from_limbs(xl[0, 1]) == X.b

    def test_missing_compression_flag_rejected(self):
        row = _sig_bytes().copy()
        row[0] &= 0x7F                       # clear bit 383
        _, _, _, bad = gp.g2_compressed_to_limbs(row)
        assert bad[0]
        # garbage framing must not echo its flag bits: an uncompressed
        # row with the infinity bit set is invalid, NOT a signed infinity
        junk = np.zeros(96, np.uint8)
        junk[0] = 0x60                       # inf + sign, no compression
        _, sg, inf, bad2 = gp.g2_compressed_to_limbs(junk)
        assert bad2[0] and not inf[0] and not sg[0]

    def test_non_reduced_coordinate_rejected(self):
        """x and x + Q alias the same field element: only the reduced
        encoding is canonical (the other 'same point, different bytes'
        signature must be flagged, not silently accepted)."""
        row = _sig_bytes().copy()
        hi = int.from_bytes(row[:48].tobytes(), "big")
        flags = hi >> 381
        x_im = hi & ((1 << 381) - 1)
        assert x_im + o.Q < (1 << 381), "pick a key whose x.b fits x.b+Q"
        hi2 = (flags << 381) | (x_im + o.Q)
        row[:48] = np.frombuffer(hi2.to_bytes(48, "big"), np.uint8)
        _, _, _, bad = gp.g2_compressed_to_limbs(row)
        assert bad[0]
        # the low half (x real part) is checked too
        row2 = _sig_bytes().copy()
        row2[48:] = np.frombuffer((o.Q + 1).to_bytes(48, "big"), np.uint8)
        _, _, _, bad2 = gp.g2_compressed_to_limbs(row2)
        assert bad2[0]

    def test_infinity_canonical_and_not(self):
        canonical = np.zeros(96, np.uint8)
        canonical[0] = 0xC0                  # compressed + infinity
        _, sg, inf, bad = gp.g2_compressed_to_limbs(canonical)
        assert inf[0] and not bad[0] and not sg[0]
        junk = canonical.copy()
        junk[50] = 1                         # payload bits under the flag
        _, _, inf2, bad2 = gp.g2_compressed_to_limbs(junk)
        assert inf2[0] and bad2[0]
        signed_inf = canonical.copy()
        signed_inf[0] |= 0x20                # sign bit on infinity
        _, _, _, bad3 = gp.g2_compressed_to_limbs(signed_inf)
        assert bad3[0]

    def test_batch_mixes_valid_and_invalid(self):
        good = _sig_bytes()
        flagless = good.copy()
        flagless[0] &= 0x7F
        _, _, _, bad = gp.g2_compressed_to_limbs(np.stack([good, flagless]))
        assert bad.tolist() == [False, True]


class TestHashToG2Fallback:
    def test_infinity_rows_fall_back_to_oracle(self, monkeypatch):
        """The cofactor-clears-to-infinity case is measure-zero, so force
        it: a finish stub reports every row unusable, and the batch must
        answer bit-exact from the host oracle instead of raising."""
        msgs = [b"\x01" * 32, b"\x02" * 32]

        def fake_finish(x):
            import jax.numpy as jnp
            b = x.shape[0]
            return (jnp.zeros((b, 2, 2, fp.L), jnp.int32),
                    jnp.zeros(b, bool))

        monkeypatch.setattr(gp, "hash_to_g2_finish", fake_finish)
        aff = np.asarray(gp.hash_to_g2_batch(msgs))
        for i, m in enumerate(msgs):
            assert np.array_equal(aff[i], g2_affine_encode(o.hash_to_g2(m)))

    @pytest.mark.slow
    def test_partial_fallback_patches_only_bad_rows(self, monkeypatch):
        """Healthy rows keep the device result; only flagged rows are
        patched (graceful degradation is per-message, not per-batch).
        Slow tier: exercises the real device sqrt/cofactor ladders, which
        compile for minutes on XLA:CPU."""
        msgs = [b"\x03" * 32, b"\x04" * 32]
        real_finish = gp.hash_to_g2_finish
        sentinel = np.full((2, 2, fp.L), 7, np.int32)

        def finish_bad_row0(x):
            import jax.numpy as jnp
            aff, ok = real_finish(x)
            aff = np.array(aff)
            aff[0] = sentinel                # garbage the device "computed"
            return jnp.asarray(aff), jnp.asarray([False, True])

        monkeypatch.setattr(gp, "hash_to_g2_finish", finish_bad_row0)
        aff = np.asarray(gp.hash_to_g2_batch(msgs))
        assert np.array_equal(aff[0], g2_affine_encode(o.hash_to_g2(msgs[0])))
        assert not np.array_equal(aff[1], sentinel)
