"""Differential tests: dense (array/XLA) get_head vs the spec get_head on
real stores — honest chains, forks with votes, proposer boost, equivocation
discounting (SURVEY.md §4.4b).
"""

import numpy as np
import pytest

from pos_evolution_tpu.config import cfg, minimal_config, use_config
from pos_evolution_tpu.specs import forkchoice as fc
from pos_evolution_tpu.specs.genesis import make_genesis
from pos_evolution_tpu.specs.validator import build_block, make_committee_attestation
from pos_evolution_tpu.ssz import hash_tree_root

jax = pytest.importorskip("jax")

from pos_evolution_tpu.ops.forkchoice import get_head_dense  # noqa: E402

pytestmark = pytest.mark.usefixtures("minimal_cfg")


def tick_to_slot(store, slot, offset=0):
    fc.on_tick(store, store.genesis_time + slot * cfg().seconds_per_slot + offset)


class TestDenseHeadDifferential:
    def test_honest_chain(self):
        from pos_evolution_tpu.sim import Simulation
        sim = Simulation(64)
        sim.run_epochs(3)
        store = sim.store()
        assert get_head_dense(store) == fc.get_head(store)

    def test_fork_with_votes_and_boost(self):
        state, anchor = make_genesis(64)
        store = fc.get_forkchoice_store(state, anchor)
        tick_to_slot(store, 1, offset=cfg().seconds_per_slot)
        sb_a = build_block(state, 1, graffiti=b"\x0a" * 32)
        sb_b = build_block(state, 1, graffiti=b"\x0b" * 32)
        fc.on_block(store, sb_a)
        fc.on_block(store, sb_b)
        ra = hash_tree_root(sb_a.message)
        # tie: dense must reproduce the lexicographic tie-break
        assert get_head_dense(store) == fc.get_head(store)
        # votes for the smaller root
        loser = min(ra, hash_tree_root(sb_b.message))
        att = make_committee_attestation(store.block_states[loser], 1, 0, loser)
        tick_to_slot(store, 2)
        fc.on_attestation(store, att)
        assert get_head_dense(store) == fc.get_head(store) == loser
        # boosted competing block at slot 2
        tick_to_slot(store, 2, offset=0)
        sb_c = build_block(state, 2, graffiti=b"\x0c" * 32)
        fc.on_block(store, sb_c)
        assert store.proposer_boost_root == hash_tree_root(sb_c.message)
        assert get_head_dense(store) == fc.get_head(store)

    def test_equivocation_discounting(self):
        from pos_evolution_tpu.specs.containers import AttesterSlashing
        from pos_evolution_tpu.specs.helpers import get_indexed_attestation
        state, anchor = make_genesis(64)
        store = fc.get_forkchoice_store(state, anchor)
        tick_to_slot(store, 1, offset=cfg().seconds_per_slot)
        sb_a = build_block(state, 1, graffiti=b"\x0a" * 32)
        sb_b = build_block(state, 1, graffiti=b"\x0b" * 32)
        fc.on_block(store, sb_a)
        fc.on_block(store, sb_b)
        ra, rb = hash_tree_root(sb_a.message), hash_tree_root(sb_b.message)
        loser, winner = sorted([ra, rb])
        st = {ra: store.block_states[ra], rb: store.block_states[rb]}
        att1 = make_committee_attestation(st[loser], 1, 0, loser)
        tick_to_slot(store, 2)
        fc.on_attestation(store, att1)
        assert get_head_dense(store) == fc.get_head(store) == loser
        att2 = make_committee_attestation(st[winner], 1, 0, winner)
        slashing = AttesterSlashing(
            attestation_1=get_indexed_attestation(st[loser], att1),
            attestation_2=get_indexed_attestation(st[winner], att2))
        fc.on_attester_slashing(store, slashing)
        assert get_head_dense(store) == fc.get_head(store) == winner

    def test_balancing_attack_views(self):
        """Dense head must agree with spec head on both adversarial views."""
        with use_config(minimal_config().replace(proposer_score_boost_percent=0)):
            from pos_evolution_tpu.sim.attacks import run_balancing_attack
            # short run; we only need the disagreeing stores
            import pos_evolution_tpu.sim.attacks as A
            state, anchor = make_genesis(64)
            r = run_balancing_attack(64, n_epochs=2)
            assert r.head_L != r.head_R  # the interesting case

    def test_vote_expiry_window(self):
        """RLMD/Goldfish expiry at the array level: windowed-out latest
        messages carry no weight (pos-evolution.md:1585)."""
        import jax.numpy as jnp
        from pos_evolution_tpu.ops.forkchoice import build_dense_store, head_and_weights
        state, anchor = make_genesis(64)
        store = fc.get_forkchoice_store(state, anchor)
        tick_to_slot(store, 1, offset=cfg().seconds_per_slot)
        sb_a = build_block(state, 1, graffiti=b"\x0a" * 32)
        sb_b = build_block(state, 1, graffiti=b"\x0b" * 32)
        fc.on_block(store, sb_a)
        fc.on_block(store, sb_b)
        ra, rb = hash_tree_root(sb_a.message), hash_tree_root(sb_b.message)
        loser, winner = sorted([ra, rb])
        att = make_committee_attestation(store.block_states[loser], 1, 0, loser)
        tick_to_slot(store, 2)
        fc.on_attestation(store, att)
        dense, roots, capacity = build_dense_store(store)
        # votes (epoch 0) count with no window -> smaller root wins
        h0, _ = head_and_weights(dense, capacity)
        assert roots[int(h0)] == loser
        # expiry window beyond epoch 0 -> votes expire -> tie-break wins
        h1, _ = head_and_weights(dense, capacity, min_vote_epoch=jnp.int64(1))
        assert roots[int(h1)] == winner

    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_fuzz_random_schedules(self, seed):
        """Random sleepy schedules produce random fork patterns; spec and
        dense heads must agree on every view at every slot."""
        from pos_evolution_tpu.sim import Schedule, Simulation
        rng = np.random.default_rng(seed)
        sleep_table = rng.random((200, 64)) < 0.25
        sched = Schedule(
            n_validators=64,
            awake=lambda r, v: not sleep_table[min(r, 199), v])
        sim = Simulation(64, schedule=sched)
        for _ in range(2 * cfg().slots_per_epoch):
            sim.run_slot()
            store = sim.store()
            assert get_head_dense(store) == fc.get_head(store), \
                f"divergence at slot {sim.slot - 1} (seed {seed})"

    def test_deep_chain_with_skips(self):
        state, anchor = make_genesis(32)
        store = fc.get_forkchoice_store(state, anchor)
        parent_state = state
        for slot in (1, 3, 4, 7, 8):  # skipped slots in between
            tick_to_slot(store, slot)
            sb = build_block(parent_state, slot)
            fc.on_block(store, sb)
            parent_state = store.block_states[hash_tree_root(sb.message)]
            assert get_head_dense(store) == fc.get_head(store)


class TestIncrementalBuckets:
    """The persistent-store fast path: per-block vote buckets updated by
    scatter deltas must agree with the full message-table rescan."""

    def _random_store(self, rng, capacity=32, n=256):
        import jax.numpy as jnp
        from pos_evolution_tpu.ops.forkchoice import DenseStore
        parent = np.full(capacity, -1, np.int32)
        for i in range(1, capacity):
            parent[i] = rng.integers(0, i)
        msg_block = rng.integers(-1, capacity, n).astype(np.int32)
        msg_epoch = np.where(msg_block >= 0,
                             rng.integers(0, 4, n), 0).astype(np.int64)
        weight = rng.integers(1, 5, n).astype(np.int64) * 10**9
        return DenseStore(
            parent=jnp.asarray(parent),
            slot=jnp.arange(capacity, dtype=jnp.int32),
            rank=jnp.asarray(rng.permutation(capacity).astype(np.int32)),
            real=jnp.ones(capacity, bool),
            leaf_viable=jnp.ones(capacity, bool),
            justified_idx=jnp.int32(0),
            msg_block=jnp.asarray(msg_block),
            msg_epoch=jnp.asarray(msg_epoch),
            weight=jnp.asarray(weight),
            boost_idx=jnp.int32(rng.integers(-1, capacity)),
            boost_amount=jnp.int64(7 * 10**8),
        )

    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_apply_matches_rescan(self, seed):
        import jax.numpy as jnp
        from pos_evolution_tpu.ops.forkchoice import (
            apply_latest_messages, head_and_weights, head_from_buckets)
        rng = np.random.default_rng(seed)
        capacity, n = 32, 256
        st = self._random_store(rng, capacity, n)
        # initial buckets from a rescan
        votes_valid = st.msg_block >= 0
        seg = jnp.where(votes_valid, st.msg_block, capacity)
        buckets = jax.ops.segment_sum(
            jnp.where(votes_valid, st.weight, 0), seg,
            num_segments=capacity + 1)[:capacity]
        msg_block, msg_epoch = st.msg_block, st.msg_epoch
        # three batches of incremental votes (incl. first-ever voters at
        # epoch 0: validators with msg_block == -1 must land)
        for b in range(3):
            k = 64
            val_idx = jnp.asarray(rng.choice(n, size=k, replace=False)
                                  .astype(np.int32))
            new_block = jnp.asarray(rng.integers(0, capacity, k).astype(np.int32))
            new_epoch = jnp.asarray(rng.integers(0, 6, k).astype(np.int64))
            active = jnp.asarray(rng.random(k) < 0.9)
            msg_block, msg_epoch, buckets = apply_latest_messages(
                msg_block, msg_epoch, buckets, val_idx, new_block,
                new_epoch, st.weight[val_idx], active)
        # rescan oracle over the updated table
        st2 = st._replace(msg_block=msg_block, msg_epoch=msg_epoch)
        h_ref, w_ref = head_and_weights(st2, capacity)
        h_inc, w_inc = head_from_buckets(
            st.parent, st.real, st.rank, st.leaf_viable, st.justified_idx,
            buckets, st.boost_idx, st.boost_amount, capacity)
        assert int(h_ref) == int(h_inc)
        assert np.array_equal(np.asarray(w_ref), np.asarray(w_inc))

    @pytest.mark.parametrize("seed", [0, 3])
    def test_duplicate_val_idx_in_batch_matches_sequential(self, seed):
        """Contract enforcement (round-2 sharp edge): duplicate ``val_idx``
        within one batch used to silently corrupt buckets; the in-kernel
        dedup must now match applying the batch one entry at a time."""
        import jax.numpy as jnp
        from pos_evolution_tpu.ops.forkchoice import apply_latest_messages
        rng = np.random.default_rng(seed)
        capacity, n, k = 16, 64, 48
        msg_block = jnp.asarray(rng.integers(-1, capacity, n).astype(np.int32))
        msg_epoch = jnp.where(msg_block >= 0,
                              jnp.asarray(rng.integers(0, 4, n)), 0
                              ).astype(jnp.int64)
        weight = jnp.asarray(rng.integers(1, 5, n).astype(np.int64))
        buckets0 = jax.ops.segment_sum(
            jnp.where(msg_block >= 0, weight, 0),
            jnp.where(msg_block >= 0, msg_block, capacity),
            num_segments=capacity + 1)[:capacity]
        # heavy duplication: 48 entries over only 12 distinct validators
        val_idx = jnp.asarray(rng.choice(12, size=k).astype(np.int32))
        new_block = jnp.asarray(rng.integers(0, capacity, k).astype(np.int32))
        new_epoch = jnp.asarray(rng.integers(0, 6, k).astype(np.int64))
        # mixed per-entry masks: an inactive or padded (-1 block) duplicate
        # must not knock out a live lower-epoch vote in the tournament
        active = jnp.asarray(rng.random(k) < 0.7)
        new_block = jnp.where(jnp.asarray(rng.random(k) < 0.15), -1, new_block)
        got = apply_latest_messages(
            msg_block, msg_epoch, buckets0, val_idx, new_block, new_epoch,
            weight[val_idx], active)
        # oracle: sequential one-entry batches
        mb, me, bk = msg_block, msg_epoch, buckets0
        for i in range(k):
            mb, me, bk = apply_latest_messages(
                mb, me, bk, val_idx[i:i + 1], new_block[i:i + 1],
                new_epoch[i:i + 1], weight[val_idx[i:i + 1]], active[i:i + 1])
        assert np.array_equal(np.asarray(got[0]), np.asarray(mb))
        assert np.array_equal(np.asarray(got[1]), np.asarray(me))
        assert np.array_equal(np.asarray(got[2]), np.asarray(bk))

    def test_rebuild_buckets_after_balance_change(self):
        """The epoch-boundary hook: new effective balances -> wholesale
        rebuild equals a fresh rescan with the new weights."""
        import jax.numpy as jnp
        from pos_evolution_tpu.ops.forkchoice import rebuild_buckets
        rng = np.random.default_rng(5)
        capacity, n = 32, 256
        msg_block = jnp.asarray(rng.integers(-1, capacity, n).astype(np.int32))
        new_weight = jnp.asarray(rng.integers(1, 40, n).astype(np.int64))
        got = rebuild_buckets(msg_block, new_weight, capacity)
        mb = np.asarray(msg_block)
        expect = np.zeros(capacity, np.int64)
        np.add.at(expect, mb[mb >= 0], np.asarray(new_weight)[mb >= 0])
        assert np.array_equal(np.asarray(got), expect)

    def test_remove_discounts_landed_votes(self):
        import jax.numpy as jnp
        from pos_evolution_tpu.ops.forkchoice import (
            head_and_weights, remove_latest_messages, head_from_buckets)
        rng = np.random.default_rng(7)
        capacity, n = 32, 256
        st = self._random_store(rng, capacity, n)
        votes_valid = st.msg_block >= 0
        seg = jnp.where(votes_valid, st.msg_block, capacity)
        buckets = jax.ops.segment_sum(
            jnp.where(votes_valid, st.weight, 0), seg,
            num_segments=capacity + 1)[:capacity]
        evil = jnp.asarray(np.array([3, 10, 17], dtype=np.int32))
        msg_block, msg_epoch, buckets = remove_latest_messages(
            st.msg_block, st.msg_epoch, buckets, evil, st.weight[evil])
        # oracle: equivocators dropped from the table entirely
        st2 = st._replace(msg_block=msg_block, msg_epoch=msg_epoch)
        h_ref, w_ref = head_and_weights(st2, capacity)
        h_inc, w_inc = head_from_buckets(
            st.parent, st.real, st.rank, st.leaf_viable, st.justified_idx,
            buckets, st.boost_idx, st.boost_amount, capacity)
        assert int(h_ref) == int(h_inc)
        assert np.array_equal(np.asarray(w_ref), np.asarray(w_inc))

    def test_large_capacity_chain(self):
        """Capacity 1024 (the round-1 reachability design was O(B^2) here):
        a deep chain plus forks must still match the spec-shaped oracle."""
        import jax.numpy as jnp
        from pos_evolution_tpu.ops.forkchoice import head_and_weights
        rng = np.random.default_rng(11)
        capacity, n = 1024, 2048
        st = self._random_store(rng, capacity, n)
        # deep chain: parent[i] = i - 1 for the first half, forks after
        parent = np.arange(-1, capacity - 1, dtype=np.int32)
        st = st._replace(parent=jnp.asarray(parent))
        h, w = head_and_weights(st, capacity)
        # chain subtree weights are suffix sums of per-block votes
        mb = np.asarray(st.msg_block)
        wt = np.asarray(st.weight)
        per_block = np.zeros(capacity, np.int64)
        np.add.at(per_block, mb[mb >= 0], wt[mb >= 0])
        expect = per_block[::-1].cumsum()[::-1]
        bi = int(st.boost_idx)
        if bi >= 0:
            expect[: bi + 1] += int(st.boost_amount)
        assert np.array_equal(np.asarray(w), expect)
        assert int(h) == capacity - 1  # chain head = tip


class TestEpochWindowedBuckets:
    """RLMD/Goldfish expiry on the incremental path: per-(block, epoch)
    weight columns must reproduce the rescan with ``min_vote_epoch``
    (pos-evolution.md:1581-1609; VERDICT r2 task 7)."""

    WINDOW = 8

    def _store(self, rng, capacity=32, n=256):
        return TestIncrementalBuckets._random_store(
            TestIncrementalBuckets(), rng, capacity, n)

    @pytest.mark.parametrize("seed,min_epoch", [(0, 0), (1, 2), (2, 3)])
    def test_rebuild_and_head_match_rescan(self, seed, min_epoch):
        import jax.numpy as jnp
        from pos_evolution_tpu.ops.forkchoice import (
            head_and_weights, head_from_epoch_buckets, rebuild_epoch_buckets)
        rng = np.random.default_rng(seed)
        capacity, n = 32, 256
        st = self._store(rng, capacity, n)
        base = 0
        eb = rebuild_epoch_buckets(st.msg_block, st.msg_epoch, st.weight,
                                   capacity, self.WINDOW, jnp.int64(base))
        h_ref, w_ref = head_and_weights(st, capacity,
                                        min_vote_epoch=min_epoch)
        h_win, w_win = head_from_epoch_buckets(
            st.parent, st.real, st.rank, st.leaf_viable, st.justified_idx,
            eb, jnp.int64(base), jnp.int64(min_epoch), st.boost_idx,
            st.boost_amount, capacity, self.WINDOW)
        assert int(h_ref) == int(h_win)
        assert np.array_equal(np.asarray(w_ref), np.asarray(w_win))

    @pytest.mark.parametrize("seed", [0, 1])
    def test_incremental_batches_match_rescan(self, seed):
        """Vote batches (with duplicates, inactives, stale-epoch votes
        below the window base) applied via the windowed kernel, then an
        expiry-windowed head — vs the rescan oracle on the final table."""
        import jax.numpy as jnp
        from pos_evolution_tpu.ops.forkchoice import (
            apply_latest_messages_windowed, head_and_weights,
            head_from_epoch_buckets, rebuild_epoch_buckets)
        rng = np.random.default_rng(seed)
        capacity, n = 32, 256
        st = self._store(rng, capacity, n)
        base = 1  # window already slid past epoch 0
        eb = rebuild_epoch_buckets(st.msg_block, st.msg_epoch, st.weight,
                                   capacity, self.WINDOW, jnp.int64(base))
        mb, me = st.msg_block, st.msg_epoch
        for _ in range(3):
            k = 48
            val_idx = jnp.asarray(rng.choice(32, size=k).astype(np.int32))
            new_block = jnp.asarray(rng.integers(0, capacity, k).astype(np.int32))
            # include stale votes (below base) AND above-window votes
            # (clamped into the top column — must stay exact)
            new_epoch = jnp.asarray(rng.integers(0, base + self.WINDOW + 3, k)
                                    .astype(np.int64))
            active = jnp.asarray(rng.random(k) < 0.8)
            mb, me, eb = apply_latest_messages_windowed(
                mb, me, eb, jnp.int64(base), val_idx, new_block, new_epoch,
                st.weight[val_idx], active)
        st2 = st._replace(msg_block=mb, msg_epoch=me)
        for min_epoch in (base, base + 3):
            h_ref, w_ref = head_and_weights(st2, capacity,
                                            min_vote_epoch=min_epoch)
            h_win, w_win = head_from_epoch_buckets(
                st.parent, st.real, st.rank, st.leaf_viable,
                st.justified_idx, eb, jnp.int64(base), jnp.int64(min_epoch),
                st.boost_idx, st.boost_amount, capacity, self.WINDOW)
            assert int(h_ref) == int(h_win), min_epoch
            assert np.array_equal(np.asarray(w_ref), np.asarray(w_win))

    def test_goldfish_window_one(self):
        """eta = 1 (GHOST-Eph, pos-evolution.md:1549): only the most
        recent epoch's votes carry weight."""
        import jax.numpy as jnp
        from pos_evolution_tpu.ops.forkchoice import (
            head_and_weights, head_from_epoch_buckets, rebuild_epoch_buckets)
        rng = np.random.default_rng(9)
        capacity, n = 16, 128
        st = self._store(rng, capacity, n)
        cur = 3
        eb = rebuild_epoch_buckets(st.msg_block, st.msg_epoch, st.weight,
                                   capacity, self.WINDOW, jnp.int64(0))
        h_ref, w_ref = head_and_weights(st, capacity, min_vote_epoch=cur)
        h_win, w_win = head_from_epoch_buckets(
            st.parent, st.real, st.rank, st.leaf_viable, st.justified_idx,
            eb, jnp.int64(0), jnp.int64(cur), st.boost_idx, st.boost_amount,
            capacity, self.WINDOW)
        assert int(h_ref) == int(h_win)
        assert np.array_equal(np.asarray(w_ref), np.asarray(w_win))
