"""Property tests (SURVEY.md §4.3): accountable safety, plausible liveness,
ebb-and-flow prefix — the reference's security properties as executable
checks (pos-evolution.md:240-243, 1174-1196).
"""

import numpy as np
import pytest

from pos_evolution_tpu.config import minimal_config, use_config
from pos_evolution_tpu.specs.containers import AttestationData, Checkpoint
from pos_evolution_tpu.specs.helpers import is_slashable_attestation_data

pytestmark = pytest.mark.usefixtures("minimal_cfg")


def _ffg_vote(source_epoch, target_epoch, chain: int):
    """An FFG vote on one of two conflicting chains."""
    return AttestationData(
        slot=target_epoch * 8, index=0,
        beacon_block_root=bytes([chain]) * 32,
        source=Checkpoint(epoch=source_epoch, root=bytes([chain, source_epoch]) * 16),
        target=Checkpoint(epoch=target_epoch, root=bytes([chain, target_epoch]) * 16),
    )


class TestAccountableSafety:
    """pos-evolution.md:242: two conflicting finalized checkpoints imply
    more than 1/3 of stake is provably slashable."""

    def test_conflicting_finalization_yields_one_third_slashable(self):
        n = 90
        validators = np.arange(n)
        # chain A finalizes (epoch 4 -> 5): needs 2/3 of votes
        set_a = set(range(0, 60))                     # 60/90 = 2/3
        # chain B finalizes the conflicting (epoch 4 -> 5) pair
        set_b = set(range(30, 90))                    # 60/90 = 2/3
        votes: dict[int, list] = {v: [] for v in validators}
        for v in set_a:
            votes[v].append(_ffg_vote(4, 5, chain=0xA0))
        for v in set_b:
            votes[v].append(_ffg_vote(4, 5, chain=0xB0))

        slashable = {
            v for v, vs in votes.items()
            if any(is_slashable_attestation_data(d1, d2)
                   for i, d1 in enumerate(vs) for d2 in vs[i + 1:])
        }
        assert slashable == set_a & set_b
        assert len(slashable) * 3 >= n, "fewer than 1/3 provably slashable"

    def test_surround_finalization_also_accountable(self):
        """The second violation mode: a finalization surrounded by a
        wider vote span."""
        n = 90
        set_a = set(range(0, 60))
        set_b = set(range(30, 90))
        votes = {v: [] for v in range(n)}
        for v in set_a:
            votes[v].append(_ffg_vote(4, 5, chain=0xA0))   # finalize (4,5)
        for v in set_b:
            votes[v].append(_ffg_vote(3, 6, chain=0xB0))   # surrounds it
        slashable = {
            v for v, vs in votes.items()
            if any(is_slashable_attestation_data(d1, d2)
                   or is_slashable_attestation_data(d2, d1)
                   for i, d1 in enumerate(vs) for d2 in vs[i + 1:])
        }
        assert len(slashable) * 3 >= n


class TestPlausibleLiveness:
    """pos-evolution.md:243: with > 2/3 honest stake online, new
    checkpoints keep finalizing from any reachable state."""

    def test_finality_resumes_after_stall(self):
        from pos_evolution_tpu.sim import Schedule, Simulation
        c = minimal_config()
        stall_end = 3 * c.slots_per_epoch * c.intervals_per_slot
        sched = Schedule(
            n_validators=64,
            # 30/64 asleep during epochs 0-2 (no finality), then all awake
            awake=lambda r, v: (v >= 30) or (r >= stall_end))
        sim = Simulation(64, schedule=sched)
        sim.run_epochs(7)
        assert sim.finalized_epoch() >= 4, \
            "finality did not resume once 2/3 honest stake returned"


class TestEbbAndFlowPrefix:
    """pos-evolution.md:1188: LOG_fin is a prefix of LOG_da at all times."""

    def test_finalized_chain_is_prefix_of_head_chain(self):
        from pos_evolution_tpu.sim import Simulation
        from pos_evolution_tpu.specs import forkchoice as fc
        sim = Simulation(64)
        sim.run_epochs(5)
        store = sim.store()
        head = fc.get_head(store)
        finalized_root = bytes(store.finalized_checkpoint.root)
        # walk the canonical chain; the finalized block must be on it
        cur = head
        seen = set()
        while True:
            seen.add(cur)
            blk = store.blocks[cur]
            if bytes(blk.parent_root) == cur or bytes(blk.parent_root) not in store.blocks:
                break
            cur = bytes(blk.parent_root)
        assert finalized_root in seen
