"""In-loop Byzantine adversary engine (sim/adversary.py).

Covers the strategy hook contract, composability with FaultPlan message
faults and crash windows, the checkpoint/resume replay contract, and the
determinism pins: FaultPlan and RandomByzantine seeded-hash decisions
must be byte-stable (hard-coded digests), independent of call/episode
ordering, and backend-free (pure hashlib — no NumPy/JAX state).
"""

import hashlib
import json

import pytest

from pos_evolution_tpu.config import minimal_config, use_config
from pos_evolution_tpu.sim.faults import FaultPlan, stateless_unit


class TestStatelessUnit:
    # Hard-coded pins: any change to the hash layout (or any accidental
    # dependence on an array backend) breaks these on SOME platform.
    PINS = {
        (0,): 0.0968671912232041,
        (0, 0, 0, 0): 0.6299085342998938,
        (1, 2, 3): 0.7069603535111167,
        (7, 0, 5, 3): 0.8593303514433065,
        (42,): 0.20337855373603228,
    }

    def test_byte_stable_pins(self):
        for key, want in self.PINS.items():
            assert stateless_unit(*key) == want

    def test_order_independent(self):
        keys = list(self.PINS)
        forward = [stateless_unit(*k) for k in keys]
        backward = [stateless_unit(*k) for k in reversed(keys)][::-1]
        assert forward == backward

    def test_pure_python_floats(self):
        # the determinism contract says hashlib, not an array library:
        # numpy scalars here would mean backend-dependent rounding modes
        u = stateless_unit(3, 1, 4)
        assert type(u) is float
        assert 0.0 <= u < 1.0

    def test_faultplan_unit_delegates(self):
        plan = FaultPlan(seed=17)
        assert plan._unit(1, 2, 3) == stateless_unit(17, 1, 2, 3)


class TestRandomByzantineDeterminism:
    def _rb(self, **kw):
        from pos_evolution_tpu.sim.adversary import RandomByzantine
        return RandomByzantine(controlled=range(8), seed=123, **kw)

    def test_decision_table_pin(self):
        blob = json.dumps([self._rb().decisions(s) for s in range(1, 9)],
                          sort_keys=True).encode()
        assert hashlib.blake2b(blob, digest_size=16).hexdigest() == \
            "9c31912774692e76d3dbef29c591ad90"

    def test_episode_order_independent(self):
        a = self._rb()
        fwd = [a.decisions(s) for s in (1, 2, 3)]
        b = self._rb()
        rev = [b.decisions(s) for s in (3, 2, 1)][::-1]
        assert fwd == rev
        # a fresh instance after unrelated draws agrees too (no cursor)
        stateless_unit(999, 1)
        assert self._rb().decisions(2) == fwd[1]

    def test_faultplan_decision_pin(self):
        plan = FaultPlan(seed=99, drop_p=0.1, duplicate_p=0.1, reorder_p=0.2)
        rows = [plan.delivery_offsets(k, s, 0, m, g, 0.0)
                for k in ("block", "attestation")
                for s in (1, 2, 3) for m in (0, 1) for g in (0, 1)]
        blob = json.dumps(rows).encode()
        assert hashlib.blake2b(blob, digest_size=16).hexdigest() == \
            "87058f43f0b2982ea8bbfab3db9625d3"


class TestHookContract:
    def test_controlled_fold_into_corrupted(self, minimal_cfg):
        from pos_evolution_tpu.sim import AdversaryStrategy, Simulation
        sim = Simulation(16, adversaries=[AdversaryStrategy((1, 2, 3))])
        assert {1, 2, 3} <= sim.schedule.corrupted

    def test_noop_strategy_matches_silent_corruption(self, minimal_cfg):
        """A hook-less strategy must be indistinguishable from a schedule
        that merely marks the same validators corrupted."""
        from pos_evolution_tpu.sim import AdversaryStrategy, Simulation
        from pos_evolution_tpu.sim.schedule import honest_schedule
        sim_a = Simulation(16, adversaries=[AdversaryStrategy((0, 1))])
        sim_a.run_epochs(2)
        sched = honest_schedule(16)
        sched.corrupted.update({0, 1})
        sim_b = Simulation(16, schedule=sched)
        sim_b.run_epochs(2)
        assert sim_a.metrics == sim_b.metrics

    def test_hooks_called_in_phase_order(self, minimal_cfg):
        from pos_evolution_tpu.sim import AdversaryStrategy, Simulation

        calls = []

        class Probe(AdversaryStrategy):
            def before_propose(self, ctx):
                calls.append((ctx.slot, "before_propose"))

            def before_attest(self, ctx):
                calls.append((ctx.slot, "before_attest"))

            def after_attest(self, ctx):
                calls.append((ctx.slot, "after_attest"))

        sim = Simulation(16, adversaries=[Probe()])
        sim.run_until_slot(2)
        assert calls == [(1, "before_propose"), (1, "before_attest"),
                         (1, "after_attest"), (2, "before_propose"),
                         (2, "before_attest"), (2, "after_attest")]


class TestEquivocator:
    def test_double_proposal_feeds_slasher_and_both_views(self, minimal_cfg):
        from pos_evolution_tpu.sim import (
            AccountableSafetyMonitor,
            Equivocator,
            Simulation,
        )
        from pos_evolution_tpu.specs.genesis import make_genesis
        from pos_evolution_tpu.specs.helpers import get_beacon_proposer_index
        from pos_evolution_tpu.specs.validator import advance_state_to_slot

        n = 32
        state, _ = make_genesis(n)
        p2 = int(get_beacon_proposer_index(advance_state_to_slot(state, 2)))
        mon = AccountableSafetyMonitor()
        sim = Simulation(n, adversaries=[Equivocator({p2}, slots=(2,))],
                         monitors=[mon])
        sim.run_until_slot(3)
        doubles = [r for r, b in sim.store(0).blocks.items()
                   if int(b.slot) == 2]
        assert len(doubles) == 2, "equivocating proposal must land twice"
        assert len(mon.proposer_evidence) == 1
        assert int(mon.proposer_evidence[0].signed_header_1
                   .message.proposer_index) == p2
        # a mere equivocation is NOT a safety violation — evidence, not
        # conflicting finality
        assert sim.monitor_violations == []

    def test_double_votes_yield_attester_evidence(self, minimal_cfg):
        from pos_evolution_tpu.sim import (
            AccountableSafetyMonitor,
            Equivocator,
            Simulation,
        )
        from pos_evolution_tpu.specs.genesis import make_genesis
        from pos_evolution_tpu.specs.helpers import get_beacon_proposer_index
        from pos_evolution_tpu.specs.validator import advance_state_to_slot

        n = 32
        state, _ = make_genesis(n)
        p2 = int(get_beacon_proposer_index(advance_state_to_slot(state, 2)))
        controlled = {p2} | set(range(8))
        mon = AccountableSafetyMonitor()
        sim = Simulation(n, adversaries=[Equivocator(controlled)],
                         monitors=[mon])
        sim.run_epochs(1)
        assert mon.evidence, "double votes must produce AttesterSlashings"
        assert mon.implicated <= controlled


class TestComposability:
    def test_clean_faulted_adversarial_run_zero_violations(self, minimal_cfg):
        """The headline robustness claim: 64 validators, message faults
        with GST, a crash window, AND a <1/3 random-Byzantine adversary —
        the run completes and every monitor stays green."""
        from pos_evolution_tpu.config import cfg
        from pos_evolution_tpu.sim import (
            CrashWindow,
            FaultPlan,
            RandomByzantine,
            Simulation,
            default_monitors,
            faulty_schedule,
        )
        c = cfg()
        plan = FaultPlan(seed=11, drop_p=0.08, duplicate_p=0.05,
                         reorder_p=0.1, gst=8 * c.seconds_per_slot,
                         crashes=(CrashWindow(1, 4, 7),))
        sched = faulty_schedule(64, plan, n_groups=2)
        monitors = default_monitors()
        sim = Simulation(64, schedule=sched,
                         adversaries=[RandomByzantine(range(12), seed=3)],
                         monitors=monitors)
        sim.run_epochs(2)
        assert sim.monitor_violations == []
        assert sim.slot == 2 * c.slots_per_epoch + 1

    def test_adversarial_traffic_subject_to_faultplan(self, minimal_cfg):
        """Adversarial messages route through the same fault layer as
        honest traffic: with drop_p=1 pre-GST, an Equivocator's double
        proposal never reaches any store."""
        from pos_evolution_tpu.sim import (
            Equivocator,
            FaultPlan,
            Simulation,
            faulty_schedule,
        )
        from pos_evolution_tpu.specs.genesis import make_genesis
        from pos_evolution_tpu.specs.helpers import get_beacon_proposer_index
        from pos_evolution_tpu.specs.validator import advance_state_to_slot

        n = 32
        state, _ = make_genesis(n)
        p2 = int(get_beacon_proposer_index(advance_state_to_slot(state, 2)))
        sched = faulty_schedule(n, FaultPlan(seed=1, drop_p=1.0))
        sim = Simulation(n, schedule=sched,
                         adversaries=[Equivocator({p2}, slots=(2,))])
        sim.run_until_slot(3)
        assert all(int(b.slot) != 2 for b in sim.store(0).blocks.values())


class TestResumeReplay:
    def test_stateless_strategy_replays_from_mid_run_checkpoint(
            self, minimal_cfg):
        """RandomByzantine is a pure function of (seed, slot, validator):
        a run checkpointed mid-attack and resumed with a FRESH strategy
        instance must match the uninterrupted run bit-for-bit."""
        from pos_evolution_tpu.sim import RandomByzantine, Simulation

        def adv():
            return RandomByzantine(controlled=range(10), seed=77,
                                   p_double_propose=0.8)

        ref = Simulation(32, adversaries=[adv()])
        ref.run_until_slot(12)

        sim = Simulation(32, adversaries=[adv()])
        sim.run_until_slot(6)
        snap = sim.checkpoint()
        resumed = Simulation.resume(snap, adversaries=[adv()])
        assert resumed.schedule.corrupted >= set(range(10))
        resumed.run_until_slot(12)

        assert resumed.metrics == ref.metrics
        assert (resumed.store(0).finalized_checkpoint ==
                ref.store(0).finalized_checkpoint)
        import pos_evolution_tpu.specs.forkchoice as fc
        assert fc.get_head(resumed.store(0)) == fc.get_head(ref.store(0))


class TestSplitVoter:
    def test_needs_partition(self, minimal_cfg):
        from pos_evolution_tpu.sim import Simulation, SplitVoter
        with pytest.raises(AssertionError):
            Simulation(16, adversaries=[SplitVoter(range(5))])

    def test_double_finality_is_accountable(self, minimal_cfg):
        """The Casper FFG theorem, end to end (pos-evolution.md:233-238):
        a split-brain network + exactly-1/3 double-voting stake drives the
        two views to CONFLICTING FINALIZED checkpoints, and the
        ``AccountableSafetyMonitor`` must attribute >= 1/3 of total stake
        from the double votes alone — safety died, but accountably."""
        from pos_evolution_tpu.sim import (
            AccountableSafetyMonitor,
            Simulation,
            SplitVoter,
        )
        from pos_evolution_tpu.sim.attacks import split_brain_schedule

        n = 48
        controlled = set(range(n // 3))
        mon = AccountableSafetyMonitor()
        sim = Simulation(n, schedule=split_brain_schedule(n, controlled),
                         adversaries=[SplitVoter(controlled)],
                         monitors=[mon])
        c = minimal_cfg
        finalized = []
        while not finalized and sim.slot <= 8 * c.slots_per_epoch:
            sim.run_slot()
            finalized = [v for v in sim.monitor_violations
                         if v["checkpoint"] == "finalized"]
        assert finalized, "double finality never detected"
        v = finalized[0]
        assert v["kind"] == "accountable_fault"
        assert 3 * v["slashable_stake"] >= v["total_stake"]
        assert v["evidence_size"] == len(controlled)
        assert mon.implicated == controlled
        # the conflict is real: both views finalized past genesis, on
        # different roots
        assert sim.finalized_epoch(0) >= 1 and sim.finalized_epoch(1) >= 1
        assert (sim.store(0).finalized_checkpoint
                != sim.store(1).finalized_checkpoint)


class TestFinalityLivenessMonitor:
    def test_fires_on_a_genuine_stall(self, minimal_cfg):
        """A split-brain network with <1/3 corrupted and NO coherent
        adversary: neither view can reach 2/3, finality stalls at
        genesis, and the liveness monitor must flag it once the lag
        passes its bound."""
        from pos_evolution_tpu.sim import FinalityLivenessMonitor, Simulation
        from pos_evolution_tpu.sim.attacks import split_brain_schedule

        n = 48
        corrupted = set(range(n // 3 - 1))      # strictly below 1/3: armed
        mon = FinalityLivenessMonitor(bound_epochs=2, armed_after_epoch=0)
        sim = Simulation(n, schedule=split_brain_schedule(n, corrupted),
                         monitors=[mon])
        sim.run_epochs(4)
        assert mon.disarmed_reason is None
        stalls = [v for v in sim.monitor_violations
                  if v["kind"] == "liveness_violation"]
        assert stalls, "finality stall never flagged"
        assert stalls[0]["lag_epochs"] > 2
        assert stalls[0]["best_finalized_epoch"] == 0

    def test_disarms_loudly_at_one_third_corruption(self, minimal_cfg):
        from pos_evolution_tpu.sim import FinalityLivenessMonitor, Simulation
        from pos_evolution_tpu.sim.schedule import honest_schedule

        sched = honest_schedule(48)
        sched.corrupted.update(range(16))       # exactly 1/3
        mon = FinalityLivenessMonitor(bound_epochs=1)
        sim = Simulation(48, schedule=sched, monitors=[mon])
        assert mon.disarmed_reason is not None
        sim.run_epochs(3)
        assert sim.monitor_violations == []     # disarmed, not asserting


class TestBalancerStrategy:
    def test_swayer_balancing_holds_tie_through_simulation(self):
        """The Balancer strategy (swayer balancing, pre-boost Gasper)
        driven through Simulation, inside its viable envelope: with the
        committee-balanced view assignment the reference's precondition
        (enough swayers in EVERY slot, pos-evolution.md:1330) holds for
        all of epoch 0 — the tie must persist through every slot of it,
        epoch 0 must never justify, and finality stays at genesis for the
        whole run. The epoch-1 committee reshuffle breaks the balanced
        assignment, which is exactly the reference's "enough Byzantine
        validators in every slot" condition failing — the in-loop form of
        the scripted ``run_balancing_attack``."""
        with use_config(minimal_config().replace(
                proposer_score_boost_percent=0)) as c:
            from pos_evolution_tpu.sim import Balancer, Simulation
            from pos_evolution_tpu.sim.attacks import (
                committee_balanced_split_schedule,
            )
            from pos_evolution_tpu.specs import forkchoice as fc
            from pos_evolution_tpu.specs.genesis import make_genesis
            from pos_evolution_tpu.specs.helpers import (
                get_beacon_proposer_index,
            )
            from pos_evolution_tpu.specs.validator import (
                advance_state_to_slot,
            )

            n = 64
            state, _ = make_genesis(n)
            corrupted = set(range(int(n * 0.3)))
            # the strategy's slot-1 equivocation requires the slot-1
            # proposer under adversary control
            corrupted.add(int(get_beacon_proposer_index(
                advance_state_to_slot(state, 1))))
            sched = committee_balanced_split_schedule(n, corrupted)
            sim = Simulation(n, schedule=sched,
                             adversaries=[Balancer(corrupted)])
            tie = {}
            for _ in range(2 * c.slots_per_epoch + 1):
                sim.run_slot()
                done = sim.slot - 1
                tie[done] = (fc.get_head(sim.store(0))
                             != fc.get_head(sim.store(1)))
            epoch0 = [tie[s] for s in range(1, c.slots_per_epoch)]
            assert all(epoch0), f"tie lost inside epoch 0: {tie}"
            assert sim.justified_epoch(0) == 0
            assert sim.justified_epoch(1) == 0
            assert sim.finalized_epoch(0) == 0
            assert sim.finalized_epoch(1) == 0
